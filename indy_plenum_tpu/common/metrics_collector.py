"""Metrics: named event accumulators + timing around the hot paths.

Reference: plenum/common/metrics_collector.py (`MetricsCollector`,
`KvStoreMetricsCollector`, ``measure_time``/``async_measure_time``). Every
event is (name, value); the collector keeps running stats per name
(count/sum/min/max) cheap enough for the consensus hot path, and the KV
variant persists periodic snapshots so a long-running node's history
survives restarts.

The names cover what the device-plane design must be able to justify with
data: device flush counts and latencies, auth batch sizes and durations,
3PC batch timings.
"""
from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Dict, Optional


class MetricsName:
    # ingress — AUTH_BATCH_* measures work the device actually verified;
    # the admission plane's shed/queue accounting lives under dedicated
    # ingress.* names so overload never pollutes the hot-path stats
    AUTH_BATCH_SIZE = "auth.batch_size"
    AUTH_BATCH_TIME = "auth.batch_time"
    # admission control (ingress/admission.py): pre-drain queue depth per
    # tick (Stat.last = current, max = the bound actually reached),
    # admitted/shed totals (Stat.total), and the device-proof read path's
    # batch sizes / served counts / wall-clock qps gauge
    INGRESS_QUEUE_DEPTH = "ingress.queue_depth"
    INGRESS_ADMITTED = "ingress.admitted"
    INGRESS_SHED = "ingress.shed"
    # closed-loop retry (ingress/retry.py): seeded-backoff re-offers the
    # retry driver actually fired, requests whose retry budget ran out
    # (fail closed), and admitted requests that needed >= 1 retry — the
    # goodput split: admitted - retry_admitted is first-attempt goodput
    INGRESS_RETRIES = "ingress.retries"
    INGRESS_RETRY_EXHAUSTED = "ingress.retry_exhausted"
    INGRESS_RETRY_ADMITTED = "ingress.retry_admitted"
    READ_BATCH_SIZE = "ingress.read_batch_size"
    READ_SERVED = "ingress.read_served"
    READ_QPS = "ingress.read_qps"
    # read-path backpressure: the read queue's own bounded-queue law
    # (same seeded drop-newest shed as writes) — pre-drain depth per
    # drain and shed totals, segregated from the write-side series
    READ_QUEUE_DEPTH = "ingress.read_queue_depth"
    READ_SHED = "ingress.read_shed"
    # state-proof plane (proofs/): windows captured per checkpoint
    # stabilization, serve-path hit/miss accounting (hits are dict
    # lookups — zero pairings, the proof gate's core assertion), reads
    # served WITH a pool proof attached, and the pairing work the
    # batched verifier actually performed
    PROOF_WINDOWS_SIGNED = "proof.windows_signed"
    PROOF_CACHE_HIT = "proof.cache_hit"
    PROOF_CACHE_MISS = "proof.cache_miss"
    PROOF_SERVED = "proof.served"
    PROOF_PAIRINGS = "proof.pairings"
    PROOF_VERIFY_BATCH = "proof.verify_batch"
    # 3PC
    BACKUP_ORDERED = "3pc.backup_ordered"
    ORDERED_BATCH_SIZE = "3pc.ordered_batch_size"
    # device plane
    DEVICE_FLUSH = "device.flush"
    DEVICE_FLUSH_TIME = "device.flush_time"
    DEVICE_FLUSH_VOTES = "device.flush_votes"
    # dispatch plane (tick-batched mode): how many device steps one tick
    # actually cost, and what fraction of each padded scatter carried
    # real votes. Together they are the measured amortization story —
    # device_dispatches_per_tick should sit near 1, flush_occupancy near
    # the votes-per-tick / padded-shape ratio (see README "Performance").
    DEVICE_DISPATCHES_PER_TICK = "device.dispatches_per_tick"
    DEVICE_FLUSH_OCCUPANCY = "device.flush_occupancy"
    # mesh-sharded dispatch plane: shard count (Stat.last = the current
    # mesh width) and per-shard vote/capacity counters, recorded as
    # "<prefix>.<shard_index>". Votes and capacity are separate series
    # (capacity counts REAL, non-pad rows only) so every consumer
    # derives the SAME cumulative occupancy — sum(votes)/sum(capacity),
    # the VotePlaneGroup.shard_occupancy definition — instead of an
    # average of per-dispatch ratios that diverges once flush shapes
    # vary. Only recorded when the group runs on a mesh (> 1 shard).
    DEVICE_SHARD_COUNT = "device.shard_count"
    DEVICE_SHARD_FLUSH_VOTES = "device.shard_flush_votes"
    DEVICE_SHARD_FLUSH_CAPACITY = "device.shard_flush_capacity"
    # ordering fast path (device-side quorum eval): bytes actually
    # crossing the device->host boundary per absorb — O(newly certified
    # + frontier) in device-eval mode, the full event matrix under the
    # host_eval fallback. DEVICE_READBACK_COMPACT records the mode as a
    # gauge (Stat.last: 1 = compact/device eval, 0 = host eval) so
    # snapshots can label the bytes they report.
    DEVICE_READBACK_BYTES = "device.readback_bytes"
    DEVICE_READBACK_COMPACT = "device.readback_compact"
    # multi-tick device residency (tpu/vote_plane.py): the configured
    # ring depth (gauge, recorded once when a group runs resident),
    # ticks whose votes rode the ring instead of dispatching, and ticks
    # whose compact readback deferred behind residency — together the
    # measured amortization of the fused multi-tick consume
    DEVICE_RESIDENT_DEPTH = "device.resident_depth"
    DEVICE_RESIDENT_TICKS = "device.resident_ticks"
    DEVICE_READBACKS_DEFERRED = "device.readbacks_deferred"
    # dispatch governor (adaptive tick, tpu/governor.py): the effective
    # interval after every tick (Stat.last = the CURRENT interval; the
    # histogram records how long the pool dwelt on each rung) and the
    # occupancy EWMA the control law acted on — together they make an
    # adaptive run's trajectory a comparable, replayable artifact
    GOVERNOR_TICK_INTERVAL = "governor.tick_interval"
    GOVERNOR_OCCUPANCY_EWMA = "governor.occupancy_ewma"
    # per-shard EWMAs under a mesh ("<prefix>.<shard_index>"): the
    # series the hottest-shard law acts on
    GOVERNOR_SHARD_OCCUPANCY_EWMA = "governor.shard_occupancy_ewma"
    # execution
    COMMIT_TIME = "exec.commit_time"
    # state-commit plane (state/sparse_merkle_state.py): per-3PC-batch
    # tree hashes the one-walk batched commit actually performed (the
    # O(delta) claim, measured — leaf + internal-node hashes, placement-
    # independent) and the valid-request count flushed per batch; the
    # per-state node-cache hit/miss totals live on the state object
    # (cache_hits/cache_misses) and surface through profile_rbft's
    # `state` block
    STATE_COMMIT_HASHES = "state.commit_hashes"
    STATE_COMMIT_BATCH_SIZE = "state.commit_batch_size"
    # catchup (chaos-hardened recovery plane): rounds completed, txns
    # fetched+applied, audit-proof verifications the leecher performed
    # on leeched batches (and the txns it REJECTED for failing them —
    # byzantine seeders), and re-requests the retry law issued
    CATCHUP_FAILED = "catchup.failed"
    CATCHUP_ROUNDS = "catchup.rounds"
    CATCHUP_TXNS_LEECHED = "catchup.txns_leeched"
    CATCHUP_PROOFS_VERIFIED = "catchup.proofs_verified"
    CATCHUP_REPS_REJECTED = "catchup.reps_rejected"
    CATCHUP_RETRIES = "catchup.retries"
    # seeder-side throttle (server/catchup/seeder_service.py): txns this
    # node served to leechers, and CATCHUP_REQ slices it deferred to a
    # later virtual instant because the token bucket was dry — seeding a
    # returning node must not stall the seeder's own ordering
    CATCHUP_SEEDER_TXNS = "catchup.seeder_txns"
    CATCHUP_SEEDER_DEFERRED = "catchup.seeder_deferred"
    # ordering lanes (keyspace-partitioned write path, lanes/): lane
    # count (Stat.last), per-lane ordered totals and router assignments
    # ("<prefix>.<lane>"), the barrier's sealed-window ordinal, and the
    # seal lag (first lane ready -> all lanes ready, virtual seconds) —
    # how long the fastest lane waited on the slowest per window
    LANE_COUNT = "lanes.count"
    LANE_ORDERED = "lanes.ordered"
    LANE_ROUTED = "lanes.routed"
    LANE_SEALED_WINDOW = "lanes.sealed_window"
    LANE_BARRIER_SEAL_LAG = "lanes.barrier_seal_lag"
    # transport
    ZSTACK_DROPPED = "zstack.dropped"
    # simulation network / chaos plane
    SIM_NET_DELIVERED = "sim_net.delivered"
    SIM_NET_DROPPED = "sim_net.dropped"
    CHAOS_FAULTS_BEGUN = "chaos.faults_begun"
    # long-horizon telemetry plane (observability/telemetry.py);
    # per-resource gauges ride "telemetry.resource.<name>" keys
    TELEMETRY_WINDOWS = "telemetry.windows"
    TELEMETRY_ANOMALIES = "telemetry.anomalies"


class Stat:
    __slots__ = ("count", "total", "min", "max", "last")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        # most recent value: for control variables (the governor's tick
        # interval) "current" is the question dashboards ask
        self.last: Optional[float] = None

    def add(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        self.last = value

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {"count": self.count, "sum": self.total, "avg": self.avg,
                "min": self.min, "max": self.max, "last": self.last}


# distinct buckets kept per histogram: control variables take few values
# (the governor's ladder is multiplicative steps inside fixed bounds), so
# overflow means a bug upstream — excess lands in one "other" bucket
# instead of growing without bound
HISTOGRAM_MAX_BUCKETS = 64
HISTOGRAM_OVERFLOW_KEY = "other"


class MetricsCollector:
    def __init__(self):
        self._stats: Dict[str, Stat] = {}
        self._histograms: Dict[str, Dict[Any, int]] = {}

    def add_event(self, name: str, value: float = 1.0) -> None:
        stat = self._stats.get(name)
        if stat is None:
            stat = self._stats[name] = Stat()
        stat.add(value)

    def add_to_histogram(self, name: str, bucket: Any) -> None:
        """Count ``bucket`` occurrences under ``name`` (bounded: at most
        HISTOGRAM_MAX_BUCKETS distinct buckets, then "other")."""
        hist = self._histograms.get(name)
        if hist is None:
            hist = self._histograms[name] = {}
        if bucket not in hist and len(hist) >= HISTOGRAM_MAX_BUCKETS:
            bucket = HISTOGRAM_OVERFLOW_KEY
        hist[bucket] = hist.get(bucket, 0) + 1

    def histogram(self, name: str) -> Optional[Dict[Any, int]]:
        hist = self._histograms.get(name)
        return dict(hist) if hist is not None else None

    def stat(self, name: str) -> Optional[Stat]:
        return self._stats.get(name)

    def sized_resources(self, prefix: str = "metrics."):
        """Resource-ledger registration (observability.telemetry): stat
        names come from the fixed MetricsName space (leak-law watched),
        and the widest histogram must respect HISTOGRAM_MAX_BUCKETS
        (+1 for the overflow key)."""
        from ..observability.telemetry import SizedResource

        return (
            SizedResource(prefix + "stats", lambda: len(self._stats),
                          bound=None, entry_bytes=96),
            SizedResource(prefix + "histogram_buckets",
                          lambda: max((len(h) for h in
                                       self._histograms.values()),
                                      default=0),
                          bound=HISTOGRAM_MAX_BUCKETS + 1,
                          entry_bytes=48),
        )

    def summary(self) -> Dict[str, Dict[str, Any]]:
        return {name: s.as_dict() for name, s in sorted(self._stats.items())}

    @contextmanager
    def measure_time(self, name: str):
        """Time the body into ``name`` — EXCEPT when it raises: failure
        paths land under ``<name>.error`` instead, so a retry storm of
        raising bodies can never pollute the hot-path latency stats the
        dispatch plane is judged by (and the error count is itself an
        observable)."""
        # da: allow-file[nondet-source] -- wall-duration METERS only: metric values never feed consensus state, message contents or any *_hash fingerprint
        t0 = time.perf_counter()
        try:
            yield
        except BaseException:
            self.add_event(name + ".error", time.perf_counter() - t0)
            raise
        else:
            self.add_event(name, time.perf_counter() - t0)

    def close(self) -> None:
        """Teardown hook: persistent collectors flush; the in-memory
        base has nothing to do."""


class NullMetricsCollector(MetricsCollector):
    """Zero-cost sink for compositions that don't collect."""

    def add_event(self, name: str, value: float = 1.0) -> None:
        pass

    def add_to_histogram(self, name: str, bucket: Any) -> None:
        pass

    @contextmanager
    def measure_time(self, name: str):
        yield


# histogram entries share the stat keyspace; the prefix keeps them
# distinguishable (no metric name starts with it — MetricsName values
# are dotted lowercase words)
_HISTOGRAM_KEY_PREFIX = "hist!"


class KvMetricsCollector(MetricsCollector):
    """Persists summary snapshots into a KV store (reference: the
    KvStoreMetricsCollector's accumulated storage). Re-opening over a
    non-empty store SEEDS the counters from the persisted snapshot —
    stats AND histograms (``governor.tick_interval`` dwell history
    included), so history genuinely survives restarts instead of being
    overwritten by the new process's counters. ``close()`` flushes the
    up-to-``flush_every - 1`` events a periodic-only flush would lose on
    a clean shutdown — Node teardown calls it."""

    def __init__(self, store, flush_every: int = 1000):
        super().__init__()
        self._store = store
        self._flush_every = flush_every
        self._events_since_flush = 0
        for name, snap in self.load_persisted().items():
            stat = self._stats[name] = Stat()
            stat.count = snap.get("count", 0)
            stat.total = snap.get("sum", 0.0)
            stat.min = snap.get("min")
            stat.max = snap.get("max")
            stat.last = snap.get("last")
        for name, hist in self.load_persisted_histograms().items():
            self._histograms[name] = dict(hist)

    def add_event(self, name: str, value: float = 1.0) -> None:
        super().add_event(name, value)
        self._events_since_flush += 1
        if self._events_since_flush >= self._flush_every:
            self.flush()

    def flush(self) -> None:
        import json

        self._events_since_flush = 0
        for name, stat in self._stats.items():
            self._store.put(name.encode(),
                            json.dumps(stat.as_dict()).encode())
        for name, hist in self._histograms.items():
            # [bucket, count] pairs, not an object: JSON object keys are
            # strings, and the governor's float buckets must round-trip
            # as floats
            self._store.put(
                (_HISTOGRAM_KEY_PREFIX + name).encode(),
                json.dumps(sorted(
                    ([b, c] for b, c in hist.items()),
                    key=lambda pair: str(pair[0]))).encode())

    def close(self) -> None:
        self.flush()

    def load_persisted(self) -> Dict[str, Dict[str, Any]]:
        import json

        out = {}
        for key, value in self._store.iterator():
            name = bytes(key).decode()
            if name.startswith(_HISTOGRAM_KEY_PREFIX):
                continue
            out[name] = json.loads(bytes(value))
        return out

    def load_persisted_histograms(self) -> Dict[str, Dict[Any, int]]:
        import json

        out: Dict[str, Dict[Any, int]] = {}
        for key, value in self._store.iterator():
            name = bytes(key).decode()
            if not name.startswith(_HISTOGRAM_KEY_PREFIX):
                continue
            pairs = json.loads(bytes(value))
            out[name[len(_HISTOGRAM_KEY_PREFIX):]] = {
                # JSON has no tuple/int-key subtleties for our buckets
                # (floats and strings); lists would be unhashable, guard
                (tuple(b) if isinstance(b, list) else b): c
                for b, c in pairs}
        return out
