"""Timer service — the ONLY clock the protocol state machines see.

Reference: plenum/common/timer.py (`TimerService`, `QueueTimer`,
`RepeatingTimer`). Keeping all time behind this interface is what makes the
whole consensus engine deterministic under the simulation harness
(`indy_plenum_tpu.simulation.mock_timer.MockTimer` drives a virtual clock).
"""
from __future__ import annotations

import time
from abc import ABC, abstractmethod
from heapq import heappush, heappop
from typing import Callable, NamedTuple


class TimerService(ABC):
    """Schedule callbacks against a monotonic clock."""

    @abstractmethod
    def get_current_time(self) -> float:
        ...

    @abstractmethod
    def schedule(self, delay: float, callback: Callable[[], None],
                 barrier: bool = False) -> None:
        ...

    @abstractmethod
    def cancel(self, callback: Callable[[], None]) -> None:
        """Cancel ALL pending occurrences of ``callback``."""
        ...


class _Event(NamedTuple):
    timestamp: float
    # barrier events sort AFTER every plain event due at the same
    # timestamp: the dispatch-plane tick must observe a fully drained
    # delivery set, never race a same-instant message
    priority: int
    counter: int  # tie-break so heap order is deterministic & insertion-stable
    callback: Callable[[], None]


class QueueTimer(TimerService):
    """Heap-based timer; ``service()`` fires everything due at current time."""

    def __init__(self, get_current_time: Callable[[], float] = time.monotonic):
        self._get_current_time = get_current_time
        self._events: list[_Event] = []
        self._cancelled: set[int] = set()
        self._counter = 0

    def get_current_time(self) -> float:
        return self._get_current_time()

    def queue_size(self) -> int:
        return len(self._events) - len(self._cancelled)

    def schedule(self, delay: float, callback: Callable[[], None],
                 barrier: bool = False) -> None:
        """``barrier=True`` defers the event behind every plain event due
        at the same timestamp (the tick-batched dispatch plane's drain
        contract: deliveries first, quorum evaluation after)."""
        self._counter += 1
        heappush(
            self._events,
            _Event(self.get_current_time() + delay, 1 if barrier else 0,
                   self._counter, callback),
        )

    def cancel(self, callback: Callable[[], None]) -> None:
        for ev in self._events:
            if ev.callback == callback and ev.counter not in self._cancelled:
                self._cancelled.add(ev.counter)

    def service(self) -> int:
        """Fire all due events; returns the number fired.

        Only events scheduled before this call are eligible — a 0-delay
        callback rescheduled from inside a callback fires on the NEXT
        service() pass, so a virtual clock that never advances cannot hang
        the loop.
        """
        fired = 0
        now = self.get_current_time()
        counter_at_entry = self._counter
        while (self._events and self._events[0].timestamp <= now
               and self._events[0].counter <= counter_at_entry):
            ev = heappop(self._events)
            if ev.counter in self._cancelled:
                self._cancelled.discard(ev.counter)
                continue
            ev.callback()
            fired += 1
        return fired

    def next_event_time(self) -> float | None:
        while self._events and self._events[0].counter in self._cancelled:
            self._cancelled.discard(self._events[0].counter)
            heappop(self._events)
        return self._events[0].timestamp if self._events else None


class RepeatingTimer:
    """Re-schedules ``callback`` every ``interval`` until stopped.

    Each start() opens a new generation; occurrences from a stopped
    generation never fire or reschedule, so stop()+start() from inside the
    callback (watchdog reset) cannot double the chain.
    """

    def __init__(self, timer: TimerService, interval: float,
                 callback: Callable[[], None], active: bool = True,
                 barrier: bool = False):
        if interval <= 0:
            raise ValueError("interval must be positive")
        self._timer = timer
        self._interval = interval
        self._user_callback = callback
        self._active = False
        self._generation = 0
        self._barrier = barrier
        self._pending: Callable[[], None] | None = None
        if active:
            self.start()

    def _schedule_next(self) -> None:
        generation = self._generation
        def occurrence():
            self._fire(generation)
        self._pending = occurrence
        self._timer.schedule(self._interval, occurrence,
                             barrier=self._barrier)

    def _fire(self, generation: int) -> None:
        if not self._active or generation != self._generation:
            return
        self._user_callback()
        if self._active and generation == self._generation:
            self._schedule_next()

    def start(self) -> None:
        if not self._active:
            self._active = True
            self._generation += 1
            self._schedule_next()

    def stop(self) -> None:
        if self._active:
            self._active = False
            self._generation += 1
            if self._pending is not None:
                self._timer.cancel(self._pending)
                self._pending = None

    @property
    def interval(self) -> float:
        """The CURRENT interval (the dispatch governor retunes it live)."""
        return self._interval

    def update_interval(self, interval: float) -> None:
        """Takes effect at the next (re)schedule: calling this from inside
        the callback — the governor's pattern — retimes the very next
        occurrence, because _fire reschedules after the callback returns."""
        if interval <= 0:
            raise ValueError("interval must be positive")
        self._interval = interval
