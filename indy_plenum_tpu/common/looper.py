"""Looper: the production event loop driving timers and transports.

Reference: stp_core/loop/looper.py (`Looper`, `Prodable`) and motor.py
(`Motor`). The reference wraps asyncio; here the loop is an explicit
synchronous pump — deterministic, exception-isolating, and trivially
embeddable in tests — that *prods* every registered prodable (ZStacks,
nodes) and then services the shared QueueTimer each pass, sleeping only
when a pass did no work.

Pump order IS the deployed node's dispatch-plane barrier (README
"Performance"): transports drain first — every pending socket read lands
in its handlers (signed ingress into the auth queue, votes recorded
host-side) — and only then do due timer events fire. A barrier-scheduled
quorum tick (``Node._quorum_tick``) therefore always observes a fully
drained transport, exactly like the simulation's tick observes a drained
delivery set: drain → scatter → single grouped step → read events holds
over real zstack sockets too.

A raising prodable/timer callback is logged and isolated (the reference
Looper's per-prodable error guard): one faulty component must not stall
the node's clock or its peers' IO.
"""
from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional

from .timer import QueueTimer, TimerService

logger = logging.getLogger(__name__)


class Prodable:
    """Anything the loop pumps: return the amount of work done."""

    def prod(self) -> int:  # pragma: no cover — interface
        raise NotImplementedError

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass


class Looper:
    def __init__(self, timer: Optional[TimerService] = None,
                 idle_sleep: float = 0.002):
        # epoch-aligned monotonic clock: protocol timestamps (ppTime) are
        # wall-clock epoch seconds, but scheduling must never jump backwards
        # da: allow-file[nondet-source] -- the DEPLOYED event loop runs on real time; simulation pools inject MockTimer and never construct this clock
        epoch_offset = time.time() - time.monotonic()
        self.timer = timer or QueueTimer(
            lambda: epoch_offset + time.monotonic())
        self._prodables: List = []
        self._idle_sleep = idle_sleep
        self.errors = 0

    def add(self, prodable) -> None:
        self._prodables.append(prodable)
        if hasattr(prodable, "start"):
            try:
                prodable.start()
            except NotImplementedError:
                pass

    def remove(self, prodable) -> None:
        if prodable in self._prodables:
            self._prodables.remove(prodable)

    def _pump_once(self) -> int:
        worked = 0
        # transports BEFORE timers (the zstack transport barrier): a due
        # quorum tick must fire against a drained socket set — reads that
        # were already pending when the tick came due land first, so the
        # tick's one device step carries them instead of the next tick's
        for prodable in list(self._prodables):
            try:
                fn = getattr(prodable, "prod", None) or prodable.service
                worked += fn() or 0
            except Exception:  # noqa: BLE001
                logger.exception("prodable %r raised", prodable)
                self.errors += 1
        try:
            worked += self.timer.service()
        except Exception:  # noqa: BLE001 — isolate faulty callbacks
            logger.exception("timer callback raised")
            self.errors += 1
        return worked

    def run_for(self, seconds: float) -> None:
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            if self._pump_once() == 0:
                time.sleep(self._idle_sleep)

    def run_until(self, condition: Callable[[], bool],
                  timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if condition():
                return True
            if self._pump_once() == 0:
                time.sleep(self._idle_sleep)
        return condition()

    def shutdown(self) -> None:
        for prodable in self._prodables:
            if hasattr(prodable, "stop"):
                try:
                    prodable.stop()
                except Exception:  # noqa: BLE001
                    logger.exception("prodable stop raised")
        self._prodables.clear()
