"""Protocol constants: ledger ids, txn types, roles, field names.

Reference: plenum/common/constants.py and plenum/common/types.py (the ``f``
field-name container). Values are semantically equivalent but independently
chosen where the reference's exact wire values are historical accidents.
"""
from __future__ import annotations

# --- ledger ids (ordering matters: audit first in catchup) ---------------
POOL_LEDGER_ID = 0
DOMAIN_LEDGER_ID = 1
CONFIG_LEDGER_ID = 2
AUDIT_LEDGER_ID = 3

VALID_LEDGER_IDS = (POOL_LEDGER_ID, DOMAIN_LEDGER_ID, CONFIG_LEDGER_ID,
                    AUDIT_LEDGER_ID)

# catchup order: audit pins the target sizes of the others (SURVEY.md §3.3)
CATCHUP_ORDER = (AUDIT_LEDGER_ID, POOL_LEDGER_ID, CONFIG_LEDGER_ID,
                 DOMAIN_LEDGER_ID)

# --- transaction types ----------------------------------------------------
NYM = "1"  # domain: identity CRUD
NODE = "0"  # pool: validator membership
GET_TXN = "3"
POOL_CONFIG = "111"  # config: pool-wide protocol parameters
WRITES = "writes"  # POOL_CONFIG field: pool accepts write requests
AUDIT = "2"  # audit ledger txn (one per 3PC batch)
GET_NYM = "105"
# action types (executed immediately on the receiving node, never written
# to a ledger; reference: plenum's ActionReqManager)
POOL_RESTART = "118"
VALIDATOR_INFO = "119"

# --- roles ----------------------------------------------------------------
TRUSTEE = "0"
STEWARD = "2"
IDENTITY_OWNER = None  # a NYM with no role

# --- NYM txn fields -------------------------------------------------------
TARGET_NYM = "dest"
VERKEY = "verkey"
ROLE = "role"
ALIAS = "alias"

# --- NODE txn data fields -------------------------------------------------
NODE_IP = "node_ip"
NODE_PORT = "node_port"
CLIENT_IP = "client_ip"
CLIENT_PORT = "client_port"
# the node's CurveZMQ transport public key, carried in NODE txn data so
# membership changes can rewire transports (the reference derives curve
# keys from the node verkey; an explicit field is the honest equivalent
# for our from-seed curve keys)
TRANSPORT_VERKEY = "transport_verkey"
SERVICES = "services"
BLS_KEY = "blskey"
BLS_KEY_PROOF = "blskey_pop"
VALIDATOR = "VALIDATOR"

# --- audit txn fields -----------------------------------------------------
AUDIT_TXN_VIEW_NO = "viewNo"
AUDIT_TXN_PP_SEQ_NO = "ppSeqNo"
AUDIT_TXN_LEDGERS_SIZE = "ledgerSize"
AUDIT_TXN_LEDGER_ROOT = "ledgerRoot"
AUDIT_TXN_STATE_ROOT = "stateRoot"
AUDIT_TXN_PRIMARIES = "primaries"
AUDIT_TXN_DIGEST = "digest"

# --- txn envelope fields --------------------------------------------------
TXN_TYPE = "type"
TXN_PAYLOAD = "txn"
TXN_PAYLOAD_DATA = "data"
TXN_PAYLOAD_METADATA = "metadata"
TXN_PAYLOAD_METADATA_FROM = "from"
TXN_PAYLOAD_METADATA_REQ_ID = "reqId"
TXN_PAYLOAD_METADATA_DIGEST = "digest"
TXN_METADATA = "txnMetadata"
TXN_METADATA_SEQ_NO = "seqNo"
TXN_METADATA_TIME = "txnTime"
TXN_SIGNATURE = "reqSignature"
TXN_VERSION = "ver"

CURRENT_TXN_VERSION = "1"

# --- misc protocol --------------------------------------------------------
CURRENT_PROTOCOL_VERSION = 2
GENESIS_FILE_SUFFIX = "_genesis"


class f:
    """Wire field names (reference: plenum/common/types.py ``f``)."""

    IDENTIFIER = "identifier"
    REQ_ID = "reqId"
    OPERATION = "operation"
    SIGNATURE = "signature"
    SIGNATURES = "signatures"  # multi-sig endorsements
    DIGEST = "digest"
    PROTOCOL_VERSION = "protocolVersion"
    VIEW_NO = "viewNo"
    INST_ID = "instId"
    PP_SEQ_NO = "ppSeqNo"
    PP_TIME = "ppTime"
    REQ_IDRS = "reqIdr"
    DISCARDED = "discarded"
    STATE_ROOT = "stateRootHash"
    TXN_ROOT = "txnRootHash"
    LEDGER_ID = "ledgerId"
    SEQ_NO_START = "seqNoStart"
    SEQ_NO_END = "seqNoEnd"
    CATCHUP_TILL = "catchupTill"
    TXNS = "txns"
    CONS_PROOF = "consProof"
    MERKLE_ROOT = "merkleRoot"
    OLD_MERKLE_ROOT = "oldMerkleRoot"
    NEW_MERKLE_ROOT = "newMerkleRoot"
    HASHES = "hashes"
    RESULT = "result"
    REASON = "reason"
    MSG = "msg"
    SENDER = "sender"
    BLS_SIG = "blsSig"
    BLS_MULTI_SIG = "blsMultiSig"
    AUDIT_TXN_ROOT = "auditTxnRootHash"
    PRIMARIES = "primaries"
    CHECKPOINTS = "checkpoints"
    STABLE_CHECKPOINT = "stableCheckpoint"
    PREPARED = "prepared"
    PREPREPARED = "preprepared"
    BATCHES = "batches"
    VIEW_CHANGES = "viewChanges"
    TIMESTAMP = "timestamp"
