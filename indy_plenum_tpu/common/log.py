"""Logging subsystem: namespaced loggers + time-and-size rotation.

Reference: stp_core/common/log.py (``getlogger``) and
stp_core/common/logging/TimeAndSizeRotatingFileHandler.py. A long-running
validator needs bounded on-disk logs: the handler rolls over when EITHER
the active file exceeds ``max_bytes`` OR the time interval elapses —
whichever comes first — keeping ``backup_count`` rotated files.
``setup_logging`` applies the config's verbosity and attaches the handler
process-wide; libraries keep using stdlib ``logging`` so nothing in the
package needs to import this module to be captured.
"""
from __future__ import annotations

import logging
import logging.handlers
import os
import time
from typing import Optional

DEFAULT_FORMAT = ("%(asctime)s | %(levelname)-8s | %(name)s "
                  "(%(filename)s:%(lineno)d) | %(message)s")


class TimeAndSizeRotatingFileHandler(
        logging.handlers.TimedRotatingFileHandler):
    """Rolls over on size OR time, whichever trips first."""

    def __init__(self, filename: str, when: str = "h", interval: int = 1,
                 backup_count: int = 10, max_bytes: int = 10 * 1024 * 1024,
                 **kwargs):
        super().__init__(filename, when=when, interval=interval,
                         backupCount=backup_count, **kwargs)
        self.max_bytes = max_bytes

    def shouldRollover(self, record) -> bool:  # noqa: N802 — stdlib API
        if super().shouldRollover(record):
            return True
        if self.max_bytes <= 0:
            return False
        if self.stream is None:
            self.stream = self._open()
        msg = f"{self.format(record)}\n"
        self.stream.seek(0, 2)
        return self.stream.tell() + len(msg) >= self.max_bytes

    def rotation_filename(self, default_name: str) -> str:
        """Size-triggered rollovers within one time bucket must not
        collide (TimedRotatingFileHandler names by time only, so two
        rollovers in the same second would silently overwrite)."""
        name = default_name
        counter = 0
        while os.path.exists(name):
            counter += 1
            name = f"{default_name}.{counter}"
        return name

    def doRollover(self) -> None:  # noqa: N802 — stdlib API
        super().doRollover()
        self._prune_backups()

    def _prune_backups(self) -> None:
        """Own pruning: the stdlib deletion regex does not match the
        uniquified same-bucket names, so without this the backups would
        grow unbounded — the exact failure this handler exists to stop."""
        if self.backupCount <= 0:
            return
        directory = os.path.dirname(self.baseFilename)
        base = os.path.basename(self.baseFilename)
        backups = sorted(
            (f for f in os.listdir(directory)
             if f.startswith(base + ".")),
            key=lambda f: os.path.getmtime(os.path.join(directory, f)))
        while len(backups) > self.backupCount:
            try:
                os.unlink(os.path.join(directory, backups.pop(0)))
            except OSError:  # pragma: no cover — raced with an external
                pass  # cleaner; a leftover file is not worth crashing for


def getlogger(name: Optional[str] = None) -> logging.Logger:
    """The reference's accessor: module loggers under one namespace."""
    return logging.getLogger(name or "indy_plenum_tpu")


def setup_logging(level: str = "INFO",
                  log_file: Optional[str] = None,
                  max_bytes: int = 10 * 1024 * 1024,
                  backup_count: int = 10,
                  when: str = "h",
                  interval: int = 1,
                  logger: Optional[logging.Logger] = None
                  ) -> Optional[TimeAndSizeRotatingFileHandler]:
    """Apply verbosity + attach the rotating file handler.

    Returns the handler (None when ``log_file`` is not given) so a
    composition can detach it on shutdown. Idempotent enough for tests:
    a second call with the same file replaces the previous handler.
    """
    root = logger if logger is not None else logging.getLogger()
    root.setLevel(getattr(logging, str(level).upper(), logging.INFO))
    if log_file is None:
        return None
    os.makedirs(os.path.dirname(os.path.abspath(log_file)), exist_ok=True)
    for h in list(root.handlers):
        if isinstance(h, TimeAndSizeRotatingFileHandler) \
                and getattr(h, "baseFilename", None) == os.path.abspath(
                    log_file):
            root.removeHandler(h)
            h.close()
    handler = TimeAndSizeRotatingFileHandler(
        log_file, when=when, interval=interval,
        backup_count=backup_count, max_bytes=max_bytes, utc=True)
    formatter = logging.Formatter(DEFAULT_FORMAT)
    # UTC everywhere: %(asctime)s goes through the FORMATTER's converter
    # (a converter on the handler is read by nothing), and utc=True keeps
    # rollover filenames consistent — cross-node log correlation breaks
    # the moment hosts disagree on timezone
    formatter.converter = time.gmtime
    handler.setFormatter(formatter)
    root.addHandler(handler)
    return handler
