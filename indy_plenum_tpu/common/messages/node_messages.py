"""Every inter-node wire message as a typed, validated class.

Reference: plenum/common/messages/node_messages.py (PrePrepare, Prepare,
Commit, Checkpoint, ViewChange, NewView, InstanceChange, Propagate,
LedgerStatus, ConsistencyProof, CatchupReq, CatchupRep, MessageReq,
MessageRep, Ordered, Batch). Field names follow
:class:`indy_plenum_tpu.common.constants.f`.

BatchID convention (reference plenum/server/consensus/batch_id.py): a 4-list
``[view_no, pp_view_no, pp_seq_no, pp_digest]`` — ``pp_view_no`` is the view
the batch's PRE-PREPARE was originally created in (survives re-ordering
across view changes), ``view_no`` the view it is being ordered in.
"""
from __future__ import annotations

from .fields import (
    AnyField,
    Base58Field,
    BooleanField,
    EnumField,
    FixedLengthTupleField,
    IntegerField,
    IterableField,
    LedgerIdField,
    LimitedLengthStringField,
    MapField,
    MerkleRootField,
    NonEmptyStringField,
    NonNegativeNumberField,
    ProtocolVersionField,
    SerializedValueField,
    SignatureField,
    TimestampField,
)
from .message_base import MessageBase, node_message_registry

_DIGEST = LimitedLengthStringField(max_length=512)
_SENDER = LimitedLengthStringField(max_length=256)

BATCH_ID_FIELD = FixedLengthTupleField((
    NonNegativeNumberField(),  # view_no
    NonNegativeNumberField(),  # pp_view_no
    NonNegativeNumberField(),  # pp_seq_no
    LimitedLengthStringField(max_length=512),  # pp_digest
))

CHECKPOINT_VALUE_FIELD = FixedLengthTupleField((
    NonNegativeNumberField(),  # view_no
    NonNegativeNumberField(),  # pp_seq_no
    LimitedLengthStringField(max_length=512),  # digest
))


def register(cls):
    return node_message_registry.register(cls)


@register
class Propagate(MessageBase):
    typename = "PROPAGATE"
    schema = (
        ("request", AnyField()),  # full client request dict
        ("senderClient", LimitedLengthStringField(max_length=256,
                                                  nullable=True)),
    )


@register
class PrePrepare(MessageBase):
    typename = "PREPREPARE"
    schema = (
        ("instId", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField()),
        ("ppSeqNo", NonNegativeNumberField()),
        ("ppTime", TimestampField()),
        ("reqIdr", IterableField(_DIGEST)),  # ordered request digests
        ("discarded", NonNegativeNumberField()),
        ("digest", _DIGEST),
        ("ledgerId", LedgerIdField()),
        ("stateRootHash", MerkleRootField(nullable=True)),
        ("txnRootHash", MerkleRootField(nullable=True)),
        ("sub_seq_no", NonNegativeNumberField()),
        ("final", BooleanField()),
        ("poolStateRootHash", MerkleRootField(nullable=True, optional=True)),
        ("auditTxnRootHash", MerkleRootField(nullable=True, optional=True)),
        ("blsMultiSig", AnyField(optional=True, nullable=True)),
        ("originalViewNo", NonNegativeNumberField(optional=True,
                                                  nullable=True)),
    )


@register
class Prepare(MessageBase):
    typename = "PREPARE"
    schema = (
        ("instId", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField()),
        ("ppSeqNo", NonNegativeNumberField()),
        ("ppTime", TimestampField()),
        ("digest", _DIGEST),
        ("stateRootHash", MerkleRootField(nullable=True)),
        ("txnRootHash", MerkleRootField(nullable=True)),
        ("auditTxnRootHash", MerkleRootField(nullable=True, optional=True)),
    )


@register
class Commit(MessageBase):
    typename = "COMMIT"
    schema = (
        ("instId", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField()),
        ("ppSeqNo", NonNegativeNumberField()),
        ("blsSig", LimitedLengthStringField(max_length=512, optional=True,
                                            nullable=True)),
        ("blsSigs", MapField(NonEmptyStringField(),
                             LimitedLengthStringField(max_length=512),
                             optional=True, nullable=True)),
    )


@register
class Checkpoint(MessageBase):
    typename = "CHECKPOINT"
    schema = (
        ("instId", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField()),
        ("seqNoStart", NonNegativeNumberField()),
        ("seqNoEnd", NonNegativeNumberField()),
        ("digest", _DIGEST),
    )


@register
class InstanceChange(MessageBase):
    typename = "INSTANCE_CHANGE"
    schema = (
        ("viewNo", NonNegativeNumberField()),
        ("reason", IntegerField()),  # suspicion code
    )


@register
class ViewChange(MessageBase):
    typename = "VIEW_CHANGE"
    schema = (
        ("viewNo", NonNegativeNumberField()),
        ("stableCheckpoint", NonNegativeNumberField()),
        ("prepared", IterableField(BATCH_ID_FIELD)),
        ("preprepared", IterableField(BATCH_ID_FIELD)),
        ("checkpoints", IterableField(CHECKPOINT_VALUE_FIELD)),
    )


@register
class ViewChangeAck(MessageBase):
    typename = "VIEW_CHANGE_ACK"
    schema = (
        ("viewNo", NonNegativeNumberField()),
        ("name", _SENDER),  # whose VIEW_CHANGE is being acked
        ("digest", _DIGEST),
    )


@register
class NewView(MessageBase):
    typename = "NEW_VIEW"
    schema = (
        ("viewNo", NonNegativeNumberField()),
        # [(sender, view_change_digest)] the primary built the view from
        ("viewChanges", IterableField(FixedLengthTupleField(
            (_SENDER, _DIGEST)))),
        ("checkpoint", CHECKPOINT_VALUE_FIELD),
        ("batches", IterableField(BATCH_ID_FIELD)),
        ("primary", _SENDER),
    )


@register
class Ordered(MessageBase):
    typename = "ORDERED"
    schema = (
        ("instId", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField()),
        ("ppSeqNo", NonNegativeNumberField()),
        ("ppTime", TimestampField()),
        ("reqIdr", IterableField(_DIGEST)),
        ("discarded", NonNegativeNumberField()),
        ("ledgerId", LedgerIdField()),
        ("stateRootHash", MerkleRootField(nullable=True)),
        ("txnRootHash", MerkleRootField(nullable=True)),
        ("auditTxnRootHash", MerkleRootField(nullable=True, optional=True)),
        ("primaries", IterableField(_SENDER, optional=True, nullable=True)),
        ("originalViewNo", NonNegativeNumberField(optional=True,
                                                  nullable=True)),
        ("digest", _DIGEST.__class__(max_length=512, optional=True,
                                     nullable=True)),
    )


@register
class LedgerStatus(MessageBase):
    typename = "LEDGER_STATUS"
    schema = (
        ("ledgerId", LedgerIdField()),
        ("txnSeqNo", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField(nullable=True)),
        ("ppSeqNo", NonNegativeNumberField(nullable=True)),
        ("merkleRoot", MerkleRootField()),
        ("protocolVersion", ProtocolVersionField()),
        # True marks a fork-point PROBE: "what is your root at this
        # size?" — a question, not an assertion about the sender's own
        # ledger. Receivers must answer probes (SeederService) but never
        # count them as status evidence (divergence/tip votes), or a
        # diverged prober's corrupt prefix root would masquerade as a
        # genuine accusation against healthy nodes.
        ("probe", BooleanField(optional=True)),
    )


@register
class ConsistencyProof(MessageBase):
    typename = "CONSISTENCY_PROOF"
    schema = (
        ("ledgerId", LedgerIdField()),
        ("seqNoStart", NonNegativeNumberField()),
        ("seqNoEnd", NonNegativeNumberField()),
        ("viewNo", NonNegativeNumberField(nullable=True)),
        ("ppSeqNo", NonNegativeNumberField(nullable=True)),
        ("oldMerkleRoot", MerkleRootField()),
        ("newMerkleRoot", MerkleRootField()),
        ("hashes", IterableField(NonEmptyStringField())),
    )


@register
class CatchupReq(MessageBase):
    typename = "CATCHUP_REQ"
    schema = (
        ("ledgerId", LedgerIdField()),
        ("seqNoStart", NonNegativeNumberField()),
        ("seqNoEnd", NonNegativeNumberField()),
        ("catchupTill", NonNegativeNumberField()),
    )


@register
class CatchupRep(MessageBase):
    """Txn range + per-txn audit paths.

    TPU-first redesign: the reference's CatchupRep carries one consistency
    proof per rep, verified by an incremental host-side tree fold; here
    EVERY txn carries its own audit path against the quorum-agreed target
    root, so one vmapped device kernel call
    (:func:`indy_plenum_tpu.tpu.sha256.verify_audit_paths`) verifies the
    whole slice — BASELINE config 5's batched proof verification.
    """

    typename = "CATCHUP_REP"
    schema = (
        ("ledgerId", LedgerIdField()),
        # seqNo(str, msgpack keys) -> txn
        ("txns", MapField(NonEmptyStringField(), AnyField())),
        # seqNo(str) -> [b58 sibling hashes], leaf->root at size catchupTill
        ("auditPaths", MapField(NonEmptyStringField(),
                                IterableField(NonEmptyStringField()))),
        ("catchupTill", NonNegativeNumberField()),
    )


@register
class ObservedData(MessageBase):
    """One committed batch pushed to a non-validator observer.

    Reference: plenum/server/observer/ (``ObservedData`` + the
    each-batch sync policy). Proof-carrying redesign: the attached pool
    BLS multi-signature co-signs BOTH the state root and the txn root of
    the batch, so an observer holding the pool's BLS keys can trust ONE
    validator's push — it re-applies the txns and checks its own
    recomputed roots against the co-signed ones. Without BLS an observer
    falls back to f+1 identical pushes from distinct validators.
    """

    typename = "OBSERVED_DATA"
    schema = (
        ("ledgerId", LedgerIdField()),
        ("ppSeqNo", NonNegativeNumberField()),
        ("ppTime", TimestampField()),
        ("txns", IterableField(AnyField())),
        ("stateRootHash", MerkleRootField(nullable=True)),
        ("txnRootHash", MerkleRootField(nullable=True)),
        ("multiSignature", AnyField(optional=True, nullable=True)),
    )


@register
class Reply(MessageBase):
    """Node -> client: the committed txn for an executed request
    (reference: plenum/common/messages/node_messages.py Reply)."""

    typename = "REPLY"
    schema = (
        ("result", AnyField()),  # the committed txn incl. seqNo + roots
    )


@register
class RequestAck(MessageBase):
    """Node -> client: request accepted into propagation."""

    typename = "REQACK"
    schema = (
        ("identifier", LimitedLengthStringField(max_length=256,
                                                nullable=True)),
        ("reqId", NonNegativeNumberField()),
    )


@register
class RequestNack(MessageBase):
    """Node -> client: request rejected at ingress (bad signature, replay)."""

    typename = "REQNACK"
    schema = (
        ("identifier", LimitedLengthStringField(max_length=256,
                                                nullable=True)),
        ("reqId", NonNegativeNumberField()),
        ("reason", LimitedLengthStringField(max_length=512)),
    )


@register
class MessageReq(MessageBase):
    typename = "MESSAGE_REQUEST"
    schema = (
        ("msg_type", NonEmptyStringField()),
        ("params", MapField(NonEmptyStringField(), AnyField())),
    )


@register
class MessageRep(MessageBase):
    typename = "MESSAGE_RESPONSE"
    schema = (
        ("msg_type", NonEmptyStringField()),
        ("params", MapField(NonEmptyStringField(), AnyField())),
        ("msg", AnyField(nullable=True)),
    )


@register
class Batch(MessageBase):
    """Transport-level envelope coalescing several messages to one remote.

    Reference: plenum/common/batched.py -- outgoing messages per event-loop
    flush are packed into one signed Batch.
    """

    typename = "BATCH"
    schema = (
        ("messages", IterableField(SerializedValueField())),
        ("signature", SignatureField(nullable=True)),
    )


@register
class BlsMultiSigMsg(MessageBase):
    """Carrier for a BLS multi-signature value (attached to PRE-PREPAREs)."""

    typename = "BLS_MULTI_SIG"
    schema = (
        ("signature", NonEmptyStringField()),
        ("participants", IterableField(_SENDER)),
        ("value", AnyField()),  # MultiSignatureValue dict
    )


# --- BatchID helpers -------------------------------------------------------

def batch_id(view_no: int, pp_view_no: int, pp_seq_no: int,
             pp_digest: str) -> list:
    return [view_no, pp_view_no, pp_seq_no, pp_digest]


def bid_view(b) -> int:
    return b[0]


def bid_pp_view(b) -> int:
    return b[1]


def bid_seq(b) -> int:
    return b[2]


def bid_digest(b) -> str:
    return b[3]
