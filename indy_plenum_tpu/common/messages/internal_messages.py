"""Internal-bus events between consensus services (never hit the wire).

Reference: plenum/common/messages/internal_messages.py. Plain NamedTuples:
no validation needed (trusted, in-process).
"""
from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Tuple


class NeedViewChange(NamedTuple):
    view_no: Optional[int] = None  # None = next view


class ViewChangeStarted(NamedTuple):
    view_no: int


class NewViewAccepted(NamedTuple):
    view_no: int
    checkpoint: Tuple[int, int, str]  # (view_no, pp_seq_no, digest)
    batches: List[list]  # BatchIDs to re-order
    primary: str


class NewViewCheckpointsApplied(NamedTuple):
    view_no: int
    checkpoint: Tuple[int, int, str]
    batches: List[list]


class ViewChangeFinished(NamedTuple):
    view_no: int


class CheckpointStabilized(NamedTuple):
    inst_id: int
    last_stable_3pc: Tuple[int, int]  # (view_no, pp_seq_no)


class NeedBackupCatchup(NamedTuple):
    inst_id: int
    caught_up_till_3pc: Tuple[int, int]


class NodeNeedViewChange(NamedTuple):
    view_no: int


class PrimaryDisconnected(NamedTuple):
    inst_id: int


class PrimarySelected(NamedTuple):
    pass


class VoteForViewChange(NamedTuple):
    suspicion: Any  # Suspicion
    view_no: Optional[int] = None


class NewViewTimeoutExpired(NamedTuple):
    view_no: int


class ReOrderedInNewView(NamedTuple):
    pass


class CatchupFinished(NamedTuple):
    last_caught_up_3pc: Tuple[int, int]
    master_last_ordered: Tuple[int, int]


class NeedMasterCatchup(NamedTuple):
    pass


class RequestPropagates(NamedTuple):
    """Ask the node to re-broadcast PROPAGATEs for missing requests."""

    bad_requests: List[str]  # digests


class PreSigVerification(NamedTuple):
    """A batch of inbound signed messages queued for device verification."""

    msgs: List[Any]


class MissingMessage(NamedTuple):
    msg_type: str
    key: Any
    inst_id: int
    dst: Optional[List[str]]
    stash_data: Optional[Any] = None


class RaisedSuspicion(NamedTuple):
    inst_id: int
    ex: Any  # SuspiciousNode


class Ordered3PC(NamedTuple):
    """Internal companion to the wire-level Ordered (master instance only)."""

    inst_id: int
    view_no: int
    pp_seq_no: int
