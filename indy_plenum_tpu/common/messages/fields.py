"""Per-field validation DSL for wire messages.

Reference: plenum/common/messages/fields.py (NonNegativeNumberField,
LimitedLengthStringField, MerkleRootField, Base58Field, SignatureField,
TimestampField, IterableField, MapField, ProtocolVersionField, ...).

A field validator is a small object with ``validate(value) -> Optional[str]``
returning an error string or None. Composable; messages declare an ordered
schema of (name, validator) pairs.
"""
from __future__ import annotations

from typing import Any, Iterable, Optional, Sequence

from ...utils.base58 import b58decode


class FieldBase:
    _base_types: Sequence[type] = ()

    def __init__(self, optional: bool = False, nullable: bool = False):
        self.optional = optional
        self.nullable = nullable

    def validate(self, val: Any) -> Optional[str]:
        if val is None:
            return None if self.nullable else "missing value"
        if self._base_types and not isinstance(val, tuple(self._base_types)):
            want = "/".join(t.__name__ for t in self._base_types)
            return f"expected types {want}, got {type(val).__name__}"
        return self._specific(val)

    def _specific(self, val: Any) -> Optional[str]:
        return None

    def __repr__(self):
        return type(self).__name__


class AnyField(FieldBase):
    pass


class BooleanField(FieldBase):
    _base_types = (bool,)


class NonNegativeNumberField(FieldBase):
    _base_types = (int,)

    def _specific(self, val):
        if isinstance(val, bool):
            return "expected int, got bool"
        return "negative value" if val < 0 else None


class IntegerField(FieldBase):
    _base_types = (int,)


class NonEmptyStringField(FieldBase):
    _base_types = (str,)

    def _specific(self, val):
        return "empty string" if not val else None


class LimitedLengthStringField(FieldBase):
    _base_types = (str,)

    def __init__(self, max_length: int, **kw):
        super().__init__(**kw)
        self.max_length = max_length

    def _specific(self, val):
        if not val:
            return "empty string"
        if len(val) > self.max_length:
            return f"length {len(val)} > limit {self.max_length}"
        return None


class Base58Field(FieldBase):
    _base_types = (str,)

    def __init__(self, byte_lengths: Optional[Iterable[int]] = None, **kw):
        super().__init__(**kw)
        self.byte_lengths = set(byte_lengths or ())

    def _specific(self, val):
        try:
            raw = b58decode(val)
        except ValueError as exc:
            return str(exc)
        if self.byte_lengths and len(raw) not in self.byte_lengths:
            return f"b58-decoded length {len(raw)} not in {sorted(self.byte_lengths)}"
        return None


class MerkleRootField(Base58Field):
    def __init__(self, **kw):
        super().__init__(byte_lengths=(32,), **kw)


class IdentifierField(Base58Field):
    """DID (16 bytes) or full verkey (32 bytes), base58."""

    def __init__(self, **kw):
        super().__init__(byte_lengths=(16, 32), **kw)


class DestNodeField(Base58Field):
    def __init__(self, **kw):
        super().__init__(byte_lengths=(16, 32), **kw)


class VerkeyField(FieldBase):
    _base_types = (str,)

    def _specific(self, val):
        body, abbreviated = (val[1:], True) if val.startswith("~") else (val, False)
        try:
            raw = b58decode(body)
        except ValueError as exc:
            return str(exc)
        want = 16 if abbreviated else 32
        if len(raw) != want:
            return f"verkey length {len(raw)} != {want}"
        return None


class SignatureField(LimitedLengthStringField):
    def __init__(self, **kw):
        kw.setdefault("max_length", 512)
        super().__init__(**kw)


class TimestampField(FieldBase):
    _base_types = (int, float)
    _oldest = 1499906902  # sanity floor as in the reference

    def _specific(self, val):
        if val < self._oldest:
            return f"timestamp {val} implausibly old"
        return None


class LedgerIdField(FieldBase):
    _base_types = (int,)

    def _specific(self, val):
        from ..constants import VALID_LEDGER_IDS

        return None if val in VALID_LEDGER_IDS else f"unknown ledger id {val}"


class ProtocolVersionField(FieldBase):
    _base_types = (int,)

    def __init__(self, **kw):
        kw.setdefault("nullable", True)
        kw.setdefault("optional", True)
        super().__init__(**kw)

    def _specific(self, val):
        from ..constants import CURRENT_PROTOCOL_VERSION

        if val not in (1, 2, CURRENT_PROTOCOL_VERSION):
            return f"unsupported protocol version {val}"
        return None


class RequestIdField(NonNegativeNumberField):
    pass


class IterableField(FieldBase):
    _base_types = (list, tuple)

    def __init__(self, inner: FieldBase, min_length: Optional[int] = None,
                 max_length: Optional[int] = None, **kw):
        super().__init__(**kw)
        self.inner = inner
        self.min_length = min_length
        self.max_length = max_length

    def _specific(self, val):
        if self.min_length is not None and len(val) < self.min_length:
            return f"length {len(val)} < {self.min_length}"
        if self.max_length is not None and len(val) > self.max_length:
            return f"length {len(val)} > {self.max_length}"
        for i, item in enumerate(val):
            err = self.inner.validate(item)
            if err:
                return f"[{i}]: {err}"
        return None


class MapField(FieldBase):
    _base_types = (dict,)

    def __init__(self, key: FieldBase, value: FieldBase, **kw):
        super().__init__(**kw)
        self.key = key
        self.value = value

    def _specific(self, val):
        for k, v in val.items():
            err = self.key.validate(k)
            if err:
                return f"key {k!r}: {err}"
            err = self.value.validate(v)
            if err:
                return f"value of {k!r}: {err}"
        return None


class FixedLengthTupleField(FieldBase):
    """Positionally-typed tuple, e.g. a BatchID (view, pp_view, seq, digest)."""

    _base_types = (list, tuple)

    def __init__(self, inners: Sequence[FieldBase], **kw):
        super().__init__(**kw)
        self.inners = tuple(inners)

    def _specific(self, val):
        if len(val) != len(self.inners):
            return f"length {len(val)} != {len(self.inners)}"
        for i, (item, inner) in enumerate(zip(val, self.inners)):
            err = inner.validate(item)
            if err:
                return f"[{i}]: {err}"
        return None


class EnumField(FieldBase):
    def __init__(self, allowed: Iterable[Any], **kw):
        super().__init__(**kw)
        self.allowed = set(allowed)

    def _specific(self, val):
        return None if val in self.allowed else f"{val!r} not in {self.allowed}"


class SerializedValueField(FieldBase):
    _base_types = (bytes, str)

    def _specific(self, val):
        return "empty" if not val else None


class HexField(FieldBase):
    _base_types = (str,)

    def __init__(self, length: Optional[int] = None, **kw):
        super().__init__(**kw)
        self.length = length

    def _specific(self, val):
        try:
            bytes.fromhex(val)
        except ValueError:
            return "not hex"
        if self.length is not None and len(val) != self.length:
            return f"hex length {len(val)} != {self.length}"
        return None
