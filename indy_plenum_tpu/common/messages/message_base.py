"""Typed wire messages: schema-validated, immutable, registry-dispatched.

Reference: plenum/common/messages/message_base.py (`MessageBase`) and the
type registry in plenum/common/messages/node_message_factory.py. Messages
are lightweight frozen objects; each class declares

    typename : str            -- wire tag ("op" field)
    schema   : ((name, FieldBase), ...)

Construction validates every field; ``as_dict``/``from_dict`` round-trip via
the wire serializers.
"""
from __future__ import annotations

from typing import Any, ClassVar, Dict, Tuple, Type

from ..exceptions import InvalidMessageError
from .fields import FieldBase

OP_FIELD_NAME = "op"


class MessageBase:
    typename: ClassVar[str] = ""
    schema: ClassVar[Tuple[Tuple[str, FieldBase], ...]] = ()
    __slots__ = ("_values",)

    def __init__(self, *args, **kwargs):
        names = [name for name, _ in self.schema]
        if len(args) > len(names):
            raise InvalidMessageError(
                f"{self.typename}: too many positional args")
        values: Dict[str, Any] = dict(zip(names, args))
        overlap = set(values) & set(kwargs)
        if overlap:
            raise InvalidMessageError(
                f"{self.typename}: duplicate args {sorted(overlap)}")
        values.update(kwargs)
        unknown = set(values) - set(names)
        if unknown:
            raise InvalidMessageError(
                f"{self.typename}: unknown fields {sorted(unknown)}")
        for name, validator in self.schema:
            val = values.setdefault(name, None)
            if val is None and validator.optional:
                continue
            err = validator.validate(val)
            if err:
                raise InvalidMessageError(f"{self.typename}.{name}: {err}")
        # fields live in the instance __dict__ (subclasses declare no
        # __slots__, so one exists): attribute reads become native lookups
        # instead of __getattr__ -> dict fetch — 3PC handlers read several
        # fields per message and this is measurably the hottest attribute
        # path in a dense pool. _values ALIASES that dict (not a copy:
        # thousands of stashed messages must not pay double storage).
        # __setattr__ still blocks mutation.
        self.__dict__.update(values)
        object.__setattr__(self, "_values", self.__dict__)

    def __setattr__(self, key, value):
        raise AttributeError(f"{type(self).__name__} is immutable")

    def __getattr__(self, item):
        try:
            return object.__getattribute__(self, "_values")[item]
        except KeyError:
            raise AttributeError(item) from None

    @property
    def _fields(self) -> Dict[str, Any]:
        # SCHEMA fields only: _values aliases the instance __dict__, so a
        # stray attribute smuggled in via object.__setattr__ must never
        # leak into wire serialization, equality, or hashing (a tagged
        # message would stop round-tripping: "unknown fields")
        values = object.__getattribute__(self, "_values")
        return {name: values[name] for name, _v in self.schema}

    def as_dict(self) -> Dict[str, Any]:
        out = {OP_FIELD_NAME: self.typename}
        out.update(self._fields)
        return out

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "MessageBase":
        data = dict(data)
        data.pop(OP_FIELD_NAME, None)
        return cls(**data)

    def __eq__(self, other):
        return (type(self) is type(other)
                and self._fields == other._fields)

    def __hash__(self):
        return hash((self.typename,
                     tuple(sorted(
                         (k, _hashable(v)) for k, v in self._fields.items()))))

    def __repr__(self):
        inner = ", ".join(f"{k}={v!r}" for k, v in self._fields.items())
        return f"{type(self).__name__}({inner})"


def _hashable(v):
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    return v


class MessageRegistry:
    """typename -> class; the wire deserializer's dispatch table."""

    def __init__(self):
        self._by_name: Dict[str, Type[MessageBase]] = {}

    def register(self, cls: Type[MessageBase]) -> Type[MessageBase]:
        if not cls.typename:
            raise ValueError(f"{cls.__name__} has no typename")
        if cls.typename in self._by_name:
            raise ValueError(f"duplicate message type {cls.typename}")
        self._by_name[cls.typename] = cls
        return cls

    def get(self, typename: str) -> Type[MessageBase] | None:
        return self._by_name.get(typename)

    def obj_from_dict(self, data: Dict[str, Any]) -> MessageBase:
        op = data.get(OP_FIELD_NAME)
        cls = self._by_name.get(op)
        if cls is None:
            raise InvalidMessageError(f"unknown message type {op!r}")
        return cls.from_dict(data)


node_message_registry = MessageRegistry()
