"""Exception taxonomy.

Reference: plenum/common/exceptions.py. Only the classes other components
actually raise/catch are kept; suspicion-carrying errors reference
:mod:`indy_plenum_tpu.server.suspicion_codes`.
"""
from __future__ import annotations


class PlenumError(Exception):
    """Base for all framework errors."""


class InvalidMessageError(PlenumError):
    """Schema/field validation failed on an inbound message."""


class InvalidClientRequest(PlenumError):
    def __init__(self, identifier=None, req_id=None, reason=""):
        self.identifier = identifier
        self.req_id = req_id
        self.reason = reason
        super().__init__(f"InvalidClientRequest({identifier}, {req_id}): {reason}")


class InvalidClientMessageException(InvalidClientRequest):
    pass


class UnauthorizedClientRequest(InvalidClientRequest):
    """Request failed dynamic authorization (role/ownership rules)."""


class CouldNotAuthenticate(PlenumError):
    def __init__(self, identifier=None):
        self.identifier = identifier
        super().__init__(f"could not authenticate {identifier}")


class InsufficientSignatures(CouldNotAuthenticate):
    def __init__(self, provided: int, required: int):
        self.provided = provided
        self.required = required
        PlenumError.__init__(
            self, f"insufficient signatures: {provided} of {required}"
        )


class MissingSignature(CouldNotAuthenticate):
    pass


class InvalidSignature(CouldNotAuthenticate):
    def __init__(self, identifier=None):
        self.identifier = identifier
        PlenumError.__init__(self, f"invalid signature by {identifier}")


class SuspiciousNode(PlenumError):
    """Byzantine evidence attributed to a peer (see suspicion_codes)."""

    def __init__(self, node: str, suspicion, offending_msg=None):
        self.node = node
        self.suspicion = suspicion
        self.offending_msg = offending_msg
        code = getattr(suspicion, "code", suspicion)
        reason = getattr(suspicion, "reason", "")
        super().__init__(f"suspicious node {node} ({code}): {reason}")


class SuspiciousClient(PlenumError):
    pass


class BlowUp(PlenumError):
    """Unrecoverable internal invariant violation — crash the node."""


class MismatchedMessageReplyException(PlenumError):
    """MESSAGE_RESPONSE did not match what was requested."""


class LedgerChronologicalOrderingError(PlenumError):
    pass


class StorageError(PlenumError):
    pass


class KeysNotFoundException(PlenumError):
    MSG = "Keys not found in the given directory; run key init first."
