"""Request <-> transaction conversion and txn envelope accessors.

Reference: plenum/common/txn_util.py (`reqToTxn`, `append_txn_metadata`,
`get_payload_data`, ...). Envelope layout (see constants):

    {ver, txn: {type, data, metadata: {from, reqId, digest}},
     txnMetadata: {seqNo, txnTime}, reqSignature}
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from .constants import (
    CURRENT_TXN_VERSION,
    TXN_METADATA,
    TXN_METADATA_SEQ_NO,
    TXN_METADATA_TIME,
    TXN_PAYLOAD,
    TXN_PAYLOAD_DATA,
    TXN_PAYLOAD_METADATA,
    TXN_PAYLOAD_METADATA_DIGEST,
    TXN_PAYLOAD_METADATA_FROM,
    TXN_PAYLOAD_METADATA_REQ_ID,
    TXN_SIGNATURE,
    TXN_TYPE,
    TXN_VERSION,
)
from .request import Request


def reqToTxn(req: Request) -> Dict[str, Any]:
    """Strip txn-type out of the operation into the envelope; keep the rest
    as payload data; record signer(s) and digest."""
    op = dict(req.operation)
    typ = op.pop(TXN_TYPE, None)
    sig = None
    if req.signature is not None:
        sig = {"type": "ED25519", "values": [
            {"from": req.identifier, "value": req.signature}]}
    elif req.signatures:
        sig = {"type": "ED25519", "values": [
            {"from": idr, "value": s} for idr, s in sorted(req.signatures.items())]}
    return {
        TXN_VERSION: CURRENT_TXN_VERSION,
        TXN_PAYLOAD: {
            TXN_TYPE: typ,
            TXN_PAYLOAD_DATA: op,
            TXN_PAYLOAD_METADATA: {
                TXN_PAYLOAD_METADATA_FROM: req.identifier,
                TXN_PAYLOAD_METADATA_REQ_ID: req.reqId,
                TXN_PAYLOAD_METADATA_DIGEST: req.digest,
            },
        },
        TXN_METADATA: {},
        TXN_SIGNATURE: sig or {},
    }


def append_txn_metadata(txn: Dict[str, Any], seq_no: Optional[int] = None,
                        txn_time: Optional[int] = None) -> Dict[str, Any]:
    md = txn.setdefault(TXN_METADATA, {})
    if seq_no is not None:
        md[TXN_METADATA_SEQ_NO] = seq_no
    if txn_time is not None:
        md[TXN_METADATA_TIME] = txn_time
    return txn


def get_type(txn: Dict[str, Any]) -> Optional[str]:
    return txn.get(TXN_PAYLOAD, {}).get(TXN_TYPE)


def get_payload_data(txn: Dict[str, Any]) -> Dict[str, Any]:
    return txn.get(TXN_PAYLOAD, {}).get(TXN_PAYLOAD_DATA, {})


def get_from(txn: Dict[str, Any]) -> Optional[str]:
    return (txn.get(TXN_PAYLOAD, {}).get(TXN_PAYLOAD_METADATA, {})
            .get(TXN_PAYLOAD_METADATA_FROM))


def get_req_id(txn: Dict[str, Any]) -> Optional[int]:
    return (txn.get(TXN_PAYLOAD, {}).get(TXN_PAYLOAD_METADATA, {})
            .get(TXN_PAYLOAD_METADATA_REQ_ID))


def get_digest(txn: Dict[str, Any]) -> Optional[str]:
    return (txn.get(TXN_PAYLOAD, {}).get(TXN_PAYLOAD_METADATA, {})
            .get(TXN_PAYLOAD_METADATA_DIGEST))


def get_seq_no(txn: Dict[str, Any]) -> Optional[int]:
    return txn.get(TXN_METADATA, {}).get(TXN_METADATA_SEQ_NO)


def get_txn_time(txn: Dict[str, Any]) -> Optional[int]:
    return txn.get(TXN_METADATA, {}).get(TXN_METADATA_TIME)


def get_version(txn: Dict[str, Any]) -> Optional[str]:
    return txn.get(TXN_VERSION)
