"""In-process pub/sub buses.

Reference: plenum/common/event_bus.py (`InternalBus`, `ExternalBus`).
`InternalBus` carries typed events between consensus services inside one
node; `ExternalBus` abstracts "send a message to the network" so services
never touch sockets — in production it is wired to the ZMQ node stack, in
simulation to the in-memory network (`indy_plenum_tpu.simulation`).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Any, Callable, NamedTuple


class _DispatchCache:
    """MRO-walk + duplicate-handler dedupe, memoized per concrete type.

    Both buses deliver to every handler subscribed at any level of the
    message's MRO, each at most once per send. Doing that walk (and the
    O(handlers^2) bound-method equality dedupe) on EVERY delivery is the
    hottest line of a dense pool simulation; the subscription set changes
    rarely, so the flattened handler tuple is computed once per concrete
    type and invalidated on subscribe/unsubscribe.
    """

    def __init__(self):
        self._handlers: dict[type, list[Callable]] = defaultdict(list)
        self._cache: dict[type, tuple] = {}

    def subscribe(self, message_type: type, handler: Callable) -> None:
        self._handlers[message_type].append(handler)
        self._cache.clear()

    def unsubscribe(self, message_type: type, handler: Callable) -> None:
        if handler in self._handlers.get(message_type, []):
            self._handlers[message_type].remove(handler)
            self._cache.clear()

    def handlers_for(self, cls: type) -> tuple:
        cached = self._cache.get(cls)
        if cached is None:
            seen = []
            for base in cls.__mro__:
                for handler in self._handlers.get(base, ()):
                    if handler not in seen:  # == dedupes bound methods too
                        seen.append(handler)
            cached = self._cache[cls] = tuple(seen)
        return cached


class InternalBus(_DispatchCache):
    """Synchronous typed pub/sub: subscribers keyed by message class."""

    def send(self, message: Any, *args) -> None:
        for handler in self.handlers_for(type(message)):
            handler(message, *args)


class ExternalBus(_DispatchCache):
    """Network abstraction handed to consensus services.

    ``send_handler(msg, dst)`` with dst=None means broadcast to all
    connected peers. Inbound messages are delivered via ``process_incoming``.
    Connection state is tracked so services (e.g. the primary-connection
    monitor) can ask who is reachable.
    """

    class Connected(NamedTuple):
        name: str

    class Disconnected(NamedTuple):
        name: str

    def __init__(self, send_handler: Callable[[Any, str | None], None]):
        super().__init__()
        self._send_handler = send_handler
        self._connecteds: set[str] = set()

    @property
    def connecteds(self) -> set[str]:
        return set(self._connecteds)

    def is_connected(self, name: str) -> bool:
        """O(1) membership, no defensive copy (the per-delivery check)."""
        return name in self._connecteds

    def send(self, message: Any, dst: str | list[str] | None = None) -> None:
        self._send_handler(message, dst)

    def process_incoming(self, message: Any, frm: str) -> None:
        for handler in self.handlers_for(type(message)):
            handler(message, frm)

    def update_connecteds(self, connecteds: set[str]) -> None:
        added = connecteds - self._connecteds
        removed = self._connecteds - connecteds
        self._connecteds = set(connecteds)
        for name in sorted(added):
            self.process_incoming(self.Connected(name), name)
        for name in sorted(removed):
            self.process_incoming(self.Disconnected(name), name)
