"""In-process pub/sub buses.

Reference: plenum/common/event_bus.py (`InternalBus`, `ExternalBus`).
`InternalBus` carries typed events between consensus services inside one
node; `ExternalBus` abstracts "send a message to the network" so services
never touch sockets — in production it is wired to the ZMQ node stack, in
simulation to the in-memory network (`indy_plenum_tpu.simulation`).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Any, Callable, NamedTuple


class InternalBus:
    """Synchronous typed pub/sub: subscribers keyed by message class."""

    def __init__(self):
        self._handlers: dict[type, list[Callable]] = defaultdict(list)

    def subscribe(self, message_type: type, handler: Callable) -> None:
        self._handlers[message_type].append(handler)

    def unsubscribe(self, message_type: type, handler: Callable) -> None:
        if handler in self._handlers.get(message_type, []):
            self._handlers[message_type].remove(handler)

    def send(self, message: Any, *args) -> None:
        # Walk the MRO so handlers may subscribe to base classes; a handler
        # subscribed at several levels still fires at most once per send.
        seen = []
        for cls in type(message).__mro__:
            for handler in tuple(self._handlers.get(cls, ())):
                if handler not in seen:  # == dedupes equal bound methods too
                    seen.append(handler)
                    handler(message, *args)


class ExternalBus:
    """Network abstraction handed to consensus services.

    ``send_handler(msg, dst)`` with dst=None means broadcast to all
    connected peers. Inbound messages are delivered via ``process_incoming``.
    Connection state is tracked so services (e.g. the primary-connection
    monitor) can ask who is reachable.
    """

    class Connected(NamedTuple):
        name: str

    class Disconnected(NamedTuple):
        name: str

    def __init__(self, send_handler: Callable[[Any, str | None], None]):
        self._send_handler = send_handler
        self._handlers: dict[type, list[Callable]] = defaultdict(list)
        self._connecteds: set[str] = set()

    @property
    def connecteds(self) -> set[str]:
        return set(self._connecteds)

    def subscribe(self, message_type: type, handler: Callable) -> None:
        self._handlers[message_type].append(handler)

    def unsubscribe(self, message_type: type, handler: Callable) -> None:
        if handler in self._handlers.get(message_type, []):
            self._handlers[message_type].remove(handler)

    def send(self, message: Any, dst: str | list[str] | None = None) -> None:
        self._send_handler(message, dst)

    def process_incoming(self, message: Any, frm: str) -> None:
        seen = []
        for cls in type(message).__mro__:
            for handler in tuple(self._handlers.get(cls, ())):
                if handler not in seen:  # == dedupes equal bound methods too
                    seen.append(handler)
                    handler(message, frm)

    def update_connecteds(self, connecteds: set[str]) -> None:
        added = connecteds - self._connecteds
        removed = self._connecteds - connecteds
        self._connecteds = set(connecteds)
        for name in sorted(added):
            self.process_incoming(self.Connected(name), name)
        for name in sorted(removed):
            self.process_incoming(self.Disconnected(name), name)
