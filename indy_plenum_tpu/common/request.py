"""Client request object with canonical digest.

Reference: plenum/common/request.py (`Request`, `SafeRequest`). A request is
{identifier, reqId, operation, protocolVersion, signature | signatures}; its
``digest`` is sha256 over the canonical signing serialization of everything
except the signature(s) — all honest nodes derive the same digest, which is
the key for propagation quorums and 3PC request references.
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional

from .constants import f, CURRENT_PROTOCOL_VERSION
from .exceptions import InvalidClientRequest
from .messages.fields import (
    AnyField,
    IdentifierField,
    MapField,
    NonEmptyStringField,
    NonNegativeNumberField,
    ProtocolVersionField,
    SignatureField,
)
from .serializers.serialization import serialize_for_signing


class Request:
    def __init__(self,
                 identifier: Optional[str] = None,
                 reqId: Optional[int] = None,
                 operation: Optional[Dict[str, Any]] = None,
                 signature: Optional[str] = None,
                 signatures: Optional[Dict[str, str]] = None,
                 protocolVersion: Optional[int] = CURRENT_PROTOCOL_VERSION):
        self.identifier = identifier
        self.reqId = reqId
        self.operation = operation or {}
        self.signature = signature
        self.signatures = signatures
        self.protocolVersion = protocolVersion
        # digests are content hashes computed ONCE on first access (they
        # key every propagation/3PC map, and the consensus hot path reads
        # them constantly): mutate the payload only before the first read
        self._digest: Optional[str] = None
        self._payload_digest: Optional[str] = None

    @property
    def key(self) -> str:
        return self.digest

    @property
    def digest(self) -> str:
        if self._digest is None:
            self._digest = hashlib.sha256(
                serialize_for_signing(self.signing_payload())).hexdigest()
        return self._digest

    @property
    def payload_digest(self) -> str:
        """Digest without identifier -- used for replay detection across
        differently-signed duplicates (reference: Request.payload_digest)."""
        if self._payload_digest is None:
            payload = self.signing_payload()
            payload.pop(f.IDENTIFIER, None)
            self._payload_digest = hashlib.sha256(
                serialize_for_signing(payload)).hexdigest()
        return self._payload_digest

    def signing_payload(self) -> Dict[str, Any]:
        return {
            f.IDENTIFIER: self.identifier,
            f.REQ_ID: self.reqId,
            f.OPERATION: self.operation,
            f.PROTOCOL_VERSION: self.protocolVersion,
        }

    def signing_bytes(self) -> bytes:
        return serialize_for_signing(self.signing_payload())

    @property
    def txn_type(self) -> Optional[str]:
        from .constants import TXN_TYPE

        return self.operation.get(TXN_TYPE)

    def all_identifiers(self) -> List[str]:
        """Signer identifiers: single signature or multi-sig endorsements."""
        out = []
        if self.signatures:
            out.extend(self.signatures.keys())
        if self.identifier and self.identifier not in out:
            out.append(self.identifier)
        return out

    def as_dict(self) -> Dict[str, Any]:
        out = {
            f.IDENTIFIER: self.identifier,
            f.REQ_ID: self.reqId,
            f.OPERATION: self.operation,
            f.PROTOCOL_VERSION: self.protocolVersion,
        }
        if self.signature is not None:
            out[f.SIGNATURE] = self.signature
        if self.signatures is not None:
            out[f.SIGNATURES] = self.signatures
        return out

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Request":
        return cls(
            identifier=data.get(f.IDENTIFIER),
            reqId=data.get(f.REQ_ID),
            operation=data.get(f.OPERATION),
            signature=data.get(f.SIGNATURE),
            signatures=data.get(f.SIGNATURES),
            protocolVersion=data.get(f.PROTOCOL_VERSION,
                                     CURRENT_PROTOCOL_VERSION),
        )

    def __eq__(self, other):
        return isinstance(other, Request) and self.as_dict() == other.as_dict()

    def __hash__(self):
        return hash(self.digest)

    def __repr__(self):
        return (f"Request(identifier={self.identifier!r}, "
                f"reqId={self.reqId!r}, op={self.operation!r})")


_REQUEST_SCHEMA = (
    (f.IDENTIFIER, IdentifierField(nullable=True)),
    (f.REQ_ID, NonNegativeNumberField()),
    (f.OPERATION, MapField(NonEmptyStringField(), AnyField())),
    (f.SIGNATURE, SignatureField(nullable=True)),
    (f.PROTOCOL_VERSION, ProtocolVersionField()),
)


class SafeRequest(Request):
    """Request constructed from untrusted wire data: validates field shapes."""

    def __init__(self, **kwargs):
        for name, validator in _REQUEST_SCHEMA:
            val = kwargs.get(name)
            if val is None and (validator.optional or validator.nullable):
                continue
            err = validator.validate(val)
            if err:
                raise InvalidClientRequest(
                    kwargs.get(f.IDENTIFIER), kwargs.get(f.REQ_ID),
                    f"{name}: {err}")
        if not kwargs.get(f.SIGNATURE) and not kwargs.get(f.SIGNATURES):
            raise InvalidClientRequest(
                kwargs.get(f.IDENTIFIER), kwargs.get(f.REQ_ID),
                "missing signature(s)")
        known = {name for name, _ in _REQUEST_SCHEMA} | {f.SIGNATURES}
        unknown = set(kwargs) - known
        if unknown:
            raise InvalidClientRequest(
                kwargs.get(f.IDENTIFIER), kwargs.get(f.REQ_ID),
                f"unknown fields {sorted(unknown)}")
        super().__init__(
            identifier=kwargs.get(f.IDENTIFIER),
            reqId=kwargs.get(f.REQ_ID),
            operation=kwargs.get(f.OPERATION),
            signature=kwargs.get(f.SIGNATURE),
            signatures=kwargs.get(f.SIGNATURES),
            protocolVersion=kwargs.get(f.PROTOCOL_VERSION,
                                       CURRENT_PROTOCOL_VERSION),
        )
