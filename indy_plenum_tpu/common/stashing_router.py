"""Message routing with bounded stash queues.

Reference: plenum/common/stashing_router.py (`StashingRouter`) and
plenum/common/router.py (`Router`). A handler returns a verdict:

- ``PROCESS`` (None or 0): handled.
- ``DISCARD``: drop, with a reason.
- any other positive int: STASH under that reason code; the message is
  re-delivered when ``process_stashed(reason)`` is called (e.g. after a
  catchup completes or a view change finishes).

Stash queues are bounded (byzantine peers must not grow host memory).
"""
from __future__ import annotations

import logging
from collections import defaultdict, deque
from typing import Any, Callable, Iterable

logger = logging.getLogger(__name__)

PROCESS = 0
DISCARD = -1

# Common stash reason codes (services may define more; any int > 0 works).
STASH_VIEW_3PC = 1        # wrong view / not yet in view
STASH_CATCH_UP = 2        # node is catching up
STASH_WATERMARKS = 3      # outside [h, H]
STASH_WAITING_VIEW_CHANGE = 4
STASH_WAITING_NEW_VIEW = 5


class Router:
    """Plain type-dispatch router (no stashing)."""

    def __init__(self):
        self._handlers: dict[type, Callable] = {}

    def add(self, message_type: type, handler: Callable) -> None:
        self._handlers[message_type] = handler

    def remove(self, message_type: type) -> None:
        self._handlers.pop(message_type, None)

    def handlers(self, message_type: type) -> Callable | None:
        for cls in message_type.__mro__:
            if cls in self._handlers:
                return self._handlers[cls]
        return None

    def process(self, message: Any, *args) -> Any:
        handler = self.handlers(type(message))
        if handler is None:
            logger.debug("no handler for %s", type(message).__name__)
            return None
        return handler(message, *args)


class RouterSpy:
    """Test instrumentation: records every routed message with its
    verdict (reference: plenum/test/testable.py ``Spyable`` /
    plenum/test/test_node.py spylog). Attach via
    ``StashingRouter.spy``; fault-injection tests can then assert
    e.g. "node X processed PREPARE from Y exactly once" instead of
    relying only on end-state convergence.
    """

    def __init__(self, clock: Callable | None = None):
        import time as _time

        self._clock = clock or _time.monotonic
        self.log: list = []  # (message, frm, verdict_code, t)

    def record(self, message, frm, verdict) -> None:
        self.log.append((message, frm, verdict, self._clock()))

    def events(self, msg_type: type | None = None,
               frm: str | None = None,
               verdict: int | None = None) -> list:
        return [e for e in self.log
                if (msg_type is None or isinstance(e[0], msg_type))
                and (frm is None or e[1] == frm)
                and (verdict is None or e[2] == verdict)]

    def count(self, msg_type: type | None = None, frm: str | None = None,
              verdict: int | None = None) -> int:
        return len(self.events(msg_type, frm, verdict))

    def clear(self) -> None:
        self.log.clear()


class StashingRouter(Router):
    def __init__(self, limit: int, buses: Iterable[Any] = (),
                 unstash_handler: Callable | None = None):
        super().__init__()
        self._limit = limit
        self._queues: dict[int, deque] = defaultdict(lambda: deque(maxlen=limit))
        self._unstash_handler = unstash_handler
        self._buses = list(buses)
        self.spy: RouterSpy | None = None  # test-only; None in production

    def subscribe(self, message_type: type, handler: Callable) -> None:
        """Route ``message_type`` to ``handler`` and listen for it on all
        attached buses. The single shared ``_process_from_bus`` bound method
        plus the buses' per-send handler dedupe guarantee exactly-once
        processing even when base and derived types are both subscribed."""
        self.add(message_type, handler)
        for bus in self._buses:
            bus.subscribe(message_type, self._process_from_bus)

    def _process_from_bus(self, message, *args) -> None:
        self.process(message, *args)

    def unsubscribe_all(self) -> None:
        """Detach from every bus and drop stashes (a torn-down replica's
        handlers must stop firing on the shared external bus)."""
        for bus in self._buses:
            if hasattr(bus, "unsubscribe"):
                for mtype in list(self._handlers):  # types WE subscribed
                    bus.unsubscribe(mtype, self._process_from_bus)
        self._handlers.clear()
        self._queues.clear()

    def stash_size(self, reason: int | None = None) -> int:
        if reason is not None:
            return len(self._queues[reason])
        return sum(len(q) for q in self._queues.values())

    def process(self, message: Any, *args) -> Any:
        handler = self.handlers(type(message))
        if handler is None:
            return None
        verdict = handler(message, *args)
        code, reason = verdict if isinstance(verdict, tuple) else (verdict, None)
        if self.spy is not None:
            self.spy.record(message, args[0] if args else None, code)
        if code is None or code == PROCESS:
            return PROCESS
        if code == DISCARD:
            logger.debug("discarding %s: %s", type(message).__name__, reason)
            return DISCARD
        queue = self._queues[code]
        if len(queue) == queue.maxlen:
            logger.debug("stash %s full; evicting oldest to admit %s", code,
                         type(message).__name__)
        queue.append((message, args))
        return code

    def process_stashed(self, reason: int) -> int:
        """Replay everything stashed under ``reason``; returns count replayed."""
        queue = self._queues[reason]
        processed = 0
        # Bound the replay to the entry length: re-stashed messages must
        # not cause an infinite loop within one call. Re-check emptiness
        # every iteration — processing a message can REENTER
        # process_stashed for the same reason (e.g. a fetched old-view
        # PRE-PREPARE unstashes its successor, which unstashes further)
        # and drain the queue under this loop.
        bound = len(queue)
        while processed < bound and queue:
            message, args = queue.popleft()
            self.process(message, *args)
            processed += 1
        if processed and self._unstash_handler:
            self._unstash_handler(reason, processed)
        return processed

    def process_all_stashed(self) -> int:
        return sum(self.process_stashed(r) for r in list(self._queues))

    def discard_stashed(self, reason: int) -> None:
        self._queues[reason].clear()
