"""Canonical serialization: every node must hash/sign identical bytes.

Reference: common/serializers/serialization.py (signing serializer = ordered
msgpack; base58 root serializers; JSON txn serializer). The signing
serialization here is msgpack with recursively key-sorted maps — canonical
and language-independent; `None` values are dropped (absent field == None,
as the reference's signing serializer does).
"""
from __future__ import annotations

import json
from typing import Any

import msgpack

from ...utils.base58 import b58encode, b58decode


def _canonical(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {k: _canonical(v) for k, v in sorted(obj.items())
                if v is not None}
    if isinstance(obj, (list, tuple)):
        return [_canonical(v) for v in obj]
    return obj


def serialize_for_signing(obj: Any) -> bytes:
    """Deterministic bytes for signing/digesting (ordered msgpack)."""
    return msgpack.packb(_canonical(obj), use_bin_type=True)


def deserialize_msgpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


def serialize_msg(obj: Any) -> bytes:
    """Wire serialization for node/client messages (msgpack, order kept)."""
    return msgpack.packb(obj, use_bin_type=True)


class JsonSerializer:
    """Ledger txn serializer: compact, key-sorted JSON (stable digests)."""

    @staticmethod
    def dumps(obj: Any) -> bytes:
        return json.dumps(obj, sort_keys=True,
                          separators=(",", ":")).encode()

    @staticmethod
    def loads(data: bytes | str) -> Any:
        if isinstance(data, (bytes, bytearray)):
            data = data.decode()
        return json.loads(data)


ledger_txn_serializer = JsonSerializer()


class Base58Serializer:
    """Root-hash serializer: 32-byte roots <-> base58 text."""

    @staticmethod
    def serialize(raw: bytes) -> str:
        return b58encode(raw)

    @staticmethod
    def deserialize(txt: str) -> bytes:
        return b58decode(txt)


state_roots_serializer = Base58Serializer()


class ProofNodesSerializer:
    """State-proof node list <-> msgpack bytes (client-verifiable)."""

    @staticmethod
    def serialize(nodes: Any) -> bytes:
        return msgpack.packb(nodes, use_bin_type=True)

    @staticmethod
    def deserialize(data: bytes) -> Any:
        return msgpack.unpackb(data, raw=False)


proof_nodes_serializer = ProofNodesSerializer()
