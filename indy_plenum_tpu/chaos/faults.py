"""Fault primitives and seeded fault plans for the chaos plane.

RBFT's claim (Aublin et al., ICDCS 2013) is safety + liveness under up to
``f`` Byzantine replicas; exercising that claim needs *generated* fault
scenarios, not one-off hand-written adversaries. A :class:`FaultPlan` is a
list of :class:`Fault` primitives with virtual-time start offsets and
durations — crash/restart, partition/heal, probabilistic message drop,
delay, duplication, reorder, clock skew, and composable Byzantine
strategies (equivocation, silence) — compiled by the
:class:`~indy_plenum_tpu.chaos.scheduler.FaultScheduler` into
:class:`~indy_plenum_tpu.simulation.mock_timer.MockTimer` events driving a
:class:`~indy_plenum_tpu.simulation.sim_network.SimNetwork` pool. All
randomness flows from ONE ``random.Random(seed)``, so a plan replays
bit-for-bit from its seed.
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field, fields
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

# message-type filters are stored as class NAMES (plans must be
# JSON-serializable for the replayable report); resolved lazily against
# the node message namespace
from ..common.messages import node_messages as _node_messages

Undo = Optional[Callable[[], None]]


def resolve_message_types(names) -> Tuple[type, ...]:
    return tuple(getattr(_node_messages, name) for name in names)


@dataclass
class FaultContext:
    """Everything a fault may touch when it begins/ends."""

    pool: Any  # SimPool or NodePool (duck-typed: .node(), .network, ...)
    network: Any  # SimNetwork
    timer: Any  # MockTimer
    rng: random.Random  # THE plan rng — every draw is seed-deterministic
    trace: Callable[[str], None]


@dataclass
class Fault:
    """Base fault: active on [at, at + duration) of virtual time.

    ``duration=None`` means permanent (never reverted). Subclasses return
    an undo callable from :meth:`begin`; the scheduler invokes it at the
    fault's end time.
    """

    at: float = 0.0
    duration: Optional[float] = None

    def begin(self, ctx: FaultContext) -> Undo:
        raise NotImplementedError

    @property
    def byzantine_nodes(self) -> FrozenSet[str]:
        """Nodes this fault makes actively malicious (excluded from the
        honest-agreement checks)."""
        return frozenset()

    @property
    def crashed_nodes(self) -> FrozenSet[str]:
        """Nodes this fault fail-stops (excluded from liveness if never
        restarted)."""
        return frozenset()

    def describe(self) -> str:
        return self.as_dict()["kind"] + " " + ", ".join(
            f"{k}={v}" for k, v in sorted(self.as_dict().items())
            if k != "kind")

    @staticmethod
    def _jsonable(v):
        if isinstance(v, frozenset):
            return sorted(v)
        if isinstance(v, (tuple, list)):
            return [Fault._jsonable(x) for x in v]
        return v

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"kind": type(self).__name__}
        for f in fields(self):
            # deep list conversion so a saved report JSON-round-trips
            # equal to as_dict() (PartitionFault.groups nests tuples)
            out[f.name] = self._jsonable(getattr(self, f.name))
        return out


def _match(types: Tuple[type, ...], frm: Optional[str], to: Optional[str],
           msg, sender: str, dest: str) -> bool:
    if types and not isinstance(msg, types):
        return False
    if frm is not None and sender != frm:
        return False
    if to is not None and dest != to:
        return False
    return True


@dataclass
class LinkFault(Fault):
    """Shared shape for delayer-based faults: an optional message-type /
    endpoint filter. ``types`` holds node-message CLASS NAMES."""

    types: Tuple[str, ...] = ()
    frm: Optional[str] = None
    to: Optional[str] = None

    def _delayer(self, ctx: FaultContext) -> Callable:
        raise NotImplementedError

    def begin(self, ctx: FaultContext) -> Undo:
        return ctx.network.add_delayer(self._delayer(ctx))


@dataclass
class CrashFault(Fault):
    """Fail-stop: the node drops off the network (both directions); with a
    duration it restarts (reconnects) and must re-join ordering."""

    node: str = ""

    def begin(self, ctx: FaultContext) -> Undo:
        ctx.network.disconnect(self.node)
        if self.duration is None:
            return None
        return lambda: ctx.network.reconnect(self.node)

    @property
    def crashed_nodes(self) -> FrozenSet[str]:
        return frozenset({self.node})


@dataclass
class PartitionFault(Fault):
    """Split the pool into isolated groups; cross-group messages drop.
    Nodes named in no group are isolated singletons. Healing (the undo)
    removes the cut."""

    groups: Tuple[Tuple[str, ...], ...] = ()

    def begin(self, ctx: FaultContext) -> Undo:
        side = {name: i for i, grp in enumerate(self.groups) for name in grp}

        def cut(msg, sender, dest):
            if side.get(sender, -1) != side.get(dest, -2):
                return float("inf")
            return None

        return ctx.network.add_delayer(cut)


@dataclass
class DropFault(LinkFault):
    """Drop matched messages with seeded probability (1.0 = a hard cut)."""

    probability: float = 1.0

    def _delayer(self, ctx: FaultContext):
        types = resolve_message_types(self.types)

        def drop(msg, sender, dest):
            if not _match(types, self.frm, self.to, msg, sender, dest):
                return None
            if self.probability >= 1.0 or ctx.rng.random() < self.probability:
                return float("inf")
            return None

        return drop


@dataclass
class DelayFault(LinkFault):
    """Add fixed extra latency to matched messages (slow link / slow node)."""

    seconds: float = 1.0

    def _delayer(self, ctx: FaultContext):
        types = resolve_message_types(self.types)

        def slow(msg, sender, dest):
            if _match(types, self.frm, self.to, msg, sender, dest):
                return self.seconds
            return None

        return slow


@dataclass
class ReorderFault(LinkFault):
    """Seeded per-message jitter far above the base link latency, so
    delivery order scrambles relative to send order."""

    jitter: float = 0.5

    def _delayer(self, ctx: FaultContext):
        types = resolve_message_types(self.types)

        def scramble(msg, sender, dest):
            if _match(types, self.frm, self.to, msg, sender, dest):
                return ctx.rng.uniform(0.0, self.jitter)
            return None

        return scramble


@dataclass
class DuplicateFault(LinkFault):
    """Deliver matched messages ``copies`` times, ``gap`` seconds apart —
    the at-least-once transport every vote path must tolerate."""

    copies: int = 2
    gap: float = 0.05

    def _delayer(self, ctx: FaultContext):
        types = resolve_message_types(self.types)
        offsets = tuple(i * self.gap for i in range(self.copies))

        def dup(msg, sender, dest):
            if _match(types, self.frm, self.to, msg, sender, dest):
                return offsets
            return None

        return dup


@dataclass
class ClockSkewFault(Fault):
    """Model a node whose local clock lags by ``skew`` seconds: everything
    it RECEIVES lands ``skew`` late (its pipeline runs behind the pool),
    and its own sends leave on time. One shared MockTimer drives the whole
    simulation, so skew is expressed at the delivery boundary."""

    node: str = ""
    skew: float = 1.0

    def begin(self, ctx: FaultContext) -> Undo:
        def lag(msg, sender, dest):
            return self.skew if dest == self.node else None

        return ctx.network.add_delayer(lag)


@dataclass
class SilenceFault(LinkFault):
    """Byzantine silence: the node stays connected (so crash detection
    does NOT fire) but drops its outbound matched messages."""

    node: str = ""

    def _delayer(self, ctx: FaultContext):
        types = resolve_message_types(self.types)

        def mute(msg, sender, dest):
            # the silenced node IS the frm filter; to narrows further
            if _match(types, self.node, self.to, msg, sender, dest):
                return float("inf")
            return None

        return mute

    @property
    def byzantine_nodes(self) -> FrozenSet[str]:
        return frozenset({self.node})


@dataclass
class EquivocateFault(Fault):
    """Byzantine equivocation: the node's outbound PRE-PREPAREs carry a
    per-recipient forged digest for roughly half the pool, trying to split
    the prepare quorum (the classic split-brain attack the digest-filtered
    vote collection must defeat)."""

    node: str = ""

    def begin(self, ctx: FaultContext) -> Undo:
        import hashlib

        PrePrepare = _node_messages.PrePrepare
        bus = ctx.pool.node(self.node).external_bus
        original = bus._send_handler
        peers = sorted(set(ctx.pool.validators) - {self.node})
        forked = set(peers[len(peers) // 2:])

        def equivocate(msg, dst=None):
            if not isinstance(msg, PrePrepare):
                return original(msg, dst)
            if dst is None:
                targets = list(peers)
            elif isinstance(dst, str):
                targets = [dst]
            else:
                targets = list(dst)
            for to in targets:
                out = msg
                if to in forked:
                    forged = msg._fields
                    forged["digest"] = hashlib.sha256(
                        (msg.digest + to).encode()).hexdigest()
                    out = PrePrepare(**forged)
                ctx.network._deliver_later(out, self.node, to)

        bus._send_handler = equivocate

        def undo():
            bus._send_handler = original

        return undo

    @property
    def byzantine_nodes(self) -> FrozenSet[str]:
        return frozenset({self.node})


@dataclass
class CorruptCatchupRepFault(Fault):
    """Byzantine seeder: every ``CATCHUP_REP`` the node serves carries
    silently-corrupted txn payloads (the audit paths still reference the
    honest tree, so the leecher's batched proof verification MUST reject
    the whole slice, raise CATCHUP_REP_WRONG suspicion, and re-request
    from an honest seeder — corrupted history must never apply). The node
    stays honest in 3PC; only its catchup answers lie."""

    node: str = ""

    def begin(self, ctx: FaultContext) -> Undo:
        CatchupRep = _node_messages.CatchupRep
        bus = ctx.pool.node(self.node).external_bus
        original = bus._send_handler

        def corrupt(msg, dst=None):
            if not isinstance(msg, CatchupRep):
                return original(msg, dst)
            forged = msg._fields
            forged["txns"] = {
                seq: {**txn, "evil": "corrupted-by-" + self.node}
                if isinstance(txn, dict) else txn
                for seq, txn in dict(msg.txns).items()}
            ctx.trace(f"{self.node} corrupting CATCHUP_REP "
                      f"({len(forged['txns'])} txns, ledger "
                      f"{msg.ledgerId})")
            return original(CatchupRep(**forged), dst)

        bus._send_handler = corrupt

        def undo():
            bus._send_handler = original

        return undo

    @property
    def byzantine_nodes(self) -> FrozenSet[str]:
        return frozenset({self.node})


@dataclass
class CorruptOrderedLogFault(Fault):
    """Deliberately-broken adversary: silently rewrite the victim's LAST
    executed batch digest, modelling an undetected ordering/execution bug
    on an otherwise honest replica. The node is NOT marked byzantine —
    the agreement invariant MUST catch this, proving the checker is not
    vacuous."""

    node: str = ""

    def begin(self, ctx: FaultContext) -> Undo:
        node = ctx.pool.node(self.node)
        if not node.ordered_log:
            ctx.trace(f"corruption no-op: {self.node} has ordered nothing")
            return None
        entry = node.ordered_log[-1]
        forged = entry._fields
        forged["digest"] = "corrupted:" + (entry.digest or "")
        forged["reqIdr"] = ["corrupted:" + d for d in entry.reqIdr]
        node.ordered_log[-1] = type(entry)(**forged)
        ctx.trace(f"corrupted {self.node} ordered batch "
                  f"seq={entry.ppSeqNo}")
        return None


@dataclass
class FaultPlan:
    """A seed plus an ordered list of faults — the full, serializable
    description of one chaos run's adversary."""

    seed: int
    faults: List[Fault] = field(default_factory=list)

    @property
    def byzantine_nodes(self) -> FrozenSet[str]:
        out: FrozenSet[str] = frozenset()
        for fault in self.faults:
            out |= fault.byzantine_nodes
        return out

    @property
    def crashed_forever_nodes(self) -> FrozenSet[str]:
        """Crashed with no restart: alive for safety checks on what they
        ordered BEFORE dying, but exempt from liveness."""
        out: FrozenSet[str] = frozenset()
        for fault in self.faults:
            if fault.crashed_nodes and fault.duration is None:
                out |= fault.crashed_nodes
        return out

    @property
    def restarted_nodes(self) -> FrozenSet[str]:
        """Crashed WITH a restart: the nodes a catchup scenario expects
        to detect their gap, leech it back, and rejoin ordering."""
        out: FrozenSet[str] = frozenset()
        for fault in self.faults:
            if fault.crashed_nodes and fault.duration is not None:
                out |= fault.crashed_nodes
        return out

    @property
    def end_time(self) -> float:
        """Offset at which the last bounded fault has been reverted."""
        end = 0.0
        for fault in self.faults:
            end = max(end, fault.at + (fault.duration or 0.0))
        return end

    def as_dicts(self) -> List[Dict[str, Any]]:
        return [fault.as_dict() for fault in self.faults]
