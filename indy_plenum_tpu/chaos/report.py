"""Reproducible chaos-run reports.

A run's full forensic record as JSON: the seed and scenario (everything
needed to replay it exactly), the compiled fault plan, the virtual-time
event trace, network delivery accounting, pool metrics, per-node ordering
state and every invariant verdict. A failing run's report IS its repro —
``replay_command`` re-executes the identical schedule.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass
class ChaosReport:
    scenario: str
    seed: int
    n_nodes: int
    plan: List[Dict[str, Any]]
    trace: List[Tuple[float, str]]
    invariants: List[Dict[str, Any]]
    expected_failures: List[str]
    network: Dict[str, Any] = field(default_factory=dict)
    metrics: Dict[str, Any] = field(default_factory=dict)
    ordered_per_node: Dict[str, int] = field(default_factory=dict)
    # sha256 of each node's ordered-digest sequence: lets two runs (e.g.
    # per-message vs tick-batched vs adaptive-tick on the same seed) be
    # compared for ORDERING identity, not just count identity, without
    # embedding every digest in the report
    ordered_hash_per_node: Dict[str, str] = field(default_factory=dict)
    # RBFT monitor views, for pools whose nodes carry one (NodePool)
    monitor_per_node: Dict[str, Any] = field(default_factory=dict)
    # catchup plane (real-execution scenarios): per-node leecher meters
    # (rounds / txns leeched / proofs verified / reps rejected / retry-law
    # re-requests), per-node committed-ledger hashes — the ordering
    # fingerprint that stays comparable across catchup, asserted
    # bit-identical by the budget script's catchup gate — and the
    # proof-read closing check (the freshly caught-up node serving a
    # verify_proved_read-able reply from the window it just leeched)
    catchup: Dict[str, Any] = field(default_factory=dict)
    byzantine_nodes: List[str] = field(default_factory=list)
    periodic_checks: int = 0
    first_violation: Optional[Tuple[float, str]] = None
    virtual_seconds: float = 0.0
    # how the run was routed through the dispatch plane (device quorum /
    # tick / adaptive / mesh shape — "4" member-sharded or "2x2" for the
    # 2-axis member x validator fabric): replay_command must reproduce
    # the exact pipeline, not just the fault schedule — a mesh run
    # replayed unsharded (or a 2-axis run replayed 1-axis) would still
    # order identically (that's the tested contract) but would no longer
    # exercise the path being debugged
    dispatch_mode: Dict[str, Any] = field(default_factory=dict)
    # consensus flight recorder (observability.trace): the trace
    # fingerprint (bit-identical across replays of the same seed), where
    # the full JSONL dump landed, and every triggered tail snapshot
    # (invariant violation / ordering stall / governor anomaly) — the
    # report carries the flight-recorder moment itself, replayable via
    # replay_command
    trace_hash: Optional[str] = None
    trace_file: Optional[str] = None
    flight_recorder: List[Dict[str, Any]] = field(default_factory=list)
    # causal request journeys (observability.causal, traced runs only):
    # journey counts + completeness, the byte-stable journey_hash, e2e
    # percentiles per request class, and — because chaos fault begin/end
    # marks ride the same timeline — the measured latency cost of the
    # requests whose journey crossed a fault window vs the ones that
    # ran clear
    journeys: Dict[str, Any] = field(default_factory=dict)
    # ordering lanes (laned scenarios): router distribution, barrier
    # counters (sealed window / seals / fingerprint chain tip), per-lane
    # ordered hashes — the cross-lane ordering record the cross_lane
    # invariant verified during the run
    lanes: Dict[str, Any] = field(default_factory=dict)
    # overload robustness plane (workload-bearing scenarios): the
    # admission/shed/retry record of the saturating open-loop load the
    # scenario ran under — workload counters, admission counters, the
    # shed_hash / retry_hash fingerprints (byte-identical per seed, so
    # the overload gate replays them like trace_hash), and the
    # per-seeder throttle meters proving the pool kept ordering while
    # it seeded the returning victim
    ingress: Dict[str, Any] = field(default_factory=dict)
    # geo plane (edge_poison scenarios): the cache-poisoning closing
    # check's record — tampered/caught counts on the byzantine edge,
    # the honest edge's verification record, and the fallback
    # accounting proving every poisoned reply was re-served from the
    # origin after verification caught it
    edge: Dict[str, Any] = field(default_factory=dict)

    @property
    def failed(self) -> List[str]:
        return [r["name"] for r in self.invariants
                if r["verdict"] != "PASS"]

    @property
    def verdict_as_expected(self) -> bool:
        """True when exactly the designed-to-fail invariants failed —
        the pass criterion for scenarios proving the checker non-vacuous."""
        return sorted(self.failed) == sorted(self.expected_failures)

    @property
    def replay_command(self) -> str:
        cmd = (f"python scripts/chaos_run.py --seed {self.seed} "
               f"--scenario {self.scenario} --nodes {self.n_nodes}")
        mode = self.dispatch_mode
        if mode.get("device_quorum"):
            cmd += " --device-quorum"
        if mode.get("tick"):
            cmd += f" --tick {mode['tick']}"
        if mode.get("adaptive"):
            cmd += " --adaptive-tick"
        if mode.get("mesh"):
            cmd += f" --mesh {mode['mesh']}"
        if mode.get("resident"):
            cmd += f" --resident-depth {mode['resident']}"
        if mode.get("trace"):
            cmd += " --trace"
        if mode.get("lanes"):
            cmd += f" --lanes {mode['lanes']}"
        return cmd

    def as_dict(self) -> Dict[str, Any]:
        return {
            "scenario": self.scenario,
            "seed": self.seed,
            "n_nodes": self.n_nodes,
            "replay_command": self.replay_command,
            "dispatch_mode": dict(self.dispatch_mode),
            "verdict_as_expected": self.verdict_as_expected,
            "invariants": self.invariants,
            "expected_failures": list(self.expected_failures),
            "byzantine_nodes": list(self.byzantine_nodes),
            "plan": self.plan,
            "trace": [[t, e] for t, e in self.trace],
            "network": self.network,
            "metrics": self.metrics,
            "ordered_per_node": self.ordered_per_node,
            "ordered_hash_per_node": self.ordered_hash_per_node,
            "monitor_per_node": self.monitor_per_node,
            "catchup": self.catchup,
            "periodic_checks": self.periodic_checks,
            "first_violation": (list(self.first_violation)
                                if self.first_violation else None),
            "virtual_seconds": self.virtual_seconds,
            "trace_hash": self.trace_hash,
            "trace_file": self.trace_file,
            "flight_recorder": self.flight_recorder,
            "journeys": self.journeys,
            "lanes": self.lanes,
            "edge": self.edge,
        }

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.as_dict(), indent=indent, sort_keys=False)

    def save(self, path: str) -> str:
        with open(path, "w") as fh:
            fh.write(self.to_json() + "\n")
        return path

    def summary_lines(self) -> List[str]:
        lines = [f"scenario={self.scenario} seed={self.seed} "
                 f"nodes={self.n_nodes} "
                 f"virtual={self.virtual_seconds:.0f}s"]
        for r in self.invariants:
            mark = "PASS" if r["verdict"] == "PASS" else "FAIL"
            lines.append(f"  [{mark}] {r['name']}: {r['detail']}")
        net = self.network
        lines.append(
            f"  network: sent={net.get('sent')} "
            f"dropped={net.get('dropped')} "
            f"duplicated={net.get('duplicated')}")
        if self.first_violation is not None:
            t, what = self.first_violation
            lines.append(f"  first violation at t={t:.2f}: {what}")
        if self.catchup:
            lines.append(
                f"  catchup: rounds={self.catchup.get('rounds')} "
                f"txns_leeched={self.catchup.get('txns_leeched')} "
                f"proofs_verified={self.catchup.get('proofs_verified')} "
                f"reps_rejected={self.catchup.get('reps_rejected')} "
                f"retries={self.catchup.get('retries')}")
            pr = self.catchup.get("proof_read")
            if pr:
                lines.append(
                    f"  proof read: node={pr.get('node')} "
                    f"index={pr.get('index')} window={pr.get('window')} "
                    f"verified={pr.get('verified')}")
        if self.journeys:
            j = self.journeys
            e2e = (j.get("e2e") or {}).get("write") or {}
            lines.append(
                f"  journeys: {j.get('complete')}/{j.get('count')} "
                f"complete (orphans={j.get('orphan_spans')}, "
                f"via_catchup={j.get('catchup_journeys')}) "
                f"e2e p50={e2e.get('p50')} p99={e2e.get('p99')} "
                f"hash={str(j.get('journey_hash'))[:16]}…")
            fw = j.get("fault_window")
            if fw:
                lines.append(
                    f"  fault cost: {fw['through_fault']['count']} "
                    f"journeys crossed a fault window "
                    f"(p50 {fw['through_fault']['p50']} vs "
                    f"{fw['clear']['p50']} clear; "
                    f"p50_cost={fw['p50_cost']})")
        if self.lanes:
            ln = self.lanes
            barrier = ln.get("barrier") or {}
            lines.append(
                f"  lanes: {ln.get('count')} "
                f"router={ln.get('router', {}).get('distribution')} "
                f"sealed_window={barrier.get('sealed_window')} "
                f"seal_fp={str(barrier.get('seal_fingerprint'))[:16]}…")
        if self.edge:
            poisoned = self.edge.get("poisoned") or {}
            honest = self.edge.get("honest") or {}
            lines.append(
                f"  edge: tampered={poisoned.get('tampered')} "
                f"caught={poisoned.get('caught')} "
                f"fallbacks={poisoned.get('origin_fallbacks')} "
                f"honest_verified={honest.get('verified')}/"
                f"{honest.get('served')}")
        if self.trace_hash is not None:
            dumped = ", ".join(sorted({d.get("reason", "?")
                                       for d in self.flight_recorder})) \
                or "none"
            lines.append(f"  trace: hash={self.trace_hash[:16]}… "
                         f"file={self.trace_file} flight_dumps={dumped}")
        lines.append(f"  replay: {self.replay_command}")
        return lines
