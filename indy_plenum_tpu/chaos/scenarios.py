"""Named chaos scenarios: seed -> FaultPlan generators.

Each scenario is a recipe that expands ``(seed, n_nodes)`` into a
concrete :class:`FaultPlan` through ONE ``random.Random(seed)`` — victim
selection, fault timing and probabilities are all drawn from it, so a
scenario replays exactly from its seed (the whole point of the chaos
plane: any red run is a repro, not an anecdote).

``expect_fail`` names invariants a scenario is DESIGNED to violate — the
checker-vacuity proof (``broken_agreement``) must fail agreement, and a
runner treats exactly those failures as the expected outcome.
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Tuple

from .faults import (
    ClockSkewFault,
    CorruptCatchupRepFault,
    CorruptOrderedLogFault,
    CrashFault,
    DelayFault,
    DropFault,
    DuplicateFault,
    EquivocateFault,
    FaultPlan,
    PartitionFault,
    ReorderFault,
    SilenceFault,
)

THREE_PC_TYPES = ("PrePrepare", "Prepare", "Commit")
# the messages a seeder answers catchup with: silencing them models a
# seeder that accepts requests and never replies (retry law territory)
CATCHUP_REPLY_TYPES = ("CatchupRep", "ConsistencyProof", "LedgerStatus")


@dataclass
class Scenario:
    name: str
    build: Callable[[random.Random, List[str]], List]
    description: str = ""
    n_nodes: int = 4
    initial_requests: int = 8
    # a steady client trickle keeps work in flight while faults are
    # active, so crashes/partitions hit mid-protocol, not an idle pool
    trickle_requests: int = 12
    trickle_interval: float = 1.5
    run_seconds: float = 30.0
    liveness_timeout: float = 40.0
    expect_fail: Tuple[str, ...] = ()
    config_overrides: Dict = field(default_factory=dict)
    # catchup-plane scenarios run REAL ledgers (the leecher needs them);
    # bls additionally arms the state-proof plane so the freshly
    # caught-up node can serve verify_proved_read-able replies
    real_execution: bool = False
    bls: bool = False
    num_instances: int = 1  # RBFT protocol instances (0 = auto f+1)
    # extra invariants the runner appends for catchup scenarios — each
    # is ASSERTED from the pool's leecher meters, never assumed:
    # require_catchup: every crashed-and-restarted node completed >= 1
    #   leecher round, leeched > 0 txns, proof-verified every applied
    #   batch, and is participating again;
    # require_rejection: >= 1 CATCHUP_REP was rejected by audit-proof
    #   verification (byzantine-seeder scenarios);
    # require_retries: the retry law re-requested >= 1 silent slice;
    # proof_read: the caught-up node serves a proof-attached read from
    #   the window it just leeched that passes verify_proved_read
    #   against the pool's BLS keys (needs bls=True).
    require_catchup: bool = False
    require_rejection: bool = False
    require_retries: bool = False
    proof_read: bool = False
    # geo plane: arm the cache-poisoning closing check — a byzantine
    # region-local edge cache tampers every proof reply it serves, and
    # the client verification loop must catch 100% of it (asserted
    # non-vacuously, alongside an honest edge serving the same reads).
    # Needs bls=True + real_execution=True (the edge replicates a real
    # stabilized window's proof-attached replies).
    edge_poison: bool = False
    # ordering lanes: > 1 routes the scenario through a LanedPool of
    # this many lanes — faults apply INSIDE lane 0 (the runner's fault
    # facade), per-lane safety aggregates, the cross_lane invariant
    # (barrier seal/skew/fingerprint) probes continuously, and liveness
    # probes every lane
    lanes: int = 0
    # overload robustness plane: workload_rate > 0 drives a seeded
    # open-loop population (profiled via workload_profile, closed-loop
    # retries when the config overrides arm IngressRetryMax) through the
    # pool's ADMISSION path for the scenario's whole fault arc. Requires
    # the tick-batched dispatch plane (the ingress drain rides the tick)
    # and sign_requests (the runner arms both); IngressQueueCapacity
    # must come from config_overrides or nothing ever sheds.
    workload_rate: float = 0.0
    workload_duration: float = 0.0
    workload_start: float = 0.0
    workload_profile: str = "steady"
    workload_clients: int = 10_000

    def plan(self, seed: int, n_nodes: int = 0) -> FaultPlan:
        n = n_nodes or self.n_nodes
        validators = [f"node{i}" for i in range(n)]
        rng = random.Random(seed)
        return FaultPlan(seed=seed, faults=self.build(rng, validators))


SCENARIOS: Dict[str, Scenario] = {}


def register(scenario: Scenario) -> Scenario:
    SCENARIOS[scenario.name] = scenario
    return scenario


def get_scenario(name: str) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown chaos scenario {name!r}; known: "
            f"{', '.join(sorted(SCENARIOS))}") from None


def _split(validators: List[str], rng: random.Random
           ) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    """An rng-chosen ~half/half partition of the pool."""
    shuffled = list(validators)
    rng.shuffle(shuffled)
    cut = len(shuffled) // 2
    return tuple(shuffled[:cut]), tuple(shuffled[cut:])


# --- the acceptance scenario: f crashes + a partition that heals ---------

def _f_crash_partition(rng: random.Random, validators: List[str]) -> List:
    f = (len(validators) - 1) // 3
    # crash f non-primary nodes (staggered, all restart): the pool keeps
    # ordering on the remaining n-f quorum, and the restarted nodes must
    # re-join. node0 is the view-0 primary under the round-robin selector.
    victims = rng.sample(validators[1:], f)
    faults: List = [
        CrashFault(node=victim, at=2.0 + 2.0 * i, duration=6.0)
        for i, victim in enumerate(victims)]
    # then a clean ~half/half partition: no side may have a commit quorum,
    # ordering stalls, and the heal must bring progress back
    groups = _split(validators, rng)
    faults.append(PartitionFault(groups=groups, at=14.0, duration=6.0))
    return faults


register(Scenario(
    name="f_crash_partition",
    build=_f_crash_partition,
    description="f staggered crash/restarts, then a half/half partition "
                "that heals; all invariants must hold",
    run_seconds=30.0))


# --- single-primitive scenarios (each fault class in isolation) ----------

def _crash_restart(rng: random.Random, validators: List[str]) -> List:
    victim = rng.choice(validators)  # may be the primary: exercises VC
    return [CrashFault(node=victim, at=2.0, duration=8.0)]


register(Scenario(
    name="crash_restart",
    build=_crash_restart,
    description="one node (possibly the primary) fail-stops and restarts",
    run_seconds=25.0))


def _partition_heal(rng: random.Random, validators: List[str]) -> List:
    return [PartitionFault(groups=_split(validators, rng),
                           at=3.0, duration=8.0)]


register(Scenario(
    name="partition_heal",
    build=_partition_heal,
    description="half/half partition for 8s, then heal",
    run_seconds=25.0))


def _flaky_links(rng: random.Random, validators: List[str]) -> List:
    # probabilistic 3PC message loss on the whole mesh — below the drop
    # rate that starves a quorum, ordering must still make progress
    return [DropFault(types=THREE_PC_TYPES, probability=0.15,
                      at=2.0, duration=10.0)]


register(Scenario(
    name="flaky_links",
    build=_flaky_links,
    description="15% seeded loss on all 3PC traffic for 10s",
    run_seconds=30.0))


def _dup_reorder(rng: random.Random, validators: List[str]) -> List:
    # at-least-once + out-of-order delivery: vote collection must be
    # idempotent and order-insensitive
    return [
        DuplicateFault(types=THREE_PC_TYPES, copies=3, gap=0.07,
                       at=1.0, duration=10.0),
        ReorderFault(types=THREE_PC_TYPES, jitter=0.4,
                     at=1.0, duration=10.0),
    ]


register(Scenario(
    name="dup_reorder",
    build=_dup_reorder,
    description="3PC messages delivered 3x with 0.4s reorder jitter",
    run_seconds=25.0))


def _clock_skew(rng: random.Random, validators: List[str]) -> List:
    victim = rng.choice(validators[1:])
    return [ClockSkewFault(node=victim, skew=0.6, at=2.0, duration=10.0),
            DelayFault(frm=victim, seconds=0.3, at=2.0, duration=10.0)]


register(Scenario(
    name="clock_skew",
    build=_clock_skew,
    description="one replica runs 0.6s behind the pool (plus slow uplink)",
    run_seconds=25.0))


def _silent_primary(rng: random.Random, validators: List[str]) -> List:
    # byzantine silence, bounded: the primary withholds PRE-PREPAREs for a
    # while (slow-but-alive byzantine); ordering must resume after
    return [SilenceFault(node=validators[0], types=("PrePrepare",),
                         at=2.0, duration=6.0)]


register(Scenario(
    name="silent_primary",
    build=_silent_primary,
    description="primary withholds PRE-PREPAREs for 6s, then behaves",
    run_seconds=25.0))


def _equivocating_primary(rng: random.Random, validators: List[str]) -> List:
    # permanent equivocation by the view-0 primary: conflicting digests
    # can never gather a prepare quorum, suspicion evidence votes the
    # primary out, and the HONEST pool must stay consistent and live
    return [EquivocateFault(node=validators[0], at=1.0)]


register(Scenario(
    name="equivocating_primary",
    build=_equivocating_primary,
    description="primary sends per-recipient forged PRE-PREPARE digests "
                "until voted out",
    run_seconds=45.0,
    liveness_timeout=60.0))


def _storm(rng: random.Random, validators: List[str]) -> List:
    # everything at once, long horizon: crashes, loss, duplication,
    # reorder, skew — the 'as many scenarios as you can imagine' soak
    faults: List = [
        DropFault(types=THREE_PC_TYPES, probability=0.1,
                  at=1.0, duration=25.0),
        DuplicateFault(copies=2, gap=0.05, at=1.0, duration=25.0),
        ReorderFault(jitter=0.3, at=1.0, duration=25.0),
    ]
    f = (len(validators) - 1) // 3
    for i, victim in enumerate(rng.sample(validators[1:], f)):
        faults.append(CrashFault(node=victim, at=4.0 + 3.0 * i,
                                 duration=5.0))
        faults.append(ClockSkewFault(node=victim, skew=0.4,
                                     at=12.0 + 2.0 * i, duration=6.0))
    return faults


register(Scenario(
    name="storm",
    build=_storm,
    description="25s soak: loss + duplication + reorder + crashes + skew",
    run_seconds=60.0,
    liveness_timeout=60.0,
    initial_requests=16))


# --- catchup plane: recovery across checkpoint GC ------------------------
#
# The pre-catchup chaos library pinned CHK_FREQ high so a whole run fit
# one checkpoint window (a node behind a stabilized checkpoint could not
# recover). These scenarios do the opposite ON PURPOSE: tiny windows, a
# crash long enough for >= StateProofCacheWindows checkpoints to
# stabilize AND garbage-collect in the victim's absence, then a restart
# — the victim must detect the gap (f+1 checkpoints beyond its H),
# leech the missed range from seeders with every batch audit-proof
# verified, and rejoin 3PC ordering.

_CATCHUP_CONFIG = {
    "Max3PCBatchSize": 1,  # checkpoints move per txn
    "Max3PCBatchWait": 0.1,
    "CHK_FREQ": 2,
    "LOG_SIZE": 6,
    # several small slices per ledger so round-robin assignment spreads
    # requests across seeders (byzantine/silent seeders get their turn)
    "CatchupBatchSize": 2,
    # snappy, deterministic retry law under the mock clock
    "ConsistencyProofsTimeout": 1.0,
    "CatchupRequestTimeout": 1.5,
    "CatchupMaxRetries": 8,
    "OrderingStallTimeout": 4.0,
    "StateProofCacheWindows": 2,
}


def _crash_across_gc(rng: random.Random, validators: List[str],
                     at: float = 2.0, duration: float = 12.0) -> tuple:
    """A non-primary victim crashed long enough for >= 2 checkpoint
    windows to stabilize and GC without it (the trickle keeps batches —
    and therefore checkpoints — flowing the whole time)."""
    victim = rng.choice(validators[1:])
    return victim, CrashFault(node=victim, at=at, duration=duration)


def _f_crash_gc_catchup(rng: random.Random, validators: List[str]) -> List:
    _, crash = _crash_across_gc(rng, validators)
    return [crash]


register(Scenario(
    name="f_crash_gc_catchup",
    build=_f_crash_gc_catchup,
    description="node crashes, >= 2 checkpoint windows stabilize and GC "
                "in its absence, restart -> full leecher round (every "
                "batch audit-proof verified) -> rejoin; the caught-up "
                "node then serves a verify_proved_read-able reply",
    run_seconds=30.0,
    liveness_timeout=45.0,
    real_execution=True,
    bls=True,
    require_catchup=True,
    proof_read=True,
    config_overrides=dict(_CATCHUP_CONFIG)))


def _byzantine_seeder_catchup(rng: random.Random,
                              validators: List[str]) -> List:
    victim, crash = _crash_across_gc(rng, validators)
    # a byzantine seeder among the survivors: corrupted CATCHUP_REPs must
    # be rejected by proof verification, never trusted (it stays honest
    # in 3PC — only its catchup answers lie)
    evil = rng.choice([v for v in validators if v != victim])
    return [CorruptCatchupRepFault(node=evil, at=0.0), crash]


register(Scenario(
    name="byzantine_seeder_catchup",
    build=_byzantine_seeder_catchup,
    description="GC-crossing crash/restart while a byzantine seeder "
                "serves corrupted CATCHUP_REPs: proof verification must "
                "reject them (asserted) and honest seeders complete the "
                "round",
    run_seconds=30.0,
    liveness_timeout=45.0,
    real_execution=True,
    require_catchup=True,
    require_rejection=True,
    config_overrides=dict(_CATCHUP_CONFIG)))


def _silent_seeder_catchup(rng: random.Random,
                           validators: List[str]) -> List:
    victim, crash = _crash_across_gc(rng, validators)
    # one survivor answers NOTHING on the catchup plane while the victim
    # recovers: the seeded retry/timeout/backoff law must re-route its
    # slices to the live seeders instead of stalling
    mute = rng.choice([v for v in validators if v != victim])
    return [crash,
            SilenceFault(node=mute, types=CATCHUP_REPLY_TYPES,
                         at=13.0, duration=22.0)]


register(Scenario(
    name="silent_seeder_catchup",
    build=_silent_seeder_catchup,
    description="GC-crossing crash/restart with one seeder silent on the "
                "whole catchup plane: the retry law re-routes its slices "
                "(retries asserted) and recovery completes",
    run_seconds=40.0,
    liveness_timeout=45.0,
    real_execution=True,
    require_catchup=True,
    require_retries=True,
    config_overrides=dict(_CATCHUP_CONFIG)))


def _ic_storm_mid_catchup(rng: random.Random,
                          validators: List[str]) -> List:
    victim, crash = _crash_across_gc(rng, validators)
    # monitor-degradation storm mid-catchup: a byzantine backup-instance
    # primary withholds its PRE-PREPAREs for the whole recovery window
    # AND the master primary goes silent long enough for the ordering
    # stall watchdog to force an instance change while the victim is
    # still leeching — catchup must survive the view change. Under the
    # round-robin selector the instance-1 primary is validators[1] (the
    # victim is drawn from validators[1:], so skip to validators[2] when
    # they collide); the view-0 master primary is validators[0], which
    # is never the victim.
    backup_primary = validators[1] if validators[1] != victim \
        else validators[2]
    return [
        crash,
        SilenceFault(node=backup_primary, types=("PrePrepare",),
                     at=14.0, duration=8.0),
        SilenceFault(node=validators[0], types=("PrePrepare",),
                     at=15.0, duration=6.0),
    ]


register(Scenario(
    name="ic_storm_mid_catchup",
    build=_ic_storm_mid_catchup,
    description="GC-crossing crash/restart with a byzantine backup "
                "primary and a stalled master mid-catchup: the instance "
                "change fires while the victim is leeching and recovery "
                "still completes",
    run_seconds=45.0,
    liveness_timeout=60.0,
    real_execution=True,
    num_instances=0,  # auto f+1: real RBFT backup instances in the storm
    require_catchup=True,
    config_overrides=dict(_CATCHUP_CONFIG)))


# --- ordering lanes: faults inside one lane of a laned pool --------------
#
# The multi-lane write path's acceptance scenario: the f_crash_partition
# arc (f staggered crash/restarts, then a half/half partition that
# heals) applied INSIDE lane 0 of a 4-lane pool. The healthy lanes keep
# ordering — but only as far as the cross-lane barrier's skew bound
# (LOG_SIZE past the last sealed window): the continuously-probed
# cross_lane invariant asserts no lane ever stabilizes a window the
# barrier hasn't sealed, the seal fingerprint chain stays recomputable,
# and after the heal every lane resumes (per-lane liveness probes).
# Tiny checkpoint windows on purpose: the barrier must seal many times
# DURING the fault, not just at the end.

register(Scenario(
    name="lane_partition",
    build=_f_crash_partition,
    description="f crash/restarts + half/half partition INSIDE lane 0 "
                "of a 4-lane pool: healthy lanes stall at the barrier's "
                "skew bound, never past it (cross_lane asserted "
                "continuously); lane 0's crashed node leeches back "
                "across GC'd windows and every lane resumes after the "
                "heal",
    lanes=4,
    run_seconds=30.0,
    liveness_timeout=60.0,
    # real ledgers: lane 0's crash victim falls behind windows that
    # stabilize AND GC in its absence (CHK_FREQ=2), so rejoining takes
    # a real leecher round — the catchup plane must work INSIDE a lane,
    # with the barrier's lane_caught_up floor riding along; ASSERTED
    # via the catchup_recovery verdict, not assumed
    real_execution=True,
    require_catchup=True,
    config_overrides={
        "Max3PCBatchSize": 1,  # checkpoints move per txn
        "CHK_FREQ": 2,
        "LOG_SIZE": 6,
        "CatchupBatchSize": 2,
        "ConsistencyProofsTimeout": 1.0,
        "CatchupRequestTimeout": 1.5,
        "CatchupMaxRetries": 8,
        # the healthy lanes WILL stall at the skew bound while lane 0
        # is partitioned — give the stall watchdog room so they don't
        # churn instance changes against a wait that is by design
        "OrderingStallTimeout": 10.0,
    }))


# --- overload robustness: catchup while ingress saturates ----------------
#
# The catchup scenarios above recover on an otherwise-idle pool; real
# recoveries happen while the pool is busiest. Here the GC-crossing
# crash/restart arc runs UNDER a flash-crowd workload with closed-loop
# retries: the victim restarts right as the crowd spikes, so the pool is
# simultaneously (a) shedding + absorbing the retry storm, (b) ordering
# the admitted backlog, and (c) seeding the victim's leecher — with the
# seeder token bucket throttling (c) so it cannot stall (b). Verdicts
# assert recovery (catchup_recovery) and the shed/retry fingerprints in
# the report let the overload gate assert byte-identical replays.

def _f_crash_catchup_under_saturation(rng: random.Random,
                                      validators: List[str]) -> List:
    _, crash = _crash_across_gc(rng, validators, at=2.0, duration=8.0)
    return [crash]


register(Scenario(
    name="f_crash_catchup_under_saturation",
    build=_f_crash_catchup_under_saturation,
    description="GC-crossing crash/restart while a flash-crowd profile "
                "saturates ingress and shed clients retry on seeded "
                "backoff: the victim leeches back through a throttled "
                "seeder (deferrals metered, ordering never stalls) and "
                "the shed/retry sets replay byte-identically",
    run_seconds=30.0,
    liveness_timeout=60.0,
    real_execution=True,
    require_catchup=True,
    # the crowd: a modest base rate whose flash spike (12x for 2s,
    # absolute t=9.5..11.5) lands exactly as the victim restarts (t=10)
    # and starts leeching
    workload_rate=15.0,
    workload_duration=6.0,
    workload_start=6.0,
    workload_profile="flash",
    config_overrides={
        **_CATCHUP_CONFIG,
        # checkpoints still move fast (CHK_FREQ=2 in pp_seq space, the
        # trickle keeps single-request batches flowing through the
        # crash) but the crowd's admitted flood orders in REAL batches,
        # and the victim leeches it back in REAL slices — at the catchup
        # library's Max3PCBatchSize=1 / CatchupBatchSize=2 the backlog
        # and the slice chatter alone would dominate the wall clock
        "Max3PCBatchSize": 12,
        "CatchupBatchSize": 10,
        # admission + closed-loop retry: small queue so the spike sheds,
        # snappy seeded backoff so retries land inside the run window
        "IngressQueueCapacity": 6,
        "IngressRetryMax": 3,
        "IngressRetryBase": 0.3,
        "IngressRetryBackoffMult": 2.0,
        "IngressRetryBackoffMax": 4.0,
        "WorkloadProfilePeak": 12.0,
        "WorkloadProfileFlashAt": 3.5,
        "WorkloadProfileFlashDuration": 2.0,
        # seeder throttle: slices cost up to 10 txns (CatchupBatchSize),
        # the 10-token bucket refills at 40 txns/s — back-to-back slices
        # defer (metered) while the leecher's retry law rides the delay
        "CatchupSeederThrottleTxnsPerSec": 40.0,
        "CatchupSeederThrottleBurst": 10,
    }))


# --- geo plane: edge cache poisoning -------------------------------------
#
# The edge proof tier (proofs/edge_cache.py) is UNTRUSTED by design:
# verification, not the cache, is the security boundary. This arc proves
# that boundary non-vacuously: after a clean run seals checkpoint
# windows, the closing check replicates the last window's proof-attached
# replies into TWO region-local edges, arms deterministic tampering on
# one (leaf flips / root flips / corrupted multi-sigs), serves the same
# read set from both, and asserts (a) the client verification loop
# catches EVERY tampered reply and falls back to the origin validator,
# (b) the honest edge's replies all verify, (c) the tamper counter is
# non-zero (the check actually exercised the byzantine path).

def _edge_cache_poisoning(rng: random.Random, validators: List[str]) -> List:
    # the byzantine actor lives OUTSIDE consensus — a poisoned edge in
    # the closing check, not a network fault
    return []


register(Scenario(
    name="edge_cache_poisoning",
    build=_edge_cache_poisoning,
    description="a byzantine region-local edge cache tampers every proof "
                "reply it serves: clients catch 100% by offline "
                "verification and fall back to the origin validator, "
                "while an honest edge serving the same reads stays fully "
                "verifiable (all asserted, non-vacuously)",
    run_seconds=20.0,
    liveness_timeout=30.0,
    real_execution=True,
    bls=True,
    edge_poison=True,
    config_overrides=dict(_CATCHUP_CONFIG)))


# --- the checker-vacuity proof -------------------------------------------

def _broken_agreement(rng: random.Random, validators: List[str]) -> List:
    # an 'undetectable' state-corruption bug on an honest replica: the
    # agreement invariant MUST flag it, or the checker is vacuous
    victim = rng.choice(validators[1:])
    return [CorruptOrderedLogFault(node=victim, at=6.0)]


register(Scenario(
    name="broken_agreement",
    build=_broken_agreement,
    description="deliberately corrupt one honest replica's executed log; "
                "the agreement invariant must FAIL",
    run_seconds=12.0,
    expect_fail=("agreement", "ordered_prefix")))
