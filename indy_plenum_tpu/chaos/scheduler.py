"""Compile a :class:`FaultPlan` into virtual-timer events + run a trace.

The scheduler is the deterministic bridge between a plan and the live
pool: every fault begin/end becomes a :class:`MockTimer` event, every
application is appended to an ``(virtual_time, description)`` trace, and
an optional safety probe (the invariant checker's non-liveness checks)
runs on a repeating virtual timer DURING the run — a violation is caught
at the moment it happens, with its timestamp, not just post-mortem.
Same pool seed + same plan ⇒ identical trace, identical pool history.
"""
from __future__ import annotations

import random
from typing import Any, Callable, List, Optional, Tuple

from ..common.timer import RepeatingTimer
from ..observability.trace import NULL_TRACE
from .faults import Fault, FaultContext, FaultPlan


class FaultScheduler:
    def __init__(self, pool: Any, plan: FaultPlan,
                 safety_probe: Optional[Callable[[], List]] = None,
                 probe_interval: float = 1.0):
        self.pool = pool
        self.plan = plan
        self.rng = random.Random(plan.seed)
        self.trace: List[Tuple[float, str]] = []
        # flight recorder: fault begin/end marks ride the pool's span
        # trace too (cat "chaos"), and the FIRST safety violation dumps
        # the trace tail — the run's forensic record at the moment it
        # went wrong, not just post-mortem
        pool_trace = getattr(pool, "trace", None)
        self._span_trace = pool_trace if pool_trace is not None \
            else NULL_TRACE
        self.active_faults = 0
        self.probe_results: List[Tuple[float, bool]] = []
        self.first_violation: Optional[Tuple[float, str]] = None
        self._safety_probe = safety_probe
        self._probe_timer: Optional[RepeatingTimer] = None
        self._probe_interval = probe_interval
        self._ctx = FaultContext(
            pool=pool, network=pool.network, timer=pool.timer,
            rng=self.rng, trace=self._record)

    # --- trace ----------------------------------------------------------

    def _record(self, event: str) -> None:
        self.trace.append((self.pool.timer.get_current_time(), event))
        if self._span_trace.enabled:
            self._span_trace.record(event, cat="chaos")

    # --- wiring ---------------------------------------------------------

    def install(self) -> "FaultScheduler":
        """Schedule every fault's begin (and bounded end) on the pool's
        virtual clock, relative to now. Idempotent per plan instance is
        NOT attempted — install once."""
        for fault in self.plan.faults:
            self.pool.timer.schedule(
                fault.at, lambda f=fault: self._begin(f))
        if self._safety_probe is not None:
            self._probe_timer = RepeatingTimer(
                self.pool.timer, self._probe_interval, self._run_probe)
        return self

    def stop_probe(self) -> None:
        if self._probe_timer is not None:
            self._probe_timer.stop()

    def _begin(self, fault: Fault) -> None:
        undo = fault.begin(self._ctx)
        self.active_faults += 1
        self._record("begin " + fault.describe())
        metrics = getattr(self.pool, "metrics", None)
        if metrics is not None:
            from ..common.metrics_collector import MetricsName

            metrics.add_event(MetricsName.CHAOS_FAULTS_BEGUN)
        if fault.duration is not None:
            self.pool.timer.schedule(
                fault.duration, lambda: self._end(fault, undo))

    def _end(self, fault: Fault, undo) -> None:
        if undo is not None:
            undo()
        self.active_faults -= 1
        self._record("end " + fault.describe())

    def _run_probe(self) -> None:
        results = self._safety_probe()
        ok = all(r.passed for r in results)
        self.probe_results.append(
            (self.pool.timer.get_current_time(), ok))
        if not ok and self.first_violation is None:
            failed = "; ".join(r.name for r in results if not r.passed)
            self.first_violation = (
                self.pool.timer.get_current_time(), failed)
            self._record("safety violation: " + failed)
            if self._span_trace.enabled:
                self._span_trace.trigger_dump("invariant_violation",
                                              args={"failed": failed})
