"""Scenario runner: pool up, plan in, invariants out, report saved.

One call drives the whole chaos loop deterministically on the virtual
clock: build a :class:`SimPool`, compile the scenario's seeded
:class:`FaultPlan` onto its timer, feed client traffic, run past the last
bounded fault, then hand the pool to the
:class:`~indy_plenum_tpu.chaos.invariants.InvariantChecker` (safety
continuously during the run via the scheduler's probe, safety + liveness
at the end) and emit a replayable :class:`ChaosReport`.
"""
from __future__ import annotations

import hashlib
from typing import Optional

from ..config import getConfig
from ..simulation.pool import SimPool
from .invariants import InvariantChecker
from .report import ChaosReport
from .scenarios import Scenario, get_scenario
from .scheduler import FaultScheduler

# the simulation-friendly protocol tunables every scenario starts from;
# scenario config_overrides layer on top
BASE_CONFIG = {
    "Max3PCBatchWait": 0.1,
    "Max3PCBatchSize": 5,
    # keep the WHOLE run inside one checkpoint window: plain SimPool has
    # no ledger catchup, so a replica that falls behind a stabilized
    # checkpoint could never re-sync — recovery during chaos runs rides
    # 3PC re-request + NEW_VIEW re-ordering, both of which need peers to
    # still hold the logs
    "CHK_FREQ": 50,
    "LOG_SIZE": 150,
    # tight PBFT stall timer: chaos runs stall pools on purpose and the
    # recovery path (stall votes -> view change -> re-propose) is exactly
    # what the liveness invariant exercises
    "OrderingStallTimeout": 4.0,
}


def run_scenario(scenario: "str | Scenario", seed: int,
                 n_nodes: int = 0,
                 out_path: Optional[str] = None,
                 probe_interval: float = 1.0,
                 device_quorum: bool = False,
                 quorum_tick_interval: float = 0.0,
                 quorum_tick_adaptive: bool = False,
                 mesh=None,
                 host_eval: bool = False,
                 trace: bool = False,
                 trace_out: Optional[str] = None) -> ChaosReport:
    """``device_quorum`` + ``quorum_tick_interval`` > 0 route the scenario
    through the tick-batched dispatch plane (grouped device flushes, per-
    tick quorum evaluation) — fault paths must survive the tick barrier
    exactly as they do the per-message loop, and the report's metrics
    then carry the dispatch amortization numbers.
    ``quorum_tick_adaptive`` additionally hands the tick to the dispatch
    governor: the report's ``governor.tick_interval`` metrics then record
    the interval trajectory (deterministic — replaying the same seed
    yields the identical trajectory, which tests assert).
    ``mesh`` shards the grouped vote plane's member axis across a jax
    device mesh — fault paths must survive the mesh-sharded dispatch
    plane bit-for-bit (``ordered_hash_per_node`` equal to the 1-device
    run on the same seed), which the slow-lane mesh chaos test asserts.
    ``trace`` arms the consensus flight recorder on the pool's virtual
    clock: fault begin/end marks and the full 3PC/dispatch span timeline
    land in one ring, the first invariant violation (and any ordering
    stall / governor anomaly) snapshots its tail into the report's
    ``flight_recorder``, and the report carries ``trace_hash`` — a
    replay of the same seed must reproduce it bit-for-bit.
    ``trace_out`` additionally dumps the whole ring as JSONL
    (``scripts/trace_tool.py`` consumes it)."""
    if mesh is not None and not device_quorum:
        raise ValueError("mesh requires device_quorum")
    if quorum_tick_interval > 0 and not device_quorum:
        # the services gate tick mode on having a vote plane: without
        # device_quorum the override would silently run the plain
        # per-message loop while the caller believes otherwise
        raise ValueError("quorum_tick_interval requires device_quorum")
    if quorum_tick_adaptive and quorum_tick_interval <= 0:
        raise ValueError("quorum_tick_adaptive requires a tick interval")
    if isinstance(scenario, str):
        scenario = get_scenario(scenario)
    n = n_nodes or scenario.n_nodes
    plan = scenario.plan(seed, n)

    overrides = {**BASE_CONFIG, **scenario.config_overrides}
    if quorum_tick_interval > 0:
        overrides["QuorumTickInterval"] = quorum_tick_interval
        overrides["QuorumTickAdaptive"] = quorum_tick_adaptive
    config = getConfig(overrides)
    pool = SimPool(n_nodes=n, seed=seed, config=config,
                   device_quorum=device_quorum, mesh=mesh,
                   host_eval=host_eval, trace=trace)
    checker = InvariantChecker(
        pool,
        byzantine=plan.byzantine_nodes,
        crashed=plan.crashed_forever_nodes)
    scheduler = FaultScheduler(
        pool, plan,
        safety_probe=checker.check_safety,
        probe_interval=probe_interval).install()

    # client traffic from t=0, plus a steady trickle across the fault
    # window so crashes/partitions hit in-flight ordering
    for i in range(scenario.initial_requests):
        pool.submit_request(i)
    for i in range(scenario.trickle_requests):
        pool.timer.schedule(
            (i + 1) * scenario.trickle_interval,
            lambda seq=scenario.initial_requests + i:
            pool.submit_request(seq))

    # run past the last bounded fault, then let the pool settle
    horizon = max(scenario.run_seconds, plan.end_time + 5.0)
    pool.run_for(horizon)
    scheduler.stop_probe()

    results = checker.check_all(
        probes=3, liveness_timeout=scenario.liveness_timeout)

    report = ChaosReport(
        scenario=scenario.name,
        seed=seed,
        n_nodes=n,
        dispatch_mode={
            "device_quorum": device_quorum,
            "tick": quorum_tick_interval,
            "adaptive": quorum_tick_adaptive,
            # the mesh SHAPE, chaos_run.py --mesh syntax ("4" = member
            # sharded, "2x2" = the 2-axis fabric): replay_command must
            # reproduce the exact grid, not just the device count
            "mesh": ("x".join(str(d) for d in mesh.devices.shape)
                     if mesh is not None else 0),
            "host_eval": host_eval,
            "trace": trace,
        },
        plan=plan.as_dicts(),
        trace=list(scheduler.trace),
        invariants=[r.as_dict() for r in results],
        expected_failures=list(scenario.expect_fail),
        network=pool.network.counters(),
        metrics=pool.metrics.summary(),
        ordered_per_node={nd.name: len(nd.ordered_digests)
                          for nd in pool.nodes},
        ordered_hash_per_node={
            nd.name: hashlib.sha256(
                "|".join(nd.ordered_digests).encode()).hexdigest()
            for nd in pool.nodes},
        monitor_per_node={
            nd.name: nd.monitor.snapshot() for nd in pool.nodes
            if getattr(nd, "monitor", None) is not None},
        byzantine_nodes=sorted(plan.byzantine_nodes),
        periodic_checks=len(scheduler.probe_results),
        first_violation=scheduler.first_violation,
        virtual_seconds=pool.timer.get_current_time()
        - 1_700_000_000.0,
    )
    if trace:
        # serialize the ring ONCE: the hash and the dump are the same
        # bytes by construction
        jsonl = pool.trace.to_jsonl()
        report.trace_hash = hashlib.sha256(jsonl.encode()).hexdigest()
        report.flight_recorder = [dict(d) for d in pool.trace.dumps]
        if trace_out is not None:
            with open(trace_out, "w") as fh:
                fh.write(jsonl)
            report.trace_file = trace_out
    if out_path is not None:
        report.save(out_path)
    return report
