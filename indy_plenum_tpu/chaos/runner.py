"""Scenario runner: pool up, plan in, invariants out, report saved.

One call drives the whole chaos loop deterministically on the virtual
clock: build a :class:`SimPool`, compile the scenario's seeded
:class:`FaultPlan` onto its timer, feed client traffic, run past the last
bounded fault, then hand the pool to the
:class:`~indy_plenum_tpu.chaos.invariants.InvariantChecker` (safety
continuously during the run via the scheduler's probe, safety + liveness
at the end) and emit a replayable :class:`ChaosReport`.
"""
from __future__ import annotations

import hashlib
from typing import Dict, Optional

from ..config import getConfig
from ..simulation.pool import SimPool
from .faults import CrashFault
from .invariants import InvariantChecker, InvariantResult
from .report import ChaosReport
from .scenarios import Scenario, get_scenario
from .scheduler import FaultScheduler

# the simulation-friendly protocol tunables every scenario starts from;
# scenario config_overrides layer on top
BASE_CONFIG = {
    "Max3PCBatchWait": 0.1,
    "Max3PCBatchSize": 5,
    # keep executor-faked runs inside one checkpoint window: without real
    # ledgers there is no catchup, so a replica that falls behind a
    # stabilized checkpoint could never re-sync — recovery rides 3PC
    # re-request + NEW_VIEW re-ordering, both of which need peers to
    # still hold the logs. Catchup scenarios (real_execution=True)
    # OVERRIDE this with tiny windows on purpose: crossing a GC'd
    # checkpoint boundary and leeching back is exactly what they test.
    "CHK_FREQ": 50,
    "LOG_SIZE": 150,
    # tight PBFT stall timer: chaos runs stall pools on purpose and the
    # recovery path (stall votes -> view change -> re-propose) is exactly
    # what the liveness invariant exercises
    "OrderingStallTimeout": 4.0,
}


def _catchup_block(pool, plan, scenario, leech_floor) -> dict:
    """The report's catchup forensic record: per-node leecher meters,
    pool totals, per-node committed-ledger hashes (the ordering
    fingerprint that stays comparable ACROSS catchup — a caught-up
    node's ordered_log legitimately skips the leeched middle), and the
    proof-read closing check when the scenario requests it."""
    leechers = {nd.name: nd.leecher for nd in pool.nodes
                if getattr(nd, "leecher", None) is not None}
    if not leechers:
        return {}
    per_node = {name: l.catchup_stats() for name, l in leechers.items()}
    totals = {k: sum(per_node[name][k] for name in sorted(per_node))
              for k in ("rounds_completed", "txns_leeched",
                        "proofs_verified", "reps_rejected", "retries")}
    block = {
        "per_node": per_node,
        "rounds": totals["rounds_completed"],
        "txns_leeched": totals["txns_leeched"],
        "proofs_verified": totals["proofs_verified"],
        "reps_rejected": totals["reps_rejected"],
        "retries": totals["retries"],
        "restarted_nodes": sorted(plan.restarted_nodes),
        "leech_floor": dict(leech_floor),
        "ledger_hash_per_node": {nd.name: pool.ledger_hash(nd.name)
                                 for nd in pool.nodes},
    }
    if scenario.proof_read and pool.bls_keys is not None \
            and plan.restarted_nodes:
        from ..client.state_proof import verify_proved_read

        victim = sorted(plan.restarted_nodes)[0]
        # read a leaf from INSIDE the leeched range (0-based index =
        # the victim's committed size at restart = first leeched seq-1),
        # served by the victim itself against the stabilized window it
        # captured after rejoining — the window's tree COVERS the range
        # it just leeched
        index = leech_floor.get(victim, 0)
        service = pool.make_read_service(victim, mode="auto")
        service.submit(index)
        replies = service.drain()
        reply = replies[-1] if replies else None
        n = len(pool.validators)
        quorum = n - (n - 1) // 3
        keys = {name: pk for name, (kp, pk, pop) in pool.bls_keys.items()}
        verified = bool(
            reply is not None and reply.multi_sig is not None
            and verify_proved_read(reply, keys, min_participants=quorum))
        block["proof_read"] = {
            "node": victim,
            "index": index,
            "window": list(reply.window) if reply is not None
            and reply.window is not None else None,
            "has_multi_sig": bool(reply is not None
                                  and reply.multi_sig is not None),
            "verified": verified,
        }
    return block


def _catchup_verdicts(pool, plan, scenario, block) -> list:
    """The scenario's catchup requirements as first-class invariant
    results — ASSERTED from the leecher meters and the client-side
    proof verdict, so a chaos run can never 'pass' by silently skipping
    recovery."""
    out = []
    if scenario.require_catchup:
        problems = []
        if not plan.restarted_nodes:
            problems.append("no crashed-and-restarted node in the plan")
        for victim in sorted(plan.restarted_nodes):
            stats = (block.get("per_node") or {}).get(victim)
            if stats is None:
                problems.append(f"{victim} has no leecher")
                continue
            if stats["rounds_completed"] < 1:
                problems.append(f"{victim} completed no catchup round")
            if stats["txns_leeched"] < 1:
                problems.append(f"{victim} leeched no txns")
            if stats["proofs_verified"] < stats["txns_leeched"]:
                problems.append(
                    f"{victim} applied {stats['txns_leeched']} txns but "
                    f"proof-verified only {stats['proofs_verified']}")
            if not pool.node(victim).data.is_participating:
                problems.append(f"{victim} is not participating again")
        out.append(InvariantResult(
            "catchup_recovery", not problems,
            "; ".join(problems) if problems else
            f"restarted {sorted(plan.restarted_nodes)} completed "
            f"{block.get('rounds', 0)} round(s), "
            f"{block.get('txns_leeched', 0)} txns leeched, "
            f"{block.get('proofs_verified', 0)} proofs verified"))
    if scenario.require_rejection:
        rejected = block.get("reps_rejected", 0)
        out.append(InvariantResult(
            "catchup_rejection", rejected >= 1,
            f"{rejected} corrupted CATCHUP_REP(s) rejected by proof "
            "verification" if rejected else
            "no CATCHUP_REP was rejected — the byzantine seeder was "
            "never exercised (or its corruption was trusted)"))
    if scenario.require_retries:
        retries = block.get("retries", 0)
        out.append(InvariantResult(
            "catchup_retry", retries >= 1,
            f"retry law re-requested {retries} slice(s)" if retries else
            "no retry fired — the silent seeder was never exercised"))
    if scenario.proof_read:
        pr = block.get("proof_read") or {}
        out.append(InvariantResult(
            "catchup_proof_read", bool(pr.get("verified")),
            f"caught-up node {pr.get('node')} served index "
            f"{pr.get('index')} from window {pr.get('window')}; "
            "verify_proved_read against the pool BLS keys: "
            f"{bool(pr.get('verified'))}"))
    return out


def _edge_block(pool, scenario, seed: int) -> Dict[str, object]:
    """The geo plane's cache-poisoning closing check (``edge_poison``
    scenarios): replicate the last stabilized window's proof-attached
    replies into TWO region-local edge caches, arm deterministic
    tampering on one, route the same read set through both via
    :class:`~indy_plenum_tpu.proofs.edge_cache.GeoReadFabric`, and
    record what client verification caught. Verification — not the
    cache — is the security boundary, so every tampered reply must fail
    offline verification and be re-served from the origin validator."""
    if not scenario.edge_poison or pool.bls_keys is None:
        return {}
    from ..proofs.edge_cache import EdgeProofCache, GeoReadFabric
    from ..simulation.sim_network import RegionLatencyMatrix

    origin = pool.make_read_service("node0", mode="host")
    if origin.proof_cache is None or origin.proof_cache.current() is None:
        return {"error": "no stabilized proof window to replicate"}
    entry = origin.proof_cache.current()
    n_reads = min(entry.tree_size, 24)
    for i in range(n_reads):
        origin.submit(i)
    replies = origin.drain()
    keys = {name: pk for name, (kp, pk, pop) in pool.bls_keys.items()}
    quorum = len(pool.validators) - (len(pool.validators) - 1) // 3
    matrix = RegionLatencyMatrix(2, seed=seed, intra_band=(0.01, 0.05),
                                 wan_band=(0.08, 0.25))
    clock = pool.timer.get_current_time
    block: Dict[str, object] = {"window": list(entry.window),
                                "replicated": len(replies)}
    for label, poison in (("honest", False), ("poisoned", True)):
        edge = EdgeProofCache(region=1, keep_windows=2,
                              max_entries=4096, clock=clock)
        edge.replicate(entry.window, replies)
        if poison:
            edge.poison(seed)
        fabric = GeoReadFabric(
            origin, matrix, keys, min_participants=quorum, n_regions=2,
            origin_region=0, edges={1: edge}, seed=seed, clock=clock)
        for i in range(n_reads):
            fabric.submit(2 * i + 1, i)  # every client homes in region 1
        answered = fabric.drain()
        counters = fabric.counters()
        block[label] = {
            "served": counters["served"],
            "edge_served": counters["edge_served"],
            "verified": sum(b["verified"] for b
                            in counters["regions"].values()),
            "tampered": edge.tampered_total,
            "caught": counters["verify_caught"],
            "origin_fallbacks": counters["origin_served"],
            "stale_fallbacks": counters["stale_fallbacks"],
            "edge_serve_pairings": counters["edge_serve_pairings"],
            "answered": len(answered),
        }
    return block


def _edge_verdicts(scenario, block) -> list:
    """Poisoning verdicts from the edge closing check: catching is
    asserted NON-VACUOUSLY (tampered > 0), and the honest arm proves
    the check passes for the right reason, not by rejecting everything."""
    if not scenario.edge_poison:
        return []
    if not block or "poisoned" not in block:
        return [InvariantResult(
            "edge_poisoning", False,
            str(block.get("error")) if block
            else "edge closing check did not run")]
    poisoned, honest = block["poisoned"], block["honest"]
    tampered, caught = poisoned["tampered"], poisoned["caught"]
    return [
        InvariantResult(
            "edge_poisoning",
            tampered > 0 and caught == tampered
            and poisoned["origin_fallbacks"] == tampered
            and poisoned["answered"] == poisoned["served"],
            f"byzantine edge tampered {tampered} replies; client "
            f"verification caught {caught}/{tampered}, "
            f"{poisoned['origin_fallbacks']} re-served from the origin"
            if tampered else
            "no reply was tampered — the poisoned edge was never "
            "exercised (vacuous)"),
        InvariantResult(
            "edge_honest_serve",
            honest["tampered"] == 0 and honest["served"] > 0
            and honest["verified"] == honest["served"]
            and honest["edge_served"] == honest["served"]
            and honest["edge_serve_pairings"] == 0,
            f"honest edge served {honest['edge_served']}/"
            f"{honest['served']} reads region-locally, "
            f"{honest['verified']} verified offline, "
            f"{honest['edge_serve_pairings']} pairings on the edge "
            "serve path"),
    ]


class _LaneZeroFacade:
    """The fault plan's view of a :class:`~indy_plenum_tpu.lanes.pool
    .LanedPool`: faults target lane 0 (the scenario's fault lane — its
    network, its nodes), while the timer / trace / metrics are the
    laned pool's shared ones. The healthy lanes feel the fault only
    through the cross-lane barrier, which is exactly the coupling the
    ``cross_lane`` invariant probes."""

    def __init__(self, laned_pool):
        self._lane = laned_pool.lane_pools[0]
        self.network = self._lane.network
        self.timer = laned_pool.timer
        self.trace = laned_pool.trace
        self.metrics = laned_pool.metrics
        self.validators = self._lane.validators
        self.nodes = self._lane.nodes

    def node(self, name: str):
        return self._lane.node(name)


def _run_laned_scenario(scenario: Scenario, seed: int, n: int,
                        out_path: Optional[str],
                        probe_interval: float,
                        device_quorum: bool,
                        quorum_tick_interval: float,
                        quorum_tick_adaptive: bool,
                        trace: bool,
                        trace_out: Optional[str]) -> ChaosReport:
    """Laned scenarios (``scenario.lanes > 1``): the fault plan applies
    inside lane 0 of a LanedPool, safety aggregates per lane + the
    cross-lane barrier invariant, and liveness probes EVERY lane."""
    from ..lanes import LanedPool
    from .invariants import check_laned_liveness, check_laned_safety

    plan = scenario.plan(seed, n)
    overrides = {**BASE_CONFIG, **scenario.config_overrides}
    if quorum_tick_interval > 0:
        overrides["QuorumTickInterval"] = quorum_tick_interval
        overrides["QuorumTickAdaptive"] = quorum_tick_adaptive
    config = getConfig(overrides)
    pool = LanedPool(lanes=scenario.lanes, n_nodes=n, seed=seed,
                     config=config, device_quorum=device_quorum,
                     real_execution=scenario.real_execution,
                     bls=scenario.bls,
                     num_instances=scenario.num_instances,
                     trace=trace)
    facade = _LaneZeroFacade(pool)
    scheduler = FaultScheduler(
        facade, plan,
        safety_probe=lambda: check_laned_safety(pool),
        probe_interval=probe_interval).install()

    for i in range(scenario.initial_requests):
        pool.submit_request(i)
    for i in range(scenario.trickle_requests):
        pool.timer.schedule(
            (i + 1) * scenario.trickle_interval,
            lambda seq=scenario.initial_requests + i:
            pool.submit_request(seq))

    # faults land in lane 0: snapshot its restarted victims' committed
    # ledger sizes at their restart instants (the leeched range starts
    # there), exactly like the unlaned path
    fault_lane = pool.lane_pools[0]
    leech_floor: Dict[str, int] = {}
    if scenario.real_execution:
        from ..common.constants import DOMAIN_LEDGER_ID

        def _snap_floor(victim: str) -> None:
            node = fault_lane.node(victim)
            if node.boot is not None:
                leech_floor[victim] = node.boot.db.get_ledger(
                    DOMAIN_LEDGER_ID).size

        for fault in plan.faults:
            if isinstance(fault, CrashFault) and fault.duration is not None:
                pool.timer.schedule(fault.at + fault.duration,
                                    lambda v=fault.node: _snap_floor(v))

    horizon = max(scenario.run_seconds, plan.end_time + 5.0)
    pool.run_for(horizon)
    scheduler.stop_probe()

    results = check_laned_safety(pool)
    results.append(check_laned_liveness(
        pool, probes=3, timeout=scenario.liveness_timeout))
    # liveness mutated pool history (per-lane probes): re-verify the
    # safety + cross-lane set over the post-probe state
    results[:4] = check_laned_safety(pool)
    metrics_summary = pool.metrics.summary()
    # catchup requirements assert against the FAULT lane (recovery
    # happened inside lane 0) — a laned scenario can no more 'pass' by
    # silently skipping recovery than an unlaned one
    catchup_block = _catchup_block(fault_lane, plan, scenario,
                                   leech_floor)
    results.extend(_catchup_verdicts(fault_lane, plan, scenario,
                                     catchup_block))

    network_totals = {"per_lane": {
        f"lane{lane}": lp.network.counters()
        for lane, lp in enumerate(pool.lane_pools)}}
    for key in ("sent", "dropped", "duplicated"):
        network_totals[key] = sum(
            c[key] for c in (lp.network.counters()
                             for lp in pool.lane_pools))
    report = ChaosReport(
        scenario=scenario.name,
        seed=seed,
        n_nodes=n,
        dispatch_mode={
            "device_quorum": device_quorum,
            "tick": quorum_tick_interval,
            "adaptive": quorum_tick_adaptive,
            "mesh": 0,
            "host_eval": False,
            "trace": trace,
            "lanes": scenario.lanes,
        },
        plan=plan.as_dicts(),
        trace=list(scheduler.trace),
        invariants=[r.as_dict() for r in results],
        expected_failures=list(scenario.expect_fail),
        network=network_totals,
        metrics=metrics_summary,
        ordered_per_node={
            f"lane{lane}/{nd.name}": len(nd.ordered_digests)
            for lane, lp in enumerate(pool.lane_pools)
            for nd in lp.nodes},
        ordered_hash_per_node={
            f"lane{lane}/{nd.name}": hashlib.sha256(
                "|".join(nd.ordered_digests).encode()).hexdigest()
            for lane, lp in enumerate(pool.lane_pools)
            for nd in lp.nodes},
        lanes={
            "count": pool.n_lanes,
            "router": pool.router.counters(),
            "barrier": pool.barrier.counters(),
            "ordered_hash_per_lane": pool.ordered_hashes(),
            "ordered_per_lane": pool.ordered_per_lane(),
        },
        catchup=catchup_block,
        byzantine_nodes=sorted(plan.byzantine_nodes),
        periodic_checks=len(scheduler.probe_results),
        first_violation=scheduler.first_violation,
        virtual_seconds=pool.timer.get_current_time()
        - 1_700_000_000.0,
    )
    if trace:
        jsonl = pool.trace.to_jsonl()
        report.trace_hash = hashlib.sha256(jsonl.encode()).hexdigest()
        report.flight_recorder = [dict(d) for d in pool.trace.dumps]
        from ..observability.causal import journey_summary

        report.journeys = journey_summary(pool.trace.events())
        if trace_out is not None:
            with open(trace_out, "w") as fh:
                fh.write(jsonl)
            report.trace_file = trace_out
    if out_path is not None:
        report.save(out_path)
    return report


def run_scenario(scenario: "str | Scenario", seed: int,
                 n_nodes: int = 0,
                 out_path: Optional[str] = None,
                 probe_interval: float = 1.0,
                 device_quorum: bool = False,
                 quorum_tick_interval: float = 0.0,
                 quorum_tick_adaptive: bool = False,
                 mesh=None,
                 host_eval: bool = False,
                 trace: bool = False,
                 trace_out: Optional[str] = None,
                 resident_depth: int = 0) -> ChaosReport:
    """``device_quorum`` + ``quorum_tick_interval`` > 0 route the scenario
    through the tick-batched dispatch plane (grouped device flushes, per-
    tick quorum evaluation) — fault paths must survive the tick barrier
    exactly as they do the per-message loop, and the report's metrics
    then carry the dispatch amortization numbers.
    ``quorum_tick_adaptive`` additionally hands the tick to the dispatch
    governor: the report's ``governor.tick_interval`` metrics then record
    the interval trajectory (deterministic — replaying the same seed
    yields the identical trajectory, which tests assert).
    ``mesh`` shards the grouped vote plane's member axis across a jax
    device mesh — fault paths must survive the mesh-sharded dispatch
    plane bit-for-bit (``ordered_hash_per_node`` equal to the 1-device
    run on the same seed), which the slow-lane mesh chaos test asserts.
    ``trace`` arms the consensus flight recorder on the pool's virtual
    clock: fault begin/end marks and the full 3PC/dispatch span timeline
    land in one ring, the first invariant violation (and any ordering
    stall / governor anomaly) snapshots its tail into the report's
    ``flight_recorder``, and the report carries ``trace_hash`` — a
    replay of the same seed must reproduce it bit-for-bit.
    ``trace_out`` additionally dumps the whole ring as JSONL
    (``scripts/trace_tool.py`` consumes it).
    ``resident_depth`` > 1 arms multi-tick device residency on the tick
    plane (votes accumulate in device-side ring slots across that many
    ticks before one fused step consumes them) — fault paths must
    survive the deferred-readback window bit-for-bit, which the
    residency chaos test asserts."""
    if mesh is not None and not device_quorum:
        raise ValueError("mesh requires device_quorum")
    if resident_depth > 1:
        if quorum_tick_interval <= 0 or not device_quorum:
            raise ValueError(
                "resident_depth requires the tick-batched dispatch "
                "plane (device_quorum=True, quorum_tick_interval > 0)")
        if host_eval:
            raise ValueError("resident_depth is a device-eval "
                             "optimization; host_eval would silently "
                             "run per-tick")
    if quorum_tick_interval > 0 and not device_quorum:
        # the services gate tick mode on having a vote plane: without
        # device_quorum the override would silently run the plain
        # per-message loop while the caller believes otherwise
        raise ValueError("quorum_tick_interval requires device_quorum")
    if quorum_tick_adaptive and quorum_tick_interval <= 0:
        raise ValueError("quorum_tick_adaptive requires a tick interval")
    if isinstance(scenario, str):
        scenario = get_scenario(scenario)
    n = n_nodes or scenario.n_nodes
    if scenario.lanes > 1:
        if mesh is not None or host_eval or resident_depth > 1:
            raise ValueError(
                "laned scenarios run per-lane vote planes; mesh/"
                "host_eval/resident_depth overrides are not supported "
                "on the laned path")
        return _run_laned_scenario(
            scenario, seed, n, out_path, probe_interval, device_quorum,
            quorum_tick_interval, quorum_tick_adaptive, trace, trace_out)
    plan = scenario.plan(seed, n)

    overrides = {**BASE_CONFIG, **scenario.config_overrides}
    if quorum_tick_interval > 0:
        overrides["QuorumTickInterval"] = quorum_tick_interval
        overrides["QuorumTickAdaptive"] = quorum_tick_adaptive
    if resident_depth > 1:
        overrides["ResidentTickDepth"] = resident_depth
    config = getConfig(overrides)
    saturating = scenario.workload_rate > 0
    if saturating and (quorum_tick_interval <= 0 or not device_quorum):
        # the admission queue only drains on the dispatch tick: running
        # a workload scenario per-message would fill the queue forever
        # and 'pass' without ever exercising the overload plane
        raise ValueError(
            f"scenario {scenario.name!r} drives a saturating workload "
            "and requires the tick-batched dispatch plane "
            "(device_quorum=True, quorum_tick_interval > 0)")
    pool = SimPool(n_nodes=n, seed=seed, config=config,
                   device_quorum=device_quorum, mesh=mesh,
                   host_eval=host_eval, trace=trace,
                   real_execution=scenario.real_execution,
                   bls=scenario.bls,
                   sign_requests=saturating,
                   num_instances=scenario.num_instances)
    generator = None
    if saturating:
        # the overload plane: a seeded profiled open-loop population
        # (flash crowds and all) submits through ADMISSION for the whole
        # fault arc; with IngressRetryMax armed the pool's retry driver
        # closes the loop on its sheds. Same seed as the fault plan, so
        # the storm replays with the run.
        from ..ingress.workload import (
            WorkloadGenerator,
            WorkloadProfile,
            WorkloadSpec,
        )

        wl_seq = [0]

        def _wl_write(client: int, key: int) -> None:
            wl_seq[0] += 1
            pool.submit_request(1_000_000 + wl_seq[0],
                               client_id="c%d" % client)

        generator = WorkloadGenerator(WorkloadSpec(
            n_clients=scenario.workload_clients,
            rate=scenario.workload_rate,
            duration=scenario.workload_duration,
            start=scenario.workload_start,
            seed=seed,
            profile=WorkloadProfile.from_config(
                scenario.workload_profile, config)))
        generator.start(pool.timer, _wl_write)
    checker = InvariantChecker(
        pool,
        byzantine=plan.byzantine_nodes,
        crashed=plan.crashed_forever_nodes)
    scheduler = FaultScheduler(
        pool, plan,
        safety_probe=checker.check_safety,
        probe_interval=probe_interval).install()

    # client traffic from t=0, plus a steady trickle across the fault
    # window so crashes/partitions hit in-flight ordering
    for i in range(scenario.initial_requests):
        pool.submit_request(i)
    for i in range(scenario.trickle_requests):
        pool.timer.schedule(
            (i + 1) * scenario.trickle_interval,
            lambda seq=scenario.initial_requests + i:
            pool.submit_request(seq))

    # catchup scenarios: snapshot each restarted victim's committed
    # ledger size at its restart instant — the leeched range starts
    # there, and the proof-read check reads from INSIDE it
    leech_floor: Dict[str, int] = {}
    if scenario.real_execution:
        from ..common.constants import DOMAIN_LEDGER_ID

        def _snap_floor(victim: str) -> None:
            node = pool.node(victim)
            if node.boot is not None:
                leech_floor[victim] = node.boot.db.get_ledger(
                    DOMAIN_LEDGER_ID).size

        for fault in plan.faults:
            if isinstance(fault, CrashFault) and fault.duration is not None:
                pool.timer.schedule(fault.at + fault.duration,
                                    lambda v=fault.node: _snap_floor(v))

    # run past the last bounded fault (and the workload window, for
    # overload scenarios), then let the pool settle
    horizon = max(scenario.run_seconds, plan.end_time + 5.0,
                  scenario.workload_start + scenario.workload_duration
                  + 5.0)
    pool.run_for(horizon)
    scheduler.stop_probe()

    results = checker.check_all(
        probes=3, liveness_timeout=scenario.liveness_timeout)
    # metrics snapshot before the closing checks: they serve extra reads
    # whose events belong to the checks, not the scenario's record
    metrics_summary = pool.metrics.summary()
    catchup_block = _catchup_block(pool, plan, scenario, leech_floor)
    results.extend(_catchup_verdicts(pool, plan, scenario, catchup_block))
    edge_block = _edge_block(pool, scenario, seed)
    results.extend(_edge_verdicts(scenario, edge_block))

    # overload robustness plane: the saturation forensic record — the
    # shed/retry fingerprints let the overload gate assert byte-
    # identical replays, the seeder meters prove the throttle engaged
    # while the pool kept ordering
    ingress_block: Dict[str, object] = {}
    if saturating and pool.admission is not None:
        adm = pool.admission
        ingress_block = {
            "profile": scenario.workload_profile,
            "workload": generator.counters(),
            "admission": adm.counters(),
            "shed_hash": adm.shed_hash(),
        }
        if pool.retry is not None:
            ingress_block["retry"] = pool.retry.counters()
            ingress_block["retry_hash"] = pool.retry.retry_hash()
        seeders = {
            nd.name: {"served_txns": nd.seeder.served_txns,
                      "deferred": nd.seeder.deferred_total}
            for nd in pool.nodes
            if getattr(nd, "seeder", None) is not None}
        if seeders:
            ingress_block["seeder_throttle"] = {
                "per_node": seeders,
                "served_txns": sum(seeders[n]["served_txns"]
                                   for n in sorted(seeders)),
                "deferred": sum(seeders[n]["deferred"]
                                for n in sorted(seeders)),
            }

    report = ChaosReport(
        scenario=scenario.name,
        seed=seed,
        n_nodes=n,
        dispatch_mode={
            "device_quorum": device_quorum,
            "tick": quorum_tick_interval,
            "adaptive": quorum_tick_adaptive,
            # the mesh SHAPE, chaos_run.py --mesh syntax ("4" = member
            # sharded, "2x2" = the 2-axis fabric): replay_command must
            # reproduce the exact grid, not just the device count
            "mesh": ("x".join(str(d) for d in mesh.devices.shape)
                     if mesh is not None else 0),
            "host_eval": host_eval,
            "trace": trace,
            "resident": resident_depth,
        },
        plan=plan.as_dicts(),
        trace=list(scheduler.trace),
        invariants=[r.as_dict() for r in results],
        expected_failures=list(scenario.expect_fail),
        network=pool.network.counters(),
        metrics=metrics_summary,
        ordered_per_node={nd.name: len(nd.ordered_digests)
                          for nd in pool.nodes},
        ordered_hash_per_node={
            nd.name: hashlib.sha256(
                "|".join(nd.ordered_digests).encode()).hexdigest()
            for nd in pool.nodes},
        monitor_per_node={
            nd.name: nd.monitor.snapshot() for nd in pool.nodes
            if getattr(nd, "monitor", None) is not None},
        catchup=catchup_block,
        ingress=ingress_block,
        edge=edge_block,
        byzantine_nodes=sorted(plan.byzantine_nodes),
        periodic_checks=len(scheduler.probe_results),
        first_violation=scheduler.first_violation,
        virtual_seconds=pool.timer.get_current_time()
        - 1_700_000_000.0,
    )
    if trace:
        # serialize the ring ONCE: the hash and the dump are the same
        # bytes by construction
        jsonl = pool.trace.to_jsonl()
        report.trace_hash = hashlib.sha256(jsonl.encode()).hexdigest()
        report.flight_recorder = [dict(d) for d in pool.trace.dumps]
        # causal request journeys: cross-node e2e latency with the
        # fault windows' measured cost (a journey that spans a fault
        # window shows the fault's latency price directly)
        from ..observability.causal import journey_summary

        report.journeys = journey_summary(pool.trace.events())
        if trace_out is not None:
            with open(trace_out, "w") as fh:
                fh.write(jsonl)
            report.trace_file = trace_out
    if out_path is not None:
        report.save(out_path)
    return report
