"""Pool-wide PBFT safety/liveness invariants over a simulated pool.

The checks are the Castro & Liskov (OSDI 1999) safety arguments turned
into executable assertions over the simulation pools
(:class:`~indy_plenum_tpu.simulation.pool.SimPool` or
:class:`~indy_plenum_tpu.simulation.node_pool.NodePool`):

- **agreement** — no two honest replicas commit different batch digests
  at the same ``(view, seqNo)`` (checked per seqNo across views too:
  execution order is total, so a seqNo maps to ONE batch pool-wide);
- **ordered_prefix** — honest executed-request logs are prefix-consistent
  (a lagging replica is a prefix of a leading one, never a fork);
- **ledger_roots** — honest replicas agree on the committed (Merkle)
  root at every common height, via the executor's memoized roots
  (:class:`SimExecutor`) or the real domain ledger under
  ``real_execution``;
- **liveness** — once active faults drop to ≤ f, newly submitted probe
  requests order on every reachable honest replica within a bounded
  amount of virtual time.

Byzantine nodes (known from the :class:`FaultPlan`) are excluded from the
honest set; crashed-forever nodes are exempt from liveness only.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, FrozenSet, Iterable, List, Optional

AGREEMENT = "agreement"
ORDERED_PREFIX = "ordered_prefix"
LEDGER_ROOTS = "ledger_roots"
LIVENESS = "liveness"
CROSS_LANE = "cross_lane"

SAFETY_INVARIANTS = (AGREEMENT, ORDERED_PREFIX, LEDGER_ROOTS)


@dataclass
class InvariantResult:
    name: str
    passed: bool
    detail: str = ""

    def as_dict(self) -> Dict[str, Any]:
        return {"name": self.name,
                "verdict": "PASS" if self.passed else "FAIL",
                "detail": self.detail}


class InvariantChecker:
    def __init__(self, pool: Any,
                 byzantine: Iterable[str] = (),
                 crashed: Iterable[str] = ()):
        self.pool = pool
        self.byzantine: FrozenSet[str] = frozenset(byzantine)
        self.crashed: FrozenSet[str] = frozenset(crashed)

    @property
    def honest_nodes(self) -> List[Any]:
        return [n for n in self.pool.nodes if n.name not in self.byzantine]

    # --- safety ---------------------------------------------------------

    def check_agreement(self) -> InvariantResult:
        # seqNo -> digest -> [node names]; batch digest is the PRE-PREPARE
        # digest every commit certificate voted on
        by_seq: Dict[int, Dict[str, List[str]]] = {}
        for node in self.honest_nodes:
            for o in node.ordered_log:
                digest = o.digest or "|".join(o.reqIdr)
                by_seq.setdefault(o.ppSeqNo, {}) \
                    .setdefault(digest, []).append(node.name)
        conflicts = [
            (seq, {d: names for d, names in digests.items()})
            for seq, digests in sorted(by_seq.items())
            if len(digests) > 1]
        if conflicts:
            seq, split = conflicts[0]
            return InvariantResult(
                AGREEMENT, False,
                f"honest replicas committed {len(split)} different batches "
                f"at seqNo {seq}: {split} "
                f"(+{len(conflicts) - 1} more conflicting seqNos)")
        return InvariantResult(
            AGREEMENT, True,
            f"{len(by_seq)} seqNos, single digest each across "
            f"{len(self.honest_nodes)} honest replicas")

    def _ordered_seq(self, node: Any) -> tuple:
        """One node's ordering fingerprint sequence. Real-execution nodes
        use the committed domain ledger's request-digest sequence: a node
        that CAUGHT UP across a GC'd window never saw the leeched range's
        ``Ordered`` events, but the fetched txns carry the original
        request digests — the ledger IS its ordering record, comparable
        bit-for-bit against the survivors. Executor-faked pools keep the
        ordered_log view."""
        if getattr(node, "boot", None) is not None \
                and hasattr(type(node), "committed_request_digests"):
            return tuple(node.committed_request_digests)
        return tuple(node.ordered_digests)

    def check_ordered_prefix(self) -> InvariantResult:
        logs = {n.name: self._ordered_seq(n)
                for n in self.honest_nodes}
        longest_name = max(logs, key=lambda name: len(logs[name]))
        longest = logs[longest_name]
        for name, log in logs.items():
            if log != longest[:len(log)]:
                split = next(i for i in range(min(len(log), len(longest)))
                             if log[i] != longest[i])
                return InvariantResult(
                    ORDERED_PREFIX, False,
                    f"{name} forks from {longest_name} at position {split}:"
                    f" {log[split]!r} != {longest[split]!r}")
        return InvariantResult(
            ORDERED_PREFIX, True,
            f"all honest logs are prefixes of {longest_name} "
            f"(len {len(longest)})")

    def _committed_roots(self, node: Any) -> Optional[Dict[int, Any]]:
        """seqNo -> committed root for whatever executor the node runs."""
        executor = getattr(node, "executor", None)
        roots = getattr(executor, "roots_by_seq", None)
        if roots is not None:
            return dict(roots)
        return None

    def check_ledger_roots(self) -> InvariantResult:
        honest = self.honest_nodes
        roots = {n.name: self._committed_roots(n) for n in honest}
        if any(r is None for r in roots.values()):
            # real execution: compare the domain ledger's committed merkle
            # root at the minimum common height
            from ..common.constants import DOMAIN_LEDGER_ID

            ledgers = {n.name: n.boot.db.get_ledger(DOMAIN_LEDGER_ID)
                       for n in honest}
            common = min(l.size for l in ledgers.values())
            at_common = {name: l.root_hash_at(common)
                         for name, l in ledgers.items()}
            if len(set(at_common.values())) > 1:
                return InvariantResult(
                    LEDGER_ROOTS, False,
                    f"domain ledger roots diverge at height {common}: "
                    f"{ {k: v.hex() for k, v in at_common.items()} }")
            return InvariantResult(
                LEDGER_ROOTS, True,
                f"domain ledger root equal across {len(honest)} honest "
                f"replicas at common height {common}")
        common_seqs = None
        for r in roots.values():
            common_seqs = set(r) if common_seqs is None else common_seqs & set(r)
        for seq in sorted(common_seqs or ()):
            at_seq = {name: r[seq] for name, r in roots.items()}
            if len(set(at_seq.values())) > 1:
                return InvariantResult(
                    LEDGER_ROOTS, False,
                    f"committed roots diverge at seqNo {seq}: {at_seq}")
        return InvariantResult(
            LEDGER_ROOTS, True,
            f"committed roots equal across {len(honest)} honest replicas "
            f"at {len(common_seqs or ())} common seqNos")

    def check_safety(self) -> List[InvariantResult]:
        return [self.check_agreement(),
                self.check_ordered_prefix(),
                self.check_ledger_roots()]

    # --- liveness -------------------------------------------------------

    def _submit_probe(self, seq: int) -> None:
        pool = self.pool
        if hasattr(pool, "submit_request"):  # SimPool
            pool.submit_request(seq)
            return
        # NodePool: a signed write submitted to one reachable honest node
        req = pool.make_nym_request(seq=seq)
        entry = next(n.name for n in self.pool.nodes
                     if n.name not in self.byzantine
                     and n.name not in self.crashed)
        pool.submit_to(entry, req)

    def check_liveness(self, probes: int = 3, timeout: float = 30.0,
                       probe_seq_base: int = 900_000) -> InvariantResult:
        """Submit fresh requests and require ordering progress on every
        honest, never-permanently-crashed replica within ``timeout``
        virtual seconds. Run this AFTER the plan's bounded faults ended
        (active faults ≤ f) — during a full partition no protocol can be
        live."""
        eligible = [n for n in self.honest_nodes
                    if n.name not in self.crashed]
        before = {n.name: len(n.ordered_digests) for n in eligible}
        for i in range(probes):
            self._submit_probe(probe_seq_base + i)
        waited = 0.0
        step = 1.0
        while waited < timeout:
            self.pool.run_for(step)
            waited += step
            if all(len(n.ordered_digests) >= before[n.name] + probes
                   for n in eligible):
                return InvariantResult(
                    LIVENESS, True,
                    f"{probes} probe requests ordered on all "
                    f"{len(eligible)} reachable honest replicas within "
                    f"{waited:.0f}s virtual")
        stuck = {n.name: len(n.ordered_digests) - before[n.name]
                 for n in eligible
                 if len(n.ordered_digests) < before[n.name] + probes}
        return InvariantResult(
            LIVENESS, False,
            f"ordering did not resume within {timeout:.0f}s virtual; "
            f"progress per stuck replica: {stuck}")

    def check_all(self, probes: int = 3,
                  liveness_timeout: float = 30.0) -> List[InvariantResult]:
        results = self.check_safety()
        results.append(self.check_liveness(probes=probes,
                                           timeout=liveness_timeout))
        # liveness mutates pool history (probe requests); re-verify safety
        # over the post-probe state so the final verdicts cover it
        results[:3] = self.check_safety()
        return results


# ----------------------------------------------------------------------
# ordering lanes (lanes/): cross-lane consistency + laned liveness
# ----------------------------------------------------------------------

def check_cross_lane(laned_pool) -> InvariantResult:
    """The barrier contract as an executable assertion over a
    :class:`~indy_plenum_tpu.lanes.pool.LanedPool`:

    1. **no lane commits past the seal** — every node's stable
       checkpoint window is at or below the barrier's sealed window;
    2. **bounded skew** — no lane's ordering ran more than ``LOG_SIZE``
       batches past the sealed boundary (the watermark stall the held
       stabilization produces);
    3. **fingerprint integrity** — the sealed-window chain recomputes
       bit-for-bit from the per-lane digests each seal folded.
    """
    import hashlib as _hashlib

    from ..lanes.barrier import GENESIS_FINGERPRINT

    barrier = laned_pool.barrier
    problems: List[str] = []
    for lane, lane_pool in enumerate(laned_pool.lane_pools):
        for node in lane_pool.nodes:
            stable_window = barrier.window_of(node.data.stable_checkpoint)
            if stable_window > barrier.sealed_window:
                problems.append(
                    f"lane {lane} {node.name} stabilized window "
                    f"{stable_window} past the seal "
                    f"({barrier.sealed_window})")
    bound = (barrier.sealed_window * barrier.chk_freq
             + laned_pool.config.LOG_SIZE)
    for lane, lane_pool in enumerate(laned_pool.lane_pools):
        for node in lane_pool.nodes:
            seq = node.data.last_ordered_3pc[1]
            if seq > bound:
                problems.append(
                    f"lane {lane} {node.name} ordered seq {seq} past "
                    f"the skew bound {bound} (sealed window "
                    f"{barrier.sealed_window} + LOG_SIZE)")
    # recompute the chain over the RETAINED windows (a bounded barrier
    # prunes old seal records; the oldest retained window's predecessor
    # fingerprint seeds the fold — GENESIS when nothing was pruned)
    start = min(barrier.seal_digests) if barrier.seal_digests else 1
    chain = GENESIS_FINGERPRINT if start == 1 \
        else barrier.fingerprints.get(start - 1)
    if chain is None:
        problems.append(
            f"retained chain has no seed fingerprint for window "
            f"{start - 1}")
        chain = GENESIS_FINGERPRINT
    for window in range(start, barrier.sealed_window + 1):
        digests = barrier.seal_digests.get(window)
        if digests is None or len(digests) != barrier.lanes:
            problems.append(f"window {window} has no seal record")
            continue
        chain = _hashlib.sha256(
            ("%s|%d|%s" % (chain, window,
                           "|".join(digests))).encode()).hexdigest()
        if barrier.fingerprints.get(window) != chain:
            problems.append(
                f"window {window} fingerprint does not recompute from "
                f"its per-lane digests")
    if barrier.sealed_window and chain != barrier.seal_fingerprint:
        problems.append("seal fingerprint chain tip mismatch")
    if problems:
        return InvariantResult(CROSS_LANE, False, "; ".join(problems[:4]))
    return InvariantResult(
        CROSS_LANE, True,
        f"{barrier.lanes} lanes, sealed window {barrier.sealed_window}, "
        f"chain tip {barrier.seal_fingerprint[:12]}…, no lane past the "
        f"seal or the skew bound")


def check_laned_safety(laned_pool) -> List[InvariantResult]:
    """Per-lane safety, aggregated per invariant (one result each, a
    failing lane named in the detail) + the cross-lane check — the
    laned scenarios' periodic safety probe."""
    aggregated: List[InvariantResult] = []
    per_lane = [InvariantChecker(lane_pool).check_safety()
                for lane_pool in laned_pool.lane_pools]
    for i, name in enumerate(SAFETY_INVARIANTS):
        bad = [(lane, results[i]) for lane, results in enumerate(per_lane)
               if not results[i].passed]
        if bad:
            lane, result = bad[0]
            aggregated.append(InvariantResult(
                name, False,
                f"lane {lane}: {result.detail}"
                + (f" (+{len(bad) - 1} more lanes)" if len(bad) > 1
                   else "")))
        else:
            aggregated.append(InvariantResult(
                name, True,
                f"holds in all {len(per_lane)} lanes"))
    aggregated.append(check_cross_lane(laned_pool))
    return aggregated


def _node_progress(node) -> int:
    """Per-node progress gauge for laned liveness: real-execution nodes
    count committed domain-ledger txns — a victim that recovered the
    probe range BY CATCHUP made progress even though the leeched middle
    never emitted ``Ordered`` (the ledger is its ordering record, same
    argument as :meth:`InvariantChecker._ordered_seq`)."""
    if getattr(node, "boot", None) is not None:
        from ..common.constants import DOMAIN_LEDGER_ID

        return node.boot.db.get_ledger(DOMAIN_LEDGER_ID).size
    return len(node.ordered_digests)


def check_laned_liveness(laned_pool, probes: int = 3,
                         timeout: float = 40.0,
                         probe_seq_base: int = 900_000) -> InvariantResult:
    """Targeted probes into EVERY lane (bypassing the router, so no lane
    can pass vacuously): each lane's every node must advance by all its
    probes within ``timeout`` virtual seconds. Probes double as the
    recovery trigger: a victim that fell behind a GC'd window needs
    peers to checkpoint PAST its high watermark before lag detection
    fires, and the probe traffic provides exactly that."""
    before = [[_node_progress(node) for node in lane_pool.nodes]
              for lane_pool in laned_pool.lane_pools]
    for lane in range(laned_pool.n_lanes):
        for i in range(probes):
            laned_pool.submit_to_lane(
                probe_seq_base + lane * probes + i, lane)

    def _done() -> bool:
        return all(
            _node_progress(node) >= before[lane][ni] + probes
            for lane, lane_pool in enumerate(laned_pool.lane_pools)
            for ni, node in enumerate(lane_pool.nodes))

    waited = 0.0
    while waited < timeout:
        laned_pool.run_for(1.0)
        waited += 1.0
        if _done():
            return InvariantResult(
                LIVENESS, True,
                f"{probes} probes per lane ordered on every node of all "
                f"{laned_pool.n_lanes} lanes within {waited:.0f}s virtual")
    stuck = {
        f"lane{lane}.{node.name}":
            _node_progress(node) - before[lane][ni]
        for lane, lane_pool in enumerate(laned_pool.lane_pools)
        for ni, node in enumerate(lane_pool.nodes)
        if _node_progress(node) < before[lane][ni] + probes}
    return InvariantResult(
        LIVENESS, False,
        f"laned ordering did not resume within {timeout:.0f}s virtual; "
        f"progress per stuck replica: {stuck}")
