"""Chaos plane: deterministic fault injection + pool-wide invariants.

The correctness-tooling layer for the RBFT simulation: seeded
:class:`FaultPlan` generation (:mod:`.scenarios`), compilation onto the
virtual clock (:mod:`.scheduler`), PBFT safety/liveness assertions
(:mod:`.invariants`) and replayable JSON reports (:mod:`.report`,
:mod:`.runner`, ``scripts/chaos_run.py``).
"""
from .faults import (  # noqa: F401
    ClockSkewFault,
    CorruptCatchupRepFault,
    CorruptOrderedLogFault,
    CrashFault,
    DelayFault,
    DropFault,
    DuplicateFault,
    EquivocateFault,
    Fault,
    FaultPlan,
    PartitionFault,
    ReorderFault,
    SilenceFault,
)
from .invariants import (  # noqa: F401
    AGREEMENT,
    LEDGER_ROOTS,
    LIVENESS,
    ORDERED_PREFIX,
    InvariantChecker,
    InvariantResult,
)
from .report import ChaosReport  # noqa: F401
from .runner import run_scenario  # noqa: F401
from .scenarios import SCENARIOS, Scenario, get_scenario  # noqa: F401
from .scheduler import FaultScheduler  # noqa: F401
