"""Key-value storage: uniform API over sqlite / in-memory backends.

Reference: storage/kv_store.py + storage/kv_store_leveldb.py /
kv_store_rocksdb.py / kv_in_memory.py and the ``initKeyValueStorage``
switch in storage/helper.py. This environment has no LevelDB/RocksDB
bindings; sqlite3 (stdlib, C-backed, crash-safe) is the durable backend and
preserves the same iteration/batch semantics. Keys and values are bytes;
iteration is byte-lexicographic as in LevelDB.
"""
from __future__ import annotations

import os
import sqlite3
from abc import ABC, abstractmethod
from typing import Iterable, Iterator, Optional, Tuple

from ..common.exceptions import StorageError


def _to_bytes(x) -> bytes:
    if isinstance(x, bytes):
        return x
    if isinstance(x, str):
        return x.encode()
    if isinstance(x, int):
        return str(x).encode()
    raise StorageError(f"unsupported key/value type {type(x)}")


class KeyValueStorage(ABC):
    @abstractmethod
    def get(self, key) -> bytes:
        """Raises KeyError when absent."""

    @abstractmethod
    def put(self, key, value) -> None:
        ...

    @abstractmethod
    def remove(self, key) -> None:
        ...

    @abstractmethod
    def iterator(self, start=None, end=None, include_value: bool = True
                 ) -> Iterator:
        """Byte-ordered iteration over [start, end] (inclusive bounds)."""

    @abstractmethod
    def do_batch(self, batch: Iterable[Tuple[bytes, Optional[bytes]]]) -> None:
        """Atomically apply (key, value) puts; value None means delete."""

    @abstractmethod
    def close(self) -> None:
        ...

    @abstractmethod
    def drop(self) -> None:
        ...

    @property
    @abstractmethod
    def size(self) -> int:
        ...

    def has_key(self, key) -> bool:
        try:
            self.get(key)
            return True
        except KeyError:
            return False

    # convenience
    def get_equal_or_none(self, key, default=None):
        try:
            return self.get(key)
        except KeyError:
            return default


class KeyValueStorageInMemory(KeyValueStorage):
    def __init__(self):
        self._dict: dict[bytes, bytes] = {}

    def get(self, key) -> bytes:
        return self._dict[_to_bytes(key)]

    def put(self, key, value) -> None:
        self._dict[_to_bytes(key)] = _to_bytes(value)

    def remove(self, key) -> None:
        self._dict.pop(_to_bytes(key), None)

    def iterator(self, start=None, end=None, include_value=True):
        start_b = _to_bytes(start) if start is not None else None
        end_b = _to_bytes(end) if end is not None else None
        for k in sorted(self._dict):
            if start_b is not None and k < start_b:
                continue
            if end_b is not None and k > end_b:
                break
            yield (k, self._dict[k]) if include_value else k

    def do_batch(self, batch):
        for k, v in batch:
            if v is None:
                self.remove(k)
            else:
                self.put(k, v)

    def close(self):
        pass

    def drop(self):
        self._dict.clear()

    @property
    def size(self) -> int:
        return len(self._dict)


class KeyValueStorageSqlite(KeyValueStorage):
    """Durable KV on sqlite3 (WAL mode): the RocksDB stand-in."""

    def __init__(self, db_dir: str, db_name: str):
        os.makedirs(db_dir, exist_ok=True)
        self._path = os.path.join(db_dir, db_name + ".sqlite")
        self._conn = sqlite3.connect(self._path)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB)")
        self._conn.commit()

    def get(self, key) -> bytes:
        row = self._conn.execute(
            "SELECT v FROM kv WHERE k = ?", (_to_bytes(key),)).fetchone()
        if row is None:
            raise KeyError(key)
        return row[0]

    def put(self, key, value) -> None:
        self._conn.execute(
            "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)",
            (_to_bytes(key), _to_bytes(value)))
        self._conn.commit()

    def remove(self, key) -> None:
        self._conn.execute("DELETE FROM kv WHERE k = ?", (_to_bytes(key),))
        self._conn.commit()

    def iterator(self, start=None, end=None, include_value=True):
        q = "SELECT k, v FROM kv"
        clauses, params = [], []
        if start is not None:
            clauses.append("k >= ?")
            params.append(_to_bytes(start))
        if end is not None:
            clauses.append("k <= ?")
            params.append(_to_bytes(end))
        if clauses:
            q += " WHERE " + " AND ".join(clauses)
        q += " ORDER BY k"
        for k, v in self._conn.execute(q, params):
            yield (bytes(k), bytes(v)) if include_value else bytes(k)

    def do_batch(self, batch):
        cur = self._conn.cursor()
        try:
            for k, v in batch:
                if v is None:
                    cur.execute("DELETE FROM kv WHERE k = ?", (_to_bytes(k),))
                else:
                    cur.execute(
                        "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)",
                        (_to_bytes(k), _to_bytes(v)))
            self._conn.commit()
        except Exception:
            self._conn.rollback()
            raise

    def close(self):
        self._conn.close()

    def drop(self):
        self._conn.execute("DELETE FROM kv")
        self._conn.commit()

    @property
    def size(self) -> int:
        return self._conn.execute("SELECT COUNT(*) FROM kv").fetchone()[0]


def initKeyValueStorage(storage_type: str, data_dir: str, name: str
                        ) -> KeyValueStorage:
    """Reference: storage/helper.py initKeyValueStorage switch."""
    if storage_type == "memory":
        return KeyValueStorageInMemory()
    if storage_type == "sqlite":
        return KeyValueStorageSqlite(data_dir, name)
    if storage_type == "chunked_file":
        from .file_stores import ChunkedFileStore

        return ChunkedFileStore(data_dir, name)
    if storage_type == "text_file":
        from .file_stores import TextFileStore

        return TextFileStore(data_dir, name)
    raise StorageError(f"unknown storage type {storage_type}")
