"""Replay protection: request digest -> committed (ledger_id, seq_no).

Reference: plenum/persistence/req_id_to_txn.py (`ReqIdrToTxn`). Every
executed request is recorded under BOTH its full digest and its
signature-independent payload digest; a re-submitted request (same payload,
same or different signature) is detected at ingress and rejected with a
pointer to the already-committed txn instead of being re-ordered and
re-executed.
"""
from __future__ import annotations

from typing import Optional, Tuple

from .kv_store import KeyValueStorage, KeyValueStorageInMemory


class ReqIdrToTxn:
    def __init__(self, store: Optional[KeyValueStorage] = None):
        self._store = store or KeyValueStorageInMemory()

    @staticmethod
    def _val(ledger_id: int, seq_no: int) -> bytes:
        return f"{ledger_id}~{seq_no}".encode()

    def add(self, digest: str, payload_digest: str,
            ledger_id: int, seq_no: int) -> None:
        val = self._val(ledger_id, seq_no)
        self._store.put(b"d:" + digest.encode(), val)
        self._store.put(b"p:" + payload_digest.encode(), val)

    def _get(self, key: bytes) -> Optional[Tuple[int, int]]:
        try:
            raw = self._store.get(key)
        except KeyError:
            return None
        if raw is None:
            return None
        lid, seq = raw.decode().split("~")
        return int(lid), int(seq)

    def get(self, digest: str) -> Optional[Tuple[int, int]]:
        return self._get(b"d:" + digest.encode())

    def get_by_payload_digest(self, payload_digest: str
                              ) -> Optional[Tuple[int, int]]:
        return self._get(b"p:" + payload_digest.encode())
