"""File-backed stores: the reference's legacy ledger storage family.

Reference: storage/text_file_store.py (`TextFileStore`) and
storage/chunked_file_store.py (`ChunkedFileStore`) — plenum's original
ledger persistence before the KV backends. Re-implemented against this
package's :class:`KeyValueStorage` API so a
:class:`~indy_plenum_tpu.ledger.ledger.Ledger` can run directly on a
chunked file store (reachable through
``initKeyValueStorage(config.LedgerStorageType, ...)``), and a human can
still inspect a validator's txn log with ``less``.

- :class:`TextFileStore`: append-only ``key<TAB>value`` hex lines with a
  rebuilt in-memory index; removals append tombstones; ``compact()``
  rewrites the live set.
- :class:`ChunkedFileStore`: integer-keyed append-only log split across
  fixed-size chunk files (the ledger txn shape: monotonically appended,
  truncated only from the tail by catchup's ``reset_to``).
"""
from __future__ import annotations

import os
from typing import Dict, Iterable, Iterator, Optional, Tuple

from .kv_store import KeyValueStorage, _to_bytes


class TextFileStore(KeyValueStorage):
    """Line-per-record KV store; the whole history is a readable file."""

    def __init__(self, db_dir: str, db_name: str):
        os.makedirs(db_dir, exist_ok=True)
        self._path = os.path.join(db_dir, db_name + ".txt")
        self._index: Dict[bytes, bytes] = {}
        if os.path.exists(self._path):
            with open(self._path) as fh:
                for line in fh:
                    line = line.rstrip("\n")
                    if not line:
                        continue
                    key_hex, _, value_hex = line.partition("\t")
                    key = bytes.fromhex(key_hex)
                    if value_hex == "-":  # tombstone
                        self._index.pop(key, None)
                    else:
                        self._index[key] = bytes.fromhex(value_hex)
        self._fh = open(self._path, "a")

    def _append(self, key: bytes, value: Optional[bytes]) -> None:
        self._fh.write(
            f"{key.hex()}\t{'-' if value is None else value.hex()}\n")

    def get(self, key) -> bytes:
        return self._index[bytes(_to_bytes(key))]

    def put(self, key, value) -> None:
        key, value = bytes(_to_bytes(key)), bytes(_to_bytes(value))
        self._index[key] = value
        self._append(key, value)
        self._fh.flush()

    def remove(self, key) -> None:
        key = bytes(_to_bytes(key))
        self._index.pop(key, None)
        self._append(key, None)
        self._fh.flush()

    def iterator(self, start=None, end=None, include_value: bool = True
                 ) -> Iterator:
        lo = bytes(_to_bytes(start)) if start is not None else None
        hi = bytes(_to_bytes(end)) if end is not None else None
        for key in sorted(self._index):
            if lo is not None and key < lo:
                continue
            if hi is not None and key > hi:
                break  # keys are sorted: nothing later can be in range
            yield (key, self._index[key]) if include_value else key

    def do_batch(self, batch: Iterable[Tuple[bytes, Optional[bytes]]]
                 ) -> None:
        # convert the WHOLE batch before mutating anything: a bad entry
        # mid-batch must not leave earlier entries applied (the KV
        # contract's atomicity)
        entries = [(bytes(_to_bytes(key)),
                    None if value is None else bytes(_to_bytes(value)))
                   for key, value in batch]
        for key, value in entries:
            if value is None:
                self._index.pop(key, None)
            else:
                self._index[key] = value
            self._append(key, value)
        self._fh.flush()

    def compact(self) -> None:
        """Rewrite the file with only live records (tombstone GC). A
        failed rewrite (disk full) leaves the original file intact and
        the store usable."""
        self._fh.close()
        tmp = self._path + ".compact"
        try:
            with open(tmp, "w") as fh:
                for key in sorted(self._index):
                    fh.write(f"{key.hex()}\t{self._index[key].hex()}\n")
            os.replace(tmp, self._path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
            self._fh = open(self._path, "a")

    def close(self) -> None:
        self._fh.close()

    def drop(self) -> None:
        self.close()
        if os.path.exists(self._path):
            os.unlink(self._path)
        self._index.clear()
        self._fh = open(self._path, "a")

    @property
    def size(self) -> int:
        return len(self._index)


class ChunkedFileStore(KeyValueStorage):
    """Append-only integer-keyed log over fixed-size chunk files.

    Keys are 8-byte big-endian integers (the Ledger's seqNo keys). Writes
    must arrive in append order; removal is tail-only (``reset_to``'s
    truncation shape) — both enforced, because silent out-of-order writes
    would corrupt the chunk arithmetic.
    """

    def __init__(self, db_dir: str, db_name: str, chunk_size: int = 1000):
        if chunk_size <= 0:
            raise ValueError("chunk_size must be positive")
        self._dir = os.path.join(db_dir, db_name)
        os.makedirs(self._dir, exist_ok=True)
        # chunk_size is part of the ON-DISK layout: reopening with a
        # different value would silently corrupt the seq->chunk
        # arithmetic, so the persisted value always wins and the ctor
        # argument only seeds NEW stores
        meta = os.path.join(self._dir, "chunk_size")
        if os.path.exists(meta):
            with open(meta) as fh:
                raw = fh.read().strip()
            persisted = int(raw) if raw.isdigit() else 0
            if persisted <= 0:
                raise ValueError(
                    f"corrupt chunk_size meta at {meta!r}: {raw!r}")
            chunk_size = persisted
        else:
            # tmp+rename: a crash mid-write must not leave a truncated
            # meta that bricks every future open
            with open(meta + ".tmp", "w") as fh:
                fh.write(str(chunk_size))
            os.replace(meta + ".tmp", meta)
        self._chunk_size = chunk_size
        # chunk i holds entries [i*chunk_size + 1, (i+1)*chunk_size]
        self._chunks: Dict[int, list] = {}
        self._count = 0
        for name in sorted(os.listdir(self._dir)):
            if not name.endswith(".chunk"):
                continue
            idx = int(name.split(".")[0])
            with open(os.path.join(self._dir, name)) as fh:
                lines = [bytes.fromhex(line.strip())
                         for line in fh if line.strip()]
            self._chunks[idx] = lines
        self._count = sum(len(c) for c in self._chunks.values())

    @staticmethod
    def _seq(key) -> int:
        if isinstance(key, int):
            return key
        return int.from_bytes(_to_bytes(key), "big")

    def _chunk_path(self, idx: int) -> str:
        return os.path.join(self._dir, f"{idx:06d}.chunk")

    def _persist_chunk(self, idx: int) -> None:
        tmp = self._chunk_path(idx) + ".tmp"
        with open(tmp, "w") as fh:
            for value in self._chunks.get(idx, []):
                fh.write(value.hex() + "\n")
        os.replace(tmp, self._chunk_path(idx))

    def get(self, key) -> bytes:
        seq = self._seq(key)
        if not 1 <= seq <= self._count:
            raise KeyError(key)
        idx, off = divmod(seq - 1, self._chunk_size)
        return self._chunks[idx][off]

    def _append_line(self, idx: int, value: bytes) -> None:
        """Append path: ONE line written, not a chunk rewrite — catchup
        replays txns one Ledger.add at a time, and rewriting ~chunk_size/2
        lines per append would make a 1M-txn sync quadratic in disk IO."""
        with open(self._chunk_path(idx), "a") as fh:
            fh.write(value.hex() + "\n")

    def put(self, key, value) -> None:
        seq = self._seq(key)
        value = bytes(_to_bytes(value))
        idx, off = divmod(seq - 1, self._chunk_size)
        if seq == self._count:  # idempotent last-entry overwrite
            self._chunks[idx][off] = value
            self._persist_chunk(idx)
        elif seq == self._count + 1:
            self._chunks.setdefault(idx, []).append(value)
            self._count = seq
            self._append_line(idx, value)
        else:
            raise ValueError(
                f"append-only: next key is {self._count + 1}, got {seq}")

    def remove(self, key) -> None:
        seq = self._seq(key)
        if seq != self._count:
            raise ValueError(
                f"tail-only removal: last key is {self._count}, got {seq}")
        idx, off = divmod(seq - 1, self._chunk_size)
        del self._chunks[idx][off]
        if not self._chunks[idx]:
            del self._chunks[idx]
            path = self._chunk_path(idx)
            if os.path.exists(path):
                os.unlink(path)
        else:
            self._persist_chunk(idx)
        self._count -= 1

    def iterator(self, start=None, end=None, include_value: bool = True
                 ) -> Iterator:
        lo = self._seq(start) if start is not None else 1
        hi = self._seq(end) if end is not None else self._count
        for seq in range(max(1, lo), min(self._count, hi) + 1):
            key = seq.to_bytes(8, "big")
            yield (key, self.get(key)) if include_value else key

    def do_batch(self, batch: Iterable[Tuple[bytes, Optional[bytes]]]
                 ) -> None:
        """Validate-then-apply: the whole batch is checked against the
        append/tail discipline BEFORE any mutation, so an invalid batch
        raises with memory and disk untouched (the atomicity the KV
        contract promises — per-chunk writes are individually atomic via
        tmp+rename; a mid-batch IO failure can still leave earlier chunks
        newer than later ones, same as any non-journaled file store)."""
        entries = []
        simulated = self._count
        for key, value in batch:
            seq = self._seq(key)
            if value is None:
                if seq != simulated:
                    raise ValueError(
                        f"tail-only removal: last key is {simulated}, "
                        f"got {seq}")
                simulated -= 1
                entries.append((seq, None))
            else:
                if seq not in (simulated, simulated + 1):
                    raise ValueError(
                        f"append-only: next key is {simulated + 1}, "
                        f"got {seq}")
                simulated = max(simulated, seq)
                entries.append((seq, bytes(_to_bytes(value))))
        touched = set()
        for seq, value in entries:
            idx, off = divmod(seq - 1, self._chunk_size)
            if value is None:
                del self._chunks[idx][off]
                if not self._chunks[idx]:
                    del self._chunks[idx]
                self._count -= 1
            elif seq == self._count:
                self._chunks[idx][off] = value
            else:
                self._chunks.setdefault(idx, []).append(value)
                self._count = seq
            touched.add(idx)
        for idx in touched:
            if idx in self._chunks:
                self._persist_chunk(idx)
            else:
                path = self._chunk_path(idx)
                if os.path.exists(path):
                    os.unlink(path)

    def close(self) -> None:
        pass  # chunks are persisted on every mutation

    def drop(self) -> None:
        for idx in list(self._chunks):
            path = self._chunk_path(idx)
            if os.path.exists(path):
                os.unlink(path)
        self._chunks.clear()
        self._count = 0
        # the layout parameter belongs to the DATA; with the data gone a
        # later store over this directory must get its own chunk_size
        meta = os.path.join(self._dir, "chunk_size")
        if os.path.exists(meta):
            os.unlink(meta)

    @property
    def size(self) -> int:
        return self._count
