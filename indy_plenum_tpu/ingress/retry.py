"""Closed-loop client retry: shed/NACKed requests come BACK.

The ingress plane's shed law (admission.py) models the pool's side of
overload; this module models the CLIENTS' side — the part that makes
real overload compound. An open-loop generator walks away from a shed
request; a real wallet retries it, which re-offers exactly when the pool
is weakest (RBFT's robustness claim is about sustained misbehaviour, and
a retry storm is sustained load the pool itself manufactured). PR 6's
saturation story was open-loop only; :class:`RetryPolicy` +
:class:`RetryDriver` close the loop.

:class:`RetryPolicy` mirrors the catchup plane's
:class:`~indy_plenum_tpu.server.catchup.retry.RetryLaw` shape — seeded
exponential backoff with per-key sha256 jitter and a max-attempts budget
— so both retry laws in the system read the same way and replay the same
way: every delay is a pure function of (seed, digest, attempt), no
shared RNG state, and exhaustion fails CLOSED (the request is abandoned
and counted, never re-asked forever).

:class:`RetryDriver` runs the loop on the pool's virtual timer: the
admission drain hands it each tick's sheds, it schedules seeded-backoff
re-offers, and every re-offer re-enters admission like any arrival —
counting against the per-client fairness cap (no retry-based cap
evasion) and competing in the same-instant shed cohort. Observability
mirrors the shed law's: ``req.retry`` trace marks (the ``retry`` hop in
causal journeys), ``ingress.retries`` / ``ingress.retry_exhausted``
metrics, and :meth:`RetryDriver.retry_hash` — a canonical fingerprint
over the (digest, attempt) retry set, byte-identical per seed exactly
like ``shed_hash``.
"""
from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, List, Optional

from ..server.catchup.retry import RetryLaw


class RetryPolicy(RetryLaw):
    """Seeded, deterministic per-request exponential backoff + budget —
    the catchup :class:`RetryLaw` itself (delay / jitter / exhaustion
    are INHERITED, so the two laws can never silently diverge), with
    the ingress knob surface and a client-flavoured budget name:
    ``max_attempts`` sheds and the client gives up (fail closed).

    Delay after the ``attempt``-th shed (1-based):

        base * mult^(attempt-1), capped at ``max_delay``, stretched by a
        seeded jitter in [0, jitter_frac] of itself — sha256 over
        ``seed|digest|attempt`` drives the stretch, so a shed cohort's
        retries desynchronize instead of re-thundering as one wave.
    """

    def __init__(self, base: float, mult: float = 2.0,
                 max_delay: float = 30.0, jitter_frac: float = 0.5,
                 seed: int = 0, max_attempts: int = 3):
        if max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1: {max_attempts}")
        super().__init__(base=base, mult=mult, max_delay=max_delay,
                         jitter_frac=jitter_frac, seed=seed,
                         max_retries=max_attempts)

    @property
    def max_attempts(self) -> int:
        return self.max_retries

    @classmethod
    def from_config(cls, config, seed: int = 0) -> "RetryPolicy":
        """The ``IngressRetry*`` knob surface; ``seed`` defaults to the
        pool seed (simulation) so the retry schedule replays with the
        run, mirroring the admission tiebreak's seeding."""
        return cls(base=config.IngressRetryBase,
                   mult=config.IngressRetryBackoffMult,
                   max_delay=config.IngressRetryBackoffMax,
                   jitter_frac=config.IngressRetryJitterFrac,
                   seed=seed,
                   max_attempts=config.IngressRetryMax)


class RetryDriver:
    """The closed loop: sheds in, seeded-backoff re-offers out.

    ``resubmit(req, client_id)`` is the injected re-offer path (the
    pool's admission offer — a re-offered request is an arrival like any
    other). All scheduling rides the injected virtual ``timer``, so the
    storm replays byte-for-byte per seed.
    """

    def __init__(self, policy: RetryPolicy, timer,
                 resubmit: Callable[[Any, Optional[str]], None],
                 metrics=None, trace=None):
        from ..common.metrics_collector import NullMetricsCollector
        from ..observability.trace import NULL_TRACE

        self.policy = policy
        self._timer = timer
        self._resubmit = resubmit
        self.metrics = metrics if metrics is not None \
            else NullMetricsCollector()
        self.trace = trace if trace is not None else NULL_TRACE
        self._attempts: Dict[str, int] = {}  # digest -> sheds seen
        self.outstanding = 0  # scheduled re-offers not yet fired
        self.reoffers_total = 0
        self.exhausted_total = 0
        self.retried_digests: set = set()
        # the run's retry fingerprint entries: "digest|attempt" per
        # re-offer actually scheduled
        self._records: List[str] = []

    def sized_resources(self, prefix: str = "retry."):
        """Resource-ledger registration (observability.telemetry): the
        live cohort (attempt counters + scheduled re-offers). The
        ``_records``/``retried_digests`` fingerprint spines are run-long
        by design and stay off the ledger."""
        from ..observability.telemetry import SizedResource

        return (
            SizedResource(prefix + "attempts",
                          lambda: len(self._attempts),
                          bound=None, entry_bytes=96),
            SizedResource(prefix + "outstanding",
                          lambda: self.outstanding,
                          bound=None, entry_bytes=512),
        )

    # ------------------------------------------------------------------

    def on_shed(self, req: Any, client_id: Optional[str],
                reason: str) -> None:
        """One shed (or NACK) from the drain: schedule the seeded
        re-offer, or give up once the budget is spent."""
        from ..common.metrics_collector import MetricsName

        digest = req.digest
        attempt = self._attempts.get(digest, 0) + 1
        self._attempts[digest] = attempt
        if self.policy.exhausted(attempt):
            self.exhausted_total += 1
            self.metrics.add_event(MetricsName.INGRESS_RETRY_EXHAUSTED)
            if self.trace.enabled:
                self.trace.record("req.retry_exhausted", cat="req",
                                  key=(digest,),
                                  args={"attempts": attempt - 1,
                                        "reason": reason})
            return
        delay = self.policy.delay(digest, attempt)
        self.outstanding += 1
        self._records.append("%s|%d" % (digest, attempt))
        self._timer.schedule(
            delay, lambda: self._fire(req, client_id, attempt))

    def _fire(self, req: Any, client_id: Optional[str],
              attempt: int) -> None:
        from ..common.metrics_collector import MetricsName

        self.outstanding -= 1
        self.reoffers_total += 1
        self.retried_digests.add(req.digest)
        self.metrics.add_event(MetricsName.INGRESS_RETRIES)
        if self.trace.enabled:
            # the journey's ``retry`` hop closes at the LAST of these
            # marks: first shed -> final re-offer is the client's whole
            # backoff wait
            self.trace.record("req.retry", cat="req", key=(req.digest,),
                              args={"attempt": attempt})
        self._resubmit(req, client_id)

    # ------------------------------------------------------------------

    def retry_hash(self) -> str:
        """sha256 over the SORTED ``digest|attempt`` re-offer records —
        THE retry-storm fingerprint (canonical set hash like
        ``shed_hash``: independent of the timer-heap pop order within an
        instant, byte-identical per seed)."""
        return hashlib.sha256(
            "|".join(sorted(self._records)).encode()).hexdigest()

    def counters(self) -> Dict[str, int]:
        return {
            "reoffers": self.reoffers_total,
            "exhausted": self.exhausted_total,
            "outstanding": self.outstanding,
            "requests_retried": len(self.retried_digests),
        }
