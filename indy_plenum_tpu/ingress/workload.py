"""Open-loop workload generator: a seeded virtual client population.

RBFT and PBFT both evaluate under sustained client load; plenum's pools
face the open-loop version — millions of independent wallets whose
arrival rate does not slow down because the pool is busy. This module
models that population WITHOUT instantiating it: clients exist as a
Zipf-skewed index space (client ``0`` is the hottest wallet) and keys as
a second Zipf space (hot NYM/attrib targets), both sampled per arrival
from one seeded RNG. Arrival times are a seeded Poisson process.

:class:`WorkloadProfile` modulates that process: real load is not flat —
wallets follow the sun (diurnal curves) and pile onto events (flash
crowds). The profile is a pure piecewise function of VIRTUAL time since
the window opened (no wall clock, no extra RNG draws), scaling the
instantaneous Poisson rate, so a profiled run replays byte-identically
exactly like a steady one.

Everything rides the pool's virtual clock: the generator schedules ONE
timer event at a time (each arrival schedules its successor), so the
timer heap stays O(1) no matter how many arrivals the run produces, and
a seeded run is replay-identical — same arrival instants, same clients,
same keys, same read/write choices. That determinism is what lets the
admission plane's shed set and the pool's ``ordered_hash``/``trace_hash``
be compared byte-for-byte across runs (tests/test_ingress.py).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Optional

PROFILE_KINDS = ("steady", "diurnal", "flash")


@dataclass(frozen=True)
class WorkloadProfile:
    """Seeded-replayable rate modulation: ``multiplier(t)`` scales the
    base arrival rate as a pure function of virtual seconds since the
    arrival window opened.

    - ``steady`` — the identity (multiplier 1.0 everywhere): profiled
      and unprofiled runs are bit-identical;
    - ``diurnal`` — a raised-cosine day curve between ``trough`` and
      ``peak`` with period ``period`` (the window opens at the trough:
      load ramps up, crests mid-period, ramps back down);
    - ``flash`` — baseline 1.0 with a ``peak``-multiplier crowd spike on
      ``[flash_at, flash_at + flash_duration)`` — the retry-storm arm's
      overload trigger (bench ``saturation``, the ``overload_gate``).
    """

    kind: str = "steady"
    period: float = 20.0
    trough: float = 0.5
    peak: float = 3.0
    flash_at: float = 0.0
    flash_duration: float = 2.0

    def __post_init__(self):
        if self.kind not in PROFILE_KINDS:
            raise ValueError(
                f"unknown profile kind {self.kind!r}; "
                f"known: {', '.join(PROFILE_KINDS)}")
        # validate only what the declared kind reads: from_config passes
        # every WorkloadProfile* knob through, and a config tuned for
        # one kind (e.g. FlashDuration=0 meaning "no flash") must not
        # break a steady/diurnal run that never evaluates it
        if self.kind == "diurnal":
            if self.period <= 0:
                raise ValueError("period must be positive")
            if self.trough <= 0 or self.peak <= 0:
                raise ValueError(
                    "trough and peak multipliers must be positive")
        elif self.kind == "flash":
            if self.flash_duration <= 0:
                raise ValueError("flash_duration must be positive")
            if self.peak <= 0:
                raise ValueError("peak multiplier must be positive")

    @classmethod
    def from_config(cls, kind: str, config) -> "WorkloadProfile":
        """Profile shape from the ``WorkloadProfile*`` config knobs (the
        scripted drivers and the chaos runner share one knob surface)."""
        return cls(kind=kind,
                   period=config.WorkloadProfilePeriod,
                   trough=config.WorkloadProfileTrough,
                   peak=config.WorkloadProfilePeak,
                   flash_at=config.WorkloadProfileFlashAt,
                   flash_duration=config.WorkloadProfileFlashDuration)

    def multiplier(self, t: float) -> float:
        """Rate multiplier at ``t`` virtual seconds into the window."""
        if self.kind == "diurnal":
            # raised cosine from the trough: trough at t=0 and t=period,
            # peak at t=period/2 — continuous, so the arrival chain's
            # gap math never sees a step it could amplify
            phase = 0.5 * (1.0 - math.cos(
                2.0 * math.pi * (t / self.period)))
            return self.trough + (self.peak - self.trough) * phase
        if self.kind == "flash":
            in_spike = self.flash_at <= t < self.flash_at \
                + self.flash_duration
            return self.peak if in_spike else 1.0
        return 1.0


@dataclass(frozen=True)
class WorkloadSpec:
    """One client population. ``rate`` is arrivals per SIM second (open
    loop — arrivals never wait for completions); ``read_fraction`` of
    arrivals are state reads against the hot-key space; the Zipf
    exponents (> 1) skew per-client activity and key popularity."""

    n_clients: int = 1_000_000
    rate: float = 100.0
    duration: float = 30.0
    start: float = 0.0
    read_fraction: float = 0.0
    zipf_clients: float = 1.1
    zipf_keys: float = 1.2
    n_keys: int = 4096
    seed: int = 0
    # rate modulation (None = steady: bit-identical to the pre-profile
    # generator — the arrival chain consumes the same RNG draws)
    profile: Optional[WorkloadProfile] = None

    def __post_init__(self):
        if self.rate <= 0 or self.duration <= 0:
            raise ValueError("rate and duration must be positive")
        if not (0.0 <= self.read_fraction <= 1.0):
            raise ValueError("read_fraction must be in [0, 1]")
        if self.zipf_clients <= 1.0 or self.zipf_keys <= 1.0:
            raise ValueError("zipf exponents must be > 1")


class WorkloadGenerator:
    """Schedules the population's arrivals onto an injected timer.

    ``on_write(client_idx, key_idx)`` / ``on_read(client_idx, key_idx)``
    fire at each arrival instant. The generator is single-use: one
    :meth:`start` per instance (the RNG stream is the identity of the
    run).
    """

    def __init__(self, spec: WorkloadSpec):
        import numpy as np

        self.spec = spec
        self._rng = np.random.RandomState(spec.seed)
        self._started = False
        self._stopped = False
        self.arrivals = 0
        self.writes = 0
        self.reads = 0

    # ------------------------------------------------------------------

    def _zipf_index(self, exponent: float, n: int) -> int:
        """Zipf-distributed index in [0, n): unbounded Zipf draw folded
        into the population (rank 0 is the hottest; the fold keeps the
        head's skew intact because draws beyond ``n`` are rare)."""
        return int(self._rng.zipf(exponent) - 1) % n

    def stop(self) -> None:
        """Cancel future arrivals (the pending timer event fires as a
        no-op). Counters keep their values."""
        self._stopped = True

    def start(self, timer,
              on_write: Callable[[int, int], None],
              on_read: Optional[Callable[[int, int], None]] = None) -> None:
        """Begin the open-loop arrival chain on ``timer``. Arrivals run
        from ``spec.start`` (relative to the timer's clock) until the
        first gap past ``spec.start + spec.duration``; read arrivals are
        DROPPED when no ``on_read`` is wired — the RNG draws are still
        consumed, so a reads-served and a reads-dropped run submit the
        IDENTICAL write sequence (the bench's no-reads comparison arm
        relies on it)."""
        if self._started:
            raise RuntimeError("generator already started")
        self._started = True
        spec = self.spec
        # the window is RELATIVE to the timer's clock at start() —
        # simulation pools begin at an epoch-like instant, and the
        # generator must not care
        begin = timer.get_current_time() + spec.start
        end = begin + spec.duration
        rng = self._rng

        def fire() -> None:
            if self._stopped:
                return
            client = self._zipf_index(spec.zipf_clients, spec.n_clients)
            key = self._zipf_index(spec.zipf_keys, spec.n_keys)
            is_read = (spec.read_fraction > 0.0
                       and rng.random_sample() < spec.read_fraction)
            self.arrivals += 1
            if is_read:
                self.reads += 1
                if on_read is not None:
                    on_read(client, key)
            else:
                self.writes += 1
                on_write(client, key)
            schedule_next()

        profile = spec.profile

        def rate_now() -> float:
            # piecewise-constant thinning-free modulation: the NEXT gap
            # is drawn at the instantaneous profiled rate — a pure
            # function of virtual time, so the RNG stream (and therefore
            # the replay) depends only on (seed, profile), and a steady
            # profile consumes the identical draws as no profile at all
            if profile is None:
                return spec.rate
            return spec.rate * profile.multiplier(
                timer.get_current_time() - begin)

        def schedule_next() -> None:
            gap = float(rng.exponential(1.0 / rate_now()))
            due = timer.get_current_time() + gap
            if due > end:
                return
            timer.schedule(gap, fire)

        rate0 = spec.rate if profile is None \
            else spec.rate * profile.multiplier(0.0)
        first_gap = float(rng.exponential(1.0 / rate0))
        first = begin + first_gap
        if first <= end:
            timer.schedule(
                max(first - timer.get_current_time(), 0.0), fire)

    # ------------------------------------------------------------------

    def counters(self) -> dict:
        return {"arrivals": self.arrivals, "writes": self.writes,
                "reads": self.reads}
