"""Device-proof read path: state reads that never touch the 3PC plane.

Plenum serves client reads the same way: one node answers from its
committed state with proof material (root + path + pool signature) that
makes a single answer trustworthy — reads ride no agreement round
(PBFT §"read-only operations", Castro & Liskov 1999). Here the proof
material is an RFC 6962 audit path against the serving ledger's root,
and the node VERIFIES what it hands out using the batched device
audit-proof kernel (the catchup kernel, ~170k proofs/sec device-side,
BENCH_r05) — one device dispatch covers a whole drain's worth of reads.

Contract (asserted by bench.py's ``saturation`` sub-bench and
tests/test_ingress.py):

- **zero 3PC involvement**: the service holds no reference to the vote
  plane; serving reads changes neither ``vote_group.flushes`` nor
  ``ordered_hash`` on the same seed;
- reads are answered against a SNAPSHOT ``(tree_size, root)`` captured
  at construction / :meth:`ReadService.refresh`, so a proof never
  straddles a root that moved mid-batch;
- per-drain batched verification: the whole batch rides ONE
  :func:`~indy_plenum_tpu.server.catchup.catchup_rep_service
  .verify_audit_paths_batch` call. The default ``mode="auto"`` consults
  the catchup plane's MEASURED offload policy: the device kernel where
  it wins (real TPU), the scalar SHA-NI loop where the link makes the
  kernel a tax (CPU drivers) — same proofs, same verdicts either way.

Backings adapt proof sources: :class:`LedgerBacking` serves a live
ledger's committed txns (GET_TXN-style); :class:`StaticCorpusBacking`
builds a seeded NYM/attrib corpus for workload benches where the read
universe is the generator's hot-key space.
"""
from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple


@dataclass
class ProofRead:
    """One answered read: leaf bytes + the proof that they are in the
    tree identified by ``root`` at ``tree_size``. With the state-proof
    plane attached, ``multi_sig`` carries the pool's BLS co-signature
    over that root (participants ride inside the dict) and ``window``
    the stabilized checkpoint window it was captured at — a client
    holding only the pool's BLS keys verifies the whole reply via
    :func:`indy_plenum_tpu.client.state_proof.verify_proved_read`."""

    index: int
    leaf: bytes
    root: bytes
    path: List[bytes]
    tree_size: int
    verified: bool
    multi_sig: Optional[dict] = None
    window: Optional[Tuple[int, int]] = None


class StaticCorpusBacking:
    """A seeded read corpus: ``n_keys`` deterministic NYM-record leaves
    in a compact Merkle tree. Audit paths are cached per index — Zipf
    read traffic concentrates on the head, so the cache hits almost
    always after warm-up."""

    def __init__(self, n_keys: int, seed: int = 0):
        from ..ledger.compact_merkle_tree import CompactMerkleTree

        if n_keys <= 0:
            raise ValueError("n_keys must be positive")
        self._leaves = [
            b"nym|%d|%d|verkey-%d" % (seed, i, i) for i in range(n_keys)]
        tree = CompactMerkleTree()
        tree.extend(self._leaves)
        self._tree = tree
        self.tree_size = n_keys
        self.root = tree.root_hash
        self._path_cache: Dict[int, List[bytes]] = {}

    def leaf(self, index: int) -> bytes:
        return self._leaves[index]

    def path(self, index: int,
             tree_size: Optional[int] = None) -> List[bytes]:
        # the corpus is immutable: every snapshot IS the full tree, so a
        # pinned window size can only ever equal self.tree_size — a
        # mismatched pin (a mis-installed ProofWindow) must fail loudly,
        # not hand out paths that silently verify False
        if tree_size is not None and tree_size != self.tree_size:
            raise ValueError(
                f"static corpus has no size-{tree_size} snapshot "
                f"(corpus size {self.tree_size})")
        cached = self._path_cache.get(index)
        if cached is None:
            cached = self._tree.audit_path(index, self.tree_size)
            self._path_cache[index] = cached
        return cached


class LedgerBacking:
    """Committed-txn reads from a live :class:`~indy_plenum_tpu.ledger
    .ledger.Ledger`. The (size, root) snapshot is captured at
    construction and advanced on :meth:`refresh` — refreshing
    invalidates the path cache, since audit paths are per-tree-size.

    Pass the serving node's internal ``bus`` and the snapshot rides the
    checkpoint-stabilized hook: every ``CheckpointStabilized`` the
    consensus layer emits re-snapshots (size, root), so reads serve (and
    prove) everything up to the latest stable watermark with no manual
    refresh calls. Stabilized boundaries are exactly the roots the pool
    has durable agreement on — refreshing mid-window would serve roots a
    view change could still unwind."""

    # audit-path cache bound: on a long-lived pool the pinned
    # (index, tree_size) keys are minted every stabilized window and
    # never re-keyed, so an uncapped dict grows for the life of the
    # node; LRU keeps the hot window working set and ~nothing else
    PATH_CACHE_MAX = 4096

    def __init__(self, ledger, bus=None,
                 path_cache_max: Optional[int] = None):
        self._ledger = ledger
        self.tree_size = 0
        self.root = b""
        self.refreshes = 0
        # index -> path at the live snapshot; (index, size) -> path at a
        # pinned historical size (the proof plane's window roots).
        # Bounded LRU: cleared on refresh(), capped between refreshes.
        self._path_cache: "OrderedDict[object, List[bytes]]" = OrderedDict()
        self._path_cache_max = (path_cache_max if path_cache_max is not None
                                else self.PATH_CACHE_MAX)
        self.refresh()
        if bus is not None:
            from ..common.messages.internal_messages import (
                CheckpointStabilized,
            )

            bus.subscribe(CheckpointStabilized,
                          self._on_checkpoint_stabilized)

    def sized_resources(self, prefix: str = "read_backing."):
        """Resource-ledger registration (observability.telemetry): the
        audit-path LRU is the backing's one bounded store."""
        from ..observability.telemetry import SizedResource

        return (
            SizedResource(prefix + "path_cache",
                          lambda: len(self._path_cache),
                          bound=self._path_cache_max or None,
                          entry_bytes=680),
        )

    def _on_checkpoint_stabilized(self, msg, *args) -> None:
        self.refresh()

    def refresh(self) -> None:
        size = self._ledger.size
        if size == self.tree_size:
            return
        self.tree_size = size
        self.root = self._ledger.root_hash_at(size) if size else b""
        self._path_cache.clear()
        self.refreshes += 1

    def leaf(self, index: int) -> bytes:
        # the ledger's tree hashed the stored serialized bytes — return
        # them verbatim (a loads/dumps round-trip per hot read would
        # also make proofs depend on re-serialization stability)
        return self._ledger.get_serialized(index + 1)

    def path(self, index: int,
             tree_size: Optional[int] = None) -> List[bytes]:
        # ``tree_size`` pins a HISTORICAL snapshot (the state-proof
        # plane serves the last stabilized window's root, which may
        # trail the live tip mid-window); audit paths are per-tree-size,
        # so pinned sizes key the cache alongside the index
        if tree_size is None or tree_size == self.tree_size:
            key: object = index
            pinned_size = self.tree_size
        else:
            key = (index, tree_size)
            pinned_size = tree_size
        cached = self._path_cache.get(key)
        if cached is not None:
            self._path_cache.move_to_end(key)
            return cached
        cached = self._ledger.audit_path(index + 1, pinned_size)
        self._path_cache[key] = cached
        if len(self._path_cache) > self._path_cache_max:
            self._path_cache.popitem(last=False)
        return cached


class _QueuedRead:
    """Bounded-queue payload: gives one queued read the ``digest``
    identity the admission controller's seeded rank law keys on (unique
    per submission — the same index re-read later is a new arrival)."""

    __slots__ = ("seq", "index", "digest")

    def __init__(self, seq: int, index: int):
        self.seq = seq
        self.index = index
        self.digest = "read|%d|%d" % (seq, index)


class ReadService:
    """Batches GET-style reads and answers them with device-verified
    proofs. ``clock`` (the pool's virtual clock) timestamps the
    ``ingress.read`` trace marks so traces stay deterministic, and
    ``read_qps`` derives from the SAME virtual clock (served total over
    the first→last serving-drain span), so snapshots and reports replay
    byte-identically; the wall-clock spent serving still accumulates
    host-side (``serve_wall_s``) for wall-throughput benches only.

    ``proof_cache`` (a :class:`~indy_plenum_tpu.proofs.checkpoint_cache
    .CheckpointProofCache`) attaches the state-proof plane: drains serve
    against the LAST stabilized window's (size, root) snapshot and every
    reply carries the pool's BLS multi-signature over that root — the
    attach is a dict lookup, zero pairings on the serve path.

    ``capacity`` > 0 bounds the read queue with the SAME deterministic
    drop-newest shed law writes use (an
    :class:`~indy_plenum_tpu.ingress.admission.AdmissionController`
    seeded with ``seed``), so a read flood sheds deterministically
    instead of starving the drain — ``ingress.read_shed`` /
    ``ingress.read_queue_depth`` metrics segregate it from the write
    side."""

    def __init__(self, backing, clock: Optional[Callable[[], float]] = None,
                 metrics=None, trace=None, max_batch: int = 16384,
                 mode: str = "auto", proof_cache=None,
                 capacity: int = 0, seed: int = 0, name: str = "",
                 region: Optional[int] = None):
        from ..common.metrics_collector import MetricsCollector
        from ..observability.trace import NULL_TRACE

        # mode: "device" forces the audit-proof kernel, "host" the scalar
        # verifier, "auto" (default) the catchup plane's MEASURED offload
        # policy — on a real TPU the kernel wins (~170k proofs/sec,
        # BENCH_r05); on a CPU driver the scalar SHA-NI loop does, and
        # forcing the kernel there would tax the serving loop ~10x
        # (the round-4 offload lesson, applied to reads)
        self.mode = mode
        self.backing = backing
        self.proof_cache = proof_cache
        self._clock = clock if clock is not None else (lambda: 0.0)
        self.metrics = metrics if metrics is not None \
            else MetricsCollector()
        self.trace = trace if trace is not None else NULL_TRACE
        # service identity on the read journey marks: two services
        # sharing one recorder (or N merged per-node dumps) pair their
        # submitted/served FIFO windows independently in causal.py
        self.name = name
        # geo plane: the service's home region rides the read.submitted
        # marks so causal.py segregates read e2e per region (None =
        # untagged — single-region dumps keep their exact bytes)
        self.region = region
        self.max_batch = int(max_batch)
        self._queue: List[int] = []
        self.admission = None
        if capacity > 0:
            from .admission import AdmissionController

            self.admission = AdmissionController(
                capacity=capacity, seed=seed, clock=self._clock)
        self._read_seq = 0
        self.served_total = 0
        self.verified_total = 0
        self.proofs_attached_total = 0
        self.serve_wall_s = 0.0
        # read_qps span on the VIRTUAL clock: first/last drain instant
        # that actually served reads — a pure function of the seeded
        # schedule, so every surface reporting read_qps replays
        # byte-identically (the wall meter above stays wall-only)
        self._vt_first_serve: Optional[float] = None
        self._vt_last_serve: Optional[float] = None

    # ------------------------------------------------------------------

    def reset_serve_meters(self) -> None:
        """Zero the serve accounting — benches call this after kernel
        warm-up so warm-up drains pollute neither the wall meter nor the
        virtual read_qps span."""
        self.served_total = 0
        self.verified_total = 0
        self.proofs_attached_total = 0
        self.serve_wall_s = 0.0
        self._vt_first_serve = None
        self._vt_last_serve = None

    @property
    def depth(self) -> int:
        if self.admission is not None:
            return self.admission.depth
        return len(self._queue)

    @property
    def shed_total(self) -> int:
        return self.admission.shed_total if self.admission else 0

    def shed_hash(self) -> str:
        """The read-shed fingerprint (bounded mode), same contract as
        the write side's ``AdmissionController.shed_hash``."""
        if self.admission is None:
            import hashlib

            return hashlib.sha256(b"").hexdigest()
        return self.admission.shed_hash()

    def submit(self, index: int) -> bool:
        """Queue one read for the next drain; ``index`` is folded into
        the backing's tree (the workload generator's key space may be
        larger than the corpus). Returns whether the read is queued NOW
        (always True unbounded; in bounded mode a shed read returns
        False and its drop settles in the drain's accounting)."""
        size = self.backing.tree_size
        if size <= 0:
            raise ValueError("read backing is empty")
        idx = index % size
        if self.admission is None:
            self._queue.append(idx)
            if self.trace.enabled:
                # read-journey start (causal plane): serves pair with
                # these FIFO per service, giving per-read e2e without a
                # per-item id on the serve path. Unbounded mode only —
                # a bounded queue's seeded shed would break the pairing.
                self.trace.record(
                    "read.submitted", cat="read", node=self.name,
                    args=({"region": self.region}
                          if self.region is not None else None))
            return True
        self._read_seq += 1
        return self.admission.offer(_QueuedRead(self._read_seq, idx))

    def read_one(self, index: int) -> ProofRead:
        """Synchronous single read (tests / interactive use): the proof
        still verifies — through the host tier below DEVICE_MIN_BATCH.
        Anything already queued drains too; the reply for ``index`` is
        the LAST one (drain answers in submission order)."""
        if not self.submit(index):
            raise RuntimeError("read shed by backpressure")
        return self.drain()[-1]

    def drain(self) -> List[ProofRead]:
        """Answer everything queued: gather leaves + cached paths, then
        ONE batched audit-proof verification per ``max_batch`` chunk.
        Returns the replies in submission order. In bounded mode the
        drain also settles the shed accounting (``ingress.read_shed`` /
        ``ingress.read_queue_depth``); with a proof cache attached, the
        replies serve the last stabilized window's root and carry its
        pool multi-signature."""
        from ..common.metrics_collector import MetricsName

        if self.admission is not None:
            self.metrics.add_event(MetricsName.READ_QUEUE_DEPTH,
                                   self.admission.depth)
            batch, shed = self.admission.drain()
            queued = [r.index for r in batch]
            if shed:
                self.metrics.add_event(MetricsName.READ_SHED, len(shed))
        else:
            queued, self._queue = self._queue, []
            if queued and self.trace.enabled:
                # read-journey end: one mark per drain closes the FIFO
                # window the submitted marks opened (per-read e2e =
                # serve ts - submit ts, in causal.py)
                self.trace.record("read.served", cat="read",
                                  node=self.name,
                                  args={"n": len(queued)})
        if not queued:
            return []
        from ..server.catchup.catchup_rep_service import (
            verify_audit_paths_batch,
        )

        backing = self.backing
        root, tree_size = backing.root, backing.tree_size
        ms_dict = window = None
        if self.proof_cache is not None:
            entry = self.proof_cache.attach(len(queued))
            if entry is not None:
                # the window snapshot, NOT the live tip: mid-window
                # commits stay unserved until the next stabilization, so
                # every reply's root is one the pool co-signed
                root, tree_size = entry.root, entry.tree_size
                ms_dict, window = entry.multi_sig_dict, entry.window
        out: List[ProofRead] = []
        # da: allow[nondet-source] -- serve_wall_s meter (here and at the += below): wall accounting only, never in a reply or fingerprint
        t0 = time.perf_counter()
        for lo in range(0, len(queued), self.max_batch):
            # re-fold into the SERVING snapshot: submit() folded into the
            # live tree, which may have grown past the proven window
            chunk = [i % tree_size for i in queued[lo:lo + self.max_batch]]
            leaves = [backing.leaf(i) for i in chunk]
            paths = [backing.path(i, tree_size) for i in chunk]
            verdicts = verify_audit_paths_batch(
                leaves, chunk, paths, tree_size, root, mode=self.mode)
            ok = int(verdicts.sum())
            self.verified_total += ok
            if self.trace.enabled:
                self.trace.record(
                    "ingress.read", cat="ingress",
                    args={"batch": len(chunk), "ok": ok})
            for i, leaf, path, good in zip(chunk, leaves, paths,
                                           verdicts):
                out.append(ProofRead(
                    index=i, leaf=leaf, root=root, path=path,
                    tree_size=tree_size, verified=bool(good),
                    multi_sig=ms_dict, window=window))
        # da: allow[nondet-source] -- serve_wall_s meter close (see t0 above)
        self.serve_wall_s += time.perf_counter() - t0
        self.served_total += len(queued)
        now = self._clock()
        if self._vt_first_serve is None:
            self._vt_first_serve = now
        self._vt_last_serve = now
        if ms_dict is not None:
            self.proofs_attached_total += len(queued)
        self.metrics.add_event(MetricsName.READ_BATCH_SIZE, len(queued))
        self.metrics.add_event(MetricsName.READ_SERVED, len(queued))
        # qps on the VIRTUAL serve span (zero until a second serving
        # drain opens it): deterministic per seed, so the metric stream
        # — and every snapshot built from it — replays byte-identically
        span = self._vt_last_serve - self._vt_first_serve
        if span > 0:
            self.metrics.add_event(MetricsName.READ_QPS,
                                   self.served_total / span)
        return out

    # ------------------------------------------------------------------

    def counters(self) -> Dict[str, object]:
        # read_qps from the virtual serve span — deterministic per seed
        # (the wall meter serve_wall_s stays an attribute for
        # wall-throughput benches, OUT of the replayable record)
        span = ((self._vt_last_serve - self._vt_first_serve)
                if self._vt_first_serve is not None else 0.0)
        qps = self.served_total / span if span > 0 else 0.0
        out = {
            "served": self.served_total,
            "verified": self.verified_total,
            "pending": self.depth,
            "read_qps": round(qps, 1),
            "proofs_attached": self.proofs_attached_total,
        }
        if self.admission is not None:
            out["shed"] = self.admission.shed_total
            out["capacity"] = self.admission.capacity
        return out
