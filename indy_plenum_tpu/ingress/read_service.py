"""Device-proof read path: state reads that never touch the 3PC plane.

Plenum serves client reads the same way: one node answers from its
committed state with proof material (root + path + pool signature) that
makes a single answer trustworthy — reads ride no agreement round
(PBFT §"read-only operations", Castro & Liskov 1999). Here the proof
material is an RFC 6962 audit path against the serving ledger's root,
and the node VERIFIES what it hands out using the batched device
audit-proof kernel (the catchup kernel, ~170k proofs/sec device-side,
BENCH_r05) — one device dispatch covers a whole drain's worth of reads.

Contract (asserted by bench.py's ``saturation`` sub-bench and
tests/test_ingress.py):

- **zero 3PC involvement**: the service holds no reference to the vote
  plane; serving reads changes neither ``vote_group.flushes`` nor
  ``ordered_hash`` on the same seed;
- reads are answered against a SNAPSHOT ``(tree_size, root)`` captured
  at construction / :meth:`ReadService.refresh`, so a proof never
  straddles a root that moved mid-batch;
- per-drain batched verification: the whole batch rides ONE
  :func:`~indy_plenum_tpu.server.catchup.catchup_rep_service
  .verify_audit_paths_batch` call. The default ``mode="auto"`` consults
  the catchup plane's MEASURED offload policy: the device kernel where
  it wins (real TPU), the scalar SHA-NI loop where the link makes the
  kernel a tax (CPU drivers) — same proofs, same verdicts either way.

Backings adapt proof sources: :class:`LedgerBacking` serves a live
ledger's committed txns (GET_TXN-style); :class:`StaticCorpusBacking`
builds a seeded NYM/attrib corpus for workload benches where the read
universe is the generator's hot-key space.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple


@dataclass
class ProofRead:
    """One answered read: leaf bytes + the proof that they are in the
    tree identified by ``root`` at ``tree_size``."""

    index: int
    leaf: bytes
    root: bytes
    path: List[bytes]
    tree_size: int
    verified: bool


class StaticCorpusBacking:
    """A seeded read corpus: ``n_keys`` deterministic NYM-record leaves
    in a compact Merkle tree. Audit paths are cached per index — Zipf
    read traffic concentrates on the head, so the cache hits almost
    always after warm-up."""

    def __init__(self, n_keys: int, seed: int = 0):
        from ..ledger.compact_merkle_tree import CompactMerkleTree

        if n_keys <= 0:
            raise ValueError("n_keys must be positive")
        self._leaves = [
            b"nym|%d|%d|verkey-%d" % (seed, i, i) for i in range(n_keys)]
        tree = CompactMerkleTree()
        tree.extend(self._leaves)
        self._tree = tree
        self.tree_size = n_keys
        self.root = tree.root_hash
        self._path_cache: Dict[int, List[bytes]] = {}

    def leaf(self, index: int) -> bytes:
        return self._leaves[index]

    def path(self, index: int) -> List[bytes]:
        cached = self._path_cache.get(index)
        if cached is None:
            cached = self._tree.audit_path(index, self.tree_size)
            self._path_cache[index] = cached
        return cached


class LedgerBacking:
    """Committed-txn reads from a live :class:`~indy_plenum_tpu.ledger
    .ledger.Ledger`. The (size, root) snapshot is captured at
    construction and advanced on :meth:`refresh` — refreshing
    invalidates the path cache, since audit paths are per-tree-size.

    Pass the serving node's internal ``bus`` and the snapshot rides the
    checkpoint-stabilized hook: every ``CheckpointStabilized`` the
    consensus layer emits re-snapshots (size, root), so reads serve (and
    prove) everything up to the latest stable watermark with no manual
    refresh calls. Stabilized boundaries are exactly the roots the pool
    has durable agreement on — refreshing mid-window would serve roots a
    view change could still unwind."""

    def __init__(self, ledger, bus=None):
        self._ledger = ledger
        self.tree_size = 0
        self.root = b""
        self.refreshes = 0
        self._path_cache: Dict[int, List[bytes]] = {}
        self.refresh()
        if bus is not None:
            from ..common.messages.internal_messages import (
                CheckpointStabilized,
            )

            bus.subscribe(CheckpointStabilized,
                          self._on_checkpoint_stabilized)

    def _on_checkpoint_stabilized(self, msg, *args) -> None:
        self.refresh()

    def refresh(self) -> None:
        size = self._ledger.size
        if size == self.tree_size:
            return
        self.tree_size = size
        self.root = self._ledger.root_hash_at(size) if size else b""
        self._path_cache.clear()
        self.refreshes += 1

    def leaf(self, index: int) -> bytes:
        # the ledger's tree hashed the stored serialized bytes — return
        # them verbatim (a loads/dumps round-trip per hot read would
        # also make proofs depend on re-serialization stability)
        return self._ledger.get_serialized(index + 1)

    def path(self, index: int) -> List[bytes]:
        cached = self._path_cache.get(index)
        if cached is None:
            cached = self._ledger.audit_path(index + 1, self.tree_size)
            self._path_cache[index] = cached
        return cached


class ReadService:
    """Batches GET-style reads and answers them with device-verified
    proofs. ``clock`` (the pool's virtual clock) timestamps the
    ``ingress.read`` trace marks so traces stay deterministic; the
    wall-clock spent serving accumulates host-side only (``read_qps``)."""

    def __init__(self, backing, clock: Optional[Callable[[], float]] = None,
                 metrics=None, trace=None, max_batch: int = 16384,
                 mode: str = "auto"):
        from ..common.metrics_collector import MetricsCollector
        from ..observability.trace import NULL_TRACE

        # mode: "device" forces the audit-proof kernel, "host" the scalar
        # verifier, "auto" (default) the catchup plane's MEASURED offload
        # policy — on a real TPU the kernel wins (~170k proofs/sec,
        # BENCH_r05); on a CPU driver the scalar SHA-NI loop does, and
        # forcing the kernel there would tax the serving loop ~10x
        # (the round-4 offload lesson, applied to reads)
        self.mode = mode
        self.backing = backing
        self._clock = clock if clock is not None else (lambda: 0.0)
        self.metrics = metrics if metrics is not None \
            else MetricsCollector()
        self.trace = trace if trace is not None else NULL_TRACE
        self.max_batch = int(max_batch)
        self._queue: List[int] = []
        self.served_total = 0
        self.verified_total = 0
        self.serve_wall_s = 0.0

    # ------------------------------------------------------------------

    @property
    def depth(self) -> int:
        return len(self._queue)

    def submit(self, index: int) -> None:
        """Queue one read for the next drain; ``index`` is folded into
        the backing's tree (the workload generator's key space may be
        larger than the corpus)."""
        size = self.backing.tree_size
        if size <= 0:
            raise ValueError("read backing is empty")
        self._queue.append(index % size)

    def read_one(self, index: int) -> ProofRead:
        """Synchronous single read (tests / interactive use): the proof
        still verifies — through the host tier below DEVICE_MIN_BATCH.
        Anything already queued drains too; the reply for ``index`` is
        the LAST one (drain answers in submission order)."""
        self.submit(index)
        return self.drain()[-1]

    def drain(self) -> List[ProofRead]:
        """Answer everything queued: gather leaves + cached paths, then
        ONE batched audit-proof verification per ``max_batch`` chunk.
        Returns the replies in submission order."""
        if not self._queue:
            return []
        from ..common.metrics_collector import MetricsName
        from ..server.catchup.catchup_rep_service import (
            verify_audit_paths_batch,
        )

        queued, self._queue = self._queue, []
        backing = self.backing
        root, tree_size = backing.root, backing.tree_size
        out: List[ProofRead] = []
        t0 = time.perf_counter()
        for lo in range(0, len(queued), self.max_batch):
            chunk = queued[lo:lo + self.max_batch]
            leaves = [backing.leaf(i) for i in chunk]
            paths = [backing.path(i) for i in chunk]
            verdicts = verify_audit_paths_batch(
                leaves, chunk, paths, tree_size, root, mode=self.mode)
            ok = int(verdicts.sum())
            self.verified_total += ok
            if self.trace.enabled:
                self.trace.record(
                    "ingress.read", cat="ingress",
                    args={"batch": len(chunk), "ok": ok})
            for i, leaf, path, good in zip(chunk, leaves, paths,
                                           verdicts):
                out.append(ProofRead(
                    index=i, leaf=leaf, root=root, path=path,
                    tree_size=tree_size, verified=bool(good)))
        self.serve_wall_s += time.perf_counter() - t0
        self.served_total += len(queued)
        self.metrics.add_event(MetricsName.READ_BATCH_SIZE, len(queued))
        self.metrics.add_event(MetricsName.READ_SERVED, len(queued))
        if self.serve_wall_s > 0:
            self.metrics.add_event(
                MetricsName.READ_QPS,
                self.served_total / self.serve_wall_s)
        return out

    # ------------------------------------------------------------------

    def counters(self) -> Dict[str, object]:
        qps = (self.served_total / self.serve_wall_s
               if self.serve_wall_s > 0 else 0.0)
        return {
            "served": self.served_total,
            "verified": self.verified_total,
            "pending": self.depth,
            "serve_wall_s": round(self.serve_wall_s, 4),
            "read_qps": round(qps, 1),
        }
