"""Million-client ingress plane: open-loop workload, admission, reads.

Three cooperating parts (README "Ingress plane"):

- :mod:`.workload` — a seeded open-loop client population (Zipf-skewed
  arrival rates and hot-key targets) scheduling arrivals on the pool's
  virtual clock, so every run is replay-identical;
- :mod:`.admission` — bounded auth queues with a deterministic shed
  policy (drop-newest, seeded tiebreak, per-client fairness caps) and
  the :class:`~indy_plenum_tpu.ingress.admission.BackpressureSignal`
  that closes the loop into the dispatch governor;
- :mod:`.retry` — the CLIENTS' side of overload: seeded-backoff
  closed-loop retries of shed/NACKed requests (README "Overload
  robustness") with the ``retry_hash`` fingerprint;
- :mod:`.read_service` — GET-style state reads answered from a ledger's
  Merkle tree with the device audit-proof kernel, zero 3PC involvement.
"""
from .admission import AdmissionController, BackpressureSignal
from .read_service import (
    LedgerBacking,
    ProofRead,
    ReadService,
    StaticCorpusBacking,
)
from .retry import RetryDriver, RetryPolicy
from .workload import WorkloadGenerator, WorkloadProfile, WorkloadSpec

__all__ = [
    "AdmissionController",
    "BackpressureSignal",
    "LedgerBacking",
    "ProofRead",
    "ReadService",
    "RetryDriver",
    "RetryPolicy",
    "StaticCorpusBacking",
    "WorkloadGenerator",
    "WorkloadProfile",
    "WorkloadSpec",
]
