"""Admission control: bounded auth queues with a deterministic shed law.

PBFT/RBFT evaluations (Castro & Liskov, OSDI 1999; Aublin et al., ICDCS
2013) run their pools at and beyond saturation — which only means
anything if overload has a defined behaviour. An unbounded auth queue
under open-loop load grows without limit: latency explodes, memory
grows, and the pool's *goodput* collapses behind a wall of doomed
requests. :class:`AdmissionController` bounds the queue and makes the
overflow decision a deterministic function of the arrival sequence and a
seed, so a seeded saturation run replays to the byte-identical shed set
(checkable like ``ordered_hash``):

- **fairness cap**: a client with ``per_client_cap`` requests already
  queued is shed outright — one hot client must not starve the
  population (plenum throttles per-client ingress the same way);
  anonymous submissions (``client_id=None``) carry no identity to cap
  and are outside it — the bounded queue still limits them;
- **drop-newest**: when the queue is full, only the newest arrivals
  compete; queued work is never abandoned after the pool has invested
  in it;
- **seeded tiebreak**: arrivals of the same virtual-clock instant
  compete by a seeded content rank (sha256 over seed|digest), so the
  shed set does not depend on host-side submission interleaving within
  one instant.

Shed accounting is deliberately deferred to :meth:`drain` (the dispatch
tick): the hot ``offer`` path appends to a pending list, and the drain
records the tick's sheds under the DEDICATED ``ingress.shed`` metric and
``req.shed`` trace events — shed load never pollutes the ``AUTH_BATCH_*``
hot-path stats (they measure work the device actually verified).

:class:`BackpressureSignal` is the per-tick digest the dispatch governor
consumes: pre-drain queue depth vs capacity, sheds since the last tick,
and whether any node is leeching (catching up). See
:meth:`~indy_plenum_tpu.tpu.governor.DispatchGovernor.feed_backpressure`.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class BackpressureSignal:
    """One tick's ingress pressure, fed to the dispatch governor.

    ``queue_depth`` is the PRE-drain depth (what accumulated over the
    tick interval), ``shed_delta`` the sheds since the previous tick,
    ``leeching`` whether any pool node is catching up (not
    participating), ``retry_pressure`` how many closed-loop retries are
    outstanding on the virtual timer (ingress/retry.py) — load the pool
    ALREADY owes itself, which must hold the governor's narrow even
    while the queue momentarily looks calm. A zero signal
    (0, 0, 0, False, 0) is the explicit no-pressure statement — the
    governor's law is bit-identical to the PR 3/PR 4 occupancy-only law
    under it.
    """

    queue_depth: int = 0
    capacity: int = 0
    shed_delta: int = 0
    leeching: bool = False
    retry_pressure: int = 0

    @property
    def queue_frac(self) -> float:
        # capacity == 0 is the ingress-off (or synthetic-signal) case:
        # no queue to fill means no fractional pressure, never a
        # ZeroDivisionError
        return self.queue_depth / self.capacity if self.capacity else 0.0


class AdmissionController:
    """Bounded ingress queue with the deterministic shed policy above.

    ``clock`` is injected (the pool's virtual clock) so same-instant
    cohorts — and therefore the tiebreak — are a protocol-time notion,
    never a wall-clock one. Payloads only need a ``digest`` attribute
    (:class:`~indy_plenum_tpu.common.request.Request` has one).
    """

    def __init__(self, capacity: int, per_client_cap: int = 0,
                 seed: int = 0,
                 clock: Optional[Callable[[], float]] = None):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive: {capacity}")
        self.capacity = int(capacity)
        self.per_client_cap = int(per_client_cap)
        self.seed = int(seed)
        self._clock = clock if clock is not None else (lambda: 0.0)
        # (ts, rank, client_id, req) — appended in arrival order; the
        # tail cohort (same ts) is the only eviction domain
        self._queue: List[Tuple[float, int, Optional[str], Any]] = []
        self._per_client: Dict[Optional[str], int] = {}
        # sheds since the last drain: (req, client_id, reason); recorded
        # by drain — the client id rides along so the closed-loop retry
        # driver can re-offer under the SAME identity (a retry that
        # dodged the fairness cap by dropping its client would be cap
        # evasion)
        self._shed_pending: List[Tuple[Any, Optional[str], str]] = []
        self.offered_total = 0
        self.admitted_total = 0
        self.shed_total = 0
        self.peak_depth = 0
        self.shed_digests: List[str] = []  # the run's shed fingerprint

    # ------------------------------------------------------------------

    def _rank(self, digest: str) -> int:
        """Seeded content rank: HIGHER ranks shed first within a cohort."""
        h = hashlib.sha256(
            b"%d|%s" % (self.seed, digest.encode())).digest()
        return int.from_bytes(h[:8], "big")

    @property
    def depth(self) -> int:
        return len(self._queue)

    def sized_resources(self, prefix: str = "admission."):
        """Resource-ledger registration (observability.telemetry). The
        queue and same-tick shed cohort are the controller's bounded
        stores; ``shed_digests`` is a by-design run-long fingerprint
        spine (like ``ordered_digests``) and stays off the ledger."""
        from ..observability.telemetry import SizedResource

        return (
            SizedResource(prefix + "queue", lambda: len(self._queue),
                          bound=self.capacity or None, entry_bytes=512),
            SizedResource(prefix + "shed_pending",
                          lambda: len(self._shed_pending),
                          bound=None, entry_bytes=512),
            SizedResource(prefix + "per_client",
                          lambda: len(self._per_client),
                          bound=None, entry_bytes=64),
        )

    def shed_hash(self) -> str:
        """sha256 over the SORTED shed digests — THE shed-set
        fingerprint. Canonical set hash: the shed SET is independent of
        same-instant submission interleaving, so the fingerprint must be
        too (seeded runs reproduce it byte-for-byte)."""
        return hashlib.sha256(
            "|".join(sorted(self.shed_digests)).encode()).hexdigest()

    # ------------------------------------------------------------------

    def _shed(self, req: Any, client_id: Optional[str],
              reason: str) -> None:
        self.shed_total += 1
        self.shed_digests.append(req.digest)
        self._shed_pending.append((req, client_id, reason))

    def offer(self, req: Any, client_id: Optional[str] = None) -> bool:
        """Admit ``req`` into the bounded queue or shed it. Returns
        whether the request is queued NOW (a later same-instant arrival
        with a lower seeded rank may still evict it — its shed then
        surfaces through :meth:`drain`)."""
        self.offered_total += 1
        now = self._clock()
        cap = self.per_client_cap
        if (cap > 0 and client_id is not None
                and self._per_client.get(client_id, 0) >= cap):
            self._shed(req, client_id, "client_cap")
            return False
        if len(self._queue) < self.capacity:
            self._queue.append((now, self._rank(req.digest), client_id,
                                req))
            self._per_client[client_id] = \
                self._per_client.get(client_id, 0) + 1
            if len(self._queue) > self.peak_depth:
                self.peak_depth = len(self._queue)
            return True
        # full: drop-newest — only the tail cohort (same instant as the
        # newcomer) competes, by seeded rank
        rank = self._rank(req.digest)
        worst_i, worst_rank = -1, rank
        for i in range(len(self._queue) - 1, -1, -1):
            ts, r, _cid, _req = self._queue[i]
            if ts != now:
                break
            if r > worst_rank:
                worst_i, worst_rank = i, r
        if worst_i < 0:
            self._shed(req, client_id, "queue_full")
            return False
        _ts, _r, ev_cid, ev_req = self._queue.pop(worst_i)
        self._per_client[ev_cid] = self._per_client.get(ev_cid, 1) - 1
        self._shed(ev_req, ev_cid, "queue_full")
        self._queue.append((now, rank, client_id, req))
        self._per_client[client_id] = \
            self._per_client.get(client_id, 0) + 1
        return True

    def drain(self) -> Tuple[List[Any],
                             List[Tuple[Any, Optional[str], str]]]:
        """The tick's handoff: (admitted batch in arrival order, sheds
        since the last drain as (req, client_id, reason)). Callers
        record the sheds under ``req.shed`` / ``ingress.shed`` — never
        ``AUTH_BATCH_*`` — and hand them to the retry driver when the
        closed loop is armed."""
        batch = [req for (_ts, _r, _cid, req) in self._queue]
        self._queue.clear()
        self._per_client.clear()
        self.admitted_total += len(batch)
        shed, self._shed_pending = self._shed_pending, []
        return batch, shed

    def counters(self) -> Dict[str, int]:
        return {
            "offered": self.offered_total,
            "admitted": self.admitted_total,
            "shed": self.shed_total,
            "depth": self.depth,
            "peak_depth": self.peak_depth,
            "capacity": self.capacity,
        }
