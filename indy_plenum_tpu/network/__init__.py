"""Node-to-node transport: authenticated ZMQ stacks.

Reference: stp_zmq/ (ZStack and friends). See :mod:`.zstack` for the
CurveZMQ ROUTER stack and :mod:`.keys` for key management.
"""
from ..common.event_bus import ExternalBus
from .keys import curve_keypair_from_seed
from .zstack import ZStack

__all__ = ["ZStack", "ZStackNetwork", "curve_keypair_from_seed"]


class ZStackNetwork:
    """Adapter: one node's ZStack as the Node composition's network seam
    (the same ``create_peer`` contract the simulation's SimNetwork has)."""

    def __init__(self, stack: ZStack):
        self.stack = stack
        self.bus = None

    def create_peer(self, name: str) -> ExternalBus:
        assert name == self.stack.name, (name, self.stack.name)

        def send_handler(msg, dst=None):
            if isinstance(dst, str):
                dst = [dst]
            self.stack.send(msg, dst)

        self.bus = ExternalBus(send_handler)
        self.stack.on_message = self.bus.process_incoming
        # socket-monitor liveness -> bus Connected/Disconnected events (the
        # primary-disconnect detector runs on these over real sockets)
        self.stack.on_connection_change = self._on_connection_change
        return self.bus

    def _on_connection_change(self, peer: str, up: bool) -> None:
        connecteds = set(self.bus.connecteds)
        if up:
            connecteds.add(peer)
        else:
            connecteds.discard(peer)
        self.bus.update_connecteds(connecteds)

    def mark_connected(self, peers) -> None:
        """Optimistic initial topology, reconciled against any liveness
        edges the stack observed before this composition attached (a peer
        already seen to drop must not be resurrected optimistically)."""
        known = self.stack.peer_states
        self.bus.update_connecteds(
            {p for p in peers if known.get(p, True)})

    def membership_hook(self, validators, registry) -> None:
        """Consumer for ``Node.on_membership_changed_hook`` (reference:
        KITZStack reacting to pool-ledger changes): members that left are
        disconnected; members whose NODE txn carries transport info are
        connected — or RECONNECTED when their key/address rotated. Records
        without transport info (static wiring) are left untouched."""
        from ..common.constants import (
            NODE_IP,
            NODE_PORT,
            TRANSPORT_VERKEY,
        )

        own = self.stack.name
        members = set(validators)
        for peer in list(self.stack.connected_peers):
            if peer not in members:
                self.stack.disconnect_peer(peer)
                self._on_connection_change(peer, False)
        for alias in validators:
            if alias == own:
                continue
            rec = registry.get(alias) or {}
            key = rec.get(TRANSPORT_VERKEY)
            host, port = rec.get(NODE_IP), rec.get(NODE_PORT)
            if not key or not host or not port:
                continue
            self.stack.upsert_peer(alias, (host, int(port)), key.encode())
