"""Authenticated node-to-node transport: ZMQ ROUTER + CurveZMQ.

Reference: stp_zmq/zstack.py (`ZStack`, `KITZStack`) and stp_zmq's ZAP
authenticator. Each node binds ONE ROUTER listener in curve-server mode
and opens a curve-client DEALER per peer. A minimal in-process ZAP handler
admits only Curve25519 keys from the pool registry, and — the part that
makes the byzantine tests honest — every inbound message is attributed by
the AUTHENTICATED curve key of its connection (ZMQ's User-Id metadata,
set by our ZAP handler), never by any name the bytes claim. A validator
cannot speak under another validator's name, and an unknown key cannot
complete the handshake at all.

Outgoing messages per peer are coalesced into one ``Batch`` envelope per
service() flush (reference: plenum/common/batched.py), bounded by
``OUTGOING_BATCH_SIZE``.

Wire format: msgpack of the registry dict form (``op`` field dispatch).
"""
# da: allow-file[nondet-source] -- DEPLOYED transport: reconnect/monitor timers and the wire-trace clock read real time; the seeded transport is simulation/sim_network.py on the virtual clock
from __future__ import annotations

import logging
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

import zmq
import zmq.utils.z85 as z85
from zmq.utils.monitor import recv_monitor_message

from ..common.messages.message_base import node_message_registry
from ..common.messages.node_messages import Batch
from ..common.metrics_collector import MetricsName
from ..common.serializers.serialization import (
    deserialize_msgpack,
    serialize_msg,
)
from .keys import curve_keypair_from_seed

logger = logging.getLogger(__name__)

_ZAP_ENDPOINT = "inproc://zeromq.zap.01"


class ZStack:
    """One node's transport stack (listener + per-peer connections)."""

    def __init__(self,
                 name: str,
                 seed: bytes,
                 on_message: Optional[Callable] = None,
                 bind_host: str = "127.0.0.1",
                 bind_port: int = 0,
                 max_batch: int = 100,
                 msg_len_limit: int = 128 * 1024,
                 metrics=None,
                 reconnect_interval: float = 2.0):
        self.name = name
        self.public_key, self._secret_key = curve_keypair_from_seed(seed)
        self.on_message = on_message  # (msg_obj, sender_name) -> None
        self._max_batch = max_batch
        self._msg_len_limit = msg_len_limit
        self._metrics = metrics  # optional MetricsCollector
        # causal tracing plane: with a recorder attached (build_node
        # wires the Node's), journey-joinable messages piggyback a
        # ``~trc`` context on the serialized envelope — {id, sender,
        # sender-clock send ts} — and both ends stamp net.send/net.recv
        # marks. The receiver strips the context before schema
        # validation, so untraced peers interoperate unchanged.
        from ..observability.trace import NULL_TRACE

        self.trace = NULL_TRACE
        self._net_seq = 0

        self._ctx = zmq.Context()
        # never block interpreter shutdown: ctx.term() waits for open
        # sockets forever by default, so a composition that forgot close()
        # would hang Python at GC (observed in the test suite)
        self._ctx.set(zmq.BLOCKY, False)
        self._closed = False
        # ZAP handler must exist before any curve-server socket binds.
        # ROUTER, not REP: concurrent handshakes (the whole pool connecting
        # at startup) put several ZAP requests in flight at once, and REP's
        # strict alternation would wedge the handler.
        self._zap = self._ctx.socket(zmq.ROUTER)
        self._zap.bind(_ZAP_ENDPOINT)
        self._allowed: Dict[bytes, str] = {}  # public_z85 -> node name

        self._listener = self._ctx.socket(zmq.ROUTER)
        self._listener.setsockopt(zmq.CURVE_SERVER, 1)
        self._listener.setsockopt(zmq.CURVE_SECRETKEY, self._secret_key)
        self._listener.setsockopt(zmq.LINGER, 0)
        self._listener.bind(f"tcp://{bind_host}:{bind_port}")
        endpoint = self._listener.getsockopt_string(zmq.LAST_ENDPOINT)
        self.ha: Tuple[str, int] = (bind_host, int(endpoint.rsplit(":", 1)[1]))

        self._remotes: Dict[str, zmq.Socket] = {}
        self._remote_ha: Dict[str, Tuple[str, int]] = {}
        self._outbox: Dict[str, List[bytes]] = defaultdict(list)
        self._poller = zmq.Poller()
        self._poller.register(self._listener, zmq.POLLIN)
        self._poller.register(self._zap, zmq.POLLIN)
        self.received = 0
        self.rejected_unknown_key = 0
        # messages lost to a full peer HWM ("UDP-like" sends): without this
        # counter a saturated pool is slow in a way metrics can't explain
        self.dropped = 0
        # liveness: libzmq socket monitors per remote feed the composition
        # (handshake-succeeded = peer up, disconnected = peer down) — this
        # is what lets the primary-disconnect detector work over sockets
        self._monitors: Dict[zmq.Socket, str] = {}
        self._peer_up: Dict[str, bool] = {}
        # peers whose CURVE handshake ever completed on the current
        # connection registration. NOT derivable from _peer_up: a
        # ZAP-rejected attempt still emits EVENT_DISCONNECTED (TCP-level),
        # so _peer_up can hold False entries for peers that never
        # authenticated once
        self._handshaken: set = set()
        self._down_since: Dict[str, float] = {}  # peer -> monotonic time
        self.on_connection_change = None  # (peer_name, up: bool) -> None
        # keep-in-touch (reference: stp_zmq/kit_zstack.py): periodically
        # RECREATE the DEALER of any peer whose curve handshake hasn't
        # succeeded. Necessary, not cosmetic: a ZAP-rejected handshake is
        # TERMINAL for that socket in libzmq (observed: no further
        # reconnect attempts), so a peer admitted to the registry after a
        # first failed attempt — the add-a-node flow — would never become
        # reachable without this.
        self._reconnect_interval = reconnect_interval
        self._last_reconnect_check = time.monotonic()
        self.reconnects = 0
        # per-peer recreate pacing for NEVER-handshaken peers: the same
        # grace the handshaken path gets, then exponential backoff — a
        # slow-to-boot or slow-handshaking peer must not have its DEALER
        # (and in-flight handshake) torn down every interval (round-4
        # advisor finding). (attempts, earliest next recreate).
        self._recreate_state: Dict[str, Tuple[int, float]] = {}

    # --- registry -------------------------------------------------------

    def allow_peer(self, name: str, public_z85: bytes) -> None:
        """Admit ``name``'s transport key (pool-registry driven)."""
        self._allowed[bytes(public_z85)] = name

    def disallow_peer(self, name: str) -> None:
        for key, peer in list(self._allowed.items()):
            if peer == name:
                del self._allowed[key]

    def connect(self, name: str, ha: Tuple[str, int],
                server_public_z85: bytes) -> None:
        if name in self._remotes:
            return
        sock = self._ctx.socket(zmq.DEALER)
        sock.setsockopt(zmq.CURVE_SERVERKEY, bytes(server_public_z85))
        sock.setsockopt(zmq.CURVE_PUBLICKEY, self.public_key)
        sock.setsockopt(zmq.CURVE_SECRETKEY, self._secret_key)
        sock.setsockopt(zmq.LINGER, 0)
        # EVENT_CLOSED is deliberately absent: libzmq's connecter also
        # emits it for every FAILED connect attempt (peer not bound yet),
        # which would report a never-connected peer as "down" at startup.
        # DISCONNECTED only fires after an established session drops.
        monitor = sock.get_monitor_socket(
            zmq.EVENT_HANDSHAKE_SUCCEEDED | zmq.EVENT_DISCONNECTED)
        self._monitors[monitor] = name
        self._poller.register(monitor, zmq.POLLIN)
        sock.connect(f"tcp://{ha[0]}:{ha[1]}")
        self._remotes[name] = sock
        self._remote_ha[name] = (ha[0], int(ha[1]))

    @property
    def connected_peers(self) -> List[str]:
        return list(self._remotes)

    # --- keep-in-touch registry sync (reference: stp_zmq/kit_zstack.py) -

    def _close_remote(self, name: str) -> None:
        """Close ``name``'s DEALER + monitor; registry entries survive."""
        sock = self._remotes.pop(name, None)
        if sock is None:
            return
        for mon, peer in list(self._monitors.items()):
            if peer == name:
                try:
                    self._poller.unregister(mon)
                except KeyError:
                    pass
                mon.close(0)
                del self._monitors[mon]
        try:
            sock.disable_monitor()
        except Exception:  # noqa: BLE001
            pass
        sock.close(0)

    def disconnect_peer(self, name: str) -> None:
        """Close the DEALER to ``name`` and forget its curve key (member
        removed, or about to be reconnected under a new key)."""
        self._close_remote(name)
        self._outbox.pop(name, None)
        self._remote_ha.pop(name, None)
        self.disallow_peer(name)
        self._peer_up.pop(name, None)
        # a rotated/readmitted peer's fresh connection may be rejected
        # again — the KIT retry must be willing to recreate it
        self._handshaken.discard(name)
        self._down_since.pop(name, None)
        self._recreate_state.pop(name, None)

    def _retry_dead_connections(self) -> None:
        """KIT reconnect pass: any peer without a completed handshake gets
        a FRESH DEALER (old one may be in the terminal post-ZAP-reject
        state); queued outbox survives and flushes once the new session
        comes up."""
        now = time.monotonic()
        if now - self._last_reconnect_check < self._reconnect_interval:
            return
        self._last_reconnect_check = now
        grace = 3 * self._reconnect_interval
        for name in list(self._remotes):
            if name in self._handshaken:
                # handshake once succeeded: libzmq's native reconnect
                # handles transient drops AND preserves the messages
                # already queued in the pipe — recreating the socket would
                # close(0) them away. But only within a grace window: a
                # peer that restarted into a state that ZAP-rejects us is
                # terminal for this socket, so after a prolonged outage a
                # fresh DEALER is the only way back (queued messages are
                # stale by then; MessageReq recovers protocol state).
                down = self._down_since.get(name)
                if down is None or now - down < grace:
                    continue
                self._handshaken.discard(name)
            else:
                # never handshaken: give the in-flight attempt the same
                # grace before tearing its DEALER down, then back off
                # exponentially (cap 8x grace) — recreating every interval
                # can perpetually abort a handshake slower than the
                # interval and churns socket+monitor objects forever
                attempts, next_at = self._recreate_state.get(
                    name, (0, now + grace))
                if now < next_at:
                    if name not in self._recreate_state:
                        self._recreate_state[name] = (attempts, next_at)
                    continue
                attempts = min(attempts + 1, 3)  # clamp the exponent too:
                # a permanently-dead registry entry must not grow the
                # counter (and the bignum 2**attempts) without bound
                backoff = grace * (2 ** attempts)
                self._recreate_state[name] = (attempts, now + backoff)
            ha = self._remote_ha.get(name)
            key = next((k for k, p in self._allowed.items() if p == name),
                       None)
            if ha is None or key is None:
                continue
            self._close_remote(name)
            self.connect(name, ha, key)
            self.reconnects += 1

    def upsert_peer(self, name: str, ha: Tuple[str, int],
                    public_z85: bytes) -> bool:
        """Connect a new peer, or RESTART the connection when its curve
        key or address changed (the rotation path); returns True if the
        connection was (re)established."""
        key = bytes(public_z85)
        ha = (ha[0], int(ha[1]))
        if name in self._remotes:
            current_key = next((k for k, p in self._allowed.items()
                                if p == name), None)
            if current_key == key and self._remote_ha.get(name) == ha:
                return False  # unchanged
            logger.info("%s: peer %s rotated its transport key or "
                        "address; restarting connection", self.name, name)
            self.disconnect_peer(name)
        self.allow_peer(name, key)
        self.connect(name, ha, key)
        return True

    # --- sending --------------------------------------------------------

    def send(self, msg, dst: Optional[List[str]] = None) -> None:
        """Queue ``msg`` (a MessageBase or dict) for peers; coalesced into
        Batch envelopes at the next service() flush."""
        obj = msg.as_dict() if hasattr(msg, "as_dict") else msg
        targets = list(self._remotes) if dst is None else dst
        key = None
        if self.trace.enabled and isinstance(obj, dict):
            from ..observability.causal import (
                NET_TRACED_OPS,
                net_join_key,
            )

            op = obj.get("op")
            if op in NET_TRACED_OPS:
                key = net_join_key(op, obj.get)
        if key is None:
            data = serialize_msg(obj)
            for peer in targets:
                if peer in self._remotes:
                    self._outbox[peer].append(data)
            return
        # traced: each copy carries its own context (per-peer flow id),
        # so the envelope itself is the propagation vehicle — the
        # receiving node needs no shared state to join the hop
        ts = time.perf_counter()
        for peer in targets:
            if peer not in self._remotes:
                continue
            self._net_seq += 1
            nid = "%s:%d" % (self.name, self._net_seq)
            data = serialize_msg(dict(
                obj, **{"~trc": {"id": nid, "frm": self.name,
                                 "sent": ts}}))
            if len(data) > self._msg_len_limit:
                # near-limit payload: the context would push it past the
                # receiver's oversize drop — tracing must NEVER change
                # what gets delivered, so this copy ships untraced
                self._outbox[peer].append(serialize_msg(obj))
                continue
            # da: allow[trace-guard] -- key is non-None ONLY when self.trace.enabled held at the top of send(); this loop is unreachable untraced
            self.trace.record("net.send", cat="net", node=self.name,
                              key=key,
                              args={"m": obj["op"], "to": peer,
                                    "id": nid}, ts=ts)
            self._outbox[peer].append(data)

    def _flush(self) -> None:
        for peer, queue in self._outbox.items():
            sock = self._remotes.get(peer)
            if sock is None or not queue:
                continue
            while queue:
                chunk, self._outbox[peer] = (queue[:self._max_batch],
                                             queue[self._max_batch:])
                queue = self._outbox[peer]
                if len(chunk) == 1:
                    payload = chunk[0]
                else:
                    payload = serialize_msg(Batch(
                        messages=list(chunk), signature=None).as_dict())
                try:
                    sock.send(payload, flags=zmq.NOBLOCK)
                except zmq.Again:  # peer HWM reached; drop (UDP-like)
                    self.dropped += len(chunk)
                    if self._metrics is not None:
                        self._metrics.add_event(MetricsName.ZSTACK_DROPPED,
                                                len(chunk))
                    logger.warning("%s: send queue full for %s; %d "
                                   "message(s) dropped", self.name, peer,
                                   len(chunk))
                    break

    # --- receiving ------------------------------------------------------

    def _service_zap(self) -> None:
        while True:
            try:
                frames = self._zap.recv_multipart(flags=zmq.NOBLOCK)
            except zmq.Again:
                return
            # ROUTER framing: [envelope..., b"", version, request_id,
            # domain, address, identity, mechanism, credentials...];
            # CURVE credential = raw 32-byte client key
            try:
                split = frames.index(b"")
            except ValueError:
                continue
            envelope, body = frames[:split + 1], frames[split + 1:]
            if len(body) < 6:
                continue
            version, request_id, mechanism = body[0], body[1], body[5]
            status, user_id = b"400", b""
            if mechanism == b"CURVE" and len(body) > 6:
                key_z85 = z85.encode(body[6])
                if key_z85 in self._allowed:
                    status, user_id = b"200", key_z85
                else:
                    self.rejected_unknown_key += 1
                    logger.warning("%s: ZAP rejected unknown curve key",
                                   self.name)
            self._zap.send_multipart(envelope + [
                version, request_id, status,
                b"OK" if status == b"200" else b"unknown key",
                user_id, b""])

    def _sender_of(self, frame: zmq.Frame) -> Optional[str]:
        """The AUTHENTICATED peer name: resolved from the connection's
        curve key (ZAP User-Id), never from claimed content."""
        try:
            user_id = frame.get("User-Id")
        except Exception:  # noqa: BLE001
            return None
        if not user_id:
            return None
        return self._allowed.get(user_id.encode()
                                 if isinstance(user_id, str) else user_id)

    def _dispatch(self, payload: bytes, sender: str,
                  in_batch: bool = False) -> None:
        if len(payload) > self._msg_len_limit:
            logger.warning("%s: oversize message from %s dropped",
                           self.name, sender)
            return
        try:
            data = deserialize_msgpack(payload)
            # piggybacked trace context (causal tracing plane): strip it
            # BEFORE schema validation — the wire context is advisory
            # observability, never protocol surface
            ctx = data.pop("~trc", None) if isinstance(data, dict) \
                else None
            msg = node_message_registry.obj_from_dict(data)
        except Exception as exc:  # noqa: BLE001 — wire data is untrusted
            logger.warning("%s: bad message from %s: %s", self.name,
                           sender, exc)
            return
        if ctx is not None and self.trace.enabled:
            from ..observability.causal import net_join_key

            op = data.get("op")
            key = net_join_key(op, data.get) if op else None
            if key is not None:
                # args carry the SENDER's clock reading: the two hosts'
                # clocks differ, so causal joins use it as an offset
                # estimate, not a shared timeline
                self.trace.record(
                    "net.recv", cat="net", node=self.name, key=key,
                    args={"m": op, "frm": sender,
                          "id": ctx.get("id"),
                          "sent": ctx.get("sent")})
        if isinstance(msg, Batch):
            # byzantine guards: a batch inside a batch is never legitimate
            # (unbounded recursion), and elements must be bytes (the field
            # schema also admits str) — validate ALL before dispatching ANY
            if in_batch:
                logger.warning("%s: nested BATCH from %s dropped",
                               self.name, sender)
                return
            inners = []
            for inner in msg.messages:
                if not isinstance(inner, (bytes, bytearray)):
                    logger.warning("%s: non-bytes BATCH element from %s",
                                   self.name, sender)
                    return
                inners.append(bytes(inner))
            for inner_payload in inners:
                self._dispatch(inner_payload, sender, in_batch=True)
            return
        self.received += 1
        if self.on_message is not None:
            self.on_message(msg, sender)

    @property
    def peer_states(self) -> Dict[str, bool]:
        """Last known liveness per peer (edges observed so far) — lets a
        late-attaching composition reconcile instead of losing edges."""
        return dict(self._peer_up)

    def _service_monitors(self, events) -> None:
        for mon, peer in list(self._monitors.items()):
            if mon not in events:
                continue
            while True:
                try:
                    evt = recv_monitor_message(mon, flags=zmq.NOBLOCK)
                except zmq.Again:
                    break
                kind = evt["event"]
                if kind == zmq.EVENT_HANDSHAKE_SUCCEEDED:
                    up = True
                    self._handshaken.add(peer)
                    self._down_since.pop(peer, None)
                    self._recreate_state.pop(peer, None)
                elif kind == zmq.EVENT_DISCONNECTED:
                    up = False
                    self._down_since.setdefault(peer, time.monotonic())
                else:
                    continue
                if self._peer_up.get(peer) != up:
                    self._peer_up[peer] = up
                    logger.info("%s: peer %s %s", self.name, peer,
                                "up" if up else "down")
                    if self.on_connection_change is not None:
                        self.on_connection_change(peer, up)

    def drain_inbound(self) -> int:
        """Drain EVERY pending socket read and dispatch it (the
        dispatch-plane drain step over real sockets): loops until the
        listener reports empty, so when this returns the composition
        holds the COMPLETE inbound set — signed ingress in the auth
        queue, votes recorded host-side. The Looper prods transports
        before servicing timers, so a barrier quorum tick always fires
        against a drained transport (one grouped device step then covers
        everything that arrived during the interval)."""
        handled = 0
        while True:
            try:
                frames = self._listener.recv_multipart(
                    flags=zmq.NOBLOCK, copy=False)
            except zmq.Again:
                break
            payload = frames[-1]
            sender = self._sender_of(payload)
            if sender is None:
                continue  # unauthenticated — ZAP metadata missing
            self._dispatch(bytes(payload.buffer), sender)
            handled += 1
        return handled

    def service(self, timeout_ms: int = 0) -> int:
        """Pump ZAP + inbound + outbound once; returns messages handled.

        Order per pass: handshakes (ZAP) and liveness edges first, then a
        FULL inbound drain (:meth:`drain_inbound` — the tick contract's
        drain step), then the coalesced outbound flush."""
        handled = 0
        events = dict(self._poller.poll(timeout_ms))
        if self._zap in events:
            self._service_zap()
        self._service_monitors(events)
        self._retry_dead_connections()
        if self._listener in events:
            handled += self.drain_inbound()
        self._flush()
        return handled

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for sock in self._remotes.values():
            try:
                sock.disable_monitor()
            except Exception:  # noqa: BLE001
                pass
            sock.close(0)
        for mon in self._monitors:
            mon.close(0)
        self._listener.close(0)
        self._zap.close(0)
        self._ctx.term()
