"""Stack key management: deterministic CurveZMQ keypairs from seeds.

Reference: plenum's key-init utilities (plenum/common/keygen_utils.py,
stp_core key directories). A node's transport identity is its Curve25519
keypair; the pool's key registry (here: a dict name -> public key, later
fed from the pool ledger) is what lets the ZAP authenticator pin every
inbound connection to a known validator.
"""
from __future__ import annotations

import hashlib
from typing import Tuple

import zmq
import zmq.utils.z85 as z85


def client_stack_keypair_from_seed(seed: bytes) -> Tuple[bytes, bytes]:
    """The node's CLIENT-facing listener identity, derived separately from
    its node-to-node key (publishing it must leak nothing about the
    inter-validator plane). The single definition both the listener
    (ClientZStack) and pool provisioning (generate_pool_config) use — two
    copies of this derivation would silently desync the published
    client_public from the key actually served."""
    return curve_keypair_from_seed(
        hashlib.sha256(b"client-stack" + seed).digest())


def curve_keypair_from_seed(seed: bytes) -> Tuple[bytes, bytes]:
    """(public_z85, secret_z85) derived deterministically from ``seed``.

    Any 32 bytes are a valid Curve25519 secret (libzmq clamps); hashing
    the seed decouples the wire key from other uses of the same seed.
    """
    if len(seed) != 32:
        raise ValueError("seed must be 32 bytes")
    secret_raw = hashlib.sha256(b"zstack-curve" + seed).digest()
    secret_z85 = z85.encode(secret_raw)
    public_z85 = zmq.curve_public(secret_z85)
    return public_z85, secret_z85
