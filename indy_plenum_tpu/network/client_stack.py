"""Client-facing transport: the node's second listener + the pool client.

Reference: stp_zmq/simple_zstack.py (`SimpleZStack`) and
stp_zmq/client_message_provider.py (`ClientMessageProvider`). Every
validator binds TWO sockets: the node-to-node ROUTER (zstack.py, curve
keys pinned to the pool registry) and this client-facing ROUTER, which is
curve-ENCRYPTED but not curve-PINNED — any client keypair may complete the
handshake (clients are authenticated at the application layer by their
request signatures, not at transport). Replies route back over the same
ROUTER connection by ZMQ identity, which is what ClientMessageProvider
does upstream.

Wire format:
  client -> node: msgpack of ``Request.as_dict()`` (no "op" field — the
                  only legitimate inbound traffic on this socket is
                  client requests)
  node -> client: msgpack of REPLY / REQACK / REQNACK via the node
                  message registry ("op"-dispatched)
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

import zmq

from ..common.messages.message_base import node_message_registry
from ..common.request import Request
from ..common.serializers.serialization import (
    deserialize_msgpack,
    serialize_msg,
)
from .keys import curve_keypair_from_seed

logger = logging.getLogger(__name__)

_ZAP_ENDPOINT = "inproc://zeromq.zap.01"


class ClientZStack:
    """The node-side client listener (reference: SimpleZStack)."""

    def __init__(self,
                 name: str,
                 seed: bytes,
                 on_request: Optional[Callable[[Request, str], None]] = None,
                 bind_host: str = "127.0.0.1",
                 bind_port: int = 0,
                 msg_len_limit: int = 128 * 1024):
        self.name = name
        from .keys import client_stack_keypair_from_seed

        self.public_key, self._secret_key = \
            client_stack_keypair_from_seed(seed)
        self.on_request = on_request  # (Request, client_id) -> None
        self._msg_len_limit = msg_len_limit

        # own context: ZAP policy is per-context, and this listener's
        # policy (admit any curve key) must not leak onto the node stack
        self._ctx = zmq.Context()
        self._ctx.set(zmq.BLOCKY, False)  # never hang shutdown on term()
        self._closed = False
        self._zap = self._ctx.socket(zmq.ROUTER)
        self._zap.bind(_ZAP_ENDPOINT)

        self._listener = self._ctx.socket(zmq.ROUTER)
        self._listener.setsockopt(zmq.CURVE_SERVER, 1)
        self._listener.setsockopt(zmq.CURVE_SECRETKEY, self._secret_key)
        self._listener.setsockopt(zmq.LINGER, 0)
        # unroutable replies must FAIL, not vanish: without MANDATORY a
        # ROUTER silently discards sends to a departed identity and
        # send_to_client's False path would be unreachable
        self._listener.setsockopt(zmq.ROUTER_MANDATORY, 1)
        self._listener.bind(f"tcp://{bind_host}:{bind_port}")
        endpoint = self._listener.getsockopt_string(zmq.LAST_ENDPOINT)
        self.ha: Tuple[str, int] = (bind_host,
                                    int(endpoint.rsplit(":", 1)[1]))

        self._poller = zmq.Poller()
        self._poller.register(self._listener, zmq.POLLIN)
        self._poller.register(self._zap, zmq.POLLIN)
        # client_id (identity hex) -> ROUTER identity frame for replies.
        # Bounded LRU: this listener admits ANY curve key by design, so an
        # attacker opening connections in a loop must not grow node
        # memory without bound; evicting an ACTIVE client only costs it a
        # reply (it re-submits / asks another node, reference behaviour)
        from collections import OrderedDict

        self._identities: "OrderedDict[str, bytes]" = OrderedDict()
        self._max_identities = 10_000
        self.received = 0

    # ------------------------------------------------------------------

    def _service_zap(self) -> None:
        """Permissive ZAP: every CURVE handshake is admitted. Clients are
        not pool members; their requests authenticate themselves."""
        while True:
            try:
                frames = self._zap.recv_multipart(flags=zmq.NOBLOCK)
            except zmq.Again:
                return
            try:
                split = frames.index(b"")
            except ValueError:
                continue
            envelope, body = frames[:split + 1], frames[split + 1:]
            if len(body) < 6:
                continue
            version, request_id = body[0], body[1]
            self._zap.send_multipart(envelope + [
                version, request_id, b"200", b"OK", b"client", b""])

    def _handle_payload(self, identity: bytes, payload: bytes) -> None:
        if len(payload) > self._msg_len_limit:
            logger.warning("%s: oversize client message dropped", self.name)
            return
        client_id = identity.hex()
        self._identities[client_id] = identity
        self._identities.move_to_end(client_id)
        while len(self._identities) > self._max_identities:
            self._identities.popitem(last=False)
        try:
            data = deserialize_msgpack(payload)
            req = Request.from_dict(data)
        except Exception as exc:  # noqa: BLE001 — wire data is untrusted
            logger.warning("%s: bad client request: %s", self.name, exc)
            return
        self.received += 1
        if self.on_request is not None:
            self.on_request(req, client_id)

    def send_to_client(self, client_id: str, msg) -> bool:
        """Route a REPLY/REQACK/REQNACK back over the client's own
        connection; False if the connection is gone (client's problem —
        it re-submits or asks another node, reference behaviour)."""
        identity = self._identities.get(client_id)
        if identity is None:
            return False
        payload = serialize_msg(msg.as_dict() if hasattr(msg, "as_dict")
                                else msg)
        try:
            self._listener.send_multipart([identity, payload],
                                          flags=zmq.NOBLOCK)
            return True
        except zmq.ZMQError:
            return False

    def service(self, timeout_ms: int = 0) -> int:
        handled = 0
        events = dict(self._poller.poll(timeout_ms))
        if self._zap in events:
            self._service_zap()
        if self._listener in events:
            while True:
                try:
                    frames = self._listener.recv_multipart(flags=zmq.NOBLOCK)
                except zmq.Again:
                    break
                if len(frames) < 2:
                    continue
                self._handle_payload(frames[0], frames[-1])
                handled += 1
        return handled

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._listener.close(0)
        self._zap.close(0)
        self._ctx.term()


class NodeClientSurface:
    """Glue: one node's ClientZStack pumped by the Looper — inbound
    requests into ``Node.submit_client_request``, the node's
    ``client_outbox`` drained back out (reference:
    ClientMessageProvider.transmit_to_client)."""

    def __init__(self, node, stack: ClientZStack):
        self.node = node
        self.stack = stack
        stack.on_request = self._on_request

    def _on_request(self, req: Request, client_id: str) -> None:
        try:
            self.node.submit_client_request(req, client_id=client_id)
        except Exception:  # noqa: BLE001 — one bad request must not kill
            # the client surface
            logger.exception("%s: client request failed", self.node.name)

    def service(self, timeout_ms: int = 0) -> int:
        handled = self.stack.service(timeout_ms)
        outbox, self.node.client_outbox = self.node.client_outbox, []
        for client_id, msg in outbox:
            if client_id is not None:
                self.stack.send_to_client(client_id, msg)
        return handled + len(outbox)

    def close(self) -> None:
        self.stack.close()


class PoolClientStack:
    """The client-process side: one DEALER per validator, fresh curve
    keypair, pool-published server keys (reference: the client's
    SimpleZStack connecting to every node's client port)."""

    def __init__(self,
                 name: str,
                 nodes: Dict[str, Tuple[Tuple[str, int], bytes]],
                 on_message: Optional[Callable] = None,
                 msg_len_limit: int = 128 * 1024):
        """``nodes``: node name -> ((host, port), server_public_z85)."""
        import os

        self.name = name
        self.on_message = on_message  # (node_name, msg) -> None
        self._msg_len_limit = msg_len_limit
        # da: allow[nondet-source] -- CurveZMQ session keypair generation: entropy by design (crypto keygen seam), never replayed
        public, secret = curve_keypair_from_seed(os.urandom(32))
        self._ctx = zmq.Context()
        self._ctx.set(zmq.BLOCKY, False)  # never hang shutdown on term()
        self._closed = False
        self._remotes: Dict[str, zmq.Socket] = {}
        self._poller = zmq.Poller()
        for node_name, (ha, server_public) in nodes.items():
            sock = self._ctx.socket(zmq.DEALER)
            sock.setsockopt(zmq.CURVE_SERVERKEY, bytes(server_public))
            sock.setsockopt(zmq.CURVE_PUBLICKEY, public)
            sock.setsockopt(zmq.CURVE_SECRETKEY, secret)
            sock.setsockopt(zmq.LINGER, 0)
            sock.connect(f"tcp://{ha[0]}:{ha[1]}")
            self._remotes[node_name] = sock
            self._poller.register(sock, zmq.POLLIN)

    @property
    def node_names(self) -> List[str]:
        return list(self._remotes)

    def send(self, request: Request, node_name: str) -> None:
        sock = self._remotes.get(node_name)
        if sock is None:
            logger.warning("client %s: unknown node %s", self.name,
                           node_name)
            return
        try:
            sock.send(serialize_msg(request.as_dict()), flags=zmq.NOBLOCK)
        except zmq.Again:
            logger.warning("client %s: send queue full for %s", self.name,
                           node_name)

    def service(self, timeout_ms: int = 0) -> int:
        handled = 0
        events = dict(self._poller.poll(timeout_ms))
        for node_name, sock in self._remotes.items():
            if sock not in events:
                continue
            while True:
                try:
                    payload = sock.recv(flags=zmq.NOBLOCK)
                except zmq.Again:
                    break
                if len(payload) > self._msg_len_limit:
                    continue
                try:
                    msg = node_message_registry.obj_from_dict(
                        deserialize_msgpack(payload))
                except Exception as exc:  # noqa: BLE001 — untrusted
                    logger.warning("client %s: bad message from %s: %s",
                                   self.name, node_name, exc)
                    continue
                handled += 1
                if self.on_message is not None:
                    self.on_message(node_name, msg)
        return handled

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for sock in self._remotes.values():
            sock.close(0)
        self._ctx.term()
