"""Import-and-register plugin loader (reference: plenum's PLUGIN_ROOT)."""
from __future__ import annotations

import importlib
import logging
from typing import Iterable

logger = logging.getLogger(__name__)

ENTRY_POINT = "plugin_entry"


def load_plugins(node, modules: Iterable[str]) -> int:
    """Import each module and call its ``plugin_entry(node)``. Returns the
    number of plugins loaded; a faulty plugin is logged and skipped (one
    bad extension must not keep a validator down)."""
    loaded = 0
    for name in modules or ():
        try:
            mod = importlib.import_module(name)
            entry = getattr(mod, ENTRY_POINT, None)
            if entry is None:
                logger.warning("plugin %s has no %s()", name, ENTRY_POINT)
                continue
            entry(node)
            loaded += 1
            logger.info("loaded plugin %s", name)
        except Exception:  # noqa: BLE001 — plugin code is third-party
            logger.exception("plugin %s failed to load", name)
    return loaded
