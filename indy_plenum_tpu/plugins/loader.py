"""Import-and-register plugin loader (reference: plenum's PLUGIN_ROOT)."""
from __future__ import annotations

import importlib
import logging
from typing import Iterable

logger = logging.getLogger(__name__)

ENTRY_POINT = "plugin_entry"


class PluginLoadError(Exception):
    pass


def load_plugins(node, modules: Iterable[str]) -> int:
    """Import each module and call its ``plugin_entry(node)``; returns the
    number loaded.

    FAIL-FAST: a configured plugin that cannot load raises. For a BFT
    validator, silently running without a handler its peers have is worse
    than being down — the node would reject txns of that type, compute
    divergent roots, and permanently fall out of consensus while logs
    show only a startup warning."""
    loaded = 0
    for name in modules or ():
        try:
            mod = importlib.import_module(name)
            entry = getattr(mod, ENTRY_POINT, None)
            if entry is None:
                raise PluginLoadError(
                    f"plugin {name} has no {ENTRY_POINT}()")
            entry(node)
        except PluginLoadError:
            raise
        except Exception as exc:  # noqa: BLE001 — plugin code is hostile
            raise PluginLoadError(
                f"plugin {name} failed to load: {exc}") from exc
        loaded += 1
        logger.info("loaded plugin %s", name)
    return loaded
