"""Plugin loading: extend a node with new txn types / authenticators.

Reference: plenum/server/plugin/ + the PLUGIN_ROOT loader
(plenum/common/plugin_helper.py). A plugin is an importable module
exposing ``plugin_entry(node)``; at node init every module listed in
``config.PluginModules`` is imported and its entry called with the Node,
which offers the same seams the built-ins use:

- ``node.boot.write_manager.register_req_handler(handler)`` — new write
  txn types (subclass WriteRequestHandler);
- ``node.read_manager`` handlers — new proved-read types;
- ``node.authnr`` / ReqAuthenticator — additional authenticators;
- ``node.internal_bus`` — observe protocol events (Ordered, suspicions).
"""
from .loader import load_plugins

__all__ = ["load_plugins"]
