"""Interactive CLI: provision, run and talk to a local pool from a REPL.

Reference: plenum/cli/ (`PlenumCli` — `new node`, `new client`, `send
NYM`, status commands; marked semi-legacy upstream but part of the §2.8
surface). This is the operational analog over this package's real
stack: pools provisioned by tools/local_pool, validators on one Looper
over real CurveZMQ sockets, a socket client with f+1 write quorums and
proved reads.

Commands (also `help`):
    new pool <dir> [n]      provision keys + genesis for an n-node pool
    start pool <dir>        start the validators in-process + a client
    status                  per-node view/height/connection summary
    send nym <alias>        trustee-signed NYM for a fresh DID
    get nym <alias>         proved read of an earlier alias
    stop | exit             stop the pool and leave

Scriptable: ``python -m indy_plenum_tpu.cli`` reads commands from stdin,
so tests and operators can pipe a session.
"""
from __future__ import annotations

import hashlib
import shlex
import sys
import time
from typing import Optional


class PoolCli:
    def __init__(self, out=None):
        self._out = out or sys.stdout
        self._looper = None
        self._nodes = []
        self._stacks = []
        self._client = None
        self._trustee = None
        self._aliases = {}  # alias -> DidSigner (targets we created)
        # da: allow[nondet-source] -- interactive CLI seeds req ids from the wall clock; seeded runs drive SimPool/NodePool, never the CLI
        self._req_id = int(time.time()) % 1_000_000

    def _print(self, text: str) -> None:
        print(text, file=self._out)

    # --- commands -------------------------------------------------------

    def do_new_pool(self, directory: str, n: str = "4") -> None:
        from ..tools.local_pool import generate_pool_config

        generate_pool_config(directory, n_nodes=int(n))
        self._print(f"pool of {n} provisioned in {directory}")

    def do_start_pool(self, directory: str) -> None:
        from ..crypto.signers import DidSigner
        from ..tools.local_pool import (
            build_client,
            load_secret_seed,
            run_pool,
        )

        if self._nodes:
            self._print("a pool is already running; `stop` it first")
            return
        self._looper, self._nodes, self._stacks = run_pool(directory)
        self._client, client_stack = build_client(directory, "cli-client")
        self._looper.add(client_stack)
        self._trustee = DidSigner(load_secret_seed(directory, "trustee"))
        self._looper.run_until(
            lambda: all(len(s.connected_peers) >= len(self._nodes) - 1
                        for s in self._stacks), timeout=30)
        # warm the signature-verify kernel BEFORE the first real write:
        # the first XLA compile takes tens of seconds (minutes on a
        # remote device) and would otherwise eat the write's quorum
        # timeout
        self._print("warming signature kernels...")
        from ..tools.local_pool import warm_verify_kernel

        warm_verify_kernel(self._nodes[0], self._trustee)
        connected = all(len(s.connected_peers) >= len(self._nodes) - 1
                        for s in self._stacks)
        if connected:
            self._print(
                f"{len(self._nodes)} validators up; client connected "
                f"as cli-client (trustee {self._trustee.identifier})")
        else:
            self._print(
                "WARNING: pool started but not fully connected "
                "(some handshakes pending) — writes may stall; "
                "check `status`")

    def do_status(self) -> None:
        if not self._nodes:
            self._print("no pool running")
            return
        for node in self._nodes:
            self._print(
                f"  {node.name}: view {node.data.view_no}, "
                f"ordered {len(node.ordered_digests)}, "
                f"participating {node.data.is_participating}")

    def do_send_nym(self, alias: str) -> None:
        from ..common.constants import NYM, TARGET_NYM, TXN_TYPE, VERKEY
        from ..common.request import Request
        from ..crypto.signers import DidSigner

        if self._client is None:
            self._print("no pool running")
            return
        target = DidSigner(hashlib.sha256(
            b"cli-nym-" + alias.encode()).digest())
        self._req_id += 1
        req = Request(identifier=self._trustee.identifier,
                      reqId=self._req_id,
                      operation={TXN_TYPE: NYM,
                                 TARGET_NYM: target.identifier,
                                 VERKEY: target.verkey})
        self._trustee.sign_request(req)
        digest = self._client.submit_write(req)
        res = self._await_result(digest)
        if res is not None:
            # alias registered only once the write is CONFIRMED — a
            # timed-out write must not make `get nym` consult a NYM
            # that was never committed
            self._aliases[alias] = target
            self._print(f"NYM {alias} -> {target.identifier} written "
                        f"(f+1 quorum)")
        # rejection/timeout already reported by _await_result

    def do_get_nym(self, alias: str) -> None:
        from ..common.constants import GET_NYM, TARGET_NYM, TXN_TYPE
        from ..common.request import Request

        if self._client is None:
            self._print("no pool running")
            return
        target = self._aliases.get(alias)
        if target is None:
            self._print(f"unknown alias {alias!r} (send nym {alias} first)")
            return
        self._req_id += 1
        req = Request(identifier=self._trustee.identifier,
                      reqId=self._req_id,
                      operation={TXN_TYPE: GET_NYM,
                                 TARGET_NYM: target.identifier})
        digest = self._client.submit_read(req)
        res = self._await_result(digest)
        if res is None:
            self._print(f"get nym {alias}: no verifiable reply")
        elif res.get("data") is None:
            # a proved ABSENCE is a valid verified answer, not a hit
            self._print(f"NYM {alias}: provably absent")
        else:
            self._print(f"NYM {alias}: dest={res.get('dest')} "
                        f"(proved read)")

    def _await_result(self, digest: str, timeout: float = 60.0):
        """Poll to completion OR rejection; retires the request either
        way (take_result — pending must not grow for a long session)
        and surfaces NACK evidence instead of mislabelling it a
        timeout."""
        from ..client.client import RequestRejected

        self._looper.run_until(
            lambda: (self._client.result(digest) is not None
                     or self._client.is_rejected(digest)),
            timeout=timeout)
        try:
            res = self._client.take_result(digest)
        except RequestRejected as rej:
            self._print(f"request rejected by the pool: {rej.nacks}")
            return None
        if res is None:
            self._client.retire(digest)
            self._print("no quorum within timeout")
        return res

    def do_stop(self) -> None:
        if self._looper is not None:
            self._looper.shutdown()  # stop prodables before sockets close
        for node in self._nodes:
            node.stop()
            node.client_surface.close()
        for stack in self._stacks:
            stack.close()
        if self._client is not None:
            self._client.stack.close()
        self._nodes, self._stacks, self._client = [], [], None
        self._looper = self._trustee = None
        self._aliases.clear()  # a later pool must not resolve old aliases
        self._print("pool stopped")

    HELP = (
        "commands: new pool <dir> [n] | start pool <dir> | status | "
        "send nym <alias> | get nym <alias> | stop | exit")

    # --- dispatch -------------------------------------------------------

    def run_command(self, line: str) -> bool:
        """One command; returns False when the session should end."""
        parts = shlex.split(line.strip())
        if not parts:
            return True
        cmd = parts[0].lower()
        try:
            if cmd == "exit":
                self.do_stop()
                return False
            if cmd == "help":
                self._print(self.HELP)
            elif cmd == "new" and parts[1:2] == ["pool"]:
                self.do_new_pool(*parts[2:])
            elif cmd == "start" and parts[1:2] == ["pool"]:
                self.do_start_pool(*parts[2:])
            elif cmd == "status":
                self.do_status()
            elif cmd == "send" and parts[1:2] == ["nym"]:
                self.do_send_nym(*parts[2:])
            elif cmd == "get" and parts[1:2] == ["nym"]:
                self.do_get_nym(*parts[2:])
            elif cmd == "stop":
                self.do_stop()
            else:
                self._print(f"unknown command: {line.strip()!r} — try "
                            "`help`")
        except Exception as exc:  # noqa: BLE001 — a REPL must not die on
            # a failed command; the operator sees the error and continues
            self._print(f"error: {exc}")
        return True

    def repl(self, stdin=None) -> None:
        stdin = stdin or sys.stdin
        self._print("indy-plenum-tpu cli — `help` for commands")
        for line in stdin:
            if not self.run_command(line):
                return
        self.do_stop()  # EOF: clean shutdown


def main() -> int:
    PoolCli().repl()
    return 0


if __name__ == "__main__":
    sys.exit(main())
