from .cli import PoolCli, main  # noqa: F401
