"""Consensus flight recorder: bounded, deterministic span traces.

RBFT's safety-against-slowness argument (Aublin et al., ICDCS 2013) rests
on *measuring* where time goes; the aggregate counters in
:mod:`~indy_plenum_tpu.common.metrics_collector` say how much, never
*where*. This module is the missing span layer (Dapper-style request
tracing, Sigelman et al. 2010): a ring-buffer :class:`TraceRecorder`
captures structured events for

- the per-batch 3PC lifecycle, keyed ``(view_no, pp_seq_no, digest)``:
  ``3pc.preprepare_sent`` (primary) / ``3pc.preprepare`` (applied) →
  ``3pc.prepare_quorum`` → ``3pc.commit_quorum`` → ``3pc.ordered`` →
  ``3pc.executed``, plus per-request ``req.ingress`` → ``req.finalised``
  marks (the auth phase) keyed by request digest;
- the per-tick dispatch plane (cat ``dispatch``): ``tick.drain``,
  ``flush.dispatch`` (one per grouped device step, with votes/shape/
  shard occupancy), ``flush.readback``, ``tick.flush``, ``tick.eval``,
  ``tick.governor``;
- flight events (cat ``flight``): chaos invariant violations, the
  ordering-stall watchdog firing, governor saturation anomalies. Each
  one snapshots the ring's tail (:meth:`TraceRecorder.trigger_dump`) —
  the "flight recorder" moment.

Determinism contract: the clock is INJECTED. Simulation pools hand in
``MockTimer.get_current_time`` (logical time), so a seeded run — chaos
and mesh runs included — produces a **bit-identical** JSONL dump,
checkable like ``SimPool.ordered_hash()`` (``trace_hash``). Deployed
nodes inject ``time.perf_counter`` and trade determinism for real
durations. Recording must cost ~nothing when disabled:
:data:`NULL_TRACE` (a :class:`NullTraceRecorder`) mirrors
``NullMetricsCollector`` — every hot-path call site guards non-trivial
argument construction behind ``trace.enabled``.

``scripts/trace_tool.py`` consumes dumps: per-phase latency percentiles,
critical-path breakdown per ordered batch, and Chrome trace-event JSON
(:func:`to_chrome_trace`) loadable in Perfetto.
"""
from __future__ import annotations

import hashlib
import json
from collections import deque
from contextlib import contextmanager, nullcontext
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# disabled-trace fast path: a shared no-op context manager (nullcontext
# is reentrant and reusable) so call-site span guards stay one branch:
# ``with trace.span(...) if trace.enabled else _NO_SPAN:``
_NO_SPAN = nullcontext()

DEFAULT_CAPACITY = 65536
# tail size snapshotted by a flight trigger, and how many triggered
# dumps the recorder retains (oldest evicted): a storm of stall votes
# must not grow memory without bound
FLIGHT_TAIL = 512
MAX_FLIGHT_DUMPS = 8

# canonical 3PC phase chain: each phase is the delta between two
# lifecycle marks for the same (node, key) group. ``commit_quorum`` is
# recorded when the service OBSERVES the quorum (in tick mode that is
# the tick instant), so ``order`` captures only the in-order delivery
# wait on top of it.
PHASES: Tuple[Tuple[str, str, str], ...] = (
    ("prepare", "3pc.preprepare", "3pc.prepare_quorum"),
    ("commit", "3pc.prepare_quorum", "3pc.commit_quorum"),
    ("order", "3pc.commit_quorum", "3pc.ordered"),
    ("execute", "3pc.ordered", "3pc.executed"),
    ("total_3pc", "3pc.preprepare", "3pc.executed"),
)
AUTH_PHASE = ("auth", "req.ingress", "req.finalised")
# state-proof plane: a checkpoint boundary batch's ordering → its
# window's pool proof becoming servable (CheckpointProofCache capture).
# Joined per node on (view_no, seq_no_end) — the window key IS the
# boundary batch's (view, pp_seq), so the sample measures exactly the
# stabilization wait a proved read pays before a root is servable.
PROOF_PHASE = ("proof", "3pc.ordered", "proof.window_signed")
# catchup plane: a leecher round's full recovery arc, joined per
# (node, round ordinal) — how long a lagging node took from detecting
# the gap to rejoining 3PC with every leeched batch proof-verified
# (``catchup.txns_leeched`` marks ride the same category, un-keyed).
CATCHUP_PHASE = ("catchup", "catchup.started", "catchup.completed")
# state-commit plane: a batch's execution (commit_batch returning its
# staged record) → its state root durably advanced (the executed→proof
# hop's first half). Joined per node on (view_no, pp_seq_no) — the
# ``state.commit`` mark also carries the node's cumulative tree-hash
# meter, so a dump shows hash cost alongside the latency chain.
STATE_PHASE = ("state_commit", "3pc.executed", "state.commit")


class TraceRecorder:
    """Bounded ring buffer of span events on an injected clock."""

    enabled = True

    def __init__(self, clock: Callable[[], float],
                 capacity: int = DEFAULT_CAPACITY, node: str = "",
                 flight_tail: int = FLIGHT_TAIL):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self._clock = clock
        self.capacity = capacity
        self.node = node
        self.flight_tail = flight_tail
        # (seq, ts, name, cat, node, key, dur, args) — tuples, not dicts:
        # one append per event on the hot path, serialization is lazy
        self._events: "deque[tuple]" = deque(maxlen=capacity)
        self._seq = 0
        # triggered flight dumps: {"reason", "ts", "seq", "events"}
        self.dumps: "deque[dict]" = deque(maxlen=MAX_FLIGHT_DUMPS)

    # --- recording ------------------------------------------------------

    def record(self, name: str, cat: str = "3pc", node: str = "",
               key: Optional[Sequence] = None, dur: Optional[float] = None,
               args: Optional[Dict[str, Any]] = None,
               ts: Optional[float] = None) -> None:
        self._seq += 1
        self._events.append(
            (self._seq, self._clock() if ts is None else ts, name, cat,
             node or self.node, tuple(key) if key is not None else None,
             dur, args))

    @contextmanager
    def span(self, name: str, cat: str = "dispatch", node: str = "",
             args: Optional[Dict[str, Any]] = None):
        """Record a complete span (``dur`` = clock delta around the body).
        Under a virtual clock the duration is 0 unless the body advances
        the clock — the *sequence* is the deterministic signal; real
        durations come from ``perf_counter`` on deployed nodes."""
        t0 = self._clock()
        try:
            yield
        finally:
            self.record(name, cat=cat, node=node, args=args, ts=t0,
                        dur=self._clock() - t0)

    # --- flight-recorder triggers --------------------------------------

    def trigger_dump(self, reason: str, node: str = "",
                     args: Optional[Dict[str, Any]] = None) -> dict:
        """The flight-recorder moment: record a ``flight.<reason>`` mark,
        then snapshot the ring's tail (mark included) into :attr:`dumps`.
        Returns the snapshot so callers (chaos reports) can attach it."""
        self.record("flight." + reason, cat="flight", node=node, args=args)
        snap = {"reason": reason, "ts": self._events[-1][1],
                "seq": self._seq, "events": self.tail(self.flight_tail)}
        self.dumps.append(snap)
        return snap

    # --- reading / dumping ---------------------------------------------

    @staticmethod
    def _as_dict(ev: tuple) -> Dict[str, Any]:
        seq, ts, name, cat, node, key, dur, args = ev
        out: Dict[str, Any] = {"seq": seq, "ts": ts, "name": name,
                               "cat": cat}
        if node:
            out["node"] = node
        if key is not None:
            out["key"] = list(key)
        if dur is not None:
            out["dur"] = dur
        if args:
            out["args"] = args
        return out

    def __len__(self) -> int:
        return len(self._events)

    def sized_resources(self, prefix: str = "trace."):
        """Resource-ledger registration (observability.telemetry): the
        ring and the flight-dump deque are the recorder's two bounded
        stores."""
        from .telemetry import SizedResource

        return (
            SizedResource(prefix + "ring", lambda: len(self._events),
                          bound=self._events.maxlen, entry_bytes=120,
                          ring=True),
            SizedResource(prefix + "dumps", lambda: len(self.dumps),
                          bound=self.dumps.maxlen, entry_bytes=16384,
                          ring=True),
        )

    def __bool__(self) -> bool:
        # a recorder is never falsy: with __len__ defined, an enabled
        # but still-empty ring would otherwise fail `trace or NULL_TRACE`
        # style guards and silently drop everything
        return True

    def events(self) -> List[Dict[str, Any]]:
        return [self._as_dict(ev) for ev in self._events]

    def tail(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        if n is None or n >= len(self._events):
            return self.events()
        take = list(self._events)[len(self._events) - n:]
        return [self._as_dict(ev) for ev in take]

    def to_jsonl(self) -> str:
        return events_to_jsonl(self.events())

    def dump(self, path: str, tail: Optional[int] = None) -> str:
        with open(path, "w") as fh:
            fh.write(events_to_jsonl(self.tail(tail)))
        return path

    def trace_hash(self, exclude_cats: Sequence[str] = ()) -> str:
        """sha256 of the JSONL serialization — THE trace fingerprint
        (seeded runs must reproduce it bit-for-bit, like
        ``ordered_hash``). ``exclude_cats`` drops whole categories
        before hashing: the device-eval vs host-eval identity tests
        compare the protocol timeline (3pc/req/vc) while the dispatch
        category legitimately differs (``flush.readback`` carries the
        actual readback byte counts, which are the thing being
        changed)."""
        if not exclude_cats:
            return hashlib.sha256(self.to_jsonl().encode()).hexdigest()
        drop = set(exclude_cats)
        evs = [e for e in self.events() if e.get("cat") not in drop]
        # renumber seq within the retained stream: seq is a same-ts
        # tiebreaker over ALL events, so without this an excluded
        # category's event COUNT would leak into the fingerprint (a
        # rebalanced arm emits extra dispatch marks and every later
        # protocol event's seq shifts by one)
        for i, e in enumerate(evs):
            e["seq"] = i
        return hashlib.sha256(events_to_jsonl(evs).encode()).hexdigest()

    def clear(self) -> None:
        self._events.clear()
        self.dumps.clear()


class NullTraceRecorder(TraceRecorder):
    """Zero-cost sink: the default wherever tracing is not requested.
    Call sites additionally guard argument construction behind
    ``trace.enabled`` so a disabled recorder costs one attribute load
    and one no-op call."""

    enabled = False

    def __init__(self):
        super().__init__(clock=lambda: 0.0, capacity=1)

    def record(self, name, cat="3pc", node="", key=None, dur=None,
               args=None, ts=None) -> None:
        pass

    @contextmanager
    def span(self, name, cat="dispatch", node="", args=None):
        yield

    def trigger_dump(self, reason, node="", args=None) -> dict:
        return {"reason": reason, "ts": 0.0, "seq": 0, "events": []}


NULL_TRACE = NullTraceRecorder()


class LaneTraceView:
    """A lane's view onto the pool-shared recorder (ordering lanes).

    Every event recorded through the view carries ``args["lane"]``, so
    one merged dump still attributes each mark — request lifecycle, 3PC
    waves, net send/recv — to the ordering lane that produced it (the
    causal plane keys its wave joins on it: two lanes both at
    ``(view 0, seq 5)`` must never cross-pollute each other's latency
    samples). Everything else (ring, clock, dumps, journey-rollup cache)
    delegates to the wrapped recorder, so ``trace_hash``/``to_jsonl``
    cover the whole pool regardless of which view a caller holds."""

    def __init__(self, base: TraceRecorder, lane: int):
        self._base = base
        self.lane = lane
        self.enabled = base.enabled

    def _tag(self, args: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        tagged = {"lane": self.lane}
        if args:
            tagged.update(args)
        return tagged

    def record(self, name: str, cat: str = "3pc", node: str = "",
               key: Optional[Sequence] = None, dur: Optional[float] = None,
               args: Optional[Dict[str, Any]] = None,
               ts: Optional[float] = None) -> None:
        self._base.record(name, cat=cat, node=node, key=key, dur=dur,
                          args=self._tag(args), ts=ts)

    def span(self, name: str, cat: str = "dispatch", node: str = "",
             args: Optional[Dict[str, Any]] = None):
        return self._base.span(name, cat=cat, node=node,
                               args=self._tag(args))

    def trigger_dump(self, reason: str, node: str = "",
                     args: Optional[Dict[str, Any]] = None) -> dict:
        return self._base.trigger_dump(reason, node=node,
                                       args=self._tag(args))

    def __getattr__(self, item):
        return getattr(self._base, item)


# ----------------------------------------------------------------------
# serialization
# ----------------------------------------------------------------------

def events_to_jsonl(events: List[Dict[str, Any]]) -> str:
    """One sorted-key JSON object per line: the canonical dump format
    (byte-stable for identical event sequences)."""
    return "".join(
        json.dumps(ev, sort_keys=True, separators=(",", ":")) + "\n"
        for ev in events)


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    events = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


# ----------------------------------------------------------------------
# phase analytics
# ----------------------------------------------------------------------

def _mark_times(events: List[Dict[str, Any]], cat: str,
                nodes: Optional[frozenset]
                ) -> Dict[tuple, Dict[str, float]]:
    """(node, key) -> {mark name -> earliest ts} for one category;
    ``nodes`` filters to that set (None = every node)."""
    groups: Dict[tuple, Dict[str, float]] = {}
    for ev in events:
        if ev.get("cat") != cat or ev.get("key") is None:
            continue
        ev_node = ev.get("node", "")
        if nodes is not None and ev_node not in nodes:
            continue
        marks = groups.setdefault((ev_node, tuple(ev["key"])), {})
        name = ev["name"]
        if name not in marks or ev["ts"] < marks[name]:
            marks[name] = ev["ts"]
    return groups


def phase_durations(events: List[Dict[str, Any]],
                    node: Optional[str] = None) -> Dict[str, List[float]]:
    """Per-phase duration samples from lifecycle marks. ``node=None``
    aggregates every node's samples (request marks recorded pool-level
    under node ``""`` are always included — the auth phase is a pool
    observation, not a per-replica one)."""
    out: Dict[str, List[float]] = {}
    for (_node, _key), marks in sorted(
            _mark_times(events, "3pc",
                        None if node is None
                        else frozenset((node,))).items()):
        # the primary's own batch has no applied mark; its send mark is
        # the honest phase start
        if "3pc.preprepare" not in marks \
                and "3pc.preprepare_sent" in marks:
            marks["3pc.preprepare"] = marks["3pc.preprepare_sent"]
        for phase, start, end in PHASES:
            if start in marks and end in marks:
                out.setdefault(phase, []).append(
                    marks[end] - marks[start])
    # auth phase: ingress happens on whichever node the client hit (or
    # pool-level under node ""), finalisation on EVERY node — so the
    # join runs per request digest across nodes: earliest ingress
    # anywhere → earliest finalisation on the filtered node
    ingress_ts: Dict[tuple, float] = {}
    finalised_ts: Dict[tuple, float] = {}
    for ev in events:
        if ev.get("cat") != "req" or ev.get("key") is None:
            continue
        k = tuple(ev["key"])
        if ev["name"] == AUTH_PHASE[1]:
            if k not in ingress_ts or ev["ts"] < ingress_ts[k]:
                ingress_ts[k] = ev["ts"]
        elif ev["name"] == AUTH_PHASE[2]:
            if node is not None and ev.get("node", "") not in (node, ""):
                continue
            if k not in finalised_ts or ev["ts"] < finalised_ts[k]:
                finalised_ts[k] = ev["ts"]
    for k in sorted(finalised_ts):
        if k in ingress_ts:
            out.setdefault(AUTH_PHASE[0], []).append(
                finalised_ts[k] - ingress_ts[k])
    # proof phase: per node, each proof.window_signed (key (view, seq))
    # joins the SAME node's earliest 3pc.ordered mark for the boundary
    # batch (key (view, seq, digest)) — the stabilization wait between
    # a window's last batch ordering and its pool proof being servable
    ordered_at: Dict[tuple, float] = {}
    for ev in events:
        if ev.get("cat") != "3pc" or ev["name"] != PROOF_PHASE[1] \
                or ev.get("key") is None or len(ev["key"]) < 2:
            continue
        if node is not None and ev.get("node", "") != node:
            continue
        k = (ev.get("node", ""), ev["key"][0], ev["key"][1])
        if k not in ordered_at or ev["ts"] < ordered_at[k]:
            ordered_at[k] = ev["ts"]
    for ev in events:
        if ev.get("cat") != "proof" or ev["name"] != PROOF_PHASE[2] \
                or ev.get("key") is None or len(ev["key"]) < 2:
            continue
        if node is not None and ev.get("node", "") != node:
            continue
        t0 = ordered_at.get(
            (ev.get("node", ""), ev["key"][0], ev["key"][1]))
        if t0 is not None:
            out.setdefault(PROOF_PHASE[0], []).append(ev["ts"] - t0)
    # state-commit phase: per node, each state.commit (key (view, seq))
    # joins the SAME node's earliest 3pc.executed mark for that batch
    # (key (view, seq, digest)) — how long after execution the state
    # root was durably advanced (same cross-category join as the proof
    # phase above)
    executed_at: Dict[tuple, float] = {}
    for ev in events:
        if ev.get("cat") != "3pc" or ev["name"] != STATE_PHASE[1] \
                or ev.get("key") is None or len(ev["key"]) < 2:
            continue
        if node is not None and ev.get("node", "") != node:
            continue
        k = (ev.get("node", ""), ev["key"][0], ev["key"][1])
        if k not in executed_at or ev["ts"] < executed_at[k]:
            executed_at[k] = ev["ts"]
    for ev in events:
        if ev.get("cat") != "state" or ev["name"] != STATE_PHASE[2] \
                or ev.get("key") is None or len(ev["key"]) < 2:
            continue
        if node is not None and ev.get("node", "") != node:
            continue
        t0 = executed_at.get(
            (ev.get("node", ""), ev["key"][0], ev["key"][1]))
        if t0 is not None:
            out.setdefault(STATE_PHASE[0], []).append(ev["ts"] - t0)
    # catchup phase: each leecher round's started -> completed arc,
    # joined per (node, round ordinal) like the 3PC lifecycle marks
    for (_node, _key), marks in sorted(
            _mark_times(events, "catchup",
                        None if node is None
                        else frozenset((node,))).items()):
        if CATCHUP_PHASE[1] in marks and CATCHUP_PHASE[2] in marks:
            out.setdefault(CATCHUP_PHASE[0], []).append(
                marks[CATCHUP_PHASE[2]] - marks[CATCHUP_PHASE[1]])
    return out


def percentile(samples: List[float], q: float) -> float:
    """Nearest-rank percentile over a SORTED sample list (deterministic:
    no interpolation)."""
    if not samples:
        return 0.0
    rank = max(1, -(-len(samples) * q // 100))  # ceil without floats
    return samples[int(rank) - 1]


def phase_percentiles(events: List[Dict[str, Any]],
                      node: Optional[str] = None,
                      ndigits: int = 6) -> Dict[str, Dict[str, float]]:
    """{phase: {count, p50, p90, p99, max}} — the ``phase_latency``
    block every surface reports (Monitor.snapshot, profile_rbft --json,
    bench ordered sub-benches, trace_tool)."""
    out: Dict[str, Dict[str, float]] = {}
    for phase, samples in phase_durations(events, node=node).items():
        s = sorted(samples)
        out[phase] = {
            "count": len(s),
            "p50": round(percentile(s, 50), ndigits),
            "p90": round(percentile(s, 90), ndigits),
            "p99": round(percentile(s, 99), ndigits),
            "max": round(s[-1], ndigits),
        }
    return out


# breakdown phases only (no overlapping total) — critical-path shares
# must sum to ~1.0 over an ordered batch's life
_BREAKDOWN = ("prepare", "commit", "order", "execute")


def critical_path(events: List[Dict[str, Any]],
                  node: Optional[str] = None) -> Dict[str, Any]:
    """Per ordered batch: which phase dominated its latency. Returns
    ``batches`` (groups with a complete breakdown), ``dominant`` (phase
    -> how many batches it dominated) and ``phase_share`` (phase ->
    fraction of total attributed time pool-wide)."""
    dominant: Dict[str, int] = {}
    totals: Dict[str, float] = {}
    batches = 0
    for (_node, _key), marks in sorted(
            _mark_times(events, "3pc",
                        None if node is None
                        else frozenset((node,))).items()):
        if "3pc.preprepare" not in marks \
                and "3pc.preprepare_sent" in marks:
            marks["3pc.preprepare"] = marks["3pc.preprepare_sent"]
        durs = {}
        for phase, start, end in PHASES:
            if phase in _BREAKDOWN and start in marks and end in marks:
                durs[phase] = marks[end] - marks[start]
        if not durs:
            continue
        batches += 1
        # ties break on canonical phase order (deterministic)
        top, top_d = None, float("-inf")
        for phase in _BREAKDOWN:
            if phase in durs and durs[phase] > top_d:
                top, top_d = phase, durs[phase]
        dominant[top] = dominant.get(top, 0) + 1
        for phase, d in durs.items():
            totals[phase] = totals.get(phase, 0.0) + d
    whole = sum(totals.values())
    return {
        "batches": batches,
        "dominant": {p: dominant[p] for p in _BREAKDOWN if p in dominant},
        "phase_share": {p: round(totals[p] / whole, 4)
                        for p in _BREAKDOWN if p in totals} if whole
        else {},
    }


def overlap_report(events: List[Dict[str, Any]],
                   node: Optional[str] = None) -> Dict[str, Any]:
    """Per-tick host/device overlap + readback-bytes attribution (the
    ordering fast path's measured story — ``trace_tool.py --overlap``).

    A tick's dispatch events arrive in ring order as ``tick.drain``,
    ``flush.dispatch``*, ``flush.readback``, ``tick.flush``,
    ``tick.governor``, ``tick.eval`` — the report closes a tick at each
    ``tick.flush`` mark and joins the trailing eval/governor marks to
    it. ``overlapped`` on a ``flush.readback`` means the absorb consumed
    a step DISPATCHED by an earlier flush call: its device round-trip
    hid behind at least one full tick of host work (the pipelined
    contract). ``readback_bytes`` is what actually crossed the
    device->host boundary — O(newly certified + frontier) in device
    eval, the full event matrix under host_eval.

    Mesh runs (the scale-out quorum fabric) additionally carry per-shard
    columns: ``flush.readback`` events are per member shard (``shard``
    arg) and ``flush.dispatch`` splits its votes per occupancy-grid cell
    (``shard_votes``), so the ``per_shard`` block — readback bytes per
    member shard, votes/share per cell — makes a hot shard visible from
    a trace dump alone.

    Multi-tick residency runs stage votes with ``flush.enqueue`` spans
    (these carry the votes/shard_votes; the fused ``flush.dispatch``
    then covers several ticks via its ``ticks`` arg) and record
    ``flush.defer`` when a tick ends with the ring still accumulating.
    Such traces grow per-tick ``enqueues``/``resident_ticks``/
    ``deferred`` columns plus a ``residency`` summary; traces with no
    resident events are byte-identical to before. ``rebalance.planned``
    / ``rebalance.executed`` records surface as a ``rebalances`` block
    with their marks."""
    ticks: List[Dict[str, Any]] = []
    cur = {"dispatches": 0, "votes": 0, "readbacks": 0, "overlapped": 0,
           "readback_bytes": 0}
    rcur = {"enqueues": 0, "resident_ticks": 0, "deferred": 0}
    resident_seen = False
    rtotals = {"enqueues": 0, "resident_ticks_total": 0,
               "readbacks_deferred": 0}
    rebalance_marks: List[Dict[str, Any]] = []
    rebalances_executed = 0
    shard_bytes: Dict[int, int] = {}
    shard_readbacks: Dict[int, int] = {}
    cell_votes: List[int] = []
    # per-shard data stages per tick and commits at tick.flush, so the
    # per_shard block covers exactly the same closed-tick window as the
    # totals (a trailing partial tick is dropped from BOTH views)
    pend_shard_bytes: Dict[int, int] = {}
    pend_shard_readbacks: Dict[int, int] = {}
    pend_cell_votes: List[int] = []
    for ev in events:
        if ev.get("cat") != "dispatch":
            continue
        if node is not None and ev.get("node", "") not in (node, ""):
            continue
        name, args = ev["name"], ev.get("args") or {}
        if name == "flush.dispatch":
            cur["dispatches"] += 1
            cur["votes"] += args.get("votes", 0)
            if "resident" in args:
                resident_seen = True
                rcur["resident_ticks"] += args.get("ticks", 0)
                rtotals["resident_ticks_total"] += args.get("ticks", 0)
            sv = args.get("shard_votes")
            if sv:
                if len(pend_cell_votes) < len(sv):
                    pend_cell_votes.extend(
                        [0] * (len(sv) - len(pend_cell_votes)))
                for ci, v in enumerate(sv):
                    pend_cell_votes[ci] += v
        elif name == "flush.enqueue":
            # resident staging: votes counted HERE (the fused dispatch
            # carries none, so totals stay single-counted)
            resident_seen = True
            rcur["enqueues"] += 1
            rtotals["enqueues"] += 1
            cur["votes"] += args.get("votes", 0)
            sv = args.get("shard_votes")
            if sv:
                if len(pend_cell_votes) < len(sv):
                    pend_cell_votes.extend(
                        [0] * (len(sv) - len(pend_cell_votes)))
                for ci, v in enumerate(sv):
                    pend_cell_votes[ci] += v
        elif name == "flush.defer":
            resident_seen = True
            rcur["deferred"] += 1
            rtotals["readbacks_deferred"] += 1
        elif name in ("rebalance.planned", "rebalance.executed"):
            rebalance_marks.append({"name": name, "ts": ev["ts"],
                                    "args": dict(args)})
            if name == "rebalance.executed":
                rebalances_executed += 1
        elif name == "flush.readback":
            cur["readbacks"] += 1
            cur["readback_bytes"] += args.get("bytes", 0)
            if args.get("overlapped"):
                cur["overlapped"] += 1
            shard = args.get("shard")
            if shard is not None:
                pend_shard_bytes[shard] = (pend_shard_bytes.get(shard, 0)
                                           + args.get("bytes", 0))
                pend_shard_readbacks[shard] = \
                    pend_shard_readbacks.get(shard, 0) + 1
        elif name == "tick.flush":
            cur["ts"] = ev["ts"]
            if resident_seen:
                cur.update(rcur)
            ticks.append(cur)
            cur = {"dispatches": 0, "votes": 0, "readbacks": 0,
                   "overlapped": 0, "readback_bytes": 0}
            rcur = {"enqueues": 0, "resident_ticks": 0, "deferred": 0}
            for s, b in pend_shard_bytes.items():
                shard_bytes[s] = shard_bytes.get(s, 0) + b
            for s, n in pend_shard_readbacks.items():
                shard_readbacks[s] = shard_readbacks.get(s, 0) + n
            if len(cell_votes) < len(pend_cell_votes):
                cell_votes.extend(
                    [0] * (len(pend_cell_votes) - len(cell_votes)))
            for ci, v in enumerate(pend_cell_votes):
                cell_votes[ci] += v
            pend_shard_bytes = {}
            pend_shard_readbacks = {}
            pend_cell_votes = []
    byte_series = sorted(t["readback_bytes"] for t in ticks)
    readbacks = sum(t["readbacks"] for t in ticks)
    overlapped = sum(t["overlapped"] for t in ticks)
    out = {
        "ticks": len(ticks),
        "readbacks": readbacks,
        # host/device overlap fraction: readbacks whose round-trip hid
        # behind a full tick of host work / all readbacks
        "overlap_fraction": (round(overlapped / readbacks, 4)
                             if readbacks else 0.0),
        "readback_bytes_total": sum(byte_series),
        "readback_bytes_per_tick": {
            "p50": percentile(byte_series, 50),
            "max": byte_series[-1] if byte_series else 0,
        },
        "per_tick": ticks,
    }
    if resident_seen:
        out["residency"] = dict(rtotals)
    if rebalance_marks:
        out["rebalances"] = {"executed": rebalances_executed,
                             "marks": rebalance_marks}
    if shard_bytes or cell_votes:
        n_shards = max([s + 1 for s in shard_bytes] or [0])
        total_votes = sum(cell_votes)
        out["per_shard"] = {
            # member shards: what each shard's compact blocks cost to
            # read back (and how many blocks absorbed)
            "readback_bytes": [shard_bytes.get(s, 0)
                               for s in range(n_shards)],
            "readbacks": [shard_readbacks.get(s, 0)
                          for s in range(n_shards)],
            # occupancy-grid cells (member block x validator block,
            # flattened): each cell's vote count and share — the
            # dump-local analog of VotePlaneGroup.shard_occupancy
            "votes": list(cell_votes),
            "vote_share": [round(v / total_votes, 4) if total_votes
                           else 0.0 for v in cell_votes],
        }
    return out


def rollup_report(events: List[Dict[str, Any]],
                  node: Optional[str] = None) -> Dict[str, Any]:
    """The telemetry plane's windowed-rollup view from a flight dump
    alone (``trace_tool.py --rollups`` — the long-horizon sibling of
    ``--overlap``).

    An armed plane records one ``telemetry.roll`` mark per rolled
    window (ordered/shed/retry deltas, window p99, summed and largest
    per-resource high-water) and a ``flight.telemetry.<law>`` mark per
    fired anomaly (the drift detector's ``trigger_dump``). The report
    rebuilds the per-window table, joins each anomaly to its window,
    and totals anomalies per law — so a dump from a soak run answers
    "when did throughput drift, and what was growing" without the
    run's in-memory plane."""
    rows: List[Dict[str, Any]] = []
    by_window: Dict[int, Dict[str, Any]] = {}
    for ev in events:
        if ev.get("name") != "telemetry.roll":
            continue
        if node is not None and ev.get("node", "") not in ("", node):
            continue
        row = dict(ev.get("args") or {})
        row["ts"] = ev.get("ts")
        row["anomalies"] = []
        rows.append(row)
        if row.get("window") is not None:
            by_window[int(row["window"])] = row
    anomalies: List[Dict[str, Any]] = []
    by_law: Dict[str, int] = {}
    for ev in events:
        name = ev.get("name", "")
        if ev.get("cat") != "flight" \
                or not name.startswith("flight.telemetry."):
            continue
        law = name[len("flight.telemetry."):]
        rec = dict(ev.get("args") or {})
        rec["law"] = law
        rec["ts"] = ev.get("ts")
        anomalies.append(rec)
        by_law[law] = by_law.get(law, 0) + 1
        w = rec.get("window")
        if w is not None and int(w) in by_window:
            by_window[int(w)]["anomalies"].append(law)
    ordered = [r.get("ordered") or 0 for r in rows]
    return {
        "windows": len(rows),
        "ordered_total": sum(ordered),
        "ordered_min": min(ordered) if ordered else 0,
        "ordered_max": max(ordered) if ordered else 0,
        "anomaly_count": len(anomalies),
        "anomalies_by_law": dict(sorted(by_law.items())),
        "anomalies": anomalies,
        "per_window": rows,
    }


# ----------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing)
# ----------------------------------------------------------------------

def to_chrome_trace(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace-event JSON: one pid per node (pool-level events ride
    pid "pool"), one tid per category; spans (events with ``dur``) become
    complete "X" events, marks become instant "i" events. Timestamps are
    microseconds per the format spec.

    Transport marks (cat ``net``, the causal tracing plane) additionally
    emit **flow events**: each matched ``net.send``/``net.recv`` pair
    becomes an "s"/"f" flow arc between the sender's and receiver's
    pids, so a request's PROPAGATE/3PC journey renders as arrows hopping
    across node tracks in Perfetto."""
    nodes = sorted({ev.get("node", "") for ev in events})
    cats = sorted({ev.get("cat", "") for ev in events})
    pid_of = {n: i + 1 for i, n in enumerate(nodes)}
    tid_of = {c: i + 1 for i, c in enumerate(cats)}
    out: List[Dict[str, Any]] = []
    for n in nodes:
        out.append({"ph": "M", "name": "process_name", "pid": pid_of[n],
                    "tid": 0, "args": {"name": n or "pool"}})
    for c in cats:
        for n in nodes:
            out.append({"ph": "M", "name": "thread_name",
                        "pid": pid_of[n], "tid": tid_of[c],
                        "args": {"name": c}})
    t0 = min((ev["ts"] for ev in events), default=0.0)
    for ev in events:
        args = dict(ev.get("args") or {})
        if ev.get("key") is not None:
            args["key"] = list(ev["key"])
        rec: Dict[str, Any] = {
            "name": ev["name"],
            "cat": ev.get("cat", ""),
            "pid": pid_of[ev.get("node", "")],
            "tid": tid_of[ev.get("cat", "")],
            "ts": round((ev["ts"] - t0) * 1e6, 3),
        }
        if args:
            rec["args"] = args
        is_net_mark = (ev.get("cat") == "net"
                       and ev["name"] in ("net.send", "net.recv"))
        # cross-lane checkpoint barrier (ordering lanes): each lane's
        # readiness mark flows into the seal mark, so Perfetto draws the
        # K-way barrier join as arrows converging on barrier.sealed
        is_barrier_mark = (ev.get("cat") == "lanes"
                           and ev["name"] in ("barrier.ready",
                                              "barrier.sealed"))
        if ev.get("dur") is not None:
            rec["ph"] = "X"
            rec["dur"] = round(ev["dur"] * 1e6, 3)
        elif is_net_mark or is_barrier_mark:
            # flow ends must bind to an ENCLOSING duration slice per the
            # trace-event spec — an instant can't anchor an arrow — so
            # transport marks render as 1µs slices
            rec["ph"] = "X"
            rec["dur"] = 1.0
        else:
            rec["ph"] = "i"
            rec["s"] = "p"
        out.append(rec)
        # flow arcs: a send/recv pair shares args["id"]; the send is the
        # flow start ("s") on the sender's pid, the recv binds the end
        # ("f", enclosing slice) on the receiver's — Perfetto draws the
        # cross-node arrow
        if is_net_mark:
            flow_id = (ev.get("args") or {}).get("id")
            if flow_id is not None:
                out.append({
                    "ph": "s" if ev["name"] == "net.send" else "f",
                    "bp": "e",
                    "id": str(flow_id),
                    "name": "net." + str((ev.get("args") or {})
                                         .get("m", "msg")),
                    "cat": "net",
                    "pid": rec["pid"],
                    "tid": rec["tid"],
                    "ts": rec["ts"],
                })
        elif is_barrier_mark and ev.get("key"):
            window = ev["key"][0]
            bargs = ev.get("args") or {}
            if ev["name"] == "barrier.ready":
                flow_ids = ["barrier-%s-%s" % (window, bargs.get("lane"))]
            else:
                # sealed: close one arc per lane that actually emitted a
                # readiness mark for this window — idle/skipped lanes
                # have no flow start, and a dangling end renders broken
                ready = bargs.get("ready_lanes")
                if ready is None:  # older dumps: best-effort all lanes
                    ready = range(int(bargs.get("lanes", 0)))
                flow_ids = ["barrier-%s-%s" % (window, lane)
                            for lane in ready]
            for fid in flow_ids:
                out.append({
                    "ph": "s" if ev["name"] == "barrier.ready" else "f",
                    "bp": "e",
                    "id": fid,
                    "name": "barrier.window",
                    "cat": "lanes",
                    "pid": rec["pid"],
                    "tid": rec["tid"],
                    "ts": rec["ts"],
                })
    return {"traceEvents": out, "displayTimeUnit": "ms"}
