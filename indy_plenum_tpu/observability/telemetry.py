"""Long-horizon telemetry plane: resource ledger, windowed rollups and
deterministic drift detection (README "Long-horizon telemetry & soak").

RBFT's defining mechanism is *monitoring* — the protocol continuously
measures instance throughput and acts on degradation (Aublin et al.,
RBFT, ICDCS 2013) — so telemetry here is a first-class plane, not a log
sink. Three layers:

1. **Resource ledger** (:class:`ResourceLedger`): every bounded
   structure in the system registers a :class:`SizedResource` (name,
   live entry count, declared bound, approx bytes/entry) — trace rings,
   proof/edge cache windows, barrier seal records, admission queues,
   retry cohorts, LRU node/path caches, metrics histograms. One
   ``snapshot()`` reports current/high-water occupancy for the whole
   pool, and a structure exceeding its declared bound is a **hard
   violation** surfaced as an anomaly, not a log line.

2. **Windowed rollups** (:class:`TelemetryPlane`): bounded
   per-virtual-interval time-series rings — ordered/shed/retry deltas,
   e2e p99 from virtual-clock phase latency, governor occupancy EWMA,
   per-resource window high-waters — rolled at window boundaries
   reached through checkpoint-stabilization / ordered-event pulses.
   Every row is a pure function of virtual time and existing counters,
   so same-seed runs produce byte-identical rollup streams; the running
   ``telemetry_hash`` folds each row (and each anomaly) into a sha256
   chain exactly like the barrier's seal-fingerprint chain, so the
   fingerprint survives ring eviction with O(1) state.

3. **Drift detector**: deterministic window-over-window laws —
   throughput drift (ordered delta drops more than ``drift_frac``
   against the same-phase window ``drift_lag`` back), the leak law
   (a resource's window high-water strictly increasing for
   ``leak_windows`` consecutive windows), and latency creep (p99
   strictly increasing the same way). Each law fires the flight
   recorder's ``trigger_dump`` (bounded, once per episode), counts
   ``telemetry.anomalies``, and folds the anomaly record into the hash
   chain.

The plane's own rings (windows, anomalies, latency samples) register in
the ledger like everyone else — the monitor is not exempt from the
bounded-everything contract it enforces.
"""
from __future__ import annotations

import hashlib
import json
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..common.metrics_collector import MetricsName
from .trace import percentile

# per-window bound on the e2e latency sample ring: one sample per
# executed batch per connected node, cleared at each roll — 4096 covers
# minutes of saturated ordering between rolls; overflow drops newest
# (counted), never grows
LATENCY_SAMPLES_MAX = 4096

# metric name prefix for per-resource gauges (Stat.last = current at
# the latest roll, Stat.max = high-water over rolls); the monitor's
# telemetry block enumerates the collector summary by this prefix
RESOURCE_METRIC_PREFIX = "telemetry.resource."


@dataclass(frozen=True)
class SizedResource:
    """One bounded structure's registration: ``entries`` is a cheap O(1)
    occupancy probe, ``bound`` the structure's *declared* cap (None =
    intentionally unbounded here — still watched by the leak law), and
    ``entry_bytes`` a rough per-entry size for the byte estimate.
    ``ring=True`` declares a retention ring that fills to its maxlen BY
    CONSTRUCTION (trace rings, rollup rings): monotone growth is its
    design, so the leak law skips it — the bound-violation law still
    covers it."""

    name: str
    entries: Callable[[], int]
    bound: Optional[int] = None
    entry_bytes: int = 64
    ring: bool = False


class ResourceLedger:
    """The pool-wide occupancy register. ``sample()`` probes every
    resource (O(#resources), a handful of ``len()`` calls — safe on the
    ordered-event hot path) and maintains three views: current, running
    high-water, and per-window high-water (reset at each rollup)."""

    def __init__(self) -> None:
        self._resources: "Dict[str, SizedResource]" = {}
        self._current: Dict[str, int] = {}
        self._high_water: Dict[str, int] = {}
        self._window_hw: Dict[str, int] = {}

    def register(self, resource: SizedResource) -> None:
        if resource.name in self._resources:
            raise ValueError(f"resource {resource.name!r} already "
                             "registered (ledger names are unique)")
        self._resources[resource.name] = resource

    def register_all(self, resources: Iterable[SizedResource]) -> None:
        for res in resources:
            self.register(res)

    @property
    def names(self) -> List[str]:
        return sorted(self._resources)

    def is_ring(self, name: str) -> bool:
        res = self._resources.get(name)
        return res is not None and res.ring

    def sample(self) -> List[str]:
        """Probe every resource; returns the (usually empty) list of
        bound violations ``name entries=N over bound=B``."""
        violations: List[str] = []
        for name in self._resources:
            res = self._resources[name]
            cur = int(res.entries())
            self._current[name] = cur
            if cur > self._high_water.get(name, 0):
                self._high_water[name] = cur
            if cur > self._window_hw.get(name, 0):
                self._window_hw[name] = cur
            if res.bound is not None and cur > res.bound:
                violations.append(
                    f"{name} entries={cur} over bound={res.bound}")
        return violations

    def window_high_water(self) -> Dict[str, int]:
        """Per-resource high-water since the last :meth:`reset_window`
        (sorted keys — this dict feeds the hash chain)."""
        return {name: self._window_hw.get(name, 0)
                for name in sorted(self._resources)}

    def reset_window(self) -> None:
        self._window_hw = {}

    def current(self, name: str) -> int:
        return self._current.get(name, 0)

    def high_water(self, name: str) -> int:
        return self._high_water.get(name, 0)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Current/high-water/bound/approx-bytes per resource, sorted by
        name — the monitor's telemetry block and the soak report both
        read this."""
        out: Dict[str, Dict[str, Any]] = {}
        for name in sorted(self._resources):
            res = self._resources[name]
            cur = self._current.get(name, 0)
            out[name] = {
                "entries": cur,
                "high_water": self._high_water.get(name, 0),
                "bound": res.bound,
                "approx_bytes": cur * res.entry_bytes,
            }
        return out


def _canon(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


class TelemetryPlane:
    """Windowed rollups + drift laws over a :class:`ResourceLedger`.

    Driven by ``pulse(now)`` from deterministic virtual-time hooks
    (checkpoint stabilization, ordered events, end-of-run finalize):
    each pulse samples the ledger and rolls every window boundary the
    virtual clock has crossed. Rows and anomalies fold into the running
    ``telemetry_hash`` chain; both rings are bounded and registered in
    the ledger themselves."""

    def __init__(self, ledger: ResourceLedger, t0: float,
                 window_sec: float, keep: int = 64,
                 leak_windows: int = 4, leak_grace: int = 6,
                 drift_frac: float = 0.5, drift_lag: int = 1,
                 anomaly_keep: int = 32,
                 metrics=None, trace=None) -> None:
        if window_sec <= 0:
            raise ValueError("window_sec must be positive (0 = leave "
                             "the plane unarmed instead)")
        self.ledger = ledger
        self.t0 = float(t0)
        self.window_sec = float(window_sec)
        self.leak_windows = max(1, int(leak_windows))
        self.leak_grace = max(0, int(leak_grace))
        self.drift_frac = float(drift_frac)
        self.drift_lag = max(1, int(drift_lag))
        self.metrics = metrics
        self.trace = trace
        self.windows: "deque[dict]" = deque(maxlen=max(1, int(keep)))
        self.anomalies: "deque[dict]" = deque(maxlen=max(1, int(anomaly_keep)))
        self.completed = 0  # windows rolled so far (ring may have evicted)
        self.anomaly_count = 0  # total fired (ring may have evicted)
        self._hash = hashlib.sha256(b"telemetry").hexdigest()
        self._counters: "Dict[str, Callable[[], int]]" = {}
        self._gauges: "Dict[str, Callable[[], float]]" = {}
        self._prev_counts: Dict[str, int] = {}
        # e2e latency samples (virtual seconds, ppTime -> executed),
        # cleared each roll; overflow drops newest and counts
        self._lat: "deque[float]" = deque(maxlen=LATENCY_SAMPLES_MAX)
        self._lat_dropped = 0
        # drift-law episode state
        self._ordered_ring: "deque[int]" = deque(maxlen=self.drift_lag + 1)
        self._drift_armed = True
        self._leak_streak: Dict[str, int] = {}
        self._leak_fired: Dict[str, bool] = {}
        self._prev_window_hw: Dict[str, int] = {}
        self._lat_streak = 0
        self._lat_fired = False
        self._prev_p99: Optional[float] = None
        self._violated: set = set()
        ledger.register_all(self.sized_resources())

    @classmethod
    def from_config(cls, config, ledger: ResourceLedger, t0: float,
                    metrics=None, trace=None) -> Optional["TelemetryPlane"]:
        """Composition-root constructor: None unless armed
        (``TelemetryWindowSec`` > 0) — the common path pays nothing."""
        if config.TelemetryWindowSec <= 0:
            return None
        return cls(ledger, t0,
                   window_sec=config.TelemetryWindowSec,
                   keep=config.TelemetryWindowKeep,
                   leak_windows=config.TelemetryLeakWindows,
                   leak_grace=config.TelemetryLeakGraceWindows,
                   drift_frac=config.TelemetryDriftFrac,
                   drift_lag=config.TelemetryDriftLag,
                   anomaly_keep=config.TelemetryAnomalyKeep,
                   metrics=metrics, trace=trace)

    def sized_resources(self, prefix: str = "telemetry.") -> \
            Tuple[SizedResource, ...]:
        return (
            SizedResource(prefix + "windows", lambda: len(self.windows),
                          bound=self.windows.maxlen, entry_bytes=512,
                          ring=True),
            SizedResource(prefix + "anomalies",
                          lambda: len(self.anomalies),
                          bound=self.anomalies.maxlen, entry_bytes=256,
                          ring=True),
            SizedResource(prefix + "latency_samples",
                          lambda: len(self._lat),
                          bound=self._lat.maxlen, entry_bytes=8,
                          ring=True),
        )

    # --- series wiring --------------------------------------------------

    def add_counter(self, name: str, fn: Callable[[], int]) -> None:
        """Register a cumulative counter; rollups record per-window
        deltas."""
        self._counters[name] = fn

    def add_gauge(self, name: str, fn: Callable[[], float]) -> None:
        """Register a point-in-time gauge sampled at each roll."""
        self._gauges[name] = fn

    def observe_latency(self, seconds: float) -> None:
        """One e2e sample (virtual pre-prepare -> executed); p99 per
        window. Bounded: past the ring cap newest samples drop
        (counted) rather than grow."""
        if len(self._lat) == self._lat.maxlen:
            self._lat_dropped += 1
            return
        self._lat.append(float(seconds))

    # --- the pulse ------------------------------------------------------

    def pulse(self, now: float) -> None:
        """Sample the ledger, surface bound violations, roll every
        window boundary crossed. Deterministic: everything is a pure
        function of virtual ``now`` and registered probes."""
        for violation in self.ledger.sample():
            name = violation.split(" ", 1)[0]
            if name not in self._violated:
                self._violated.add(name)
                self._anomaly("bound_violation", self.completed,
                              {"resource": name, "detail": violation})
        while self.t0 + (self.completed + 1) * self.window_sec <= now:
            self._roll()

    def finalize(self, now: float) -> None:
        """End-of-run flush: roll all fully elapsed windows (a trailing
        partial window is dropped — deterministically)."""
        self.pulse(now)

    def _roll(self) -> None:
        w = self.completed
        counts = {name: int(fn()) for name, fn in self._counters.items()}
        deltas = {name: counts[name] - self._prev_counts.get(name, 0)
                  for name in counts}
        gauges = {name: float(fn()) for name, fn in self._gauges.items()}
        hw = self.ledger.window_high_water()
        self.ledger.reset_window()
        p99 = percentile(sorted(self._lat), 99) if self._lat else None
        self._lat.clear()
        row = {
            "window": w,
            "t_end": self.t0 + (w + 1) * self.window_sec,
            "counters": deltas,
            "gauges": gauges,
            "p99": p99,
            "high_water": hw,
            "lat_dropped": self._lat_dropped,
        }
        self._lat_dropped = 0
        self.windows.append(row)
        self._fold({"row": row})
        if self.trace is not None:
            # one compact mark per roll: a flight dump then carries the
            # rollup series, and trace_tool --rollups rebuilds the
            # window table from the dump alone (largest resource named
            # so a leak suspect is visible without the full ledger)
            top = max(hw, key=lambda n: (hw[n], n)) if hw else None
            self.trace.record(
                "telemetry.roll", cat="telemetry",
                args={"window": w, "ordered": deltas.get("ordered"),
                      "shed": deltas.get("shed"),
                      "retry": deltas.get("retry"), "p99": p99,
                      "hw_total": sum(hw.values()),
                      "hw_top": top,
                      "hw_top_entries": hw.get(top, 0) if top else 0,
                      "lat_dropped": row["lat_dropped"]})
        self._prev_counts = counts
        self.completed = w + 1
        if self.metrics is not None:
            self.metrics.add_event(MetricsName.TELEMETRY_WINDOWS, 1)
            for name, value in hw.items():
                self.metrics.add_event(RESOURCE_METRIC_PREFIX + name,
                                       value)
        self._law_throughput(w, deltas)
        self._law_leak(w, hw)
        self._law_latency(w, p99)

    # --- drift laws -----------------------------------------------------

    def _law_throughput(self, w: int, deltas: Dict[str, int]) -> None:
        """Ordered-throughput drift vs the same-phase window
        ``drift_lag`` back (set the lag to profile-period/window so a
        diurnal trough never reads as drift). Shares the warm-up grace
        with the other laws: early windows hold pre-steady-state load
        (a soak's key-warming burst) that is no reference for drift."""
        cur = deltas.get("ordered")
        if cur is None:
            return
        self._ordered_ring.append(cur)
        if len(self._ordered_ring) <= self.drift_lag or w < self.leak_grace:
            return
        ref = self._ordered_ring[0]
        drifted = ref > 0 and (ref - cur) / ref > self.drift_frac
        if drifted and self._drift_armed:
            self._drift_armed = False
            self._anomaly("throughput_drift", w,
                          {"ordered": cur, "reference": ref,
                           "lag": self.drift_lag})
        elif not drifted:
            self._drift_armed = True

    def _law_leak(self, w: int, hw: Dict[str, int]) -> None:
        """The leak law: a resource's window high-water strictly
        increasing for ``leak_windows`` consecutive windows (after the
        warm-up grace) is a leak, bounded or not — one anomaly per
        episode, re-armed by any non-increasing window."""
        for name, value in hw.items():
            if self.ledger.is_ring(name):
                # retention rings (trace ring, the plane's own rollup
                # rings) grow one entry per event BY CONSTRUCTION until
                # their maxlen — monotone growth is their design, not a
                # leak; the bound-violation law still covers them
                continue
            prev = self._prev_window_hw.get(name)
            if prev is not None and value > prev and w >= self.leak_grace:
                self._leak_streak[name] = self._leak_streak.get(name, 0) + 1
            else:
                self._leak_streak[name] = 0
                self._leak_fired[name] = False
            if (self._leak_streak[name] >= self.leak_windows
                    and not self._leak_fired.get(name)):
                self._leak_fired[name] = True
                self._anomaly("resource_leak", w,
                              {"resource": name, "high_water": value,
                               "streak": self._leak_streak[name]})
        self._prev_window_hw = dict(hw)

    def _law_latency(self, w: int, p99: Optional[float]) -> None:
        """Latency creep: window p99 strictly increasing for
        ``leak_windows`` consecutive windows."""
        prev = self._prev_p99
        if p99 is not None and prev is not None and p99 > prev \
                and w >= self.leak_grace:
            self._lat_streak += 1
        else:
            self._lat_streak = 0
            self._lat_fired = False
        if self._lat_streak >= self.leak_windows and not self._lat_fired:
            self._lat_fired = True
            self._anomaly("latency_creep", w,
                          {"p99": p99, "streak": self._lat_streak})
        if p99 is not None:
            self._prev_p99 = p99

    def _anomaly(self, law: str, window: int, detail: Dict[str, Any]) \
            -> None:
        rec = {"law": law, "window": window}
        rec.update(detail)
        self.anomalies.append(rec)
        self.anomaly_count += 1
        self._fold({"anomaly": rec})
        if self.metrics is not None:
            self.metrics.add_event(MetricsName.TELEMETRY_ANOMALIES, 1)
        if self.trace is not None:
            self.trace.trigger_dump("telemetry." + law, args=rec)

    def _fold(self, entry: Dict[str, Any]) -> None:
        # the seal-fingerprint pattern (lanes/barrier.py): a running
        # sha256 chain keeps the fingerprint byte-stable with O(1)
        # state even after the bounded rings evict
        self._hash = hashlib.sha256(
            ("%s|%s" % (self._hash, _canon(entry))).encode()).hexdigest()

    # --- reading --------------------------------------------------------

    @property
    def telemetry_hash(self) -> str:
        """Chain tip over every rolled row and fired anomaly, in order —
        byte-identical across same-seed runs like ``ordered_hash``."""
        return self._hash

    def snapshot(self) -> Dict[str, Any]:
        return {
            "windows": self.completed,
            "anomalies": self.anomaly_count,
            "anomaly_tail": list(self.anomalies),
            "bound_violations": sorted(self._violated),
            "telemetry_hash": self._hash,
            "resources": self.ledger.snapshot(),
        }
