"""Causal request journeys: cross-node joins over flight-recorder dumps.

RBFT judges the master instance on *observed* end-to-end latency (Aublin
et al., ICDCS 2013), but the flight recorder's per-node timelines
(:mod:`.trace`) only let phase analytics join request phases
heuristically.  This module is the ground-truth layer: it reconstructs
each request's full **journey** across the pool — client ingress →
admission wait → auth batch → PROPAGATE fan-out → PRE-PREPARE / PREPARE
/ COMMIT → ordered → executed (→ window proof) — from the SAME JSONL
dumps, joining per-node lifecycle marks with the transport-level
``net.send``/``net.recv`` marks both transports stamp
(:class:`~indy_plenum_tpu.simulation.sim_network.SimNetwork` on the
virtual clock, :class:`~indy_plenum_tpu.network.zstack.ZStack` by
piggybacking a ``~trc`` context on the serialized envelope).

Determinism contract (the ``latency_gate``): journeys are a pure
function of the event list, the trace context is a pure function of the
request digest (:func:`trace_id`) and span ids a pure function of
``(trace_id, node, hop)`` (:func:`span_id`) — so a seeded virtual-clock
run produces a byte-identical journey table, fingerprinted by
:func:`journey_hash` exactly like ``ordered_hash``/``trace_hash``.

Attribution semantics (per hop, deterministic by construction):

- **network** — min(hop duration, median in-flight latency of the
  message wave that closes the hop), from matched send/recv marks;
- **compute** — the auth device batch and execution hops;
- **device** — the dispatch-tick quantization wait (commit-quorum
  observation → in-order delivery) when the dump shows a tick-batched
  dispatch plane (``tick.flush`` marks present), else it folds into
- **queue** — everything else: admission wait, batching wait, and each
  hop's residual after its network share.

Like ``trace_tool``, this module is deliberately free of jax imports:
it must run anywhere a dump lands.
"""
from __future__ import annotations

import hashlib
import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .trace import events_to_jsonl, percentile

# message types whose deliveries the transports stamp with
# ``net.send``/``net.recv`` marks (cat ``net``). Key shapes join the
# lifecycle marks: 3PC waves by (viewNo, ppSeqNo) — master instance
# only, backups don't trace — PROPAGATE by the "identifier|reqId" pair
# the ingress mark carries (the wire never sees the digest), catchup
# slices by ledger id.
NET_TRACED_OPS = ("PROPAGATE", "PREPREPARE", "PREPARE", "COMMIT",
                  "CATCHUP_REQ", "CATCHUP_REP")


def net_join_key(op: str, get: Callable[[str], Any]) -> Optional[tuple]:
    """The journey-joinable key for one wire message (``get`` reads a
    field off the message object or its dict form). None = untraced."""
    if op == "PROPAGATE":
        req = get("request") or {}
        if not isinstance(req, dict):
            return None
        return ("%s|%s" % (req.get("identifier"), req.get("reqId")),)
    if op in ("PREPREPARE", "PREPARE", "COMMIT"):
        if get("instId"):
            return None  # only the master instance executes / is judged
        return (get("viewNo"), get("ppSeqNo"))
    if op in ("CATCHUP_REQ", "CATCHUP_REP"):
        return (get("ledgerId"),)
    return None


def trace_id(digest: str) -> str:
    """The request's deterministic trace context: derived from the
    digest every honest node independently computes — no allocator, no
    coordination, identical across the pool by construction."""
    return hashlib.sha256(b"journey|" + digest.encode()).hexdigest()[:16]


def span_id(tid: str, node: str, hop: str) -> str:
    """Span identity as a pure function of (trace_id, node, hop): two
    nodes (or two runs) derive the identical id for the same hop."""
    return hashlib.sha256(
        ("%s|%s|%s" % (tid, node, hop)).encode()).hexdigest()[:16]


def merge_events(*event_lists: Sequence[Dict[str, Any]]
                 ) -> List[Dict[str, Any]]:
    """Merge N per-node dumps into one deterministic timeline. Within a
    pool-shared dump the ring order is already causal; across dumps the
    only shared clock is the timestamp, so ties break on (node, cat,
    name, seq) — a pure function of the inputs."""
    merged = [ev for evs in event_lists for ev in evs]
    merged.sort(key=lambda ev: (ev["ts"], ev.get("node", ""),
                                ev.get("cat", ""), ev["name"],
                                ev.get("seq", 0)))
    return merged


# ----------------------------------------------------------------------
# extraction
# ----------------------------------------------------------------------

def _r(x: Optional[float]) -> Optional[float]:
    return None if x is None else round(x, 9)


def _earliest(d: Dict, k, ts: float) -> None:
    if k not in d or ts < d[k]:
        d[k] = ts


class _Extract:
    """One pass over the merged event list; everything journeys need."""

    _LIFECYCLE = ("3pc.preprepare_sent", "3pc.preprepare",
                  "3pc.prepare_quorum", "3pc.commit_quorum",
                  "3pc.ordered", "3pc.executed")

    def __init__(self, events: List[Dict[str, Any]]):
        self.req: Dict[str, Dict[str, float]] = {}   # digest -> marks
        self.rid_of: Dict[str, str] = {}             # digest -> ident|reqId
        # closed-loop retry (overload robustness plane): re-offer count
        # per digest — the retry hop spans from the first shed to the
        # eventual admission (``marks`` carries both instants)
        self.retry_count: Dict[str, int] = {}
        # ordering lanes: every mark a laned pool records carries
        # args["lane"] (LaneTraceView), and the cross-lane barrier
        # stamps barrier.ready/barrier.sealed marks (cat "lanes") —
        # net-wave joins key on the lane so two lanes both at
        # (view 0, seq 5) never cross-pollute, and the seal instant
        # becomes each journey's "barrier" hop
        self.req_lane: Dict[str, int] = {}           # digest -> lane
        # geo plane: marks submitted with a home region carry
        # args["region"] — journeys inherit it (mirrors lane), and the
        # read FIFO pairs it through so read e2e segregates per region
        self.req_region: Dict[str, int] = {}         # digest -> region
        self.read_e2e_by_region: Dict[int, List[float]] = {}
        self._barrier_ready: Dict[tuple, int] = {}   # (lane, win) -> seq
        self.barrier_sealed: Dict[int, float] = {}   # window -> seal ts
        # batch digest -> {"keys": set[(v, s)], "reqIdr": [...],
        #                  "marks": {name: earliest ts},
        #                  "executed_by": set[node]}
        self.batches: Dict[str, Dict[str, Any]] = {}
        self.net: Dict[tuple, List[float]] = {}      # (op, key) -> lats
        self.net_drops: Dict[tuple, int] = {}
        self._send_at: Dict[Any, Tuple[float, str, tuple]] = {}
        self.catchup: Dict[str, List[Tuple[float, float]]] = {}
        self._catchup_open: Dict[tuple, float] = {}
        self.proof_at: Dict[tuple, float] = {}       # (view, seq) -> ts
        self.tick_mode = False
        self.read_e2e: List[float] = []
        # read FIFO windows are PER SERVICE (the mark's node field):
        # two ReadServices sharing a recorder — or N merged per-node
        # dumps — must never cross-pair each other's reads
        self._read_pending: Dict[str, List[float]] = {}
        self.fault_windows: List[Tuple[float, float]] = []
        self._fault_open: Dict[str, float] = {}
        for ev in events:
            self._feed(ev)
        # unclosed fault windows extend to the end of the dump
        if self._fault_open and events:
            end = max(ev["ts"] for ev in events)
            for t0 in self._fault_open.values():
                self.fault_windows.append((t0, end))
        self.fault_windows.sort()

    def _feed(self, ev: Dict[str, Any]) -> None:
        cat, name, ts = ev.get("cat", ""), ev["name"], ev["ts"]
        key = ev.get("key")
        args = ev.get("args") or {}
        if cat == "req" and key:
            marks = self.req.setdefault(key[0], {})
            _earliest(marks, name, ts)
            if name == "req.ingress" and args.get("rid"):
                self.rid_of[key[0]] = args["rid"]
            if name == "req.retry":
                self.retry_count[key[0]] = \
                    self.retry_count.get(key[0], 0) + 1
            if "lane" in args and key[0] not in self.req_lane:
                self.req_lane[key[0]] = args["lane"]
            if "region" in args and key[0] not in self.req_region:
                self.req_region[key[0]] = args["region"]
        elif cat == "3pc" and key and len(key) >= 3 \
                and name in self._LIFECYCLE:
            b = self.batches.setdefault(
                key[2], {"keys": set(), "reqIdr": None, "marks": {},
                         "executed_by": set(), "lane": None})
            b["keys"].add((key[0], key[1]))
            _earliest(b["marks"], name, ts)
            if name == "3pc.executed":
                b["executed_by"].add(ev.get("node", ""))
            if args.get("reqIdr") and b["reqIdr"] is None:
                b["reqIdr"] = list(args["reqIdr"])
            if "lane" in args and b["lane"] is None:
                b["lane"] = args["lane"]
        elif cat == "lanes" and key:
            if name == "barrier.ready" and args.get("seq") is not None:
                rkey = (args.get("lane"), key[0])
                if rkey not in self._barrier_ready:
                    self._barrier_ready[rkey] = args["seq"]
            elif name == "barrier.sealed":
                _earliest(self.barrier_sealed, key[0], ts)
        elif cat == "net":
            op, nid = args.get("m"), args.get("id")
            lane = args.get("lane")
            # ids are per-network sequences and each lane runs its own
            # network, so the send/recv join MUST key on (lane, id) —
            # bare ids collide across lanes in a merged laned dump
            if name == "net.send":
                self._send_at[(lane, nid)] = (
                    ts, op, (lane,) + tuple(key or ()))
            elif name == "net.recv":
                sent = self._send_at.pop((lane, nid), None)
                if sent is not None:
                    lat = ts - sent[0]
                    if lat >= 0.0:
                        self.net.setdefault((op, sent[2]), []).append(lat)
                elif args.get("sent") is not None:
                    # cross-process dump (ZStack): the context carries
                    # the SENDER's clock reading. perf_counter epochs
                    # are process-local, so this only yields a usable
                    # sample when both processes share a timebase (same
                    # host); negative/implausible deltas from unrelated
                    # clocks are dropped rather than poisoning the
                    # attribution
                    lat = ts - args["sent"]
                    if lat >= 0.0:
                        self.net.setdefault(
                            (op, (lane,) + tuple(key or ())),
                            []).append(lat)
            elif name == "net.drop":
                k = (op, (lane,) + tuple(key or ()))
                self.net_drops[k] = self.net_drops.get(k, 0) + 1
        elif cat == "catchup" and key:
            node = ev.get("node", "")
            if name == "catchup.started":
                self._catchup_open[(node, key[0])] = ts
            elif name in ("catchup.completed", "catchup.failed"):
                t0 = self._catchup_open.pop((node, key[0]), ts)
                if name == "catchup.completed":
                    self.catchup.setdefault(node, []).append((t0, ts))
        elif cat == "proof" and name == "proof.window_signed" \
                and key and len(key) >= 2:
            _earliest(self.proof_at, (key[0], key[1]), ts)
        elif cat == "dispatch" and name == "tick.flush":
            self.tick_mode = True
        elif cat == "read":
            svc = ev.get("node", "")
            if name == "read.submitted":
                self._read_pending.setdefault(svc, []).append(
                    (ts, args.get("region")))
            elif name == "read.served":
                n = int(args.get("n", 0))
                pending = self._read_pending.get(svc, [])
                take = pending[:n]
                del pending[:n]
                for t0, region in take:
                    self.read_e2e.append(ts - t0)
                    if region is not None:
                        self.read_e2e_by_region.setdefault(
                            region, []).append(ts - t0)
        elif cat == "chaos":
            if name.startswith("begin "):
                self._fault_open[name[6:]] = ts
            elif name.startswith("end "):
                t0 = self._fault_open.pop(name[4:], None)
                if t0 is not None:
                    self.fault_windows.append((t0, ts))

    def net_median(self, op: str, key: tuple) -> Optional[float]:
        lats = self.net.get((op, key))
        if not lats:
            return None
        return percentile(sorted(lats), 50)

    def barrier_seal_ts(self, lane: Optional[int],
                        seq: int) -> Optional[float]:
        """Seal instant of the cross-lane window covering lane-local
        batch ``seq`` (the smallest window whose boundary reaches it),
        or None when the dump never sealed that far."""
        if lane is None:
            return None
        windows = sorted(
            window for (ready_lane, window), seq_end
            in self._barrier_ready.items()
            if ready_lane == lane and seq_end >= seq)
        if not windows:
            return None
        return self.barrier_sealed.get(windows[0])


# ----------------------------------------------------------------------
# journeys
# ----------------------------------------------------------------------

# hop -> which attribution bucket its residual (after the network share)
# lands in; the ``order`` hop is the dispatch-tick / in-order wait and
# charges to ``device`` when the dump shows a tick-batched plane. The
# ``barrier`` hop (ordering lanes: executed -> the cross-lane seal of
# the batch's checkpoint window) exists only in laned dumps, and the
# ``retry`` hop (overload robustness plane: first shed -> the eventual
# admission of the backoff chain) only for requests the closed loop
# actually retried — both, like ``admission``, are skipped rather than
# counted incomplete when absent.
_HOPS = ("admission", "retry", "auth", "batching", "preprepare",
         "prepare", "commit", "order", "execute", "barrier")
_OPTIONAL_HOPS = ("admission", "retry", "barrier")
_RESIDUAL_OF = {"admission": "queue", "retry": "queue",
                "auth": "compute",
                "batching": "queue", "preprepare": "queue",
                "prepare": "queue", "commit": "queue",
                "order": "queue", "execute": "compute",
                "barrier": "queue"}
_WAVE_OF = {"preprepare": "PREPREPARE", "prepare": "PREPARE",
            "commit": "COMMIT"}


def build_journeys(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Reconstruct every request journey from a merged event list.

    Returns ``{"journeys": [...], "pending": [...], "shed": [...],
    "read_e2e": [...], "fault_windows": [...]}`` — one journey per
    request that reached an executed batch, each with per-hop
    network/queue/compute/device attribution, completeness, and the
    catchup annotation (nodes that received it by leeching rather than
    ordering)."""
    return _build_journeys(events)[0]


def _build_journeys(events: List[Dict[str, Any]]
                    ) -> Tuple[Dict[str, Any], "_Extract"]:
    """One extraction pass shared by :func:`build_journeys` and
    :func:`journey_for` (which also needs the raw wave samples)."""
    x = _Extract(events)
    journeys: List[Dict[str, Any]] = []
    ordered_digests = set()
    for bd in sorted(x.batches):
        b = x.batches[bd]
        marks = b["marks"]
        if "3pc.executed" not in marks or not b["reqIdr"]:
            continue
        # the primary's own batch never gets an applied mark (existing
        # phase-analytics convention): its send mark starts the phase
        t_sent = marks.get("3pc.preprepare_sent")
        t_pp = marks.get("3pc.preprepare", t_sent)
        batch_key = min(b["keys"])
        lane = b.get("lane")
        # net-wave samples are keyed (lane, view, seq): an unlaned dump
        # stores lane None on both sides, so the join shape is uniform
        wave_med = {hop: x.net_median(op, (lane,) + batch_key)
                    for hop, op in _WAVE_OF.items()}
        t_ord = marks.get("3pc.ordered")
        t_exe = marks["3pc.executed"]
        t_seal = x.barrier_seal_ts(lane, batch_key[1])
        leeched_by = sorted(
            node for node, rounds in x.catchup.items()
            if node not in b["executed_by"]
            and t_ord is not None
            and any(t1 > t_ord for _t0, t1 in rounds))
        proof_ts = min((x.proof_at[k] for k in b["keys"]
                        if k in x.proof_at), default=None)
        for digest in b["reqIdr"]:
            if digest in ordered_digests:
                continue  # first executed batch wins (VC re-proposal)
            ordered_digests.add(digest)
            rmarks = x.req.get(digest, {})
            t_ing = rmarks.get("req.ingress")
            t_adm = rmarks.get("req.admitted")
            t_fin = rmarks.get("req.finalised")
            # closed-loop retry: a retried-then-ordered request's wait
            # splits at its FIRST shed — admission covers the first
            # attempt, the retry hop the whole backoff chain through to
            # the eventual admission (contiguous, so attribution never
            # double-counts); unretried requests keep the exact
            # pre-overload-plane chain
            t_shed1 = rmarks.get("req.shed")
            retried = digest in x.retry_count \
                and t_shed1 is not None and t_adm is not None
            # hop chain: each entry (t0, t1); None timestamps leave the
            # hop out (and mark the journey incomplete below)
            chain = {
                "admission": ((t_ing, t_shed1) if retried
                              else (t_ing, t_adm) if t_adm is not None
                              else None),
                "retry": (t_shed1, t_adm) if retried else None,
                "auth": (t_adm if t_adm is not None else t_ing, t_fin),
                "batching": (t_fin, t_sent),
                "preprepare": (t_sent, t_pp),
                "prepare": (t_pp, marks.get("3pc.prepare_quorum")),
                "commit": (marks.get("3pc.prepare_quorum"),
                           marks.get("3pc.commit_quorum")),
                "order": (marks.get("3pc.commit_quorum"), t_ord),
                "execute": (t_ord, t_exe),
                # cross-lane barrier (ordering lanes): executed -> the
                # seal of the batch's checkpoint window across ALL
                # lanes; absent in single-lane dumps and for windows
                # the dump never sealed
                "barrier": ((t_exe, t_seal) if t_seal is not None
                            else None),
            }
            rid = x.rid_of.get(digest)
            prop_med = (x.net_median("PROPAGATE", (lane, rid))
                        if rid else None)
            tid = trace_id(digest)
            hops = []
            attrib = {"network": 0.0, "queue": 0.0, "compute": 0.0,
                      "device": 0.0}
            complete = True
            for hop in _HOPS:
                span = chain[hop]
                if hop in _OPTIONAL_HOPS and span is None:
                    continue  # plane off in this dump: no wait to split
                if span is None or span[0] is None or span[1] is None:
                    complete = False
                    continue
                dur = max(0.0, span[1] - span[0])
                net = wave_med.get(hop)
                if hop == "auth" and prop_med is not None:
                    net = prop_med  # the PROPAGATE fan-out rides the
                    # finalisation wait (f+1 quorum of propagates)
                net = min(dur, max(0.0, net)) if net is not None else 0.0
                residual = _RESIDUAL_OF[hop]
                if hop == "order" and x.tick_mode:
                    residual = "device"
                rec = {"hop": hop, "span_id": span_id(tid, "", hop),
                       "t0": _r(span[0]), "dur": _r(dur),
                       "network": _r(net),
                       residual: _r(dur - net)}
                hops.append(rec)
                attrib["network"] += net
                attrib[residual] += dur - net
            journey = {
                "digest": digest,
                "trace_id": tid,
                "class": "write",
                "batch": [batch_key[0], batch_key[1], bd],
                # ordering lanes: which lane ordered it (absent in
                # single-lane dumps — existing tables stay byte-stable)
                **({"lane": lane} if lane is not None else {}),
                # geo plane: the submitting client's home region (absent
                # in single-region dumps — tables stay byte-stable)
                **({"region": x.req_region[digest]}
                   if digest in x.req_region else {}),
                # closed-loop retry: how many re-offers it took (absent
                # for first-attempt requests — retry-free tables stay
                # byte-stable)
                **({"retries": x.retry_count[digest]}
                   if digest in x.retry_count else {}),
                "t_ingress": _r(t_ing),
                "e2e": _r(t_exe - t_ing) if complete else None,
                "hops": hops,
                "attribution": {k: _r(v) for k, v in attrib.items()},
                "complete": complete,
            }
            if proof_ts is not None:
                journey["proof_after"] = _r(proof_ts - t_exe)
            if leeched_by:
                journey["catchup"] = leeched_by
            journeys.append(journey)
    journeys.sort(key=lambda j: (j["t_ingress"] is None,
                                 j["t_ingress"] or 0.0, j["digest"]))
    # a retried request is a journey (ordered) or still PENDING (its
    # backoff chain alive at dump time), never a shed: ``shed`` means
    # TERMINALLY shed — the closed loop gave up (req.retry_exhausted) or
    # never ran. Whether the loop ran is a DUMP-level fact (a shed whose
    # first re-offer is still on the timer has no per-request retry mark
    # yet), so any retry activity anywhere in the dump marks the loop
    # armed and unexhausted sheds count as pending. Retry-free dumps are
    # exactly the old "has a req.shed mark" set.
    loop_armed = bool(x.retry_count) or any(
        "req.retry_exhausted" in m for m in x.req.values())
    shed = sorted(
        d for d, m in x.req.items()
        if "req.shed" in m and d not in ordered_digests
        and ("req.retry_exhausted" in m or not loop_armed))
    pending = sorted(
        d for d, m in x.req.items()
        if d not in ordered_digests
        and ("req.shed" not in m
             or (loop_armed and "req.retry_exhausted" not in m)))
    built = {"journeys": journeys, "pending": pending, "shed": shed,
             "read_e2e": x.read_e2e,
             "fault_windows": [[_r(a), _r(b)]
                               for a, b in x.fault_windows]}
    if x.read_e2e_by_region:
        # geo plane only — single-region dumps stay byte-compatible
        built["read_e2e_by_region"] = dict(
            sorted(x.read_e2e_by_region.items()))
    return built, x


def journey_hash(journeys: List[Dict[str, Any]]) -> str:
    """sha256 over the canonical JSONL journey table — THE cross-node
    latency fingerprint (byte-identical per seed on virtual-clock
    pools, like ``ordered_hash``/``trace_hash``)."""
    return hashlib.sha256(events_to_jsonl(journeys).encode()).hexdigest()


def _pct_block(samples: List[float], ndigits: int = 6) -> Dict[str, Any]:
    s = sorted(samples)
    return {"count": len(s),
            "p50": round(percentile(s, 50), ndigits),
            "p90": round(percentile(s, 90), ndigits),
            "p99": round(percentile(s, 99), ndigits),
            "max": round(s[-1], ndigits) if s else 0.0}


def journey_summary(events: List[Dict[str, Any]],
                    built: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """The pool-rollup block every surface reports (Monitor.snapshot,
    bench records, ChaosReport, the ``latency_gate``): journey counts +
    completeness, the table fingerprint, e2e percentiles per request
    class, per-hop percentiles, attribution shares, and — when the dump
    carries chaos fault windows — the measured latency cost of running
    through one."""
    built = built if built is not None else build_journeys(events)
    journeys = built["journeys"]
    complete = [j for j in journeys if j["complete"]]
    e2e = [j["e2e"] for j in complete]
    hop_samples: Dict[str, List[float]] = {}
    attrib_totals: Dict[str, float] = {}
    for j in complete:
        for h in j["hops"]:
            hop_samples.setdefault(h["hop"], []).append(h["dur"])
        for k, v in j["attribution"].items():
            attrib_totals[k] = attrib_totals.get(k, 0.0) + v
    whole = sum(attrib_totals.values())
    # dominant hop per journey (ties break on canonical hop order)
    dominant: Dict[str, int] = {}
    for j in complete:
        durs = {h["hop"]: h["dur"] for h in j["hops"]}
        top, top_d = None, float("-inf")
        for hop in _HOPS:
            if hop in durs and durs[hop] > top_d:
                top, top_d = hop, durs[hop]
        if top is not None:
            dominant[top] = dominant.get(top, 0) + 1
    out = {
        "count": len(journeys),
        "complete": len(complete),
        "orphan_spans": len(journeys) - len(complete),
        "pending": len(built["pending"]),
        "shed": len(built["shed"]),
        "catchup_journeys": sum(1 for j in journeys if j.get("catchup")),
        # closed-loop retry: journeys that got in only after >= 1
        # seeded-backoff re-offer (their tables carry the retry hop)
        "retried": sum(1 for j in journeys if j.get("retries")),
        "journey_hash": journey_hash(journeys),
        "e2e": {"write": _pct_block(e2e),
                "read": _pct_block(built["read_e2e"])},
        "hop_percentiles": {h: _pct_block(s)
                            for h, s in sorted(hop_samples.items())},
        "attribution_share": {
            k: round(v / whole, 4) for k, v in sorted(
                attrib_totals.items())} if whole else {},
        "critical_path": {h: dominant[h] for h in _HOPS
                          if h in dominant},
    }
    # ordering lanes: per-lane e2e percentiles + barrier-hop coverage
    # (absent for single-lane dumps — existing rollups stay byte-stable)
    lane_ids = sorted({j["lane"] for j in journeys if "lane" in j})
    if lane_ids:
        out["lanes"] = {
            "count": len(lane_ids),
            "journeys_per_lane": {
                str(l): sum(1 for j in journeys if j.get("lane") == l)
                for l in lane_ids},
            "e2e_per_lane": {
                str(l): _pct_block([j["e2e"] for j in complete
                                    if j.get("lane") == l])
                for l in lane_ids},
            "with_lane": sum(1 for j in journeys if "lane" in j),
            "with_barrier_hop": sum(
                1 for j in journeys
                if any(h["hop"] == "barrier" for h in j["hops"])),
        }
    # geo plane: per-region e2e percentiles for writes (journeys whose
    # marks carried a home region) and reads (region-tagged read FIFO
    # pairs) — absent for single-region dumps, so existing rollups stay
    # byte-stable
    region_ids = sorted({j["region"] for j in journeys if "region" in j})
    read_regions = built.get("read_e2e_by_region") or {}
    if region_ids or read_regions:
        regions = {
            "count": len(set(region_ids) | set(read_regions)),
            "with_region": sum(1 for j in journeys if "region" in j),
        }
        if region_ids:
            regions["journeys_per_region"] = {
                str(r): sum(1 for j in journeys if j.get("region") == r)
                for r in region_ids}
            regions["e2e_per_region"] = {
                str(r): _pct_block([j["e2e"] for j in complete
                                    if j.get("region") == r])
                for r in region_ids}
        if read_regions:
            regions["read_e2e_per_region"] = {
                str(r): _pct_block(s)
                for r, s in sorted(read_regions.items())}
        out["regions"] = regions
    windows = built["fault_windows"]
    if windows:
        def _in_fault(j):
            t0 = j["t_ingress"]
            t1 = t0 + j["e2e"]
            return any(a <= t1 and t0 <= b for a, b in windows)

        hit = [j["e2e"] for j in complete if _in_fault(j)]
        clear = [j["e2e"] for j in complete if not _in_fault(j)]
        out["fault_window"] = {
            "windows": len(windows),
            "through_fault": _pct_block(hit),
            "clear": _pct_block(clear),
            # the fault's direct latency cost on the requests that
            # crossed it (sim seconds at p50)
            "p50_cost": round(
                _pct_block(hit)["p50"] - _pct_block(clear)["p50"], 6)
            if hit and clear else None,
        }
    return out


def journey_for(events: List[Dict[str, Any]],
                digest_prefix: str) -> Optional[Dict[str, Any]]:
    """One request's full cross-node record (``trace_tool --journey``):
    the journey, plus every per-node lifecycle mark and the per-wave
    network latency samples behind its attribution."""
    built, x = _build_journeys(events)
    journey = next((j for j in built["journeys"]
                    if j["digest"].startswith(digest_prefix)), None)
    if journey is None:
        return None
    digest = journey["digest"]
    batch_digest = journey["batch"][2]
    tid = journey["trace_id"]
    lane = journey.get("lane")
    per_node: List[Dict[str, Any]] = []
    waves: Dict[str, List[float]] = {}
    batch_key = tuple(journey["batch"][:2])
    # wave samples are keyed (lane, view, seq) — None lane for unlaned
    wave_key = (lane,) + batch_key
    for ev in events:
        key = ev.get("key")
        cat = ev.get("cat", "")
        if cat == "3pc" and key and len(key) >= 3 \
                and key[2] == batch_digest:
            node = ev.get("node", "")
            per_node.append({
                "node": node, "name": ev["name"], "ts": _r(ev["ts"]),
                "span_id": span_id(tid, node, ev["name"])})
        elif cat == "req" and key and key[0] == digest:
            node = ev.get("node", "")
            per_node.append({
                "node": node, "name": ev["name"], "ts": _r(ev["ts"]),
                "span_id": span_id(tid, node, ev["name"])})
        elif cat == "net" and key and tuple(key) == batch_key:
            args = ev.get("args") or {}
            if ev["name"] == "net.recv":
                waves.setdefault(args.get("m", "?"), [])
    for op in list(waves) + ["PREPREPARE", "PREPARE", "COMMIT"]:
        lats = x.net.get((op, wave_key))
        if lats:
            waves[op] = [_r(v) for v in lats]
    # the PROPAGATE wave is keyed by the ingress rid, not the batch key
    # — it feeds the auth hop's network share, so it belongs here too
    rid = x.rid_of.get(digest)
    if rid is not None:
        lats = x.net.get(("PROPAGATE", (lane, rid)))
        if lats:
            waves["PROPAGATE"] = [_r(v) for v in lats]
    per_node.sort(key=lambda r: (r["ts"], r["node"], r["name"]))
    return {"journey": journey, "marks": per_node,
            "net_waves": {k: v for k, v in sorted(waves.items()) if v}}
