"""Consensus flight recorder: deterministic span traces for the 3PC
lifecycle and the dispatch plane (README "Observability")."""
from .trace import (  # noqa: F401
    NULL_TRACE,
    NullTraceRecorder,
    TraceRecorder,
    critical_path,
    phase_durations,
    phase_percentiles,
    to_chrome_trace,
)
