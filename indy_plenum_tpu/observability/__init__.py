"""Consensus flight recorder: deterministic span traces for the 3PC
lifecycle and the dispatch plane, plus the causal tracing plane that
joins them into cross-node request journeys (README "Observability")."""
from .causal import (  # noqa: F401
    build_journeys,
    journey_for,
    journey_hash,
    journey_summary,
    merge_events,
    span_id,
    trace_id,
)
from .telemetry import (  # noqa: F401
    ResourceLedger,
    SizedResource,
    TelemetryPlane,
)
from .trace import (  # noqa: F401
    NULL_TRACE,
    NullTraceRecorder,
    TraceRecorder,
    critical_path,
    phase_durations,
    phase_percentiles,
    to_chrome_trace,
)
