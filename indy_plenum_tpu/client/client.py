"""The pool client: submit requests, collect quorum replies.

Reference: plenum/client/client.py (`Client`). Transport-agnostic: the
composition supplies ``send(request, node_name, client_id)`` (a ZMQ client
stack in production, direct node handles in the simulation) and routes
every node->client message into :meth:`process_node_message`.

Write path: submit to one or more nodes, collect REPLYs, and accept a
result once f+1 DISTINCT nodes returned the identical committed txn —
at least one of them is honest. Read path (GET_NYM): submit to ONE node
and accept its single reply iff the carried state proof verifies against
the pool's BLS keys (client/state_proof.verify_proved_reply) — a proved
read from one node is as trustworthy as f+1 matching replies.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional

from ..common.constants import GET_NYM, GET_TXN, TARGET_NYM
from ..common.messages.node_messages import Reply, RequestAck, RequestNack
from ..common.request import Request
from ..common.txn_util import get_digest
from ..utils.base58 import b58decode
from .state_proof import StateProofReply, verify_proved_reply

logger = logging.getLogger(__name__)

# a proved read's multi-signature must be recent: an old root with a
# genuine pool signature could otherwise serve provably-signed STALE state
DEFAULT_PROOF_MAX_AGE = 300.0  # seconds


class RequestRejected(Exception):
    """Raised by :meth:`Client.take_result` when the pool NACKed the
    request (>f distinct rejections). Carries the evidence and frees the
    request's state — a poll loop terminates instead of spinning, and a
    long-running client doesn't accumulate rejected entries."""

    def __init__(self, digest: str, nacks: Dict[str, str]):
        super().__init__(f"request {digest} rejected: {nacks}")
        self.digest = digest
        self.nacks = dict(nacks)


class PendingRequest:
    def __init__(self, request: Request, needed: int):
        self.request = request
        self.needed = needed
        self.replies: Dict[str, dict] = {}  # node -> result
        self.acks: set = set()
        self.nacks: Dict[str, str] = {}
        self.result: Optional[dict] = None  # set once quorum reached

    def add_reply(self, node: str, result: dict) -> None:
        self.replies[node] = result
        if self.result is not None:
            return
        by_content: Dict[str, List[str]] = {}
        for n, r in self.replies.items():
            by_content.setdefault(repr(sorted(r.items())), []).append(n)
        for content, nodes in by_content.items():
            if len(nodes) >= self.needed:
                self.result = self.replies[nodes[0]]
                return


class Client:
    def __init__(self,
                 name: str,
                 validators,
                 send: Callable[[Request, str, str], Any],
                 pool_bls_keys=None,
                 now_provider: Callable[[], float] = time.time,
                 proof_max_age: float = DEFAULT_PROOF_MAX_AGE):
        """``validators`` and ``pool_bls_keys`` may be values OR zero-arg
        providers: with dynamic membership (NODE txns) the client must
        verify against the CURRENT pool, not its construction-time view."""
        self.name = name
        self._validators_src = validators
        self._send = send
        self._bls_keys_src = pool_bls_keys or {}
        self._now = now_provider
        self._proof_max_age = proof_max_age
        self.pending: Dict[str, PendingRequest] = {}  # digest -> state
        # (identifier, reqId) -> state: inbound ACK/NACK/REPLY matching is
        # O(1), not O(pending) — the load-generator shape
        self._by_idr: Dict[tuple, PendingRequest] = {}
        self.proved_reads: Dict[str, dict] = {}  # digest -> verified result

    @property
    def _validators(self) -> List[str]:
        src = self._validators_src
        return list(src() if callable(src) else src)

    @property
    def _pool_bls_keys(self) -> Dict[str, str]:
        src = self._bls_keys_src
        return dict(src() if callable(src) else src)

    @property
    def _f(self) -> int:
        return (len(self._validators) - 1) // 3

    # ------------------------------------------------------------------

    def submit_write(self, request: Request,
                     to: Optional[List[str]] = None) -> str:
        """Send a write to ``to`` (default: all validators — the client
        needs f+1 REPLYs, and up to f nodes may ignore it)."""
        targets = to if to is not None else list(self._validators)
        self._track(request, needed=self._f + 1)
        for node in targets:
            self._send(request, node, self.name)
        return request.digest

    def submit_read(self, request: Request,
                    to: Optional[str] = None) -> str:
        """Proved reads (GET_NYM) go to ONE node — the reply carries a
        verifiable proof. Reads WITHOUT a proof surface (GET_TXN) fall
        back to the f+1 matching-reply quorum across the pool: a single
        unproved answer is never trusted."""
        if request.txn_type == GET_NYM:
            node = to or self._validators[0]
            self._track(request, needed=1)
            self._send(request, node, self.name)
        else:
            self._track(request, needed=self._f + 1)
            for node in self._validators:
                self._send(request, node, self.name)
        return request.digest

    def submit_action(self, request: Request, to: Optional[str] = None
                      ) -> str:
        """Privileged operational actions (VALIDATOR_INFO, POOL_RESTART)
        are point queries: each node answers for ITSELF, so one reply
        from the asked node is the answer — no quorum to wait for."""
        node = to or self._validators[0]
        self._track(request, needed=1)
        self._send(request, node, self.name)
        return request.digest

    def _track(self, request: Request, needed: int) -> PendingRequest:
        """Register a pending request. (identifier, reqId) must be unique
        among in-flight requests — node replies carry only that pair, so
        a DIFFERENT request under a known pair would silently steal the
        earlier one's replies. Resubmitting the SAME request (retry after
        a lost REPLY) reuses its existing state and goes out again."""
        key = (request.identifier, request.reqId)
        existing = self._by_idr.get(key)
        if existing is not None:
            if existing.request.digest == request.digest:
                return existing  # retry: resend, keep collected replies
            # NOT auto-retired even when completed: the collision may be
            # an application bug and the earlier result may be unread —
            # silently dropping it would mask the bug as reply loss. The
            # recovery path for legitimate reuse (wallet counter reset)
            # is take_result()/retire(), which frees the slot.
            raise ValueError(
                f"reqId {request.reqId} already used by a different "
                f"request for {request.identifier}; take_result()/"
                f"retire() the old request or pick a fresh reqId")
        state = self.pending[request.digest] = PendingRequest(
            request, needed=needed)
        self._by_idr[key] = state
        return state

    # ------------------------------------------------------------------

    def process_node_message(self, node_name: str, msg) -> None:
        if isinstance(msg, Reply):
            self._process_reply(node_name, dict(msg.result))
        elif isinstance(msg, RequestNack):
            self._process_nack(node_name, msg)
        elif isinstance(msg, RequestAck):
            self._process_ack(node_name, msg)

    def _match_pending(self, identifier, req_id) -> Optional[PendingRequest]:
        return self._by_idr.get((identifier, req_id))

    def _process_ack(self, node_name: str, msg: RequestAck) -> None:
        state = self._match_pending(msg.identifier, msg.reqId)
        if state is not None:
            state.acks.add(node_name)

    def _process_nack(self, node_name: str, msg: RequestNack) -> None:
        state = self._match_pending(msg.identifier, msg.reqId)
        if state is not None:
            state.nacks[node_name] = msg.reason

    def _process_reply(self, node_name: str, result: dict) -> None:
        state = self._match_pending(result.get("identifier"),
                                    result.get("reqId"))
        if state is None:
            return
        reply_digest = get_digest(result)
        if reply_digest is not None and \
                reply_digest != state.request.digest:
            # a straggler for a RETIRED request whose (identifier, reqId)
            # slot was legitimately reused: counting it toward the NEW
            # request's quorum would resolve it with the old result.
            # Write replies carry the request digest in the txn envelope;
            # replies without one fall through (reads validate against
            # our own request's operation instead).
            return
        digest = state.request.digest
        # the single-reply proved path applies ONLY when WE asked a proved
        # read: a byzantine node must not be able to short-circuit a
        # write's f+1 quorum by attaching a (genuine) proof of something
        if state.request.txn_type == GET_NYM:
            proof = result.get("state_proof")
            if proof is not None and self._verify_proved_read(
                    state.request, result, proof):
                self.proved_reads[digest] = result
                state.result = result
            else:
                logger.warning("client %s: unverifiable proved reply "
                               "from %s dropped", self.name, node_name)
            return
        if state.request.txn_type == GET_TXN and state.result is None:
            # a single reply may carry an audit proof + the pool's
            # multi-signature over this ledger root — as trustworthy as
            # f+1 matching replies, which remain the fallback
            if self._verify_proved_get_txn(state.request, result):
                self.proved_reads[digest] = result
                state.result = result
                return
        state.add_reply(node_name, result)

    def _verify_proved_read(self, request: Request, result: dict,
                            proof: dict) -> bool:
        # the proof must be about the key WE asked for (from our own
        # request), never the key the reply claims to answer
        dest = request.operation.get(TARGET_NYM)
        if not isinstance(dest, str) or result.get("dest") != dest:
            return False
        try:
            reply = StateProofReply(
                key=dest.encode(),
                value=result.get("data"),
                root=b58decode(proof["root_hash"]),
                proof=proof["proof_nodes"],
                multi_sig_dict=proof.get("multi_signature"))
        except Exception:  # noqa: BLE001 — reply content is untrusted
            return False
        n = len(self._validators)
        return verify_proved_reply(
            reply, self._pool_bls_keys, min_participants=n - self._f,
            now=self._now(), max_age=self._proof_max_age)

    def _verify_proved_get_txn(self, request: Request,
                               result: dict) -> bool:
        """Audit path -> ledger root co-signed by the pool => one node's
        GET_TXN answer suffices (reference: clients verify proofs rather
        than counting replies whenever proof material exists)."""
        proof = result.get("auditProof") or {}
        ms_dict = proof.get("multi_signature")
        txn = result.get("data")
        seq_no = result.get("seqNo")
        if not ms_dict or txn is None or not isinstance(seq_no, int):
            return False
        if seq_no != request.operation.get("data"):
            return False  # answers the seqNo WE asked about, or nothing
        from ..common.constants import DOMAIN_LEDGER_ID

        if result.get("ledgerId") != request.operation.get(
                "ledgerId", DOMAIN_LEDGER_ID):
            return False  # and from the ledger WE asked about: a genuine
            # proof over the WRONG ledger's txn must not slip through
        try:
            from ..common.serializers.serialization import (
                ledger_txn_serializer,
            )
            from ..crypto.bls.bls_crypto import MultiSignature
            from ..ledger.merkle_verifier import STH, MerkleVerifier

            ms = MultiSignature.from_dict(ms_dict)
            root_b58 = proof["rootHash"]
            if ms.value.txn_root_hash != root_b58 \
                    or ms.value.ledger_id != result.get("ledgerId"):
                return False
            size = int(proof["ledgerSize"])
            path = [b58decode(h) for h in proof["auditPath"]]
            sth = STH(tree_size=size, sha256_root_hash=b58decode(root_b58))
            if not MerkleVerifier().verify_leaf_inclusion(
                    ledger_txn_serializer.dumps(txn), seq_no - 1, path,
                    sth):
                return False
            from .state_proof import verify_pool_multi_sig

            pool_keys = self._pool_bls_keys
            if not pool_keys:
                return False
            n = len(self._validators)
            return verify_pool_multi_sig(
                ms, pool_keys, min_participants=n - self._f,
                now=self._now(), max_age=self._proof_max_age)
        except Exception:  # noqa: BLE001 — reply content is untrusted
            return False

    # ------------------------------------------------------------------

    def result(self, digest: str) -> Optional[dict]:
        state = self.pending.get(digest)
        return state.result if state else None

    def take_result(self, digest: str) -> Optional[dict]:
        """``result()`` + retire: the long-running-client shape. Returns
        the result (and frees the slot) on success, None while the
        quorum is pending, and raises :class:`RequestRejected` — with
        the NACK evidence attached, freeing the slot — once >f nodes
        rejected, so a poll loop always terminates and neither outcome
        leaks memory."""
        res = self.result(digest)
        if res is not None:
            self.retire(digest)
            return res
        if self.is_rejected(digest):
            nacks = dict(self.pending[digest].nacks)
            self.retire(digest)
            raise RequestRejected(digest, nacks)
        return None

    def retire(self, digest: str) -> None:
        """Forget a request: frees its memory AND releases its
        (identifier, reqId) slot for legitimate reuse. Without this a
        long-running client grows without bound (round-4 advisor
        finding). Late replies for a retired digest are dropped by the
        normal unknown-request path."""
        state = self.pending.pop(digest, None)
        if state is not None:
            self._by_idr.pop(
                (state.request.identifier, state.request.reqId), None)
        self.proved_reads.pop(digest, None)

    def is_rejected(self, digest: str) -> bool:
        state = self.pending.get(digest)
        return bool(state and not state.result
                    and len(state.nacks) > self._f)
