"""Wallet: client-side identity and signing-key management.

Reference: plenum/client/wallet.py (`Wallet`) — holds a client's DIDs and
their signing keys, signs outgoing requests, allocates monotonically
increasing per-identifier request ids (node-side replay protection keys
on them), and persists to disk. Secrets are written owner-only (0600),
the same posture as the pool key directories in
:mod:`indy_plenum_tpu.tools.local_pool`.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional

from ..common.request import Request
from ..crypto.signers import DidSigner, Signer, SimpleSigner


class Wallet:
    def __init__(self, name: str = "wallet"):
        self.name = name
        self._signers: Dict[str, Signer] = {}  # identifier -> signer
        self.default_id: Optional[str] = None
        # last issued reqId per identifier: nodes dedup on
        # (identifier, reqId) payload digests, and the pool client refuses
        # a reused pair while one is in flight — monotone ids avoid both
        self._req_ids: Dict[str, int] = {}

    # --- identities -----------------------------------------------------

    def add_identifier(self, seed: Optional[bytes] = None,
                       did: bool = True) -> Signer:
        """Create (or import, given a seed) an identity; the first one
        becomes the default."""
        signer: Signer = DidSigner(seed) if did else SimpleSigner(seed)
        self._signers[signer.identifier] = signer
        if self.default_id is None:
            self.default_id = signer.identifier
        return signer

    def add_signer(self, signer: Signer) -> Signer:
        self._signers[signer.identifier] = signer
        if self.default_id is None:
            self.default_id = signer.identifier
        return signer

    @property
    def identifiers(self) -> List[str]:
        return list(self._signers)

    def signer(self, identifier: Optional[str] = None) -> Signer:
        ident = identifier or self.default_id
        if ident is None or ident not in self._signers:
            raise KeyError(f"no signer for identifier {ident!r}")
        return self._signers[ident]

    # --- requests -------------------------------------------------------

    def next_req_id(self, identifier: Optional[str] = None) -> int:
        ident = identifier or self.default_id
        self._req_ids[ident] = self._req_ids.get(ident, 0) + 1
        return self._req_ids[ident]

    def sign_request(self, request: Request,
                     identifier: Optional[str] = None) -> Request:
        self.signer(identifier).sign_request(request)
        return request

    def new_request(self, operation: dict,
                    identifier: Optional[str] = None) -> Request:
        """A signed request with a fresh reqId under ``identifier``."""
        ident = identifier or self.default_id
        req = Request(identifier=ident,
                      reqId=self.next_req_id(ident),
                      operation=dict(operation))
        return self.sign_request(req, ident)

    def endorse_request(self, request: Request,
                        identifiers: Iterable[str]) -> Request:
        """Multi-signature endorsement: each identifier adds an entry to
        ``request.signatures`` (the node verifies every one)."""
        for ident in identifiers:
            self.signer(ident).endorse_request(request)
        return request

    # --- persistence ----------------------------------------------------

    def save(self, path: str) -> None:
        """Owner-only secret file (seeds are the keys themselves)."""
        payload = {
            "name": self.name,
            "default_id": self.default_id,
            "req_ids": dict(self._req_ids),
            "identities": [
                {"seed": s.seed.hex(),
                 "did": isinstance(s, DidSigner)}
                for s in self._signers.values()],
        }
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        # O_CREAT's mode only applies to NEW files; overwriting an
        # existing wider-permissioned file must not leak the seeds
        os.fchmod(fd, 0o600)
        with os.fdopen(fd, "w") as fh:
            json.dump(payload, fh)

    @classmethod
    def load(cls, path: str) -> "Wallet":
        with open(path) as fh:
            payload = json.load(fh)
        wallet = cls(payload.get("name", "wallet"))
        for entry in payload.get("identities", []):
            wallet.add_identifier(bytes.fromhex(entry["seed"]),
                                  did=entry.get("did", True))
        wallet.default_id = payload.get("default_id", wallet.default_id)
        wallet._req_ids = {k: int(v)
                           for k, v in payload.get("req_ids", {}).items()}
        return wallet
