"""Client-side state-proof verification: trust ONE node's answer.

Reference: the client half of SURVEY.md §3.5 — a read reply carries
{value, state proof, BLS multi-signature}; the client checks (a) the
sparse-Merkle inclusion proof against the claimed root and (b) the pool's
n-f multi-signature over that root, so a single node's reply is as
trustworthy as f+1 matching replies.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

from ..crypto.bls.bls_crypto import BlsCryptoVerifier, MultiSignature
from ..state.sparse_merkle_state import verify_state_proof
from ..utils.base58 import b58decode, b58encode


class StateProofReply:
    """What a node returns for a proved read."""

    def __init__(self, key: bytes, value: Optional[bytes],
                 root: bytes, proof: bytes,
                 multi_sig_dict: Optional[dict]):
        self.key = key
        self.value = value
        self.root = root
        self.proof = proof
        self.multi_sig = (MultiSignature.from_dict(multi_sig_dict)
                          if multi_sig_dict else None)

    def as_dict(self) -> dict:
        return {
            "key": self.key,
            "value": self.value,
            "root": b58encode(self.root),
            "proof": self.proof,
            "multi_sig": self.multi_sig.as_dict() if self.multi_sig else None,
        }


def verify_proved_reply(reply: StateProofReply,
                        pool_bls_keys: Dict[str, str],
                        min_participants: int,
                        now: Optional[float] = None,
                        max_age: Optional[float] = None) -> bool:
    """True iff the reply proves (key -> value) under a root co-signed by
    >= min_participants validators (n-f for the reading client).

    ``pool_bls_keys``: node name -> BLS pk b58 (from the pool ledger /
    genesis — the client's trust anchor). When ``now``/``max_age`` are
    given, the multi-signature's timestamp must be recent: a byzantine
    node holding an OLD root with a genuine pool signature could otherwise
    serve provably-signed stale state (e.g. an absence proof for a key
    written since).
    """
    # 1. the Merkle proof binds (key, value) to the root
    if not verify_state_proof(reply.root, reply.key, reply.value,
                              reply.proof):
        return False
    # 2. the multi-sig binds the root to the pool
    ms = reply.multi_sig
    if ms is None:
        return False
    if ms.value.state_root_hash != b58encode(reply.root):
        return False
    return verify_pool_multi_sig(ms, pool_bls_keys, min_participants,
                                 now=now, max_age=max_age)


def verify_pool_multi_sig(ms: MultiSignature,
                          pool_bls_keys: Dict[str, str],
                          min_participants: int,
                          now: Optional[float] = None,
                          max_age: Optional[float] = None) -> bool:
    """True iff ``ms`` is a genuine >=min_participants co-signature by
    pool members over its own value (roots + timestamp). Shared by proved
    reads and the observer plane — anything that trusts a pool-signed
    root goes through here."""
    if now is not None and max_age is not None:
        ts = ms.value.timestamp
        if not isinstance(ts, (int, float)) or now - ts > max_age:
            return False
    if len(set(ms.participants)) < min_participants:
        return False
    pks = []
    for name in ms.participants:
        pk = pool_bls_keys.get(name)
        if pk is None:
            return False  # signed by someone outside the pool
        pks.append(pk)
    return BlsCryptoVerifier.verify_multi_sig(
        ms.signature, ms.value.serialize(), pks)
