"""Client-side state-proof verification: trust ONE node's answer.

Reference: the client half of SURVEY.md §3.5 — a read reply carries
{value, state proof, BLS multi-signature}; the client checks (a) the
sparse-Merkle inclusion proof against the claimed root and (b) the pool's
n-f multi-signature over that root, so a single node's reply is as
trustworthy as f+1 matching replies.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

from ..crypto.bls.bls_crypto import BlsCryptoVerifier, MultiSignature
from ..state.sparse_merkle_state import verify_state_proof
from ..utils.base58 import b58decode, b58encode


class StateProofReply:
    """What a node returns for a proved read."""

    def __init__(self, key: bytes, value: Optional[bytes],
                 root: bytes, proof: bytes,
                 multi_sig_dict: Optional[dict]):
        self.key = key
        self.value = value
        self.root = root
        self.proof = proof
        self.multi_sig = (MultiSignature.from_dict(multi_sig_dict)
                          if multi_sig_dict else None)

    def as_dict(self) -> dict:
        return {
            "key": self.key,
            "value": self.value,
            "root": b58encode(self.root),
            "proof": self.proof,
            "multi_sig": self.multi_sig.as_dict() if self.multi_sig else None,
        }


def verify_proved_reply(reply: StateProofReply,
                        pool_bls_keys: Dict[str, str],
                        min_participants: int,
                        now: Optional[float] = None,
                        max_age: Optional[float] = None) -> bool:
    """True iff the reply proves (key -> value) under a root co-signed by
    >= min_participants validators (n-f for the reading client).

    ``pool_bls_keys``: node name -> BLS pk b58 (from the pool ledger /
    genesis — the client's trust anchor). When ``now``/``max_age`` are
    given, the multi-signature's timestamp must be recent: a byzantine
    node holding an OLD root with a genuine pool signature could otherwise
    serve provably-signed stale state (e.g. an absence proof for a key
    written since).
    """
    # 1. the Merkle proof binds (key, value) to the root
    if not verify_state_proof(reply.root, reply.key, reply.value,
                              reply.proof):
        return False
    # 2. the multi-sig binds the root to the pool
    ms = reply.multi_sig
    if ms is None:
        return False
    if ms.value.state_root_hash != b58encode(reply.root):
        return False
    return verify_pool_multi_sig(ms, pool_bls_keys, min_participants,
                                 now=now, max_age=max_age)


def verify_proved_read(read,
                       pool_bls_keys: Dict[str, str],
                       min_participants: int,
                       now: Optional[float] = None,
                       max_age: Optional[float] = None) -> bool:
    """Verify a :class:`~indy_plenum_tpu.ingress.read_service.ProofRead`
    end-to-end with nothing but the pool's BLS keys (the state-proof
    plane's client half — README "State-proof plane").

    Three bindings, each independently forgeable only by breaking the
    crypto: (1) the RFC 6962 audit path binds (index, leaf) to ``root``
    at ``tree_size``; (2) the multi-signature's ``txn_root_hash`` binds
    ``root`` to the value the pool co-signed at a stabilized checkpoint
    window; (3) :func:`verify_pool_multi_sig` binds that value to
    >= ``min_participants`` pool validators. A flipped root, flipped
    signature, tampered participant set, or a proof replayed against a
    different window's root all fail one of the three. ``now``/
    ``max_age`` additionally reject STALE windows: a byzantine node
    replaying a genuinely-signed old window (e.g. an absence proof for a
    key written since) fails the freshness check even though every
    binding above holds.

    ``read`` needs ``leaf`` / ``index`` / ``path`` / ``tree_size`` /
    ``root`` / ``multi_sig`` attributes (``multi_sig`` may be the wire
    dict or a :class:`MultiSignature`).
    """
    ms = getattr(read, "multi_sig", None)
    if ms is None:
        return False
    if not isinstance(ms, MultiSignature):
        try:
            ms = MultiSignature.from_dict(dict(ms))
        except (KeyError, TypeError, ValueError):
            return False
    # 1. the audit path binds (index, leaf) to the root. The reply is
    # UNTRUSTED input: malformed fields (str root, non-bytes path
    # elements, ...) must be a False verdict, never an exception out of
    # the client's read loop — TypeError covers the bytes-concat and
    # hashing paths ValueError/IndexError do not
    if not isinstance(read.root, (bytes, bytearray)):
        return False
    from ..ledger.merkle_verifier import STH, MerkleVerifier

    try:
        ok = MerkleVerifier().verify_leaf_inclusion(
            read.leaf, read.index, read.path,
            STH(read.tree_size, read.root))
    except (ValueError, IndexError, TypeError):
        return False
    if not ok:
        return False
    # 2. the multi-sig's signed value names exactly this root
    if ms.value.txn_root_hash != b58encode(read.root):
        return False
    # 3. the pool signed that value (+ optional freshness)
    return verify_pool_multi_sig(ms, pool_bls_keys, min_participants,
                                 now=now, max_age=max_age)


def verify_read_binding(read) -> bool:
    """Bindings (1)+(2) of :func:`verify_proved_read` WITHOUT the
    multi-signature pairing check: the RFC 6962 audit path binds
    ``(index, leaf)`` to ``root`` at ``tree_size``, and the attached
    multi-sig's signed value names exactly that root.

    The geo plane's edge clients use this to amortize the pairing cost
    across a window (README "Planet-scale read fabric"): ONE full
    :func:`verify_proved_read` per distinct (window, signature,
    participants) establishes pool trust in the signed root; every
    further reply claiming the SAME signed material needs only these
    two offline bindings — a tampered leaf, path, or root fails here,
    and a reply smuggling a DIFFERENT multi-sig misses the caller's
    trust key and pays the full verification (which then fails)."""
    ms = getattr(read, "multi_sig", None)
    if ms is None:
        return False
    if not isinstance(read.root, (bytes, bytearray)):
        return False
    from ..ledger.merkle_verifier import STH, MerkleVerifier

    try:
        ok = MerkleVerifier().verify_leaf_inclusion(
            read.leaf, read.index, read.path,
            STH(read.tree_size, read.root))
    except (ValueError, IndexError, TypeError):
        return False
    if not ok:
        return False
    if isinstance(ms, MultiSignature):
        txn_root = ms.value.txn_root_hash
    else:
        try:
            txn_root = dict(ms).get("value", {}).get("txn_root_hash")
        except (TypeError, ValueError, AttributeError):
            return False
    return txn_root == b58encode(read.root)


def verify_pool_multi_sig(ms: MultiSignature,
                          pool_bls_keys: Dict[str, str],
                          min_participants: int,
                          now: Optional[float] = None,
                          max_age: Optional[float] = None) -> bool:
    """True iff ``ms`` is a genuine >=min_participants co-signature by
    pool members over its own value (roots + timestamp). Shared by proved
    reads and the observer plane — anything that trusts a pool-signed
    root goes through here."""
    if now is not None and max_age is not None:
        ts = ms.value.timestamp
        if not isinstance(ts, (int, float)) or now - ts > max_age:
            return False
    if len(set(ms.participants)) < min_participants:
        return False
    pks = []
    for name in ms.participants:
        pk = pool_bls_keys.get(name)
        if pk is None:
            return False  # signed by someone outside the pool
        pks.append(pk)
    return BlsCryptoVerifier.verify_multi_sig(
        ms.signature, ms.value.serialize(), pks)
