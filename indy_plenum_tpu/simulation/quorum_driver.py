"""Shared device-quorum wiring for the simulation pools.

Both :class:`~indy_plenum_tpu.simulation.pool.SimPool` (consensus services
wired directly) and :class:`~indy_plenum_tpu.simulation.node_pool.NodePool`
(full Node composition roots) share one grouped device vote plane and, in
tick-batched mode, one pool-level tick that flushes the whole group once
and then lets every node evaluate against the fresh snapshot.

The tick is the dispatch-plane barrier (README "Performance"): it is
scheduled with ``barrier=True`` so every network delivery due at the tick
instant lands FIRST; the tick then (1) drains the signed-request ingress
through one device batch verify, (2) scatters the whole pool's buffered
votes in one grouped device step, and (3) lets every service evaluate
against the fresh snapshot. ``device.dispatches_per_tick`` and
``device.flush_occupancy`` land in the group's metrics collector so the
amortization is a regression-guarded number
(``scripts/check_dispatch_budget.py``).

With a ``mesh`` the same contract runs SPMD: the member axis is sharded
over the devices (``shard_map``), each shard stages its own scatter rows,
and the governor observes PER-SHARD occupancy — one hot shard narrows the
tick for everyone (README "Mesh-sharded dispatch plane").
"""
from __future__ import annotations

from typing import Callable, Optional

from ..common.metrics_collector import MetricsName
from ..common.timer import RepeatingTimer, TimerService
from ..config import Config
from ..ingress.admission import BackpressureSignal
from ..observability.trace import _NO_SPAN


def make_vote_group(n_nodes: int, validators, config: Config,
                    num_instances: int = 1, mesh=None,
                    pipelined: bool = True, metrics=None,
                    host_eval: bool = False):
    """Member axis = (node x instance): member i*num_instances + inst_id
    is node i's plane for protocol instance inst_id (SURVEY §2.6's RBFT
    mapping — instances are a leading tensor dimension, so backups' vote
    tallies ride the same vmapped dispatch as the master's). ``mesh``
    shards that member axis across a device mesh via ``shard_map`` (the
    member count is padded up to a mesh multiple; quorum events gather
    back in one readback); ``pipelined`` (DEFAULT since the ordering
    fast path: README "Performance") overlaps each tick's device
    round-trip with the next tick's host work (verdicts lag one tick;
    the services' lost-wakeup guard re-arms while a step is in flight).
    ``host_eval`` selects the full-event-matrix readback fallback over
    the default on-device quorum eval + compact delta readback.
    ``config.FlushLadderAdaptive`` hands the padded flush width to the
    learned per-pool ladder; ``config.ResidentTickDepth`` > 1 turns on
    the multi-tick residency ring (one fused device dispatch per
    up-to-N ticks)."""
    from ..tpu.vote_plane import VotePlaneGroup

    return VotePlaneGroup(
        n_nodes * max(1, num_instances), list(validators),
        log_size=config.LOG_SIZE,
        n_checkpoints=max(1, config.LOG_SIZE // config.CHK_FREQ),
        mesh=mesh, pipelined=pipelined, metrics=metrics,
        adaptive_ladder=config.FlushLadderAdaptive,
        host_eval=host_eval,
        resident_depth=config.ResidentTickDepth)


def drive_group_ticks(timer: TimerService, config: Config, vote_group,
                      nodes, accounting=None,
                      ingress: Optional[Callable[[], None]] = None,
                      trace=None) -> Optional[RepeatingTimer]:
    """Start the pool-level quorum tick (tick-batched mode only).

    Each node must expose ``vote_plane`` / ``ordering`` / ``checkpoints``;
    queries between ticks read the per-tick snapshot
    (``defer_flush_on_query``), and ONE group flush per tick serves the
    whole pool. The tick is a ``barrier`` timer event: deliveries due at
    the tick instant drain before it fires, so quorum evaluation never
    races a same-instant message. ``ingress`` (optional) drains the
    pool's signed-request queue through one device batch verify at tick
    start — requests that arrived during the interval ride one Ed25519
    dispatch, then their finalisation is visible to the same tick's batch
    timers. ``accounting`` (name -> seconds) attributes each node's
    tick evaluation to it, plus the FULL shared flush time to EVERY node
    (conservative: a deployed node flushes only its own plane).

    With ``config.QuorumTickAdaptive`` the returned timer's interval is
    governed: after each tick the :class:`~indy_plenum_tpu.tpu.governor
    .DispatchGovernor` observes the tick's scattered votes / padded
    capacity / chained dispatches and retunes the interval inside the
    configured bounds (the governor rides the timer as ``.governor`` so
    pools can expose the trajectory).
    """
    if vote_group is None or config.QuorumTickInterval <= 0:
        return None
    for node in nodes:
        node.vote_plane.defer_flush_on_query = True

    from time import perf_counter

    from ..observability.trace import NULL_TRACE
    from ..tpu.governor import DispatchGovernor

    # flight recorder: per-tick dispatch-plane spans (drain / flush /
    # eval / governor decision) join the 3PC lifecycle marks the
    # services record — one attributable timeline per tick
    trace = trace if trace is not None else NULL_TRACE
    governor = DispatchGovernor.from_config(config,
                                            metrics=vote_group.metrics,
                                            trace=trace)
    # occupancy-driven rebalancing (tpu/rebalance.py): None unless the
    # group is member-sharded AND a trigger is armed — common runs pay
    # nothing. The policy only PLANS here; the group executes at its
    # next checkpoint-boundary slide (the rebalance barrier).
    from ..tpu.rebalance import RebalancePolicy

    rebalance = RebalancePolicy.from_config(config, vote_group)
    last = [vote_group.flushes, vote_group.flush_votes_total,
            vote_group.flush_capacity_total]
    # per-shard baselines (length 1 when unsharded): the governor's law
    # runs on per-shard occupancy deltas, so a mesh run's hot shard
    # narrows the tick for the whole pool
    last_shard = [list(vote_group.flush_votes_per_shard),
                  list(vote_group.flush_capacity_per_shard)]
    timer_box: list = []  # the RepeatingTimer, bound after construction

    def tick() -> None:
        # ingress stays OUTSIDE the accounted window: SimPool's shared
        # ingress is a pool-level stand-in — charging its auth batch to
        # every node's host_seconds would n-fold over-count it. The
        # drain's return value may be a BackpressureSignal (admission
        # plane): queue depth / sheds / leeching feed the governor's
        # law alongside the flush occupancy it already observes.
        drained = None
        if ingress is not None:
            if trace.enabled:
                with trace.span("tick.drain"):
                    drained = ingress()
            else:
                drained = ingress()
        # da: allow[nondet-source] -- host-CPU accounting (profile_rbft attribution); tick cadence and quorum math ride the injected timer
        t0 = perf_counter() if accounting is not None else 0.0
        vote_group.flush()
        dispatches = vote_group.flushes - last[0]
        vote_group.metrics.add_event(
            MetricsName.DEVICE_DISPATCHES_PER_TICK, dispatches)
        if trace.enabled:
            trace.record("tick.flush", cat="dispatch",
                         args={"dispatches": dispatches,
                               "votes": vote_group.flush_votes_total
                               - last[1]})
        if governor is not None:
            if isinstance(drained, BackpressureSignal):
                governor.feed_backpressure(drained)
            new_interval = governor.observe_shards(
                [a - b for a, b in zip(vote_group.flush_votes_per_shard,
                                       last_shard[0])],
                [a - b for a, b in zip(vote_group.flush_capacity_per_shard,
                                       last_shard[1])],
                dispatches,
                # pipelined plane with verdicts in flight: cap the next
                # tick at the base interval so the absorb is prompt (the
                # absorb tick dispatches nothing — see the governor's
                # absorb clamp)
                inflight=vote_group.lagging)
            timer_box[0].update_interval(new_interval)
            if trace.enabled:
                trace.record(
                    "tick.governor", cat="dispatch",
                    args={"interval": round(new_interval, 9),
                          "occupancy_ewma": round(governor.ewma, 6)})
        if rebalance is not None:
            rows = rebalance.observe(
                governor.shard_ewmas if governor is not None else None)
            if rows:
                if trace.enabled:
                    trace.record(
                        "rebalance.planned", cat="dispatch",
                        args={"rows": rows,
                              "skew": round(rebalance.last_skew, 4)})
                vote_group.schedule_rebalance(rows)
        last[:] = [vote_group.flushes, vote_group.flush_votes_total,
                   vote_group.flush_capacity_total]
        last_shard[0] = list(vote_group.flush_votes_per_shard)
        last_shard[1] = list(vote_group.flush_capacity_per_shard)
        # da: allow[nondet-source] -- accounting close (see t0 above)
        flush_dt = perf_counter() - t0 if accounting is not None else 0.0
        with trace.span("tick.eval", args={"nodes": len(nodes)}) \
                if trace.enabled else _NO_SPAN:
            for node in nodes:
                # da: allow[nondet-source] -- per-node accounting window open
                t0 = perf_counter() if accounting is not None else 0.0
                node.ordering.service_quorum_tick()
                node.checkpoints.service_quorum_tick()
                replicas = getattr(node, "replicas", None)  # SimNode: none
                for backup in (replicas.backups if replicas else ()):
                    if backup.vote_plane is not None:
                        backup.ordering.service_quorum_tick()
                        backup.checkpoints.service_quorum_tick()
                if accounting is not None:
                    # da: allow[nondet-source] -- accounting window close
                    accounting[node.name] += (perf_counter() - t0) + flush_dt

    interval = governor.interval if governor else config.QuorumTickInterval
    rt = RepeatingTimer(timer, interval, tick, barrier=True)
    timer_box.append(rt)
    rt.governor = governor
    rt.rebalance = rebalance
    return rt


def drive_lane_ticks(timer: TimerService, config: Config, lane_pools,
                     barrier=None, trace=None,
                     metrics=None) -> Optional[RepeatingTimer]:
    """One pool-level tick driving EVERY ordering lane (ordering lanes,
    README "Ordering lanes"): each lane owns a full
    :class:`~indy_plenum_tpu.tpu.vote_plane.VotePlaneGroup` on its own
    mesh slice, but the tick cadence is shared — per tick, each lane's
    ingress drains, each lane's group flushes once, every lane's
    services evaluate against their fresh snapshot, and finally the
    cross-lane checkpoint barrier re-evaluates its seal condition
    (:meth:`~indy_plenum_tpu.lanes.barrier.CrossLaneBarrier
    .service_tick`) so a lane that went idle unblocks the others at a
    deterministic instant.

    ONE dispatch governor serves all lanes: it observes the
    concatenation of every lane's per-shard occupancy deltas (the
    hottest lane-shard narrows the tick for the whole pool, exactly as
    the hottest shard does in a mesh run) and the FOLDED per-lane
    backpressure (max queue pressure, summed sheds, any-lane leeching).
    Returns None when ``config.QuorumTickInterval <= 0`` (per-message
    mode — the LanedPool then runs a plain barrier pulse instead)."""
    if config.QuorumTickInterval <= 0:
        return None
    from ..observability.trace import NULL_TRACE
    from ..tpu.governor import DispatchGovernor

    trace = trace if trace is not None else NULL_TRACE
    if metrics is None:
        metrics = lane_pools[0].metrics
    tick_groups = [lp.vote_group for lp in lane_pools
                   if lp.vote_group is not None]
    for lp in lane_pools:
        if lp.vote_group is not None:
            for node in lp.nodes:
                node.vote_plane.defer_flush_on_query = True
                replicas = getattr(node, "replicas", None)
                for backup in (replicas.backups if replicas else ()):
                    if backup.vote_plane is not None:
                        backup.vote_plane.defer_flush_on_query = True
    governor = DispatchGovernor.from_config(config, metrics=metrics,
                                            trace=trace) \
        if tick_groups else None
    last_flush = [g.flushes for g in tick_groups]
    last_shard = [(list(g.flush_votes_per_shard),
                   list(g.flush_capacity_per_shard)) for g in tick_groups]
    timer_box: list = []

    def tick() -> None:
        signals = []
        with trace.span("tick.drain") if trace.enabled else _NO_SPAN:
            for lp in lane_pools:
                if lp.authnr is not None:
                    drained = lp._ingress_tick()
                    if isinstance(drained, BackpressureSignal):
                        signals.append(drained)
        dispatches_per_lane = []
        vote_deltas: list = []
        cap_deltas: list = []
        for gi, group in enumerate(tick_groups):
            group.flush()
            dispatches_per_lane.append(group.flushes - last_flush[gi])
            last_flush[gi] = group.flushes
            votes0, caps0 = last_shard[gi]
            vote_deltas.extend(
                a - b for a, b in zip(group.flush_votes_per_shard, votes0))
            cap_deltas.extend(
                a - b for a, b in zip(group.flush_capacity_per_shard,
                                      caps0))
            last_shard[gi] = (list(group.flush_votes_per_shard),
                              list(group.flush_capacity_per_shard))
        dispatches = sum(dispatches_per_lane)
        if tick_groups:
            metrics.add_event(MetricsName.DEVICE_DISPATCHES_PER_TICK,
                              dispatches)
        if trace.enabled:
            trace.record("tick.flush", cat="dispatch",
                         args={"dispatches": dispatches,
                               "per_lane": dispatches_per_lane})
        if governor is not None:
            if signals:
                # fold per-lane pressure: the most-pressured lane's
                # queue fraction drives the narrow decision, sheds and
                # outstanding retries sum, and any lane leeching widens
                worst = max(signals, key=lambda s: s.queue_frac)
                governor.feed_backpressure(BackpressureSignal(
                    queue_depth=worst.queue_depth,
                    capacity=worst.capacity,
                    shed_delta=sum(s.shed_delta for s in signals),
                    leeching=any(s.leeching for s in signals),
                    retry_pressure=sum(s.retry_pressure
                                       for s in signals)))
            new_interval = governor.observe_shards(
                vote_deltas, cap_deltas, dispatches,
                inflight=any(g.lagging for g in tick_groups))
            timer_box[0].update_interval(new_interval)
            if trace.enabled:
                trace.record(
                    "tick.governor", cat="dispatch",
                    args={"interval": round(new_interval, 9),
                          "occupancy_ewma": round(governor.ewma, 6)})
        with trace.span("tick.eval",
                        args={"lanes": len(lane_pools)}) \
                if trace.enabled else _NO_SPAN:
            for lp in lane_pools:
                if lp.vote_group is None:
                    continue
                for node in lp.nodes:
                    node.ordering.service_quorum_tick()
                    node.checkpoints.service_quorum_tick()
                    replicas = getattr(node, "replicas", None)
                    for backup in (replicas.backups if replicas else ()):
                        if backup.vote_plane is not None:
                            backup.ordering.service_quorum_tick()
                            backup.checkpoints.service_quorum_tick()
        if barrier is not None:
            barrier.service_tick()
        # per-lane ordered totals (Monitor lanes block: Stat.last)
        for li, lp in enumerate(lane_pools):
            metrics.add_event(
                "%s.%d" % (MetricsName.LANE_ORDERED, li),
                min(len(nd.ordered_digests) for nd in lp.nodes))
        metrics.add_event(MetricsName.LANE_COUNT, len(lane_pools))

    interval = governor.interval if governor else config.QuorumTickInterval
    rt = RepeatingTimer(timer, interval, tick, barrier=True)
    timer_box.append(rt)
    rt.governor = governor
    return rt
