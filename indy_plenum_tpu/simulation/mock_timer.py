"""Virtual clock for deterministic protocol testing.

Reference: plenum/common/timer.py MockTimer + stp_core's looper-driven time.
Advancing the clock fires due callbacks; nothing real-time anywhere, so a
whole multi-node pool runs deterministically in-process (SURVEY.md §4
tier 5).
"""
from __future__ import annotations

from ..common.timer import QueueTimer


class MockTimer(QueueTimer):
    def __init__(self, start_time: float = 0.0):
        self._now = start_time
        super().__init__(get_current_time=lambda: self._now)

    def set_time(self, value: float) -> None:
        """Jump the clock forward, firing everything due on the way."""
        events = self._events  # peek the heap directly: one pass per due
        # event, not a next_event_time() + service() pair per timestamp
        # (cancelled heads are popped unfired by service() itself)
        while events and events[0].timestamp <= value:
            self._now = events[0].timestamp
            self.service()
        self._now = value
        self.service()

    def advance(self, seconds: float = 0.0) -> None:
        self.set_time(self._now + seconds)

    def run_to_completion(self, max_time: float = 3600.0) -> None:
        """Fire events (and the events they schedule) until quiescent."""
        while True:
            nxt = self.next_event_time()
            if nxt is None or nxt > max_time:
                break
            self.set_time(nxt)
