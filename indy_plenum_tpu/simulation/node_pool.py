"""Full-stack simulated pool: real Node composition roots on a sim network.

Unlike :mod:`indy_plenum_tpu.simulation.pool` (which wires the consensus
services directly and abstracts request dissemination into one shared
pool), every validator here is a real :class:`~indy_plenum_tpu.server.node
.Node`: client requests enter ONE node, get device-batch authenticated,
spread via PROPAGATE to the f+1 finalisation quorum, order through 3PC,
execute against real ledgers/SMT state, and produce client Replies. This
is the integration surface for the Node/Propagator layer.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional

from ..common.constants import TRUSTEE
from ..common.metrics_collector import MetricsCollector
from ..common.request import Request
from ..config import Config, getConfig
from ..crypto.signers import DidSigner
from ..ledger.genesis import genesis_nym_txn
from ..server.node import Node
from .mock_timer import MockTimer
from .sim_network import SimNetwork


class NodePool:
    def __init__(self, n_nodes: int = 4, seed: int = 0,
                 config: Optional[Config] = None,
                 device_quorum: bool = False,
                 bls: bool = False,
                 num_instances: int = 1,
                 with_pool_genesis: bool = False,
                 mesh=None,
                 host_eval: bool = False,
                 trace: bool = False):
        # num_instances: 1 = master only; 0 = auto f+1 (full RBFT)
        # mesh: shard the grouped vote plane's (node x instance) member
        # axis across a jax device mesh (CPU CI provisions virtual
        # devices via XLA_FLAGS=--xla_force_host_platform_device_count)
        self.config = config or getConfig(
            {"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 10,
             "PropagateBatchWait": 0.05})
        # simulation contract (config.IngressShedSeed): sim pools seed
        # the shed tiebreak from the POOL seed so the shed set replays
        # with the run; an explicit IngressShedSeed in the config wins.
        # replace(), not in-place: the caller's config may build other
        # pools and must not inherit this pool's seed
        if self.config.IngressQueueCapacity > 0 \
                and not self.config.IngressShedSeed:
            import dataclasses

            self.config = dataclasses.replace(
                self.config, IngressShedSeed=seed)
        self.timer = MockTimer(start_time=1_700_000_000.0)
        self.metrics = MetricsCollector()
        # pool-shared flight recorder on the virtual clock (deterministic
        # dumps); every Node's services + Monitor share it
        from ..observability.trace import NULL_TRACE, TraceRecorder

        self.trace = (TraceRecorder(
            self.timer.get_current_time,
            capacity=self.config.TraceRecorderCapacity)
            if trace else NULL_TRACE)
        # causal tracing plane: PROPAGATE fan-out and 3PC waves between
        # real Node compositions stamp net.send/net.recv on the shared
        # recorder — journeys join them across nodes
        self.network = SimNetwork(
            self.timer, seed=seed, metrics=self.metrics,
            trace=self.trace,
            trace_receivers=self.config.TraceNetReceivers)
        self.validators = [f"node{i}" for i in range(n_nodes)]

        self.trustee = DidSigner(b"\x09" * 32)
        domain_genesis = [genesis_nym_txn(
            self.trustee.identifier, self.trustee.verkey, role=TRUSTEE)]
        seed_keys = {self.trustee.identifier: self.trustee.verkey}

        # pool genesis: one NODE txn per initial validator, owned by one
        # steward each (membership-from-ledger mode; the PoolManager takes
        # over the validator registry)
        self.stewards: Dict[str, DidSigner] = {}
        self.pool_genesis = None
        self._domain_genesis = domain_genesis
        self._seed_keys = seed_keys
        if with_pool_genesis:
            from ..common.constants import STEWARD
            from ..ledger.genesis import genesis_node_txn

            self.pool_genesis = []
            for i, name in enumerate(self.validators):
                steward = DidSigner(hashlib.sha256(
                    b"pool-steward-%d" % i).digest())
                self.stewards[name] = steward
                domain_genesis.append(genesis_nym_txn(
                    steward.identifier, steward.verkey, role=STEWARD))
                self.pool_genesis.append(genesis_node_txn(
                    node_nym=f"nym-{name}", alias=name,
                    steward_did=steward.identifier,
                    node_port=9700 + 2 * i, client_port=9701 + 2 * i))

        self.bls_keys = None
        if bls:
            from ..bls.factory import generate_bls_keys

            self.bls_keys = {
                name: generate_bls_keys(
                    hashlib.sha256(b"sim-bls-" + name.encode()).digest())
                for name in self.validators}

        from .quorum_driver import drive_group_ticks, make_vote_group

        # resolve the instance count the same way Node does, so the
        # (node x instance) group axis matches the replicas actually built
        resolved_instances = (num_instances if num_instances > 0
                              else self.config.replicas_count(n_nodes))
        self.num_instances = resolved_instances
        self.vote_group = None
        if device_quorum:
            self.vote_group = make_vote_group(
                n_nodes, self.validators, self.config,
                num_instances=resolved_instances, mesh=mesh,
                metrics=self.metrics, host_eval=host_eval)
            self.vote_group.trace = self.trace

        tick_mode = self.config.QuorumTickInterval > 0

        def backup_plane_factory(node_idx: int):
            if self.vote_group is None:
                return None

            def factory(inst_id: int):
                plane = self.vote_group.view(
                    node_idx * resolved_instances + inst_id)
                plane.defer_flush_on_query = tick_mode
                return plane

            return factory

        self.nodes: List[Node] = []
        for i, name in enumerate(self.validators):
            plane = (self.vote_group.view(i * resolved_instances)
                     if self.vote_group else None)
            node = Node(
                name, self.validators, self.timer, self.network,
                config=self.config, domain_genesis=domain_genesis,
                pool_genesis=([dict(t) for t in self.pool_genesis]
                              if self.pool_genesis else None),
                seed_keys=dict(seed_keys), bls_keys=self.bls_keys,
                vote_plane=plane, num_instances=num_instances,
                drive_quorum_ticks=False,  # the pool drives group ticks
                # shared collector: the dispatch-plane numbers the pool
                # tick records are then visible in every node's
                # Monitor.snapshot() (and node metrics aggregate pool-wide)
                metrics=self.metrics,
                backup_vote_plane_factory=backup_plane_factory(i),
                trace=self.trace)
            self.nodes.append(node)
        self.network.connect_all()
        for node in self.nodes:
            node.start()

        _shed_seen: Dict[str, int] = {}

        def drain_auth_queues():
            # ingress rides the dispatch tick: each node's queued signed
            # requests get one device auth batch before votes scatter
            # (the per-node PropagateBatchWait timer still covers the
            # per-message mode and sub-interval bursts). With admission
            # control on, the drain aggregates the pool's backpressure —
            # the BUSIEST node's queue depth, the tick's total sheds, and
            # whether anyone is leeching — for the dispatch governor.
            depth = shed = 0
            bounded = False
            for nd in self.nodes:
                adm = nd.admission
                if adm is not None:
                    bounded = True
                    depth = max(depth, adm.depth)
                    # sheds since the LAST tick (offer-time sheds
                    # included, not just ones settled by this flush)
                    prev = _shed_seen.get(nd.name, 0)
                    nd._flush_auth_queue()
                    shed += adm.shed_total - prev
                    _shed_seen[nd.name] = adm.shed_total
                else:
                    nd._flush_auth_queue()
            if not bounded:
                return None
            from ..ingress.admission import BackpressureSignal

            return BackpressureSignal(
                queue_depth=depth,
                capacity=self.config.IngressQueueCapacity,
                shed_delta=shed,
                leeching=any(not nd.data.is_participating
                             for nd in self.nodes))

        self._quorum_tick_timer = drive_group_ticks(
            self.timer, self.config, self.vote_group, self.nodes,
            ingress=drain_auth_queues, trace=self.trace)
        self.governor = getattr(self._quorum_tick_timer, "governor", None)

        self._req_seq = 0

    def add_node(self, name: str) -> Node:
        """Spin up a validator that the pool has ALREADY admitted via a
        committed NODE txn; it bootstraps from genesis and catches up the
        ledgers (including the NODE txn that admitted it)."""
        validators = list(self.nodes[0].data.validators)
        assert name in validators, f"{name} not in the committed membership"
        node = Node(
            name, validators, self.timer, self.network,
            config=self.config,
            domain_genesis=[dict(t) for t in self._domain_genesis],
            pool_genesis=([dict(t) for t in self.pool_genesis]
                          if self.pool_genesis else None),
            seed_keys=dict(self._seed_keys),
            num_instances=1, drive_quorum_ticks=False)
        self.nodes.append(node)
        if name not in self.validators:
            self.validators.append(name)
        self.network.connect_all()
        node.start()
        node.leecher.start()  # fetch everything committed before we joined
        return node

    # ------------------------------------------------------------------

    def node(self, name: str) -> Node:
        return next(n for n in self.nodes if n.name == name)

    @property
    def primary(self) -> Node:
        return self.node(self.nodes[0].data.primaries[0])

    def make_nym_request(self, seq: Optional[int] = None,
                         signer: Optional[DidSigner] = None) -> Request:
        """A signed NYM write creating a fresh target identity."""
        from ..common.constants import NYM, TARGET_NYM, TXN_TYPE, VERKEY

        if seq is None:
            self._req_seq += 1
            seq = self._req_seq
        signer = signer or self.trustee
        target = DidSigner(hashlib.sha256(
            b"pool-target-%d" % seq).digest())
        req = Request(
            identifier=signer.identifier, reqId=seq,
            operation={TXN_TYPE: NYM, TARGET_NYM: target.identifier,
                       VERKEY: target.verkey})
        signer.sign_request(req)
        req.target_signer = target  # test convenience
        return req

    def submit_to(self, node_name: str, req: Request,
                  client_id: str = "client1") -> bool:
        """Client sends a request to exactly ONE node (the real topology)."""
        return self.node(node_name).submit_client_request(req, client_id)

    def make_client(self, name: str = "client1"):
        """A pool client wired to the sim nodes (direct-call transport)."""
        from ..client.client import Client

        static_bls = {}
        if self.bls_keys is not None:
            static_bls = {n: pk
                          for n, (kp, pk, pop) in self.bls_keys.items()}

        def live_bls_keys():
            # static sim keys + any keys the pool registry carries (a
            # node admitted by NODE txn brings its BLS key through it)
            from ..common.constants import BLS_KEY

            out = dict(static_bls)
            for alias, rec in self.nodes[0].pool_manager.registry.items():
                if rec.get(BLS_KEY):
                    out[alias] = rec[BLS_KEY]
            return out

        return Client(
            name, lambda: list(self.nodes[0].data.validators),
            send=lambda req, node, cid: self.node(node)
            .submit_client_request(req, client_id=cid),
            pool_bls_keys=live_bls_keys,
            now_provider=self.timer.get_current_time)

    def pump_client(self, client) -> None:
        """Deliver queued node->client messages to ``client``."""
        for node in self.nodes:
            keep = []
            for cid, msg in node.client_outbox:
                if cid == client.name:
                    client.process_node_message(node.name, msg)
                else:
                    keep.append((cid, msg))
            node.client_outbox = keep

    def run_for(self, seconds: float) -> None:
        self.timer.advance(seconds)

    def honest_nodes_agree(self) -> bool:
        logs = [tuple(n.ordered_digests) for n in self.nodes]
        shortest = min(len(l) for l in logs)
        return all(l[:shortest] == logs[0][:shortest] for l in logs)
