"""Simulated consensus pool: N full replica stacks on one virtual clock.

Reference pattern: plenum/test/simulation/ — ReplicaServices exchanging
messages through an in-memory network under a seeded random schedule.
Each simulated node wires the real consensus services (ordering,
checkpoint, view change, trigger, primary monitor, message-req) exactly as
the production Replica does; only the executor and request source are
simple in-memory fakes. This is the tier-5 harness AND the integration
surface for consensus changes (see .claude/skills/verify).
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional

from ..common.constants import DOMAIN_LEDGER_ID
from ..common.event_bus import InternalBus
from ..common.metrics_collector import MetricsCollector
from ..common.messages.node_messages import Ordered
from ..common.request import Request
from ..common.stashing_router import StashingRouter
from ..config import Config, getConfig
from ..server.consensus.checkpoint_service import CheckpointService
from ..server.consensus.consensus_shared_data import ConsensusSharedData
from ..server.consensus.message_req_service import MessageReqService
from ..server.consensus.ordering_service import (
    Executor,
    OrderingService,
    RequestsPool,
)
from ..server.consensus.primary_connection_monitor_service import (
    PrimaryConnectionMonitorService,
)
from ..server.consensus.primary_selector import (
    RoundRobinConstantNodesPrimariesSelector,
)
from ..server.consensus.view_change_service import ViewChangeService
from ..server.consensus.view_change_trigger_service import (
    ViewChangeTriggerService,
)
from .mock_timer import MockTimer
from .sim_network import SimNetwork


class SimExecutor(Executor):
    """Deterministic fake execution: roots = rolling sha256 over digests.

    Emulates the uncommitted-state behaviour of the real WriteRequestManager:
    batches apply speculatively (LIFO-revertible) and, per the Executor
    contract, an apply at or below the committed height returns the
    memoized historical roots without touching state.
    """

    def __init__(self):
        self.committed_chain = "genesis"
        self._committed_seq = 0
        self.roots_by_seq: Dict[int, str] = {}
        self.batch_chains: List[str] = []  # uncommitted chain tips

    def _root(self, chain: str) -> str:
        from ..utils.base58 import b58encode

        return b58encode(hashlib.sha256(chain.encode()).digest())

    def apply_batch(self, reqs, ledger_id, pp_time, pp_seq_no):
        if pp_seq_no <= self._committed_seq:
            root = self.roots_by_seq[pp_seq_no]
            return root, root
        tip = self.batch_chains[-1] if self.batch_chains \
            else self.committed_chain
        new_tip = hashlib.sha256(
            (tip + "".join(r.digest for r in reqs)).encode()).hexdigest()
        self.batch_chains.append(new_tip)
        root = self._root(new_tip)
        return root, root

    def revert_batches(self, ledger_id, count):
        count = min(count, len(self.batch_chains))
        if count:
            del self.batch_chains[len(self.batch_chains) - count:]

    def committed_seq(self) -> int:
        return self._committed_seq

    def commit_batch(self, pp_seq_no) -> None:
        if pp_seq_no <= self._committed_seq:
            return
        assert self.batch_chains, "commit with nothing staged"
        self.committed_chain = self.batch_chains.pop(0)
        self._committed_seq = pp_seq_no
        self.roots_by_seq[pp_seq_no] = self._root(self.committed_chain)


class SimRequestsPool(RequestsPool):
    """Finalised requests, shared across all nodes (propagation abstracted)."""

    def __init__(self):
        self._by_digest: Dict[str, Request] = {}
        self._queues: Dict[str, List[str]] = {}  # per node name

    def register_node(self, name: str) -> None:
        self._queues[name] = []

    def add_finalised(self, req: Request) -> None:
        self._by_digest[req.digest] = req
        for q in self._queues.values():
            q.append(req.digest)

    def view_for(self, name: str) -> "NodeRequestsView":
        return NodeRequestsView(self, name)


class NodeRequestsView(RequestsPool):
    def __init__(self, pool: SimRequestsPool, name: str):
        self._pool = pool
        self._name = name

    def pop_ready(self, ledger_id, max_count):
        q = self._pool._queues[self._name]
        take, self._pool._queues[self._name] = q[:max_count], q[max_count:]
        return [self._pool._by_digest[d] for d in take]

    def mark_ordered(self, digests) -> None:
        """Ordered requests leave the pending queue on EVERY node — the
        new primary after a view change must not re-propose them."""
        gone = set(digests)
        q = self._pool._queues[self._name]
        self._pool._queues[self._name] = [d for d in q if d not in gone]

    def get(self, digest):
        return self._pool._by_digest.get(digest)

    def has_ready(self, ledger_id):
        return bool(self._pool._queues[self._name])

    def ledger_ids_with_ready(self):
        return [DOMAIN_LEDGER_ID] if self.has_ready(DOMAIN_LEDGER_ID) else []


class SimNode:
    """One simulated validator: the full consensus service stack."""

    def __init__(self, name: str, validators: List[str], timer: MockTimer,
                 network: SimNetwork, requests: SimRequestsPool,
                 config: Config, device_quorum: bool = False,
                 domain_genesis: Optional[list] = None,
                 storage=None, bls_keys=None,
                 shadow_check: Optional[bool] = None,
                 vote_plane=None, trace=None, metrics=None,
                 barrier=None, lane: int = 0):
        # shadow_check default: on whenever the device plane decides, so
        # tests continuously prove host/device equivalence. The bench turns
        # it off to run the device plane as the SOLE quorum authority.
        # Tick-batched mode is incompatible with shadow checks by design:
        # the device snapshot is deliberately one tick stale while the host
        # dicts are live, so equivalence asserts would fire spuriously.
        if shadow_check is None:
            shadow_check = device_quorum and config.QuorumTickInterval == 0
        if shadow_check and config.QuorumTickInterval > 0:
            raise ValueError(
                "shadow_check cannot be combined with QuorumTickInterval>0:"
                " deferred device snapshots intentionally lag the host"
                " tallies")
        self.name = name
        self.config = config
        from ..observability.trace import NULL_TRACE

        # pool-shared flight recorder (virtual-clock timestamps): the
        # executed mark below completes each batch's 3PC lifecycle
        self.trace = trace if trace is not None else NULL_TRACE
        self.data = ConsensusSharedData(
            name, validators, inst_id=0, is_master=True,
            log_size=config.LOG_SIZE)
        selector = RoundRobinConstantNodesPrimariesSelector(validators)
        self.data.primaries = selector.select_primaries(0, 1)

        self.internal_bus = InternalBus()
        self.external_bus = network.create_peer(name)
        self.stasher = StashingRouter(
            limit=1000, buses=[self.internal_bus, self.external_bus])
        # instId demux (same wiring as the production Node): per-instance
        # 3PC traffic takes one dict hop to ONE router — k instances must
        # not each run their router over every inbound message
        from ..server.instance_demux import Instance3PCDemux

        self.demux = Instance3PCDemux(self.external_bus)
        self.stasher3pc = StashingRouter(
            limit=1000, buses=[self.internal_bus])
        self.demux.register(0, self.stasher3pc)
        self.boot = None
        if domain_genesis is not None:
            # real execution: ledgers + SMT states + audit spine per node
            from ..server.ledgers_bootstrap import LedgersBootstrap
            from ..server.request_managers.write_request_manager import (
                NodeExecutor,
            )

            self.boot = LedgersBootstrap(
                storage=storage, domain_genesis=domain_genesis,
                config=config).build()
            self.boot.write_manager.metrics = metrics
            self.executor = NodeExecutor(
                self.boot.write_manager,
                get_view_info=lambda: (self.data.view_no,
                                       list(self.data.primaries)))
        else:
            self.executor = SimExecutor()
        self.requests_view = requests.view_for(name)

        self.vote_plane = vote_plane
        if device_quorum and self.vote_plane is None:
            from ..tpu.vote_plane import DeviceVotePlane

            self.vote_plane = DeviceVotePlane(
                validators, log_size=config.LOG_SIZE,
                n_checkpoints=max(1, config.LOG_SIZE // config.CHK_FREQ))

        self.bls_replica = None
        if bls_keys is not None:
            from ..bls.factory import create_bls_bft_replica
            from ..utils.base58 import b58encode

            own_kp, pool_keys = bls_keys[name], {
                n: (pk, pop) for n, (kp, pk, pop) in bls_keys.items()}

            def pool_root():
                if self.boot is None:
                    return ""
                from ..common.constants import POOL_LEDGER_ID

                return b58encode(self.boot.db.get_state(
                    POOL_LEDGER_ID).committed_head_hash)

            def bls_suspicion(ex):
                from ..common.messages.internal_messages import (
                    RaisedSuspicion,
                )

                self.internal_bus.send(RaisedSuspicion(inst_id=0, ex=ex))

            self.bls_replica = create_bls_bft_replica(
                name, own_kp[0], pool_keys,
                pool_state_root_provider=pool_root,
                suspicion_sink=bls_suspicion)

        self.ordering = OrderingService(
            data=self.data, timer=timer, bus=self.internal_bus,
            network=self.external_bus, stasher=self.stasher3pc,
            executor=self.executor, requests=self.requests_view,
            config=config, vote_plane=self.vote_plane,
            shadow_check=shadow_check, bls=self.bls_replica,
            trace=self.trace)
        self.checkpoints = CheckpointService(
            data=self.data, bus=self.internal_bus,
            network=self.external_bus, stasher=self.stasher3pc,
            config=config,
            vote_plane=self.vote_plane, shadow_check=shadow_check,
            barrier=barrier, lane=lane)
        self.view_changer = ViewChangeService(
            data=self.data, timer=timer, bus=self.internal_bus,
            network=self.external_bus, stasher=self.stasher,
            checkpoint_values_provider=self.checkpoints.own_checkpoint_values,
            config=config)
        self.vc_trigger = ViewChangeTriggerService(
            data=self.data, timer=timer, bus=self.internal_bus,
            network=self.external_bus, stasher=self.stasher, config=config)
        self.primary_monitor = PrimaryConnectionMonitorService(
            data=self.data, timer=timer, bus=self.internal_bus,
            network=self.external_bus, config=config)
        self.message_req = MessageReqService(
            data=self.data, bus=self.internal_bus,
            network=self.external_bus, ordering_service=self.ordering,
            view_change_service=self.view_changer)

        # state-proof plane: per stabilized checkpoint window, capture
        # the pool's BLS multi-sig over the committed roots (already
        # aggregated by consensus) so proved reads attach it for free —
        # rides the same CheckpointStabilized hook as LedgerBacking
        self.proof_cache = None
        if self.boot is not None and self.bls_replica is not None \
                and config.StateProofCacheWindows > 0:
            from ..proofs import CheckpointProofCache

            self.proof_cache = CheckpointProofCache.for_domain(
                self.boot.db, self.bls_replica, bus=self.internal_bus,
                keep=config.StateProofCacheWindows,
                clock=timer.get_current_time,
                metrics=metrics, trace=self.trace, node=name)

        # catchup plane (requires real ledgers): every node seeds; the
        # leecher consumes NeedMasterCatchup from the checkpoint service
        self.seeder = None
        self.leecher = None
        if self.boot is not None:
            from ..server.catchup import NodeLeecherService, SeederService

            self.seeder = SeederService(
                self.external_bus, self.boot.db, own_name=name,
                timer=timer, config=config, metrics=metrics)

            def catchup_suspicion(ex):
                from ..common.messages.internal_messages import (
                    RaisedSuspicion,
                )

                self.internal_bus.send(RaisedSuspicion(inst_id=0, ex=ex))

            self.leecher = NodeLeecherService(
                data=self.data, bus=self.internal_bus,
                network=self.external_bus, timer=timer, bootstrap=self.boot,
                config=config, suspicion_sink=catchup_suspicion,
                metrics=metrics, trace=self.trace)

        # execution: commit batches as they order (the Node's job);
        # re-ordered duplicates after a view change are skipped by seqNo
        self.ordered_log: List[Ordered] = []
        self.executed_upto = 0
        self.internal_bus.subscribe(Ordered, self._on_ordered)
        from ..common.messages.internal_messages import CatchupFinished

        self.internal_bus.subscribe(CatchupFinished, self._on_catchup_finished)
        self.ordering.start()

    def _on_ordered(self, ordered: Ordered, *args) -> None:
        self.requests_view.mark_ordered(ordered.reqIdr)
        if ordered.ppSeqNo <= self.executed_upto:
            return  # already executed (re-ordered after view change)
        self.executed_upto = ordered.ppSeqNo
        self.ordered_log.append(ordered)
        staged = self.executor.commit_batch(ordered.ppSeqNo)
        if self.trace.enabled:
            self.trace.record(
                "3pc.executed", node=self.name,
                key=(ordered.viewNo, ordered.ppSeqNo, ordered.digest))
            if staged is not None and self.boot is not None:
                # executed -> durable-state-root hop (STATE_PHASE join)
                state = self.boot.db.get_state(staged.ledger_id)
                self.trace.record(
                    "state.commit", cat="state", node=self.name,
                    key=(ordered.viewNo, ordered.ppSeqNo),
                    args={"ledger": staged.ledger_id,
                          "hashes": state.hashes_total
                          if state is not None else 0})

    def _on_catchup_finished(self, msg, *args) -> None:
        # batches at/below the caught-up point were executed THROUGH the
        # ledger fetch, not through Ordered
        self.executed_upto = max(self.executed_upto,
                                 msg.last_caught_up_3pc[1])

    def read_nym_with_proof(self, did: str):
        """Proved read from THIS node alone (requires real_execution+bls):
        value + SMT inclusion proof + the pool's multi-sig over the root."""
        from ..client.state_proof import StateProofReply
        from ..common.constants import DOMAIN_LEDGER_ID
        from ..utils.base58 import b58encode

        state = self.boot.db.get_state(DOMAIN_LEDGER_ID)
        root = state.committed_head_hash
        key = did.encode()
        value = state.get(key, is_committed=True)
        proof = state.generate_state_proof(key, root=root, serialize=True)
        ms = None
        if self.bls_replica is not None:
            found = self.bls_replica.store.get(b58encode(root))
            ms = found.as_dict() if found else None
        return StateProofReply(key=key, value=value, root=root,
                               proof=proof, multi_sig_dict=ms)

    @property
    def ordered_digests(self) -> List[str]:
        out = []
        for o in self.ordered_log:
            out.extend(o.reqIdr)
        return out

    @property
    def committed_request_digests(self) -> List[str]:
        """The committed domain ledger's request-digest sequence — the
        ordering fingerprint that COVERS catchup: a node that leeched a
        range never saw its ``Ordered`` events, but the fetched txns
        carry the original request digests in their metadata, so the
        ledger sequence is bit-comparable across survivors and
        freshly-caught-up nodes. Requires real execution."""
        from ..common.constants import DOMAIN_LEDGER_ID
        from ..common.txn_util import get_digest

        ledger = self.boot.db.get_ledger(DOMAIN_LEDGER_ID)
        return [get_digest(ledger.get_by_seq_no(s)) or ""
                for s in range(1, ledger.size + 1)]


class _TelemetryTap:
    """The telemetry plane's deterministic consensus tap: per-node
    executed-txn tallies (mirroring :meth:`SimNode._on_ordered`'s
    re-order dedupe so the count means *executed*, not delivered), e2e
    latency samples (virtual pre-prepare -> executed seconds), and the
    window pulses that roll rollup boundaries — all driven by internal
    bus events at virtual instants, so every series replays
    byte-identically per seed."""

    def __init__(self, plane, clock):
        self.plane = plane
        self.clock = clock
        self.txns: Dict[str, int] = {}
        self._upto: Dict[str, int] = {}

    def attach(self, node) -> None:
        from ..common.messages.internal_messages import CheckpointStabilized

        self.txns[node.name] = 0
        self._upto[node.name] = 0
        node.internal_bus.subscribe(
            Ordered,
            lambda o, *a, _n=node.name: self._on_ordered(_n, o))
        node.internal_bus.subscribe(CheckpointStabilized,
                                    self._on_stabilized)

    def _on_ordered(self, name: str, ordered) -> None:
        if ordered.ppSeqNo <= self._upto[name]:
            return  # re-ordered after view change; already executed
        self._upto[name] = ordered.ppSeqNo
        self.txns[name] += len(ordered.reqIdr)
        now = self.clock()
        self.plane.observe_latency(now - ordered.ppTime)
        self.plane.pulse(now)

    def _on_stabilized(self, msg, *args) -> None:
        if msg.inst_id != 0:
            return  # master instance only, like the proof cache
        self.plane.pulse(self.clock())

    def ordered_txns(self) -> int:
        """Pool progress = the max per-node tally: a crashed node's
        stalled counter (its gap arrives via catchup, not Ordered) must
        not read as pool throughput loss."""
        return max(self.txns.values()) if self.txns else 0


class SimPool:
    def __init__(self, n_nodes: int = 4, seed: int = 0,
                 config: Optional[Config] = None,
                 device_quorum: bool = False,
                 real_execution: bool = False,
                 sign_requests: bool = False,
                 bls: bool = False,
                 shadow_check: Optional[bool] = None,
                 num_instances: int = 1,
                 mesh=None,
                 host_accounting: bool = False,
                 pipelined_flush: bool = True,
                 host_eval: bool = False,
                 spy: bool = False,
                 trace: bool = False,
                 trace_capacity: Optional[int] = None,
                 timer: Optional[MockTimer] = None,
                 metrics: Optional[MetricsCollector] = None,
                 trace_recorder=None,
                 drive_ticks: bool = True,
                 barrier=None,
                 lane: int = 0):
        # injection seams (ordering lanes, lanes/pool.py): a LanedPool
        # composes K SimPools as lanes on ONE shared timer / metrics
        # collector / flight-recorder ring (each lane recording through
        # its LaneTraceView), with the cross-lane checkpoint barrier
        # threaded into every lane's CheckpointService and the pool-level
        # tick replaced by the multi-lane driver (drive_ticks=False).
        self.config = config or getConfig(
            {"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 10})
        self.seed = seed
        self.timer = timer if timer is not None \
            else MockTimer(start_time=1_700_000_000.0)
        self.metrics = metrics if metrics is not None else MetricsCollector()
        self.lane = lane
        # consensus flight recorder: one pool-shared ring on the VIRTUAL
        # clock, so a seeded run (chaos and mesh runs included) dumps a
        # bit-identical trace — checkable like ordered_hash()
        from ..observability.trace import NULL_TRACE, TraceRecorder

        if trace_recorder is not None:
            self.trace = trace_recorder
        else:
            self.trace = (TraceRecorder(
                self.timer.get_current_time,
                capacity=trace_capacity
                or self.config.TraceRecorderCapacity)
                if trace else NULL_TRACE)
        # geo plane (RegionCount > 0): node i lives in region i % R and
        # cross-region deliveries draw from the seeded WAN pair band.
        # Strictly opt-in — RegionCount=0 builds the exact pre-geo
        # network (no matrix, same rng sequence, same fingerprints).
        self.regions: Dict[str, int] = {}
        self.region_matrix = None
        if self.config.RegionCount > 0:
            from .sim_network import RegionLatencyMatrix

            self.regions = {f"node{i}": i % self.config.RegionCount
                            for i in range(n_nodes)}
            self.region_matrix = RegionLatencyMatrix(
                self.config.RegionCount,
                self.config.RegionLatencySeed or seed,
                intra_band=(0.01, 0.05),
                wan_band=(self.config.RegionWanMinLatency,
                          self.config.RegionWanMaxLatency))
        # causal tracing plane: the network stamps net.send/net.recv
        # marks on the same recorder, so cross-node journeys carry
        # measured (delayer-inclusive) per-hop network latency
        self.network = SimNetwork(
            self.timer, seed=seed, metrics=self.metrics,
            trace=self.trace,
            trace_receivers=self.config.TraceNetReceivers,
            regions=self.regions or None,
            region_matrix=self.region_matrix)
        self.validators = [f"node{i}" for i in range(n_nodes)]
        # RBFT: f+1 parallel protocol instances (0 = auto f+1); backup
        # instances get their own finalised-request queue per (node, inst)
        if num_instances <= 0:
            num_instances = self.config.replicas_count(n_nodes)
        self.num_instances = num_instances
        self.requests = SimRequestsPool()
        for name in self.validators:
            self.requests.register_node(name)
            for inst in range(1, num_instances):
                self.requests.register_node(f"{name}#{inst}")

        self.real_execution = real_execution
        self.sign_requests = sign_requests
        self.trustee = None
        self.authnr = None
        domain_genesis = None
        if real_execution or sign_requests:
            from ..common.constants import TRUSTEE
            from ..crypto.signers import DidSigner
            from ..ledger.genesis import genesis_nym_txn

            self.trustee = DidSigner(b"\x09" * 32)
            domain_genesis = [genesis_nym_txn(
                self.trustee.identifier, self.trustee.verkey, role=TRUSTEE)]
        if sign_requests:
            from ..server.client_authn import CoreAuthNr

            # the ingress gate: genesis identities via seed_keys (node-state
            # backed resolution arrives with the Node composition)
            self.authnr = CoreAuthNr(seed_keys={
                self.trustee.identifier: self.trustee.verkey})
        self._ingress: List[Request] = []
        # admission control (ingress plane): a bounded auth queue with
        # the deterministic shed policy replaces the unbounded _ingress
        # list. The controller's tiebreak is seeded with the POOL seed,
        # so a seeded saturation run replays to the byte-identical shed
        # set (admission.shed_hash(), checkable like ordered_hash).
        self.admission = None
        if sign_requests and self.config.IngressQueueCapacity > 0:
            from ..ingress.admission import AdmissionController

            self.admission = AdmissionController(
                capacity=self.config.IngressQueueCapacity,
                per_client_cap=self.config.IngressPerClientCap,
                seed=seed, clock=self.timer.get_current_time)
        # closed-loop retry (overload robustness plane): shed requests
        # come BACK on a seeded backoff — the drain hands each tick's
        # sheds to the driver, the driver re-offers them through the
        # same admission path (fairness cap and shed cohort included).
        # Seeded with the POOL seed like the shed tiebreak, so the
        # retry storm replays byte-identically (retry_hash).
        self.retry = None
        if self.admission is not None and self.config.IngressRetryMax > 0:
            from ..ingress.retry import RetryDriver, RetryPolicy

            self.retry = RetryDriver(
                RetryPolicy.from_config(self.config, seed=seed),
                self.timer, self._retry_offer,
                metrics=self.metrics, trace=self.trace)

        self.bls_keys = None
        if bls:
            from ..bls.factory import generate_bls_keys

            self.bls_keys = {
                name: generate_bls_keys(
                    hashlib.sha256(b"sim-bls-" + name.encode()).digest())
                for name in self.validators}

        # all nodes share ONE stacked device plane (member axis vmapped):
        # votes for the whole pool ride a single dispatch per flush
        from .quorum_driver import drive_group_ticks, make_vote_group

        self.vote_group = None
        if device_quorum:
            # the group shares the pool's collector so the dispatch-plane
            # numbers (device.flush / dispatches_per_tick / occupancy)
            # land where bench and chaos reports already look
            self.vote_group = make_vote_group(
                n_nodes, self.validators, self.config,
                num_instances=num_instances, mesh=mesh,
                pipelined=pipelined_flush, metrics=self.metrics,
                host_eval=host_eval)
            self.vote_group.trace = self.trace

        k = num_instances
        self.nodes: List[SimNode] = [
            SimNode(name, self.validators, self.timer, self.network,
                    self.requests, self.config, device_quorum=device_quorum,
                    domain_genesis=domain_genesis if real_execution else None,
                    bls_keys=self.bls_keys, shadow_check=shadow_check,
                    vote_plane=(self.vote_group.view(i * k)
                                if self.vote_group else None),
                    trace=self.trace, metrics=self.metrics,
                    barrier=barrier, lane=lane)
            for i, name in enumerate(self.validators)]
        self.network.connect_all()

        # backup instances (RBFT): each node i runs instances 1..k-1 over
        # the shared external bus; device mode puts them on the group's
        # (node x instance) member axis, same vmapped dispatch as masters
        if k > 1:
            import types

            from ..server.consensus.primary_selector import (
                RoundRobinConstantNodesPrimariesSelector as _Sel,
            )
            from ..server.replicas import BackupReplica

            primaries_k = _Sel(self.validators).select_primaries(0, k)
            tick_mode = self.config.QuorumTickInterval > 0
            for i, node in enumerate(self.nodes):
                node.data.primaries = list(primaries_k)
                backups = []
                for inst in range(1, k):
                    plane = None
                    if self.vote_group is not None:
                        plane = self.vote_group.view(i * k + inst)
                        plane.defer_flush_on_query = tick_mode
                    replica = BackupReplica(
                        node.name, self.validators, inst, 0, primaries_k,
                        self.timer, node.external_bus, self.config,
                        requests_pool=self.requests.view_for(
                            f"{node.name}#{inst}"),
                        on_ordered=lambda o: None,
                        vote_plane=plane,
                        demux=node.demux)
                    replica.start()
                    backups.append(replica)
                # the shape quorum_driver's tick expects (Node.replicas)
                node.replicas = types.SimpleNamespace(backups=backups)

        # per-host CPU accounting: the simulation runs all n validators'
        # host loops serially in ONE process, so wall-clock understates a
        # deployed pool by ~n. With accounting on, each node's OWN work
        # (its inbound message handling including the sends it triggers,
        # its per-instance tick evaluation, and the FULL shared device
        # flush — conservative: a real node flushes only its own
        # num_instances-member plane) accumulates in host_seconds[name];
        # the busiest node bounds a deployed pool's throughput.
        # spy instrumentation (reference: plenum/test/testable.py): every
        # node's routers record (msg, sender, verdict, sim-time) — tests
        # can assert exact processing counts, not just end states. Query
        # via pool.spy_of(name, inst_id).
        self._spies: Dict[tuple, object] = {}
        if spy:
            from ..common.stashing_router import RouterSpy

            clock = self.timer.get_current_time
            for nd in self.nodes:
                for st, key in ((nd.stasher3pc, (nd.name, 0, "3pc")),
                                (nd.stasher, (nd.name, 0, "other"))):
                    st.spy = RouterSpy(clock=clock)
                    self._spies[key] = st.spy
                replicas = getattr(nd, "replicas", None)
                for backup in (replicas.backups if replicas else ()):
                    backup.stasher.spy = RouterSpy(clock=clock)
                    self._spies[(nd.name, backup.inst_id, "3pc")] = \
                        backup.stasher.spy

        self.host_seconds: Optional[Dict[str, float]] = None
        if host_accounting:
            self.host_seconds = {n.name: 0.0 for n in self.nodes}
            for nd in self.nodes:
                self._install_accounting(nd)

        # tick-batched quorum mode: ONE group flush per tick serves the
        # whole pool; services evaluate against that snapshot and votes
        # recorded during the wave buffer for the next tick. Signed
        # ingress rides the same tick: requests submitted during the
        # interval get ONE device batch verify at tick start.
        self._last_ingress_depth = 0
        self._last_ingress_shed = 0
        # drive_ticks=False: a composing driver (the multi-lane tick in
        # quorum_driver.drive_lane_ticks) owns the pool-level tick
        self._quorum_tick_timer = drive_group_ticks(
            self.timer, self.config, self.vote_group, self.nodes,
            accounting=self.host_seconds,
            ingress=(self._ingress_tick if self.authnr is not None
                     else None),
            trace=self.trace) if drive_ticks else None
        # adaptive tick mode: the governor's interval trajectory is a
        # first-class observable (bench digests, determinism tests)
        self.governor = getattr(self._quorum_tick_timer, "governor", None)
        # occupancy-driven rebalance policy (None unless sharded + armed)
        self.rebalance = getattr(self._quorum_tick_timer, "rebalance", None)
        # long-horizon telemetry plane (observability/telemetry.py):
        # TelemetryWindowSec > 0 registers every bounded structure in ONE
        # resource ledger and rolls windowed series off deterministic
        # consensus pulses; unarmed pools pay nothing (no ledger, no bus
        # subscribers). Pools that delegate their tick (drive_ticks=False,
        # the multi-lane composition) leave arming to the composer.
        self.resource_ledger = None
        self.telemetry = None
        self._telemetry_tap = None
        self._read_backing_seq = 0
        if drive_ticks and self.config.TelemetryWindowSec > 0:
            self._arm_telemetry()

    def _install_accounting(self, node: "SimNode") -> None:
        import time as _time

        acct = self.host_seconds
        name = node.name
        inflight = [False]  # MessageRep re-injection nests process_incoming

        def timed_call(inner):
            def wrapper(*args, **kwargs):
                if inflight[0]:
                    return inner(*args, **kwargs)
                inflight[0] = True
                # da: allow[nondet-source] -- per-node host-CPU accounting for profile_rbft; protocol time rides MockTimer, acct never feeds consensus
                t0 = _time.perf_counter()
                try:
                    return inner(*args, **kwargs)
                finally:
                    inflight[0] = False
                    # da: allow[nondet-source] -- accounting close (see t0 above)
                    acct[name] += _time.perf_counter() - t0
            return wrapper

        bus = node.external_bus
        bus.process_incoming = timed_call(bus.process_incoming)
        # timer-driven work is real host cost too: the primary's batch
        # build + PRE-PREPARE broadcast runs off the batch timer, not off
        # any inbound message (_on_batch_timer resolves send_3pc_batch on
        # self at CALL time, so instance-attribute wrapping takes effect)
        node.ordering.send_3pc_batch = timed_call(node.ordering.send_3pc_batch)
        replicas = getattr(node, "replicas", None)
        for backup in (replicas.backups if replicas else ()):
            backup.ordering.send_3pc_batch = timed_call(
                backup.ordering.send_3pc_batch)

    def node(self, name: str) -> SimNode:
        return next(n for n in self.nodes if n.name == name)

    def spy_of(self, name: str, inst_id: int = 0, router: str = "3pc"):
        """The RouterSpy for ``name``'s instance router (pool built with
        spy=True); ``router``: "3pc" (ordering/checkpoint traffic) or
        "other" (view change / instance change / message req)."""
        return self._spies[(name, inst_id, router)]

    @property
    def primary(self) -> SimNode:
        return self.node(self.nodes[0].data.primaries[0])

    def build_request(self, seq: int) -> Request:
        """Construct (but do not submit) the pool's standard request for
        ``seq`` — the seam the lane router needs: a LanedPool builds the
        request first, routes it by its key, THEN submits it to the
        owning lane (``submit_built``)."""
        if self.real_execution:
            from ..common.constants import NYM, TARGET_NYM, TXN_TYPE, VERKEY
            from ..crypto.signers import DidSigner

            target = DidSigner(hashlib.sha256(
                b"sim-target-%d" % seq).digest())
            req = Request(
                identifier=self.trustee.identifier, reqId=seq,
                operation={TXN_TYPE: NYM, TARGET_NYM: target.identifier,
                           VERKEY: target.verkey})
            req.target_signer = target  # test convenience
        else:
            req = Request(identifier="client1", reqId=seq,
                          operation={"type": "1", "v": seq})
        return req

    def submit_request(self, seq: int,
                       client_id: Optional[str] = None,
                       region: Optional[int] = None) -> Request:
        # client_id: the ingress plane's virtual-client identity — the
        # admission controller's per-client fairness cap keys on it
        # (None = anonymous, outside any cap)
        return self.submit_built(self.build_request(seq), client_id,
                                 region=region)

    def submit_built(self, req: Request,
                     client_id: Optional[str] = None,
                     region: Optional[int] = None) -> Request:
        if self.trace.enabled:
            # geo plane: the submitting client's home region rides the
            # ingress mark into the journey table (None = unstamped —
            # single-region dumps keep their exact bytes)
            self.trace.record(
                "req.ingress", cat="req", key=(req.digest,),
                args={"region": region} if region is not None else None)
        if self.sign_requests:
            self.trustee.sign_request(req)
            if self.admission is not None:
                self.admission.offer(req, client_id)
            else:
                self._ingress.append(req)
        else:
            self.requests.add_finalised(req)
            if self.trace.enabled:
                self.trace.record("req.finalised", cat="req",
                                  key=(req.digest,))
        return req

    def _retry_offer(self, req: Request,
                     client_id: Optional[str] = None) -> None:
        """The retry driver's re-offer seam: the SAME request (already
        signed, ``req.ingress`` already marked at first arrival)
        re-enters the bounded queue like any arrival — it competes in
        the same-instant shed cohort and counts against its client's
        fairness cap (no retry-based cap evasion)."""
        self.admission.offer(req, client_id)

    def submit_tampered_request(self, seq: int) -> Request:
        """Signed, then payload mutated: the device verify must reject it."""
        assert self.sign_requests
        req = self.submit_request(seq)
        req.operation["evil"] = True  # signature no longer covers payload
        return req

    def flush_ingress(self):
        """The node-ingress pipeline stand-in: device-batch-verify pending
        signed requests; only verified ones become finalised. Returns the
        verdict vector (test observability). In tick-batched mode the
        dispatch-plane tick calls this automatically, so every request
        submitted during the interval rides ONE Ed25519 device dispatch.

        With admission control on, the drain also settles the tick's shed
        accounting: shed requests land under the DEDICATED ``req.shed``
        trace event and ``ingress.shed`` metric — never under the
        ``AUTH_BATCH_*`` hot-path stats, which measure only work the
        device actually verified."""
        from ..common.metrics_collector import MetricsName

        trace_on = self.trace.enabled
        if self.admission is not None:
            self._last_ingress_depth = self.admission.depth
            batch, shed = self.admission.drain()
            self._last_ingress_shed = len(shed)
            self.metrics.add_event(MetricsName.INGRESS_QUEUE_DEPTH,
                                   self._last_ingress_depth)
            if batch:
                self.metrics.add_event(MetricsName.INGRESS_ADMITTED,
                                       len(batch))
            if trace_on:
                # journey hop boundary: admission wait ends (and the
                # auth device batch begins) at the tick's drain instant
                for req in batch:
                    self.trace.record("req.admitted", cat="req",
                                      key=(req.digest,))
            if self.retry is not None and batch:
                # the goodput split: admitted work that needed >= 1
                # retry vs first-attempt admissions
                readmitted = sum(
                    1 for req in batch
                    if req.digest in self.retry.retried_digests)
                if readmitted:
                    self.metrics.add_event(
                        MetricsName.INGRESS_RETRY_ADMITTED, readmitted)
            if shed:
                self.metrics.add_event(MetricsName.INGRESS_SHED,
                                       len(shed))
                if trace_on:
                    for req, _cid, reason in shed:
                        self.trace.record("req.shed", cat="req",
                                          key=(req.digest,),
                                          args={"reason": reason})
                if self.retry is not None:
                    # the closed loop: this tick's sheds schedule their
                    # seeded-backoff re-offers on the virtual timer
                    for req, cid, reason in shed:
                        self.retry.on_shed(req, cid, reason)
        else:
            batch, self._ingress = self._ingress, []
        if not batch:
            return []
        self.metrics.add_event(MetricsName.AUTH_BATCH_SIZE, len(batch))
        with self.metrics.measure_time(MetricsName.AUTH_BATCH_TIME):
            verdicts = self.authnr.authenticate_batch(batch)
        if trace_on:
            self.trace.record("tick.auth", cat="dispatch",
                              args={"batch": len(batch),
                                    "ok": int(sum(bool(v)
                                                  for v in verdicts))})
        for req, ok in zip(batch, verdicts):
            if ok:
                self.requests.add_finalised(req)
                if trace_on:
                    self.trace.record("req.finalised", cat="req",
                                      key=(req.digest,))
        return list(verdicts)

    def _ingress_tick(self):
        """The dispatch tick's ingress drain. With admission control on,
        returns the tick's :class:`~indy_plenum_tpu.ingress.admission
        .BackpressureSignal` (pre-drain queue depth, sheds, leeching) —
        the quorum driver hands it to the dispatch governor, closing the
        PR 3 "widen while leeching" loop. Without admission this is just
        ``flush_ingress``."""
        self.flush_ingress()
        if self.admission is None:
            return None
        from ..ingress.admission import BackpressureSignal

        return BackpressureSignal(
            queue_depth=self._last_ingress_depth,
            capacity=self.admission.capacity,
            shed_delta=self._last_ingress_shed,
            leeching=any(not nd.data.is_participating
                         for nd in self.nodes),
            # re-offers still waiting on the timer: load the pool owes
            # itself — holds the governor's narrow between shed bursts
            retry_pressure=(self.retry.outstanding
                            if self.retry is not None else 0))

    def _arm_telemetry(self) -> None:
        """Build the resource ledger + telemetry plane and register every
        bounded structure the pool composed: trace rings, metrics
        histograms, admission queue, retry cohort, per-node proof caches,
        SMT node caches / dirty overlays, staged write batches and
        request queues. Series: ordered txns (the tap's max-node tally),
        shed/retry counters, governor occupancy EWMA."""
        from ..observability.telemetry import (
            ResourceLedger,
            SizedResource,
            TelemetryPlane,
        )

        ledger = ResourceLedger()
        plane = TelemetryPlane.from_config(
            self.config, ledger, t0=self.timer.get_current_time(),
            metrics=self.metrics, trace=self.trace)
        self.resource_ledger = ledger
        self.telemetry = plane
        if self.trace.enabled:
            ledger.register_all(self.trace.sized_resources())
        ledger.register_all(self.metrics.sized_resources())
        if self.admission is not None:
            ledger.register_all(self.admission.sized_resources())
        if self.retry is not None:
            ledger.register_all(self.retry.sized_resources())
        for nd in self.nodes:
            p = nd.name + "."
            ledger.register(SizedResource(
                p + "requests_queue",
                (lambda _q=self.requests._queues, _n=nd.name:
                 len(_q.get(_n, ()))),
                bound=None, entry_bytes=64))
            if nd.proof_cache is not None:
                ledger.register_all(
                    nd.proof_cache.sized_resources(p + "proof_cache."))
            if nd.boot is not None:
                state = nd.boot.db.get_state(DOMAIN_LEDGER_ID)
                if state is not None and hasattr(state, "sized_resources"):
                    ledger.register_all(
                        state.sized_resources(p + "state."))
                wm = nd.boot.write_manager
                if hasattr(wm, "_staged"):
                    ledger.register(SizedResource(
                        p + "staged_batches",
                        (lambda _w=wm: len(_w._staged)),
                        bound=None, entry_bytes=256))
        tap = _TelemetryTap(plane, self.timer.get_current_time)
        for nd in self.nodes:
            tap.attach(nd)
        self._telemetry_tap = tap
        plane.add_counter("ordered", tap.ordered_txns)
        plane.add_counter(
            "shed", lambda: (self.admission.shed_total
                             if self.admission is not None else 0))
        plane.add_counter(
            "retry", lambda: (self.retry.reoffers_total
                              if self.retry is not None else 0))
        plane.add_gauge(
            "occupancy_ewma",
            lambda: (float(self.governor.ewma)
                     if self.governor is not None else 0.0))

    def make_read_service(self, name: str = "node0", mode: str = "host",
                          capacity: int = 0,
                          region: Optional[int] = None):
        """A proof-serving :class:`~indy_plenum_tpu.ingress.read_service
        .ReadService` over ``name``'s committed domain ledger (requires
        real_execution): the backing rides the node's checkpoint-
        stabilized hook and, when the node runs the state-proof plane,
        replies carry the pool's window multi-signature. ``capacity``
        bounds the read queue (seeded with the POOL seed, like the write
        side); ``region`` (default: the serving node's pool region, when
        the geo plane is armed) tags the read-journey marks so causal
        summaries segregate read e2e per region."""
        from ..ingress.read_service import LedgerBacking, ReadService

        node = self.node(name)
        assert node.boot is not None, "make_read_service needs real ledgers"
        if region is None:
            region = self.regions.get(name)
        backing = LedgerBacking(
            node.boot.db.get_ledger(DOMAIN_LEDGER_ID),
            bus=node.internal_bus)
        if self.resource_ledger is not None:
            # telemetry armed: late-built read backings join the ledger
            # too (ordinal-prefixed — a bench may build several per node)
            self._read_backing_seq += 1
            self.resource_ledger.register_all(backing.sized_resources(
                f"{name}.read_backing{self._read_backing_seq}."))
        return ReadService(
            backing, clock=self.timer.get_current_time,
            metrics=self.metrics, trace=self.trace, mode=mode,
            proof_cache=node.proof_cache, capacity=capacity,
            seed=self.config.IngressShedSeed or self.seed, name=name,
            region=region)

    def run_for(self, seconds: float) -> None:
        self.timer.advance(seconds)

    def honest_nodes_agree(self) -> bool:
        logs = [tuple(n.ordered_digests) for n in self.nodes]
        lengths = {len(l) for l in logs}
        shortest = min(lengths)
        return all(l[:shortest] == logs[0][:shortest] for l in logs)

    def ordered_hash(self) -> str:
        """sha256 of node0's ordered-digest sequence — THE pool-ordering
        fingerprint (callers assert honest_nodes_agree first, so one
        node identifies the pool). bench.py's sharded sub-bench and
        check_dispatch_budget's sharded gate compare runs on it."""
        return hashlib.sha256(
            "|".join(self.nodes[0].ordered_digests).encode()).hexdigest()

    def ledger_hash(self, name: str) -> str:
        """sha256 of ``name``'s committed domain-ledger request-digest
        sequence (real execution only) — the per-node ordering
        fingerprint that stays comparable ACROSS CATCHUP: a node that
        leeched a GC'd range has the identical ledger sequence as the
        survivors even though its ``ordered_log`` skips the leeched
        middle. The catchup gate asserts bit-identity on this."""
        return hashlib.sha256("|".join(
            self.node(name).committed_request_digests).encode()).hexdigest()
