"""The virtual-day soak: 24 simulated hours of diurnal load on a real
pool, with ONE chaos arc folded in, judged by the telemetry plane.

Long-horizon health is the claim RBFT's monitoring plane exists to make
(Aublin et al., ICDCS 2013): a pool that survives a day of realistic
load without leaking memory, shedding throughput or creeping latency.
This harness makes that claim checkable in minutes of wall clock:
everything rides the MockTimer, so 24 hours are just events, and the
whole artifact — ordered hash, state head, hourly tallies, the
telemetry plane's rollup/anomaly hash chain — is byte-identical across
same-seed runs (the ``soak`` gate runs it twice and diffs fingerprints).

The day is ONE arc, not a scenario matrix:

- **load**: a diurnal deterministic arrival grid (below) over
  ``SoakKeys`` NYM targets, all keys pre-warmed so steady-state touches
  no new state;
- **hour 6** (``SoakCrashHour``): a node fail-stops for
  ``SoakCrashHours`` — long enough that its gap crosses checkpoint GC
  (CHK_FREQ/LOG_SIZE are small here), so rejoining REQUIRES a real
  ledger catchup, verified from the leecher meters;
- **hour 12** (``SoakViewChangeHour``): the master primary drops and
  the pool must elect view 1 and keep ordering; the old primary then
  rejoins;
- **tick ~``SoakRebalanceTick``**: the occupancy rebalancer's forced
  arm fires one shard rotation mid-day (device/mesh pools only — the
  leg records itself skipped on hosts without 4 XLA devices).

The drift law needs a subtlety: at soak rates a Poisson workload's
hour-to-hour count noise (~1/sqrt(N), several percent) would swamp the
<1% hour-1 -> hour-24 throughput-drift assertion. So the soak submits a
**deterministic arrival grid** — per 60s slice, ``rate * 60 *
multiplier(phase)`` arrivals with the fractional remainder carried
within the hour and reset at hour boundaries — making every hour's
offered load byte-identical at the same diurnal phase. Key/client picks
still come from the workload plane's seeded Zipf spaces. Whatever drift
the tally shows is then the SYSTEM's (backlog, batching shift), not the
generator's.

Anomaly accounting: the chaos arc legitimately trips drift/leak laws
(ordering stalls during the view change; queues spike during the
crash). Each fired anomaly is classified **explained** when its window
falls inside a chaos leg's influence range (leg start window - 1
through leg end window + drift lag + leak streak); ``bound_violation``
anomalies are NEVER explained. The gate requires zero unexplained
anomalies — and proves the law is live by re-running a short arm with a
deliberately registered leaking resource (``synthetic_leak=True``) and
asserting the leak law catches it.
"""
from __future__ import annotations

# da: allow-file[nondet-source] -- soak harness: wall_s is REPORTED next to the deterministic verdicts (fingerprint, telemetry_hash, tallies), never folded into them

import hashlib
import time
from typing import Callable, Dict, List, Optional, Tuple

SLICE_SEC = 60.0  # arrival-grid resolution; divides the window
VC_SLICE_SEC = 5.0  # finer drive while a view change converges
WARM_WRITE_SEC = 600.0  # all keys written once across this span
WARM_SETTLE_SEC = 600.0  # then the pool drains to steady state
WARM_SEC = WARM_WRITE_SEC + WARM_SETTLE_SEC


def _mesh_or_none():
    """A (4,)-fabric mesh when the host exposes >= 4 XLA devices (the
    gate sets XLA_FLAGS before import), else None — the soak then runs
    the event-driven arm and records the rebalance leg skipped."""
    try:
        import jax

        devices = jax.devices()
    except Exception:  # pragma: no cover - jax always importable here
        return None
    if len(devices) < 4:
        return None
    from ..tpu.quorum import make_fabric_mesh

    return make_fabric_mesh(devices[:4], (4,))


def _day_config(window_sec: float, hours: float, rebalance_tick: int,
                ticked: bool):
    from ..config import getConfig

    overrides = {
        "Max3PCBatchWait": 0.25,
        "Max3PCBatchSize": 100,
        # hourly diurnal cycle: hour 1 and hour 24 sit at the SAME
        # phase, so the <1% drift law compares like with like
        "WorkloadProfilePeriod": 3600.0,
        "WorkloadProfileTrough": 0.5,
        "WorkloadProfilePeak": 2.0,
        "TelemetryWindowSec": window_sec,
        "TelemetryWindowKeep": int((hours * 3600.0 + WARM_SEC)
                                   / window_sec) + 4,
        "TelemetryDriftLag": max(1, int(3600.0 / window_sec)),
        "TelemetryLeakWindows": 6,
        # grace ~2h: warm-phase cache fill and the trace ring reaching
        # capacity are growth by design, not leaks
        "TelemetryLeakGraceWindows": max(6, int(7200.0 / window_sec)),
        "TelemetryAnomalyKeep": 64,
        # small checkpoint window so the hour-long crash gap crosses GC
        # and the rejoin exercises REAL catchup (chaos-runner knobs)
        "CHK_FREQ": 5,
        "LOG_SIZE": 15,
        "ConsistencyProofsTimeout": 1.0,
        "CatchupRequestTimeout": 1.5,
        "CatchupMaxRetries": 8,
        "OrderingStallTimeout": 4.0,
    }
    if ticked:
        overrides.update({
            # FIXED ticks: a request on the device arm needs ~2-3 tick
            # rounds to quorum, so adaptive idle-widening would push
            # order latency past any sane stall timeout during the
            # day's quiet stretches (observed: a view change every ~8
            # virtual seconds, view_no in the thousands). The rebalance
            # leg doesn't need the governor — RebalanceForceTick plans
            # unconditionally at its tick ordinal.
            "QuorumTickInterval": 2.0,
            "QuorumTickAdaptive": False,
            "RebalanceForceTick": rebalance_tick,
            # stall watchdog above the ticked-quorum worst case (~3
            # rounds x 2s) but well under a slice, so post-chaos
            # recovery still fires between arrivals
            "OrderingStallTimeout": 15.0,
        })
    return getConfig(overrides)


def _writer(pool, n_keys: int, seed: int) -> Callable[[], None]:
    """One deterministic NYM write per call: Zipf key/client picks from
    the workload plane's seeded spaces (numpy RandomState, exactly the
    WorkloadGenerator idiom) over a lazily-built signer population."""
    import numpy as np

    from ..common.constants import NYM, TARGET_NYM, TXN_TYPE, VERKEY
    from ..common.request import Request
    from ..crypto.signers import DidSigner

    rng = np.random.RandomState(seed)
    signers: Dict[int, DidSigner] = {}
    seq = [0]

    def signer_for(key: int) -> DidSigner:
        signer = signers.get(key)
        if signer is None:
            signer = DidSigner(hashlib.sha256(b"soak-key-%d" % key).digest())
            signers[key] = signer
        return signer

    def write(key: Optional[int] = None) -> None:
        if key is None:
            key = int(rng.zipf(1.2) - 1) % n_keys
        client = int(rng.zipf(1.1) - 1) % 8
        signer = signer_for(key)
        seq[0] += 1
        req = Request(
            identifier=pool.trustee.identifier,
            reqId=1_000_000 + seq[0],
            operation={TXN_TYPE: NYM, TARGET_NYM: signer.identifier,
                       VERKEY: signer.verkey})
        pool.submit_built(req, client_id="c%d" % client)

    write.count = seq  # type: ignore[attr-defined]
    return write


def _day_soak_once(hours: float, rate: float, seed: int, n_keys: int,
                   crash_hour: float, crash_hours: float,
                   vc_hour: float, rebalance_tick: int,
                   window_sec: float = 600.0,
                   synthetic_leak: bool = False) -> Dict:
    from ..ingress.workload import WorkloadProfile
    from .pool import SimPool

    mesh = _mesh_or_none()
    config = _day_config(window_sec, hours, rebalance_tick,
                         ticked=mesh is not None)
    pool = SimPool(4, seed=seed, config=config, real_execution=True,
                   device_quorum=mesh is not None,
                   shadow_check=False if mesh is not None else None,
                   mesh=mesh, trace=True, trace_capacity=8192)
    profile = WorkloadProfile.from_config("diurnal", config)
    write = _writer(pool, n_keys, seed)
    t0 = pool.timer.get_current_time()

    leak_store: List[int] = []
    if synthetic_leak:
        # the non-vacuity arm: an unbounded structure growing one entry
        # per slice — the leak law MUST catch it within its streak
        from ..observability.telemetry import SizedResource

        pool.resource_ledger.register(SizedResource(
            "soak.synthetic_leak", lambda: len(leak_store)))

    # --- warm phase: every key written once, then a settle window ----
    per_slice = max(1, n_keys // int(WARM_WRITE_SEC / SLICE_SEC))
    next_key = 0
    t = 0.0
    while t < WARM_WRITE_SEC:
        for _ in range(per_slice):
            if next_key < n_keys:
                write(next_key)
                next_key += 1
        pool.run_for(SLICE_SEC)
        t += SLICE_SEC
    while next_key < n_keys:  # remainder lands in the settle window
        write(next_key)
        next_key += 1
    pool.run_for(WARM_SETTLE_SEC)

    # --- the day ------------------------------------------------------
    tap = pool._telemetry_tap
    crash_start = crash_hour * 3600.0
    crash_end = crash_start + crash_hours * 3600.0
    vc_start = vc_hour * 3600.0
    duration = hours * 3600.0
    victim = pool.nodes[-1].name
    crashed = False
    crash_done = crash_start >= duration
    old_primary: Optional[str] = None
    vc_pending = vc_start < duration
    vc_converged_t: Optional[float] = None
    vc_survivors: List = []
    rebalance_planned_t: Optional[float] = None
    hourly_ordered: List[int] = []
    prev_ordered = tap.ordered_txns()
    arrivals = 0
    acc = 0.0
    t = 0.0  # virtual seconds since the day began

    def vc_done() -> bool:
        return all(nd.data.view_no >= 1 and not nd.data.waiting_for_new_view
                   for nd in vc_survivors)

    while t < duration - 1e-9:
        if not crash_done and not crashed and t >= crash_start:
            pool.network.disconnect(victim)
            crashed = True
        if crashed and t >= crash_end:
            pool.network.reconnect(victim)
            crashed = False
            crash_done = True
        if vc_pending and t >= vc_start:
            old_primary = pool.nodes[0].data.primaries[0]
            pool.network.disconnect(old_primary)
            vc_survivors = [nd for nd in pool.nodes
                            if nd.name != old_primary]
            vc_pending = False
        in_vc = old_primary is not None and vc_converged_t is None
        # the arrival grid: per-slice count from the diurnal multiplier
        # at the slice midpoint; remainder carried within the hour and
        # reset at hour boundaries so every hour offers the IDENTICAL
        # byte sequence at the same phase
        step = VC_SLICE_SEC if in_vc else SLICE_SEC
        acc += rate * step * profile.multiplier((t + step / 2.0) % 3600.0)
        n = int(acc)
        acc -= n
        for _ in range(n):
            write()
        arrivals += n
        pool.run_for(step)
        t += step
        if in_vc and vc_done():
            vc_converged_t = t
            pool.network.reconnect(old_primary)
            # realign to the slice grid so hour boundaries keep landing
            # exactly (the VC fine-slices may have left t off-grid)
            rem = (-t) % SLICE_SEC
            if rem:
                pool.run_for(rem)
                t += rem
        if (pool.rebalance is not None and rebalance_planned_t is None
                and pool.rebalance.planned > 0):
            rebalance_planned_t = t
        if t % 3600.0 < step / 2.0 or t >= duration - 1e-9:
            if len(hourly_ordered) < int(t // 3600.0 + 0.5):
                ordered = tap.ordered_txns()
                hourly_ordered.append(ordered - prev_ordered)
                prev_ordered = ordered
                acc = 0.0
        if synthetic_leak:
            leak_store.append(len(leak_store))
    # settle: open-loop submission stops, stragglers (a node still
    # catching up after the chaos arc) get their stall timeouts
    pool.run_for(120.0)
    pool.telemetry.finalize(pool.timer.get_current_time())

    # --- verdicts -----------------------------------------------------
    from ..common.constants import DOMAIN_LEDGER_ID
    from .state_commit_bench import soak_high_water

    catchup = pool.node(victim).leecher.catchup_stats() \
        if crash_start < duration else None
    chaos = {
        "crash": None if crash_start >= duration else {
            "victim": victim,
            "hour": crash_hour,
            "rounds_completed": catchup["rounds_completed"],
            "txns_leeched": catchup["txns_leeched"],
            "ok": catchup["rounds_completed"] >= 1
            and catchup["txns_leeched"] > 0,
        },
        "view_change": None if vc_start >= duration else {
            "old_primary": old_primary,
            "hour": vc_hour,
            "converged_at_s": vc_converged_t,
            "view_no": max(nd.data.view_no for nd in pool.nodes),
            "ok": vc_converged_t is not None,
        },
        "rebalance": {
            "armed": pool.rebalance is not None,
            "planned": (pool.rebalance.planned
                        if pool.rebalance is not None else 0),
            "planned_at_s": rebalance_planned_t,
            "ok": (pool.rebalance.planned >= 1
                   if pool.rebalance is not None else None),
        },
    }

    # explained-anomaly classification: windows inside a chaos leg's
    # influence range (see module docstring); bound violations never
    wph = int(3600.0 / window_sec)
    lag = config.TelemetryDriftLag
    streak = config.TelemetryLeakWindows

    def w_of(day_t: float) -> int:
        return int((WARM_SEC + day_t) / window_sec)

    ranges: List[Tuple[int, int]] = []
    if crash_start < duration:
        ranges.append((w_of(crash_start) - 1,
                       w_of(min(crash_end, duration)) + lag + streak))
    if vc_start < duration:
        vc_end = vc_converged_t if vc_converged_t is not None else duration
        ranges.append((w_of(vc_start) - 1, w_of(vc_end) + lag + streak))
    if rebalance_planned_t is not None:
        ranges.append((w_of(rebalance_planned_t) - 1,
                       w_of(rebalance_planned_t) + lag + streak))
    unexplained = []
    for rec in pool.telemetry.anomalies:
        explained = rec["law"] != "bound_violation" and any(
            lo <= rec["window"] <= hi for lo, hi in ranges)
        if not explained:
            unexplained.append(dict(rec))

    # flatness: per-resource window high-water over the LAST ~30% of
    # post-hour-1 windows must not exceed the first ~70% (which contains
    # the whole chaos arc — its spikes raise the baseline, not the tail)
    rows = list(pool.telemetry.windows)
    post = [r for r in rows if r["window"] >= w_of(0.0) + wph]
    k = max(1, int(len(post) * 0.7))
    first_hw, last_hw, flat = soak_high_water(
        pool, per_hour=wph, first_rows=post[:k], last_rows=post[k:] or post,
        slack_frac=0.2)

    drift = (abs(hourly_ordered[-1] - hourly_ordered[0])
             / hourly_ordered[0]) if len(hourly_ordered) > 1 \
        and hourly_ordered[0] else 0.0
    state = pool.nodes[0].boot.db.get_state(DOMAIN_LEDGER_ID)
    # ledger-level agreement: catchup-recovered nodes have HOLES in
    # ordered_digests (leeched txns never ride Ordered), so the prefix
    # check is the wrong invariant for a chaos day — what must agree is
    # the committed artifact itself
    heads = set()
    for nd in pool.nodes:
        lg = nd.boot.db.get_ledger(DOMAIN_LEDGER_ID)
        st = nd.boot.db.get_state(DOMAIN_LEDGER_ID)
        heads.add((lg.size, lg.root_hash, st.committed_head_hash))
    agree = len(heads) == 1
    fingerprint = hashlib.sha256(repr((
        pool.ordered_hash(),
        state.committed_head_hash,
        hourly_ordered,
        pool.telemetry.telemetry_hash,
    )).encode()).hexdigest()
    return {
        "hours": hours,
        "rate": rate,
        "seed": seed,
        "n_keys": n_keys,
        "device_arm": mesh is not None,
        "arrivals": arrivals,
        "warm_writes": n_keys,
        "ordered_total": tap.ordered_txns(),
        "hourly_ordered": hourly_ordered,
        "throughput_drift": round(drift, 4),
        "first_high_water": first_hw,
        "last_high_water": last_hw,
        "flat_high_water": flat,
        "windows": pool.telemetry.completed,
        "anomalies": pool.telemetry.anomaly_count,
        "anomalies_unexplained": len(unexplained),
        "unexplained": unexplained,
        "bound_violations": pool.telemetry.snapshot()["bound_violations"],
        "chaos": chaos,
        "agree": agree,
        "telemetry_hash": pool.telemetry.telemetry_hash,
        "fingerprint": fingerprint,
    }


def run_day_soak(hours: Optional[float] = None,
                 rate: Optional[float] = None,
                 seed: int = 17,
                 n_keys: Optional[int] = None,
                 crash_hour: Optional[float] = None,
                 crash_hours: Optional[float] = None,
                 vc_hour: Optional[float] = None,
                 rebalance_tick: Optional[int] = None,
                 window_sec: float = 600.0,
                 repeats: int = 2,
                 synthetic_leak: bool = False) -> Dict:
    """The virtual-day soak, ``repeats`` times on one seed: the record
    everyone asserts on (``bench.py soak``, the ``soak`` gate). Defaults
    come from the ``Soak*`` config knobs; pass explicit (scaled-down)
    hours for test slices."""
    from ..config import getConfig

    base = getConfig()
    hours = base.SoakHours if hours is None else hours
    rate = base.SoakRate if rate is None else rate
    n_keys = base.SoakKeys if n_keys is None else n_keys
    crash_hour = base.SoakCrashHour if crash_hour is None else crash_hour
    crash_hours = base.SoakCrashHours if crash_hours is None \
        else crash_hours
    vc_hour = base.SoakViewChangeHour if vc_hour is None else vc_hour
    rebalance_tick = base.SoakRebalanceTick if rebalance_tick is None \
        else rebalance_tick
    t0 = time.perf_counter()
    runs = [_day_soak_once(hours, rate, seed, n_keys, crash_hour,
                           crash_hours, vc_hour, rebalance_tick,
                           window_sec=window_sec,
                           synthetic_leak=synthetic_leak)
            for _ in range(repeats)]
    rec = dict(runs[0])
    rec.update({
        "repeats": repeats,
        "deterministic": all(r["fingerprint"] == runs[0]["fingerprint"]
                             for r in runs),
        "wall_s": round(time.perf_counter() - t0, 1),
    })
    return rec
