"""State-commit plane measurement harness (bench.py `state` + state_gate).

Two entry points, both deterministic per seed:

- :func:`run_commit_arms` — the O(delta) claim at state scale: populate a
  100k-key SMT through :meth:`SparseMerkleState.apply_batch` itself, then
  drive identical per-window delta commits through three arms (sequential
  ``set()`` loop, batched host waves, batched ``mode='auto'`` waves),
  asserting the per-window roots bit-identical across arms and measuring
  hashes/commit + commits/sec per arm. The window workload is hot-key
  (90% of writes to a 32-key hot set, 10% uniform over the keyspace —
  the ingress plane's zipf-shaped write law): last-write-wins dedupe plus
  prefix sharing is where the batched walk's >=3x reduction comes from;
  on 256 DISTINCT uniform keys the tree shares almost nothing and the
  walk saves only the duplicated near-root levels (~3%).

- :func:`run_state_soak` — the long-horizon arm: a diurnal
  ``WorkloadProfile`` drives a real-execution SimPool on the virtual
  clock for a simulated multi-hour horizon, sampling every bounded
  structure's size along the way. Flat = the last simulated hour's
  high-water for each bounded structure does not exceed the first
  hour's, ordered-throughput drift first-vs-last hour stays under
  tolerance, and the whole run (roots, ordered hash, every sample) is
  byte-identical across two same-seed runs.
"""
from __future__ import annotations

import hashlib
import random
import time
from typing import Dict, List, Optional, Tuple

from ..state.sparse_merkle_state import SparseMerkleState
from ..storage.kv_store import KeyValueStorageInMemory

# da: allow-file[nondet-source] -- bench harness: wall-clock rates (commits/sec, populate seconds) are REPORTED alongside the deterministic meters (roots, hash counts), never inside them


def _key(i: int) -> bytes:
    return b"acct%08d" % i


def window_writes(n_keys: int, delta: int, windows: int, seed: int,
                  hot_keys: int = 32, hot_frac: float = 0.9,
                  ) -> List[List[Tuple[bytes, bytes]]]:
    """The per-window write sequences every arm replays verbatim."""
    rng = random.Random(seed)
    out = []
    for w in range(windows):
        writes = []
        for i in range(delta):
            if rng.random() < hot_frac:
                k = _key(rng.randrange(hot_keys))
            else:
                k = _key(rng.randrange(n_keys))
            writes.append((k, b"w%d:%d:%d" % (w, i, rng.randrange(1 << 30))))
        out.append(writes)
    return out


def populate_state(n_keys: int, chunk: int = 4096,
                   kv=None) -> Tuple[object, bytes, float]:
    """Build the base SMT through apply_batch itself (the tentpole at
    population scale); returns (kv, committed_root, seconds)."""
    kv = kv if kv is not None else KeyValueStorageInMemory()
    state = SparseMerkleState(kv=kv, commit_mode="host")
    t0 = time.perf_counter()
    for lo in range(0, n_keys, chunk):
        state.apply_batch(
            (_key(i), b"init%d" % i)
            for i in range(lo, min(lo + chunk, n_keys)))
        state.commit()
    return kv, state.committed_head_hash, time.perf_counter() - t0


def run_commit_arms(n_keys: int = 100_000, delta: int = 256,
                    windows: int = 20, seed: int = 7,
                    hot_keys: int = 32, hot_frac: float = 0.9,
                    arms: Tuple[str, ...] = ("sequential", "host", "auto"),
                    populate_chunk: int = 4096) -> Dict:
    """Identical per-window commits through each arm; per-window roots
    asserted bit-identical, hashes/commit + commits/sec per arm."""
    kv, base_root, populate_s = populate_state(n_keys, chunk=populate_chunk)
    workload = window_writes(n_keys, delta, windows, seed,
                             hot_keys=hot_keys, hot_frac=hot_frac)
    arm_records: Dict[str, Dict] = {}
    root_seqs: Dict[str, List[bytes]] = {}
    for arm in arms:
        mode = "host" if arm == "sequential" else arm
        state = SparseMerkleState(kv=kv, initial_root=base_root,
                                  commit_mode=mode)
        roots: List[bytes] = []
        h0 = state.hashes_total
        t0 = time.perf_counter()
        for writes in workload:
            if arm == "sequential":
                for k, v in writes:
                    state.set(k, v)
            else:
                state.apply_batch(writes)
            roots.append(state.head_hash)
            # content-addressed nodes: every arm commits the SAME tree,
            # so flushing into the shared kv is idempotent across arms
            # (the per-arm working root is what we compare)
            state.commit(roots[-1])
        elapsed = time.perf_counter() - t0
        hashes = state.hashes_total - h0
        arm_records[arm] = {
            "hashes_per_commit": hashes / windows,
            "commits_per_sec": windows / elapsed if elapsed else 0.0,
            "elapsed_s": round(elapsed, 3),
            "cache_hit_rate": round(state.cache_hit_rate(), 4),
            "wave_host_hashes": state.wave_host_hashes,
            "wave_device_hashes": state.wave_device_hashes,
        }
        root_seqs[arm] = roots
    ref = root_seqs[arms[0]]
    roots_identical = all(root_seqs[a] == ref for a in arms)
    assert roots_identical, "state-commit arms diverged on a window root"
    record = {
        "n_keys": n_keys,
        "delta": delta,
        "windows": windows,
        "seed": seed,
        "hot_keys": hot_keys,
        "hot_frac": hot_frac,
        "populate_s": round(populate_s, 2),
        "roots_identical": roots_identical,
        "final_root": ref[-1].hex(),
        "arms": arm_records,
    }
    if "sequential" in arm_records and "host" in arm_records:
        record["hash_reduction"] = round(
            arm_records["sequential"]["hashes_per_commit"]
            / arm_records["host"]["hashes_per_commit"], 2)
    return record


# ---------------------------------------------------------------------------
# virtual-time soak
# ---------------------------------------------------------------------------


def _soak_once(hours: float, rate: float, seed: int, n_keys: int,
               profile_kind: str, period: float,
               sample_every: float) -> Dict:
    from ..common.constants import (
        DOMAIN_LEDGER_ID,
        NYM,
        TARGET_NYM,
        TXN_TYPE,
        VERKEY,
    )
    from ..common.request import Request
    from ..config import getConfig
    from ..crypto.signers import DidSigner
    from ..ingress.workload import (
        WorkloadGenerator,
        WorkloadProfile,
        WorkloadSpec,
    )
    from .pool import SimPool

    config = getConfig({
        "Max3PCBatchWait": 0.25,
        "Max3PCBatchSize": 100,
        "WorkloadProfilePeriod": period,
        "WorkloadProfileTrough": 0.5,
        "WorkloadProfilePeak": 2.0,
        # high-water accounting reads the telemetry plane's resource
        # ledger — ONE accounting implementation (the PR 17 bench-local
        # structure tuples are gone); windows align to the sample grid
        "TelemetryWindowSec": sample_every,
        "TelemetryWindowKeep": int(hours * 3600.0 / sample_every) + 4,
        "TelemetryLeakGraceWindows": max(1, int(3600.0 / sample_every)),
        "TelemetryDriftLag": max(1, int(period / sample_every)),
    })
    pool = SimPool(4, seed=seed, config=config, real_execution=True)
    duration = hours * 3600.0
    spec = WorkloadSpec(
        n_clients=8, rate=rate, duration=duration,
        start=0.0, read_fraction=0.0,
        n_keys=n_keys, seed=seed,
        profile=WorkloadProfile.from_config(profile_kind, config))
    signers: Dict[int, DidSigner] = {}
    wl_seq = [0]

    def _write(client: int, key: int) -> None:
        signer = signers.get(key)
        if signer is None:
            signer = DidSigner(hashlib.sha256(b"soak-key-%d" % key).digest())
            signers[key] = signer
        wl_seq[0] += 1
        req = Request(
            identifier=pool.trustee.identifier,
            reqId=1_000_000 + wl_seq[0],
            operation={TXN_TYPE: NYM, TARGET_NYM: signer.identifier,
                       VERKEY: signer.verkey})
        pool.submit_built(req, client_id="c%d" % client)

    generator = WorkloadGenerator(spec)
    generator.start(pool.timer, _write)

    # per-structure accounting rides the telemetry plane's resource
    # ledger (observability/telemetry.py): every bounded structure the
    # pool composed registers at construction, the plane rolls a
    # high-water row per sample window off consensus pulses, and the
    # ordered tally is the tap's O(1) counter (the PR 17 version
    # re-scanned ordered_log per sample — O(n^2) over the horizon)
    tap = pool._telemetry_tap
    hourly_ordered: List[int] = []
    prev_ordered = 0
    t_base = pool.timer.get_current_time()
    steps = int(duration / sample_every)
    for step in range(1, steps + 1):
        pool.run_for(sample_every)
        sim_t = pool.timer.get_current_time() - t_base
        if sim_t % 3600.0 < sample_every / 2 or step == steps:
            if len(hourly_ordered) < int(sim_t // 3600.0 + 0.5):
                ordered = tap.ordered_txns()
                hourly_ordered.append(ordered - prev_ordered)
                prev_ordered = ordered
    pool.telemetry.finalize(pool.timer.get_current_time())
    node = pool.nodes[0]
    state = node.boot.db.get_state(DOMAIN_LEDGER_ID)
    # the dirty overlay is a quantized sawtooth: it accumulates one
    # trie-path's worth of nodes per executed batch and clears at the
    # state commit, so a window's peak is (batches straddled by the
    # longest commit interval) x (~nodes per batch). The baseline
    # interval straddles 3 batches; commit phase can deterministically
    # hand a tail window a 4th, so flatness tolerates exactly that one
    # extra batch (1/3). Real leaks (a floor that never clears) are the
    # leak law's job and are NOT forgiven by this slack.
    first_hw, last_hw, flat = soak_high_water(
        pool, per_hour=max(1, int(3600.0 / sample_every)),
        slack_frac=1.0 / 3.0)
    drift = (abs(hourly_ordered[-1] - hourly_ordered[0])
             / hourly_ordered[0]) if hourly_ordered and hourly_ordered[0] \
        else 0.0
    fingerprint = hashlib.sha256(repr((
        pool.ordered_hash(),
        state.committed_head_hash,
        hourly_ordered,
        pool.telemetry.telemetry_hash,
    )).encode()).hexdigest()
    return {
        "arrivals": generator.counters()["arrivals"],
        "ordered_total": tap.ordered_txns(),
        "hourly_ordered": hourly_ordered,
        "throughput_drift": round(drift, 4),
        "first_hour_high_water": first_hw,
        "last_hour_high_water": last_hw,
        "flat_high_water": flat,
        "hashes_total": state.hashes_total,
        "cache_hit_rate": round(state.cache_hit_rate(), 4),
        "agree": pool.honest_nodes_agree(),
        "telemetry_hash": pool.telemetry.telemetry_hash,
        "anomalies": pool.telemetry.anomaly_count,
        "fingerprint": fingerprint,
    }


def soak_high_water(pool, per_hour: int,
                    first_rows=None, last_rows=None,
                    slack_frac: float = 0.0):
    """First-hour vs last-hour per-resource window high-water from the
    telemetry rollup rows — THE soak flatness law, shared by the state
    soak and the virtual-day soak. The plane's own rollup rings
    (``telemetry.*``) grow for the whole horizon by construction
    (bounded by declared maxlen, bound-violation-checked instead) and
    are excluded. ``slack_frac`` tolerates sampling jitter on transient
    sawtooth structures (dirty overlays, request queues peak with the
    diurnal phase, and a tail window's peak can top the baseline's by a
    batch) — the leak law stays the sharp instrument; flatness is the
    backstop."""
    rows = list(pool.telemetry.windows)
    first_rows = first_rows if first_rows is not None else rows[:per_hour]
    last_rows = last_rows if last_rows is not None else rows[-per_hour:]
    names = [n for n in pool.resource_ledger.names
             if not n.startswith("telemetry.")]
    first_hw = {n: max((r["high_water"].get(n, 0) for r in first_rows),
                       default=0) for n in names}
    last_hw = {n: max((r["high_water"].get(n, 0) for r in last_rows),
                      default=0) for n in names}
    flat = all(last_hw[n] <= first_hw[n] * (1.0 + slack_frac)
               for n in names)
    return first_hw, last_hw, flat


def run_state_soak(hours: float = 2.0, rate: float = 0.6, seed: int = 11,
                   n_keys: int = 400, profile_kind: str = "diurnal",
                   period: float = 1800.0, sample_every: float = 300.0,
                   repeats: int = 2) -> Dict:
    """Virtual-time soak under a diurnal profile, run ``repeats`` times
    with the same seed: the whole artifact (ordered hash, final root,
    every structure sample) must be byte-identical across runs.
    ``period`` divides 3600 so first and last hour see the same phase of
    the rate curve — drift measures the system, not the workload shape.
    """
    t0 = time.perf_counter()
    runs = [_soak_once(hours, rate, seed, n_keys, profile_kind, period,
                       sample_every) for _ in range(repeats)]
    rec = dict(runs[0])
    rec.update({
        "hours": hours,
        "rate": rate,
        "seed": seed,
        "repeats": repeats,
        "deterministic": all(r["fingerprint"] == runs[0]["fingerprint"]
                             for r in runs),
        "wall_s": round(time.perf_counter() - t0, 1),
    })
    return rec
