"""Deterministic in-memory network for multi-node simulation.

Reference: plenum/test/simulation/ (sim_network, sim_random) and the
delayer mechanism of plenum/test/delayers.py. Messages between nodes are
delivered through the shared :class:`MockTimer` with configurable
(seeded-random or fixed) latency; *delayers* are predicates that can hold
back, drop or fan out specific message types from specific senders — the
fault-injection surface the chaos plane
(:mod:`indy_plenum_tpu.chaos`) compiles :class:`FaultPlan` primitives
onto (partitions, slow links, duplication, reorder, byzantine silence).
"""
from __future__ import annotations

import random
from collections import Counter
from typing import Any, Callable, Dict, Optional, Sequence, Union

from ..common.event_bus import ExternalBus
from ..common.metrics_collector import MetricsCollector, MetricsName
from ..observability.causal import NET_TRACED_OPS, net_join_key
from .mock_timer import MockTimer

# a delayer: (msg, frm, to) -> None | float | sequence of floats.
# None = no opinion; float = extra delay seconds; float('inf') = drop;
# a sequence = deliver ONE COPY PER ENTRY offset by that many seconds
# (duplication — the at-least-once transport chaos scenarios exercise).
Delayer = Callable[[Any, str, str],
                   Union[None, float, Sequence[float]]]


def delay_message_types(*types, frm: Optional[str] = None,
                        to: Optional[str] = None,
                        seconds: float = float("inf")) -> Delayer:
    """Classic delayer (reference: ppDelay/pDelay/cDelay/icDelay...)."""

    def delayer(msg, sender, dest):
        if types and not isinstance(msg, types):
            return None
        if frm is not None and sender != frm:
            return None
        if to is not None and dest != to:
            return None
        return seconds

    return delayer


class RegionLatencyMatrix:
    """Seeded inter-region latency bands — the geo plane's WAN matrix.

    Every unordered cross-region pair gets a deterministic ``(lo, hi)``
    uniform band inside the WAN envelope: ``lo`` draws from the lower
    half of the envelope and ``hi`` from the upper half, so ``lo < hi``
    by construction and two matrices built from the same seed are
    identical. Intra-region pairs (and peers with no region assignment)
    keep the network's fast band — the matrix only REPLACES the band
    bounds fed to the one per-delivery latency draw, so region mode
    consumes exactly the same rng sequence length as single-region runs.
    """

    def __init__(self, n_regions: int, seed: int,
                 intra_band: tuple, wan_band: tuple):
        self.n_regions = n_regions
        self.intra_band = (float(intra_band[0]), float(intra_band[1]))
        self.wan_band = (float(wan_band[0]), float(wan_band[1]))
        rng = random.Random(seed)
        lo_env, hi_env = self.wan_band
        mid = (lo_env + hi_env) / 2.0
        self._bands: Dict[tuple, tuple] = {}
        for a in range(n_regions):
            for b in range(a + 1, n_regions):
                self._bands[(a, b)] = (rng.uniform(lo_env, mid),
                                       rng.uniform(mid, hi_env))

    def band(self, a: Optional[int], b: Optional[int]) -> tuple:
        """The (lo, hi) latency band for a delivery between regions
        ``a`` and ``b`` (either may be None = unassigned = local)."""
        if a is None or b is None or a == b:
            return self.intra_band
        key = (a, b) if a < b else (b, a)
        return self._bands[key]

    def as_dict(self) -> Dict[str, list]:
        """The pair bands as a JSON-able record (bench/gate reports)."""
        return {"%d-%d" % (a, b): [round(lo, 6), round(hi, 6)]
                for (a, b), (lo, hi) in sorted(self._bands.items())}


class SimNetwork:
    def __init__(self, timer: MockTimer, seed: int = 0,
                 min_latency: float = 0.01, max_latency: float = 0.05,
                 metrics: Optional[MetricsCollector] = None,
                 trace=None, trace_receivers: int = 0,
                 regions: Optional[Dict[str, int]] = None,
                 region_matrix: Optional[RegionLatencyMatrix] = None):
        self._timer = timer
        self._rng = random.Random(seed)
        self._min_latency = min_latency
        self._max_latency = max_latency
        # geo plane: per-peer region assignment + the pair-band matrix.
        # Both default off — the default path draws from the single
        # (min, max) band exactly as before, bit-identical per seed.
        self._regions: Dict[str, int] = dict(regions) if regions else {}
        self._region_matrix = region_matrix
        self.cross_region = 0
        self._peers: Dict[str, ExternalBus] = {}
        self._peer_order: list[str] = []
        self._delayers: list[Delayer] = []
        self._metrics = metrics
        # causal tracing plane: when a recorder is attached, every
        # delivery of a journey-joinable message type (PROPAGATE / 3PC
        # waves / catchup slices) stamps virtual-clock ``net.send`` /
        # ``net.recv`` marks — the delayer-added latency is measured,
        # not modeled, because the recv mark fires at the actual
        # delivery instant. ``trace_receivers`` caps the stamped
        # fan-out to deliveries INTO the first K peers (0 = all): at
        # n=64 the 3PC waves are O(n^2) messages per batch and a
        # sampled receiver set keeps the ring representative without
        # drowning it.
        from ..observability.trace import NULL_TRACE

        self._trace = trace if trace is not None else NULL_TRACE
        self._trace_receivers = trace_receivers
        self._net_seq = 0
        self.dropped = 0
        self.sent = 0
        self.duplicated = 0
        # per-message-type delivery accounting (chaos reports: which
        # traffic a fault plan actually cut)
        self.sent_by_type: Counter = Counter()
        self.dropped_by_type: Counter = Counter()

    # --- wiring ---------------------------------------------------------

    def create_peer(self, name: str) -> ExternalBus:
        bus = ExternalBus(self._make_send_handler(name))
        self._peers[name] = bus
        self._peer_order.append(name)
        return bus

    def connect_all(self) -> None:
        for name, bus in self._peers.items():
            bus.update_connecteds(set(self._peers) - {name})

    def disconnect(self, name: str) -> None:
        """Simulate a node dropping off the network."""
        for peer, bus in self._peers.items():
            if peer != name:
                bus.update_connecteds(bus.connecteds - {name})
        self._peers[name].update_connecteds(set())

    def reconnect(self, name: str) -> None:
        for peer, bus in self._peers.items():
            if peer != name:
                bus.update_connecteds(bus.connecteds | {name})
        self._peers[name].update_connecteds(set(self._peers) - {name})

    def add_delayer(self, delayer: Delayer) -> Callable[[], None]:
        self._delayers.append(delayer)
        return lambda: self._delayers.remove(delayer)

    def reset_delays(self) -> None:
        self._delayers.clear()

    def region_of(self, name: str) -> Optional[int]:
        return self._regions.get(name)

    def assign_region(self, name: str, region: int) -> None:
        """Place a peer (or a client endpoint) in a region after
        construction — the geo fabric registers client homes here."""
        self._regions[name] = region

    def counters(self) -> Dict[str, Any]:
        """Delivery accounting snapshot (chaos report / diagnostics)."""
        out = {"sent": self.sent, "dropped": self.dropped,
               "duplicated": self.duplicated,
               "sent_by_type": dict(self.sent_by_type),
               "dropped_by_type": dict(self.dropped_by_type)}
        if self._region_matrix is not None:
            # absent entirely on single-region runs: pre-geo network
            # blocks stay byte-compatible
            out["cross_region"] = self.cross_region
        return out

    # --- delivery -------------------------------------------------------

    def _make_send_handler(self, frm: str):
        def send(msg, dst=None):
            if dst is None:
                targets = sorted(set(self._peers) - {frm})
            elif isinstance(dst, str):
                targets = [dst]
            else:
                targets = list(dst)
            for to in targets:
                self._deliver_later(msg, frm, to)

        return send

    def _count_drop(self, msg, frm: str = "", to: str = "") -> None:
        self.dropped += 1
        self.dropped_by_type[type(msg).__name__] += 1
        if self._metrics is not None:
            self._metrics.add_event(MetricsName.SIM_NET_DROPPED)
        if self._trace.enabled:
            key = self._net_key(msg, to)
            if key is not None:
                self._trace.record(
                    "net.drop", cat="net", node=to, key=key,
                    args={"m": getattr(type(msg), "typename",
                                       type(msg).__name__),
                          "frm": frm})

    def _net_key(self, msg, to: str) -> Optional[tuple]:
        """Journey-join key for a traced delivery, or None when this
        delivery is not stamped (untraced type, backup instance, or a
        receiver outside the sampled set)."""
        op = getattr(type(msg), "typename", type(msg).__name__)
        if op not in NET_TRACED_OPS:
            return None
        cap = self._trace_receivers
        if cap > 0 and to not in self._peer_order[:cap]:
            return None
        return net_join_key(op, lambda f: getattr(msg, f, None))

    def _deliver_later(self, msg, frm: str, to: str) -> None:
        if to not in self._peers:
            return
        # link must be up (receiver sees sender as connected)
        if not self._peers[to].is_connected(frm):
            self._count_drop(msg, frm, to)
            return
        # ONE latency draw per delivery, region mode or not: the geo
        # matrix only swaps the band bounds, so single-region runs keep
        # their exact historical rng sequence
        lo, hi = self._min_latency, self._max_latency
        is_wan = False
        if self._region_matrix is not None:
            band = self._region_matrix.band(self._regions.get(frm),
                                            self._regions.get(to))
            is_wan = band is not self._region_matrix.intra_band
            lo, hi = band
        latency = self._rng.uniform(lo, hi)
        offsets = [0.0]  # one entry per copy that will be delivered
        for delayer in list(self._delayers):
            extra = delayer(msg, frm, to)
            if extra is None:
                continue
            if isinstance(extra, (tuple, list)):
                offsets = [o + e for o in offsets for e in extra]
                continue
            if extra == float("inf"):
                self._count_drop(msg, frm, to)
                return
            offsets = [o + extra for o in offsets]
        self.sent += len(offsets)
        self.duplicated += len(offsets) - 1
        if is_wan:
            self.cross_region += len(offsets)
        self.sent_by_type[type(msg).__name__] += len(offsets)
        if self._metrics is not None:
            self._metrics.add_event(MetricsName.SIM_NET_DELIVERED,
                                    len(offsets))
        bus = self._peers[to]
        trace_key = (self._net_key(msg, to) if self._trace.enabled
                     else None)
        op = getattr(type(msg), "typename", type(msg).__name__) \
            if trace_key is not None else None
        for off in offsets:
            if trace_key is not None:
                # one send/recv mark pair PER COPY (duplication chaos
                # delivers each copy at its own instant); the recv mark
                # fires inside the scheduled delivery so delayer-added
                # latency lands in the measured gap
                self._net_seq += 1
                nid = self._net_seq
                self._trace.record(
                    "net.send", cat="net", node=frm, key=trace_key,
                    args={"m": op, "to": to, "id": nid})
                self._timer.schedule(
                    latency + off,
                    lambda m=msg, f=frm, b=bus, k=trace_key, i=nid,
                    o=op, t=to: self._traced_delivery(m, f, b, k, i,
                                                      o, t))
            else:
                self._timer.schedule(
                    latency + off,
                    lambda m=msg, f=frm, b=bus: b.process_incoming(m, f))

    def _traced_delivery(self, msg, frm, bus, key, nid, op, to) -> None:
        self._trace.record("net.recv", cat="net", node=to, key=key,
                           args={"m": op, "frm": frm, "id": nid})
        bus.process_incoming(msg, frm)
