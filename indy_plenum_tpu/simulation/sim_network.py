"""Deterministic in-memory network for multi-node simulation.

Reference: plenum/test/simulation/ (sim_network, sim_random) and the
delayer mechanism of plenum/test/delayers.py. Messages between nodes are
delivered through the shared :class:`MockTimer` with configurable
(seeded-random or fixed) latency; *delayers* are predicates that can hold
back or drop specific message types from specific senders — the fault
injector for partitions, slow links and byzantine silence.
"""
from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional

from ..common.event_bus import ExternalBus
from .mock_timer import MockTimer

# a delayer: (msg, frm, to) -> Optional[float]; None = deliver normally,
# float = extra delay seconds, float('inf') = drop
Delayer = Callable[[Any, str, str], Optional[float]]


def delay_message_types(*types, frm: Optional[str] = None,
                        to: Optional[str] = None,
                        seconds: float = float("inf")) -> Delayer:
    """Classic delayer (reference: ppDelay/pDelay/cDelay/icDelay...)."""

    def delayer(msg, sender, dest):
        if types and not isinstance(msg, types):
            return None
        if frm is not None and sender != frm:
            return None
        if to is not None and dest != to:
            return None
        return seconds

    return delayer


class SimNetwork:
    def __init__(self, timer: MockTimer, seed: int = 0,
                 min_latency: float = 0.01, max_latency: float = 0.05):
        self._timer = timer
        self._rng = random.Random(seed)
        self._min_latency = min_latency
        self._max_latency = max_latency
        self._peers: Dict[str, ExternalBus] = {}
        self._delayers: List[Delayer] = []
        self.dropped = 0
        self.sent = 0

    # --- wiring ---------------------------------------------------------

    def create_peer(self, name: str) -> ExternalBus:
        bus = ExternalBus(self._make_send_handler(name))
        self._peers[name] = bus
        return bus

    def connect_all(self) -> None:
        for name, bus in self._peers.items():
            bus.update_connecteds(set(self._peers) - {name})

    def disconnect(self, name: str) -> None:
        """Simulate a node dropping off the network."""
        for peer, bus in self._peers.items():
            if peer != name:
                bus.update_connecteds(bus.connecteds - {name})
        self._peers[name].update_connecteds(set())

    def reconnect(self, name: str) -> None:
        for peer, bus in self._peers.items():
            if peer != name:
                bus.update_connecteds(bus.connecteds | {name})
        self._peers[name].update_connecteds(set(self._peers) - {name})

    def add_delayer(self, delayer: Delayer) -> Callable[[], None]:
        self._delayers.append(delayer)
        return lambda: self._delayers.remove(delayer)

    def reset_delays(self) -> None:
        self._delayers.clear()

    # --- delivery -------------------------------------------------------

    def _make_send_handler(self, frm: str):
        def send(msg, dst=None):
            if dst is None:
                targets = sorted(set(self._peers) - {frm})
            elif isinstance(dst, str):
                targets = [dst]
            else:
                targets = list(dst)
            for to in targets:
                self._deliver_later(msg, frm, to)

        return send

    def _deliver_later(self, msg, frm: str, to: str) -> None:
        if to not in self._peers:
            return
        # link must be up (receiver sees sender as connected)
        if not self._peers[to].is_connected(frm):
            self.dropped += 1
            return
        latency = self._rng.uniform(self._min_latency, self._max_latency)
        for delayer in list(self._delayers):
            extra = delayer(msg, frm, to)
            if extra is None:
                continue
            if extra == float("inf"):
                self.dropped += 1
                return
            latency += extra
        self.sent += 1
        bus = self._peers[to]
        self._timer.schedule(latency,
                             lambda m=msg, f=frm, b=bus: b.process_incoming(m, f))
