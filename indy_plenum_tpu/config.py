"""Configuration: every protocol tunable in one overridable namespace.

Reference: plenum/config.py (module-as-schema, ~200 attrs) with the overlay
chain from plenum/common/config_util.py (``getConfig``: package defaults ->
general config file -> network-specific -> user overrides). Here the schema
is a dataclass; overlays are dicts (loaded from JSON files or passed
directly), applied in order.
"""
from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple


@dataclass
class Config:
    # --- 3PC batching (reference: Max3PCBatchSize / Max3PCBatchWait) ------
    Max3PCBatchSize: int = 100
    Max3PCBatchWait: float = 0.25  # seconds

    # --- watermarks / checkpointing (LOG_SIZE, CHK_FREQ) ------------------
    CHK_FREQ: int = 100
    LOG_SIZE: int = 300  # = H - h window

    # --- RBFT monitor thresholds (Delta / Lambda / Omega) -----------------
    DELTA: float = 0.4  # min master/backup throughput ratio
    LAMBDA: float = 240.0  # max master latency excess (s)
    OMEGA: float = 20.0  # max avg latency gap master vs backups (s)
    ThroughputWindowSize: int = 15
    ThroughputMinCnt: int = 16
    LatencyWindowSize: int = 15
    PerfCheckFreq: float = 10.0  # monitor degradation check cadence (s)

    # --- freshness --------------------------------------------------------
    # idle pools re-sign their state roots periodically (an empty 3PC
    # batch): without this, proved reads go stale once writes stop
    # (reference: STATE_FRESHNESS_UPDATE_INTERVAL). Must sit WELL below
    # the client's proof max age (300s) so reads arriving just before a
    # freshness batch still verify.
    StateFreshnessUpdateInterval: float = 120.0  # 0 disables

    # --- view change ------------------------------------------------------
    ToleratePrimaryDisconnection: float = 2.0  # seconds
    OldViewPPRequestInterval: float = 1.0  # re-fetch missing old-view PPs
    NewViewTimeout: float = 30.0  # restart VC with v+1 if not completed
    # the canonical PBFT liveness timer (Castro & Liskov §4.5.2): a master
    # replica with work pending but no ordering progress across a full
    # interval votes INSTANCE_CHANGE (detection latency is 1-2 intervals;
    # 0 disables). Recovers from in-flight 3PC messages lost for good —
    # e.g. after a partition heals — which no retransmit path covers.
    OrderingStallTimeout: float = 12.0
    INSTANCE_CHANGE_TIMEOUT: float = 300.0  # discard stale instance changes

    # --- catchup ----------------------------------------------------------
    CatchupTransactionsTimeout: float = 6.0
    ConsistencyProofsTimeout: float = 5.0
    CatchupBatchSize: int = 5000  # txns per CATCHUP_REQ slice
    # Per-slice leecher retry law (server/catchup/retry.py): an unanswered
    # CATCHUP_REQ slice is re-assigned to another peer after
    # CatchupRequestTimeout (0 = fall back to CatchupTransactionsTimeout,
    # the pre-retry-law knob), each further silence backs the slice's
    # deadline off multiplicatively (CatchupRetryBackoffMult) with seeded
    # jitter (CatchupRetryJitterFrac of the delay, derived from
    # CatchupRetryJitterSeed | slice | attempt — deterministic, so seeded
    # sim runs replay identical retry schedules), and after
    # CatchupMaxRetries exhausted slices FAIL the round closed (the
    # leecher's CatchupFailedRetryBackoff path) instead of re-asking
    # forever — a silent seeder pool can delay recovery, never stall it.
    CatchupRequestTimeout: float = 0.0
    CatchupMaxRetries: int = 10
    CatchupRetryBackoffMult: float = 1.5
    CatchupRetryBackoffMax: float = 60.0
    CatchupRetryJitterFrac: float = 0.25
    CatchupRetryJitterSeed: int = 0
    # fail-closed retry: a node whose catchup FAILED (history convicted as
    # diverged but no honest quorum reachable, or a slice exhausted its
    # retry budget) stays non-participating and retries with exponential
    # backoff between these bounds
    CatchupFailedRetryBackoff: float = 10.0
    CatchupFailedRetryBackoffMax: float = 300.0
    # Seeder-side throttle (server/catchup/seeder_service.py): a token
    # bucket (txns/sec refill on the node's clock, Burst capacity) caps
    # how fast a seeder answers CATCHUP_REQs — a pool seeding a
    # returning node under ingress saturation must not stall its own
    # ordering to feed the leecher. A dry bucket DEFERS the reply to the
    # deterministic instant the tokens accrue (never drops it); the
    # leecher's retry law tolerates the delay. 0 = unthrottled.
    CatchupSeederThrottleTxnsPerSec: float = 0.0
    CatchupSeederThrottleBurst: int = 200

    # --- propagation ------------------------------------------------------
    PropagateBatchWait: float = 0.1

    # --- transport --------------------------------------------------------
    OUTGOING_BATCH_SIZE: int = 100
    MSG_LEN_LIMIT: int = 128 * 1024

    # --- geo plane: regional latency realism (simulation/sim_network.py) --
    # Number of simulated regions. 0 = single-region (the pre-geo
    # behaviour: one uniform latency band, byte-identical to every
    # earlier seed — region mode consumes exactly the same ONE rng draw
    # per delivery, only the band bounds change). > 0 assigns node i to
    # region i % RegionCount and draws cross-region deliveries from the
    # pair's seeded WAN band instead of the intra-region fast band.
    RegionCount: int = 0
    # WAN envelope: every cross-region pair gets a deterministic
    # (lo, hi) latency band inside [RegionWanMinLatency,
    # RegionWanMaxLatency), derived from RegionLatencySeed — the
    # inter-region latency matrix. Intra-region pairs keep the
    # SimNetwork min/max_latency fast band.
    RegionWanMinLatency: float = 0.08
    RegionWanMaxLatency: float = 0.25
    # Seed for the pair-band matrix. 0 = simulation pools fall back to
    # the pool seed, so a seeded run replays the identical matrix.
    RegionLatencySeed: int = 0

    # --- geo plane: edge proof-cache tier (proofs/edge_cache.py) ----------
    # Region-local UNTRUSTED replicas of the last sealed windows'
    # proof-attached replies. The edge holds at most this many sealed
    # windows' corpora; older windows evict when a new seal replicates
    # in (the CheckpointStabilized invalidation rule).
    EdgeProofCacheKeepWindows: int = 2
    # Bounded LRU entry cap per edge (replies across all held windows).
    # Misses fall back to the home-region validator over the WAN.
    EdgeProofCacheMaxEntries: int = 4096
    # Freshness bound clients fold into verify_proved_read against edge
    # replies: a held window older than this (vs the client's clock) is
    # treated as stale and the client falls back to the origin.
    EdgeProofCacheMaxAge: float = 300.0

    # --- device plane (TPU) ----------------------------------------------
    # Quorum evaluation cadence when the device vote plane is authoritative.
    # 0 = evaluate on every message (one padded device flush per query —
    # correct but unamortized); > 0 = defer quorum queries to a repeating
    # tick so all votes recorded in between ride ONE device flush
    # (vote_plane.py's batching contract; the Node event-loop mode).
    QuorumTickInterval: float = 0.0
    # Adaptive tick (dispatch governor, tpu/governor.py): the tick
    # interval becomes a closed-loop control variable — widened while the
    # observed flush occupancy is sparse (fewer near-empty scatters),
    # narrowed while a tick overflows one grouped step or runs hot
    # (lower quorum latency at no extra dispatch cost). The controller is
    # a pure function of the per-tick metrics, so seeded runs (incl.
    # chaos) replay to the identical interval trajectory.
    QuorumTickAdaptive: bool = False
    QuorumTickIntervalMin: float = 0.0  # 0 -> QuorumTickInterval / 4
    QuorumTickIntervalMax: float = 0.0  # 0 -> QuorumTickInterval * 4
    GovernorEwmaAlpha: float = 0.3  # weight of the newest tick's occupancy
    GovernorOccupancyLow: float = 0.02  # EWMA below this widens the tick
    GovernorOccupancyHigh: float = 0.85  # EWMA above this narrows it
    GovernorWiden: float = 1.5  # multiplicative widen step
    GovernorNarrow: float = 0.5  # multiplicative narrow step
    # Adaptive flush ladder (vote_plane.AdaptiveLadder): the grouped
    # dispatch plane learns its top padded-scatter rung from the
    # observed busiest-member votes-per-dispatch distribution (p99
    # rounded up to a power of two, clamped to the static FLUSH_LADDER
    # bounds), so a small pool stops compiling and paying the 128-wide
    # rung. Deterministic (pure function of the dispatch series);
    # learning only starts after a warm-up window, so short runs keep
    # the static ladder's exact behaviour.
    FlushLadderAdaptive: bool = True
    # Multi-tick device residency (tpu/vote_plane.py): with depth N > 1
    # the tick-batched group ENQUEUES each tick's scatter words into a
    # device-side ring (async device_put — a transfer, not an XLA
    # dispatch) and dispatches ONE fused step per up-to-N ticks, with
    # checkpoint slides folded in as per-slot operands — quorum verdicts
    # may lag up to N ticks but ordered CONTENT is bit-identical to the
    # per-tick path (PR 2's timing-robustness law; the residency gate
    # asserts it). 1 = off (the per-tick PR 7/9 behaviour, bit-exact).
    # Device-eval only: host_eval groups fall back to per-tick.
    ResidentTickDepth: int = 1
    # Occupancy-driven shard rebalancing (tpu/rebalance.py): when the
    # hottest member block's occupancy EWMA exceeds the median by this
    # factor for RebalanceDwellTicks consecutive ticks, the policy plans
    # a member-plane rotation (ring_shift_planes) executed at the next
    # checkpoint-boundary slide — the rebalance barrier. 0 = disabled
    # (the policy is not even constructed). Member-sharded groups only.
    RebalanceSkewThreshold: float = 0.0
    RebalanceDwellTicks: int = 8
    # Testing/chaos hook: force ONE planned rotation at exactly this
    # tick ordinal regardless of skew (0 = off) — digest-identity arms
    # rebalance deterministically without engineering a hot shard.
    RebalanceForceTick: int = 0

    # --- ingress plane (admission control + backpressure) -----------------
    # Bounded auth queue (ingress/admission.py): client writes queue up to
    # this many entries between dispatch ticks; overflow sheds
    # deterministically (drop-newest, seeded tiebreak). 0 = unbounded
    # (admission control off — the pre-PR 6 behaviour).
    IngressQueueCapacity: int = 0
    # Per-client fairness cap: a client with this many requests already
    # queued is shed outright (0 = no cap). One hot wallet must not
    # starve the population.
    IngressPerClientCap: int = 0
    # Shed tiebreak seed for DEPLOYED nodes (simulation pools use the
    # pool seed so the shed set replays with the run).
    IngressShedSeed: int = 0
    # Backpressure law (governor.feed_backpressure): pre-drain queue
    # depth at or above this fraction of capacity counts as queue growth
    # and narrows the tick.
    GovernorBackpressureQueueFrac: float = 0.5
    # Read-path backpressure (ingress/read_service.py): bounded read
    # queue with the same seeded drop-newest shed law as writes, so a
    # read flood cannot starve the drain. 0 = unbounded (pre-proof-plane
    # behaviour). The shed tiebreak shares IngressShedSeed.
    IngressReadQueueCapacity: int = 0

    # --- closed-loop retry (ingress/retry.py) -----------------------------
    # Per-client retry of shed/NACKed requests: the overload-robustness
    # plane's client model. A shed request re-offers after a seeded
    # exponential backoff (base * mult^(attempt-1), capped, stretched by
    # sha256(seed|digest|attempt) jitter) up to IngressRetryMax attempts,
    # then the client gives up (counted under ingress.retry_exhausted).
    # 0 = open loop (the pre-overload-plane behaviour). Every re-offer
    # re-enters admission: it counts against the fairness cap and
    # competes in the same-instant shed cohort — no retry side door.
    IngressRetryMax: int = 0
    IngressRetryBase: float = 0.25
    IngressRetryBackoffMult: float = 2.0
    IngressRetryBackoffMax: float = 30.0
    IngressRetryJitterFrac: float = 0.5

    # --- workload profiles (ingress/workload.py) --------------------------
    # Rate modulation for the open-loop generator: the diurnal curve's
    # period and trough/peak multipliers, and the flash crowd's spike
    # window (offset into the arrival window + duration) and peak
    # multiplier (shared with diurnal's crest). Pure functions of
    # virtual time — profiled runs replay byte-identically.
    WorkloadProfilePeriod: float = 20.0
    WorkloadProfileTrough: float = 0.5
    WorkloadProfilePeak: float = 3.0
    WorkloadProfileFlashAt: float = 0.0
    WorkloadProfileFlashDuration: float = 2.0

    # --- ordering lanes (lanes/) ------------------------------------------
    # Keyspace-partitioned write path: the request keyspace splits across
    # this many independent ordering lanes, each a full master-instance
    # vote plane on its own slice of the fabric mesh, with a cross-lane
    # checkpoint barrier keeping state proofs and catchup on one
    # consistent stabilized window. 0/1 = single-lane (the pre-lanes
    # behaviour; LanedPool treats both as one lane).
    OrderingLanes: int = 0
    # Router law seed (sha256(seed | routing key) % lanes). 0 = simulation
    # pools fall back to the pool seed, so a seeded run replays the
    # byte-identical lane assignment.
    LaneRouterSeed: int = 0
    # Sealed-window records (per-lane digest lists, per-window chain
    # values) the barrier retains for verification — the chain TIP is
    # O(1) state either way. 0 = retain everything (bounded sim runs,
    # full-chain recomputation in the cross_lane invariant); a deployed
    # pool should bound this like StateProofCacheWindows.
    LaneBarrierKeepWindows: int = 0

    # --- state-proof plane (proofs/) --------------------------------------
    # Stabilized checkpoint windows whose pool multi-signature stays
    # servable from the CheckpointProofCache; older windows GC with the
    # checkpoint floor. 0 disables the proof plane (reads fall back to
    # local-root proofs only). Nodes build the cache only when they also
    # run a BLS replica — there is nothing to capture without one.
    StateProofCacheWindows: int = 2

    # --- state-commit plane (state/sparse_merkle_state.py) ----------------
    # Batched O(delta) state commit: WriteRequestManager.apply_batch
    # buffers a 3PC batch's writes and flushes them through ONE bottom-up
    # SMT walk (last-write-wins dedupe, each touched internal node hashed
    # once per batch) instead of a 256-hash path walk per write. False =
    # the pre-batch sequential set() loop (roots are bit-identical either
    # way — the state_gate asserts it).
    StateCommitBatchEnabled: bool = True
    # Write sets smaller than this skip the plan/wave machinery and apply
    # sequentially — below it, prefix sharing has nothing to share and
    # the plan-node overhead costs more than it saves.
    StateCommitBatchMin: int = 4
    # Placement of the per-level hash waves: "host" = hashlib loop,
    # "device" = force the batched tpu/sha256 kernel, "auto" = the
    # measured catchup offload policy decides per wave (DEVICE_MIN_BATCH
    # floor; host SHA wins on XLA:CPU, the kernel wins on real TPU).
    # Digests are bit-identical on either path — only nanoseconds move.
    StateCommitBatchMode: str = "auto"
    # Bounded LRU node cache fronting each state's KV store (entries are
    # immutable content-addressed nodes, so the cache never invalidates).
    # ~256 bytes/node -> the default is ~16 MB per stateful ledger.
    # 0 disables.
    StateNodeCacheSize: int = 65536

    # --- storage ----------------------------------------------------------
    KVStorageType: str = "sqlite"  # sqlite | memory

    # --- request handling -------------------------------------------------
    # privileged actions must carry a node-clock timestamp this fresh
    # (replay window; seen digests are deduped inside it)
    ActionFreshnessWindow: float = 300.0

    # --- metrics / observability -----------------------------------------
    METRICS_COLLECTOR_TYPE: Optional[str] = "kv"
    # consensus flight recorder (observability.trace): span traces for
    # the 3PC lifecycle + dispatch plane. Disabled by default — recording
    # rides NULL_TRACE (zero-cost, like NullMetricsCollector); sim pools
    # enable it explicitly (trace=True) on the virtual clock so seeded
    # runs dump bit-identical traces, a deployed Node enables it here and
    # records perf_counter durations instead.
    TraceRecorderEnabled: bool = False
    TraceRecorderCapacity: int = 65536
    # causal tracing plane (observability.causal): when tracing is on,
    # the transports stamp net.send/net.recv marks for journey-joinable
    # message types. The 3PC waves are O(n^2) messages per batch, so
    # large-pool benches cap the stamped fan-out to deliveries into the
    # first K validators (0 = stamp every delivery) — the sampled set
    # keeps per-wave latency stats representative without drowning the
    # ring
    TraceNetReceivers: int = 0
    # long-horizon telemetry plane (observability/telemetry.py): windowed
    # rollups + resource ledger + drift laws on the virtual clock. 0 =
    # unarmed (no ledger, no plane, zero cost — the pre-telemetry pool).
    # Armed, the pool registers every bounded structure in one
    # ResourceLedger and rolls a time-series row every window, with the
    # running telemetry_hash chain byte-identical per seed.
    TelemetryWindowSec: float = 0.0
    # rollup rows the plane retains (the hash chain keeps fingerprinting
    # evicted rows with O(1) state, like the lane barrier's seal chain)
    TelemetryWindowKeep: int = 64
    # leak law: window high-water strictly increasing for this many
    # consecutive windows fires one anomaly per episode
    TelemetryLeakWindows: int = 4
    # windows exempt from the leak/creep laws while caches warm toward
    # their steady state (rings filling to capacity is not a leak)
    TelemetryLeakGraceWindows: int = 6
    # throughput law: ordered delta dropping by more than this fraction
    # against the window TelemetryDriftLag back is drift; set the lag to
    # profile-period/window so a diurnal trough compares to the same
    # phase a cycle earlier instead of reading as degradation
    TelemetryDriftFrac: float = 0.5
    TelemetryDriftLag: int = 1
    # anomaly records retained (total count and hash chain keep going)
    TelemetryAnomalyKeep: int = 32

    # --- virtual-day soak (simulation/soak.py) ----------------------------
    # the composed long-horizon arc: a diurnal day of real-execution
    # ordering with telemetry armed and chaos folded in — a GC-crossing
    # crash/catchup, a primary view change, and a forced shard rebalance.
    # Hours are offsets into the measured day (0 = that leg disabled).
    SoakHours: float = 24.0
    SoakRate: float = 0.1  # base writes/sec before the diurnal profile
    SoakKeys: int = 400  # distinct state keys the workload cycles over
    SoakCrashHour: float = 6.0  # non-primary crash (GC-crossing catchup)
    SoakCrashHours: float = 1.0  # outage length, in hours
    SoakViewChangeHour: float = 12.0  # primary partition -> view change
    SoakRebalanceTick: int = 5000  # RebalanceForceTick for the soak pool
    # logging (reference: stp logging config + rotating handler); the
    # five knobs below are consumed by scripts/start_node.py (deployed
    # logging setup), outside the package the analyzer walks
    logLevel: str = "INFO"  # da: allow[config-knob] -- read by scripts/start_node.py
    logRotationMaxBytes: int = 10 * 1024 * 1024  # da: allow[config-knob] -- read by scripts/start_node.py
    logRotationBackupCount: int = 10  # da: allow[config-knob] -- read by scripts/start_node.py
    logRotationWhen: str = "h"  # da: allow[config-knob] -- read by scripts/start_node.py
    logRotationInterval: int = 1  # da: allow[config-knob] -- read by scripts/start_node.py

    # --- plugins ----------------------------------------------------------
    # importable module paths, each exposing plugin_entry(node)
    PluginModules: Tuple[str, ...] = ()

    # --- misc -------------------------------------------------------------
    replicas_count_overrider: Optional[int] = None  # else f+1

    def governor_bounds(self) -> Tuple[float, float]:
        """Resolved (min, max) tick bounds for the adaptive governor; the
        0.0 defaults scale off the base interval so one knob still tunes
        a pool."""
        base = self.QuorumTickInterval
        lo = self.QuorumTickIntervalMin or base / 4.0
        hi = self.QuorumTickIntervalMax or base * 4.0
        return lo, hi

    def replicas_count(self, n_nodes: int) -> int:
        if self.replicas_count_overrider is not None:
            return self.replicas_count_overrider
        f_val = (n_nodes - 1) // 3
        return f_val + 1

    def overlay(self, overrides: Dict[str, Any]) -> "Config":
        unknown = set(overrides) - {fld.name for fld in dataclasses.fields(self)}
        if unknown:
            raise KeyError(f"unknown config keys: {sorted(unknown)}")
        return dataclasses.replace(self, **overrides)


_DEFAULT: Optional[Config] = None


def getConfig(overrides: Optional[Dict[str, Any]] = None,
              config_files: Tuple[str, ...] = ()) -> Config:
    """Overlay chain: defaults -> each JSON file in order -> overrides."""
    global _DEFAULT
    cfg = Config()
    for path in config_files:
        if os.path.exists(path):
            with open(path) as fh:
                cfg = cfg.overlay(json.load(fh))
    if overrides:
        cfg = cfg.overlay(overrides)
    if _DEFAULT is None and not overrides and not config_files:
        _DEFAULT = cfg
    return cfg
