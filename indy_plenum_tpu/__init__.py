"""indy_plenum_tpu — a TPU-native RBFT ordering service.

A ground-up redesign (NOT a port) of the capabilities of
hyperledger/indy-plenum (reference layout surveyed in SURVEY.md):

- **Host runtime** (pure Python, deterministic, single event loop per node):
  timers, event buses, stashing routers, message schemas, ledgers, MPT state,
  catchup / view-change / checkpoint state machines. Mirrors reference layers
  L1/L4/L5/L6 (`stp_core/loop/`, `plenum/common/`, `plenum/server/`) at a
  fraction of the size.
- **Device plane** (JAX/XLA/Pallas, `ops/` + `parallel/` + `models/`): all
  O(n_validators x batch) math — batched Ed25519 verification
  (reference hot path: `plenum/server/client_authn.py::CoreAuthNr.authenticate`),
  SHA-256 Merkle audit-path verification (reference:
  `ledger/merkle_verifier.py`), and the dense (validator x seqNo) quorum vote
  tally (reference: `plenum/server/consensus/ordering_service.py`) reduced
  with `psum` over a `jax.sharding.Mesh` whose axis mirrors the validator set.

Only boolean verdicts / quorum events cross back from device to the Python
replica loop.
"""

__version__ = "0.1.0"
