"""Provision and run a local validator pool (the CLI's working parts).

Reference: the reference's init utilities + scripts
(plenum/common/keygen_utils.py, scripts/generate_indy_pool_transactions,
scripts/start_plenum_node). ``generate_pool_config`` writes a directory a
human can inspect: per-node seeds, transport keys and addresses, the
trustee seed, and pool/domain genesis files (one JSON txn per line, the
reference's format). ``build_node`` reopens that directory and assembles
one validator over the authenticated ZMQ transport; ``run_pool`` drives
any number of them on one Looper (in-process pool; production runs one
process per node with the same pieces).
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

from ..common.constants import STEWARD, TRUSTEE
from ..common.looper import Looper
from ..config import Config, getConfig
from ..crypto.signers import DidSigner
from ..ledger.genesis import (
    dump_genesis_file,
    genesis_node_txn,
    genesis_nym_txn,
    load_genesis_file,
)
from ..network import ZStack, ZStackNetwork, curve_keypair_from_seed
from ..server.node import Node

POOL_GENESIS = "pool_genesis.jsonl"
DOMAIN_GENESIS = "domain_genesis.jsonl"
POOL_INFO = "pool_info.json"  # PUBLIC: addresses + public keys only
KEYS_DIR = "keys"  # PRIVATE: one secret file per identity — a deployment
#                    copies pool_info.json to every host but each node's
#                    keys/<name>.json ONLY to that node's host


def generate_pool_config(directory: str, n_nodes: int = 4,
                         base_port: int = 9700,
                         master_seed: Optional[bytes] = None) -> Dict:
    """Write keys + genesis for an n-node pool; returns the pool info.

    ``master_seed`` defaults to fresh randomness (os.urandom) — a fixed
    seed makes every derived secret publicly recomputable, so it exists
    only for reproducible test fixtures.
    """
    os.makedirs(directory, exist_ok=True)
    keys_dir = os.path.join(directory, KEYS_DIR)
    os.makedirs(keys_dir, exist_ok=True)
    if master_seed is None:
        # da: allow[nondet-source] -- master-key generation for a REAL local pool: entropy by design; reproducible fixtures pass master_seed explicitly
        master_seed = os.urandom(32)

    def derive(tag: str) -> bytes:
        return hashlib.sha256(master_seed + tag.encode()).digest()

    trustee = DidSigner(derive("trustee"))
    domain = [genesis_nym_txn(trustee.identifier, trustee.verkey,
                              role=TRUSTEE)]
    pool = []
    nodes = {}
    for i in range(n_nodes):
        name = f"node{i}"
        steward = DidSigner(derive(f"steward-{i}"))
        node_seed = derive(f"node-{i}")
        public, _secret = curve_keypair_from_seed(node_seed)
        # the client listener's curve identity (shared derivation with
        # ClientZStack — see network/keys.py)
        from ..network.keys import client_stack_keypair_from_seed

        client_public, _ = client_stack_keypair_from_seed(node_seed)
        # BLS signing identity: public key + proof of possession go into
        # the pool genesis NODE txn (reference: init_bls_keys)
        from ..bls.factory import generate_bls_keys

        _kp, bls_pk, bls_pop = generate_bls_keys(derive(f"bls-{i}"))
        domain.append(genesis_nym_txn(steward.identifier, steward.verkey,
                                      role=STEWARD))
        pool.append(genesis_node_txn(
            node_nym=f"nym-{name}", alias=name,
            steward_did=steward.identifier,
            node_port=base_port + 2 * i, client_port=base_port + 2 * i + 1,
            blskey=bls_pk, blskey_pop=bls_pop,
            transport_verkey=public.decode()))
        nodes[name] = {
            "transport_public": public.decode(),
            "client_public": client_public.decode(),
            "node_ip": "127.0.0.1",
            "node_port": base_port + 2 * i,
            "client_ip": "127.0.0.1",
            "client_port": base_port + 2 * i + 1,
            "bls_key": bls_pk,
            "bls_pop": bls_pop,
        }
        _write_secret(os.path.join(keys_dir, f"{name}.json"),
                      {"seed": node_seed.hex(),
                       "bls_seed": derive(f"bls-{i}").hex()})
    _write_secret(os.path.join(keys_dir, "trustee.json"),
                  {"seed": derive("trustee").hex()})
    info = {
        "trustee_did": trustee.identifier,
        "trustee_verkey": trustee.verkey,
        "validators": [f"node{i}" for i in range(n_nodes)],
        "nodes": nodes,
    }
    dump_genesis_file(os.path.join(directory, POOL_GENESIS), pool)
    dump_genesis_file(os.path.join(directory, DOMAIN_GENESIS), domain)
    with open(os.path.join(directory, POOL_INFO), "w") as fh:
        json.dump(info, fh, indent=2, sort_keys=True)
    return info


def _write_secret(path: str, payload: Dict) -> None:
    """Owner-only (0600) secret files, like ssh/indy keygen tooling."""
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as fh:
        json.dump(payload, fh)


def load_secret_seed(directory: str, name: str, key: str = "seed") -> bytes:
    with open(os.path.join(directory, KEYS_DIR, f"{name}.json")) as fh:
        return bytes.fromhex(json.load(fh)[key])


def load_pool_info(directory: str) -> Dict:
    with open(os.path.join(directory, POOL_INFO)) as fh:
        return json.load(fh)


def build_node(directory: str, name: str, looper: Looper,
               config: Optional[Config] = None) -> Tuple[Node, ZStack]:
    """Reopen a provisioned directory and assemble one validator."""
    info = load_pool_info(directory)
    record = info["nodes"][name]
    config = config or getConfig(
        {"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 100,
         "PropagateBatchWait": 0.05})
    node_seed = load_secret_seed(directory, name)
    # ONE collector per validator, shared by transport and node: HWM drops
    # (zstack.dropped) land in the same summary as auth/commit timings.
    # The default "kv" type persists snapshots (stats + histograms) under
    # the node directory so a restarted validator keeps its history —
    # Node.stop() closes it, flushing the final partial window.
    if config.METRICS_COLLECTOR_TYPE == "kv":
        from ..common.metrics_collector import KvMetricsCollector
        from ..storage.kv_store import initKeyValueStorage

        metrics = KvMetricsCollector(initKeyValueStorage(
            config.KVStorageType, directory, f"metrics_{name}"))
    else:
        from ..common.metrics_collector import MetricsCollector

        metrics = MetricsCollector()
    stack = ZStack(name, node_seed,
                   bind_host=record["node_ip"],
                   bind_port=record["node_port"],
                   max_batch=config.OUTGOING_BATCH_SIZE,
                   msg_len_limit=config.MSG_LEN_LIMIT,
                   metrics=metrics)
    for peer, rec in info["nodes"].items():
        if peer == name:
            continue
        key = rec["transport_public"].encode()
        stack.allow_peer(peer, key)
        stack.connect(peer, (rec["node_ip"], rec["node_port"]), key)
    net = ZStackNetwork(stack)

    # BLS: own keypair from the secret file, pool publics from pool info
    bls_keys = None
    if all("bls_key" in rec for rec in info["nodes"].values()):
        from ..bls.factory import generate_bls_keys

        own_kp, _, _ = generate_bls_keys(
            load_secret_seed(directory, name, key="bls_seed"))
        bls_keys = {
            peer: (own_kp if peer == name else None,
                   rec["bls_key"], rec["bls_pop"])
            for peer, rec in info["nodes"].items()}

    node = Node(
        name, list(info["validators"]), looper.timer, net, config=config,
        pool_genesis=load_genesis_file(
            os.path.join(directory, POOL_GENESIS)),
        domain_genesis=load_genesis_file(
            os.path.join(directory, DOMAIN_GENESIS)),
        seed_keys={info["trustee_did"]: info["trustee_verkey"]},
        bls_keys=bls_keys, metrics=metrics)
    net.mark_connected(set(info["validators"]) - {name})
    # committed NODE txns rewire the transport (KIT semantics): new
    # members get connected, departed ones dropped, rotated keys restart
    node.on_membership_changed_hook = net.membership_hook
    # causal tracing plane: the transport stamps net.send/net.recv marks
    # (and piggybacks the ~trc context on the envelope) on the node's
    # recorder — NULL_TRACE unless config.TraceRecorderEnabled
    stack.trace = node.trace

    # the client-facing listener (reference: the node's client stack)
    from ..network.client_stack import ClientZStack, NodeClientSurface

    client_stack = ClientZStack(
        name, node_seed, bind_host=record.get("client_ip", "127.0.0.1"),
        bind_port=record.get("client_port", 0),
        msg_len_limit=config.MSG_LEN_LIMIT)
    node.client_surface = NodeClientSurface(node, client_stack)
    return node, stack


def run_pool(directory: str, names: Optional[List[str]] = None,
             config: Optional[Config] = None
             ) -> Tuple[Looper, List[Node], List[ZStack]]:
    """Assemble + start validators on one Looper (in-process pool)."""
    info = load_pool_info(directory)
    names = names or list(info["validators"])
    looper = Looper()
    nodes, stacks = [], []
    for name in names:
        node, stack = build_node(directory, name, looper, config=config)
        node.start()
        looper.add(stack)
        looper.add(node.client_surface)
        nodes.append(node)
        stacks.append(stack)
    return looper, nodes, stacks


def build_client(directory: str, name: str = "client1",
                 now_provider=None):
    """A pool client over real sockets: Client logic + PoolClientStack
    transport wired together. Pump ``client.stack.service()`` (or add the
    returned stack to a Looper) to move messages."""
    import time as _time

    from ..client.client import Client
    from ..network.client_stack import PoolClientStack

    info = load_pool_info(directory)
    nodes = {
        node_name: ((rec.get("client_ip", "127.0.0.1"),
                     rec["client_port"]),
                    rec["client_public"].encode())
        for node_name, rec in info["nodes"].items()
        if "client_port" in rec and "client_public" in rec}
    stack = PoolClientStack(name, nodes)
    bls_keys = {n: rec["bls_key"] for n, rec in info["nodes"].items()
                if "bls_key" in rec}
    client = Client(
        name, list(info["validators"]),
        send=lambda req, node_name, _cid: stack.send(req, node_name),
        pool_bls_keys=bls_keys,
        now_provider=now_provider or _time.time)
    stack.on_message = client.process_node_message
    client.stack = stack
    return client, stack


def warm_verify_kernel(node, signer) -> None:
    """Compile the signature-verify kernel shapes BEFORE real traffic:
    the first XLA compile costs tens of seconds (minutes on a remote
    device) and would otherwise eat a write's quorum timeout. One
    definition for the CLI and test fixtures — the jit cache is shared
    process-wide, so warming any one node warms them all."""
    import hashlib

    from ..common.constants import NYM, TARGET_NYM, TXN_TYPE, VERKEY
    from ..common.request import Request
    from ..crypto.signers import DidSigner

    probe = DidSigner(hashlib.sha256(b"warm-verify-kernel").digest())
    req = Request(identifier=signer.identifier, reqId=1,
                  operation={TXN_TYPE: NYM, TARGET_NYM: probe.identifier,
                             VERKEY: probe.verkey})
    signer.sign_request(req)
    node.authnr.authenticate_batch([req])
