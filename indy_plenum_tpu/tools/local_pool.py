"""Provision and run a local validator pool (the CLI's working parts).

Reference: the reference's init utilities + scripts
(plenum/common/keygen_utils.py, scripts/generate_indy_pool_transactions,
scripts/start_plenum_node). ``generate_pool_config`` writes a directory a
human can inspect: per-node seeds, transport keys and addresses, the
trustee seed, and pool/domain genesis files (one JSON txn per line, the
reference's format). ``build_node`` reopens that directory and assembles
one validator over the authenticated ZMQ transport; ``run_pool`` drives
any number of them on one Looper (in-process pool; production runs one
process per node with the same pieces).
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

from ..common.constants import STEWARD, TRUSTEE
from ..common.looper import Looper
from ..config import Config, getConfig
from ..crypto.signers import DidSigner
from ..ledger.genesis import (
    dump_genesis_file,
    genesis_node_txn,
    genesis_nym_txn,
    load_genesis_file,
)
from ..network import ZStack, ZStackNetwork, curve_keypair_from_seed
from ..server.node import Node

POOL_GENESIS = "pool_genesis.jsonl"
DOMAIN_GENESIS = "domain_genesis.jsonl"
POOL_INFO = "pool_info.json"  # PUBLIC: addresses + public keys only
KEYS_DIR = "keys"  # PRIVATE: one secret file per identity — a deployment
#                    copies pool_info.json to every host but each node's
#                    keys/<name>.json ONLY to that node's host


def generate_pool_config(directory: str, n_nodes: int = 4,
                         base_port: int = 9700,
                         master_seed: Optional[bytes] = None) -> Dict:
    """Write keys + genesis for an n-node pool; returns the pool info.

    ``master_seed`` defaults to fresh randomness (os.urandom) — a fixed
    seed makes every derived secret publicly recomputable, so it exists
    only for reproducible test fixtures.
    """
    os.makedirs(directory, exist_ok=True)
    keys_dir = os.path.join(directory, KEYS_DIR)
    os.makedirs(keys_dir, exist_ok=True)
    if master_seed is None:
        master_seed = os.urandom(32)

    def derive(tag: str) -> bytes:
        return hashlib.sha256(master_seed + tag.encode()).digest()

    trustee = DidSigner(derive("trustee"))
    domain = [genesis_nym_txn(trustee.identifier, trustee.verkey,
                              role=TRUSTEE)]
    pool = []
    nodes = {}
    for i in range(n_nodes):
        name = f"node{i}"
        steward = DidSigner(derive(f"steward-{i}"))
        node_seed = derive(f"node-{i}")
        public, _secret = curve_keypair_from_seed(node_seed)
        domain.append(genesis_nym_txn(steward.identifier, steward.verkey,
                                      role=STEWARD))
        pool.append(genesis_node_txn(
            node_nym=f"nym-{name}", alias=name,
            steward_did=steward.identifier,
            node_port=base_port + 2 * i, client_port=base_port + 2 * i + 1))
        nodes[name] = {
            "transport_public": public.decode(),
            "node_ip": "127.0.0.1",
            "node_port": base_port + 2 * i,
        }
        _write_secret(os.path.join(keys_dir, f"{name}.json"),
                      {"seed": node_seed.hex()})
    _write_secret(os.path.join(keys_dir, "trustee.json"),
                  {"seed": derive("trustee").hex()})
    info = {
        "trustee_did": trustee.identifier,
        "trustee_verkey": trustee.verkey,
        "validators": [f"node{i}" for i in range(n_nodes)],
        "nodes": nodes,
    }
    dump_genesis_file(os.path.join(directory, POOL_GENESIS), pool)
    dump_genesis_file(os.path.join(directory, DOMAIN_GENESIS), domain)
    with open(os.path.join(directory, POOL_INFO), "w") as fh:
        json.dump(info, fh, indent=2, sort_keys=True)
    return info


def _write_secret(path: str, payload: Dict) -> None:
    """Owner-only (0600) secret files, like ssh/indy keygen tooling."""
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as fh:
        json.dump(payload, fh)


def load_secret_seed(directory: str, name: str) -> bytes:
    with open(os.path.join(directory, KEYS_DIR, f"{name}.json")) as fh:
        return bytes.fromhex(json.load(fh)["seed"])


def load_pool_info(directory: str) -> Dict:
    with open(os.path.join(directory, POOL_INFO)) as fh:
        return json.load(fh)


def build_node(directory: str, name: str, looper: Looper,
               config: Optional[Config] = None) -> Tuple[Node, ZStack]:
    """Reopen a provisioned directory and assemble one validator."""
    info = load_pool_info(directory)
    record = info["nodes"][name]
    config = config or getConfig(
        {"Max3PCBatchWait": 0.1, "Max3PCBatchSize": 100,
         "PropagateBatchWait": 0.05})
    stack = ZStack(name, load_secret_seed(directory, name),
                   bind_host=record["node_ip"],
                   bind_port=record["node_port"],
                   max_batch=config.OUTGOING_BATCH_SIZE,
                   msg_len_limit=config.MSG_LEN_LIMIT)
    for peer, rec in info["nodes"].items():
        if peer == name:
            continue
        key = rec["transport_public"].encode()
        stack.allow_peer(peer, key)
        stack.connect(peer, (rec["node_ip"], rec["node_port"]), key)
    net = ZStackNetwork(stack)
    node = Node(
        name, list(info["validators"]), looper.timer, net, config=config,
        pool_genesis=load_genesis_file(
            os.path.join(directory, POOL_GENESIS)),
        domain_genesis=load_genesis_file(
            os.path.join(directory, DOMAIN_GENESIS)),
        seed_keys={info["trustee_did"]: info["trustee_verkey"]})
    net.mark_connected(set(info["validators"]) - {name})
    return node, stack


def run_pool(directory: str, names: Optional[List[str]] = None,
             config: Optional[Config] = None
             ) -> Tuple[Looper, List[Node], List[ZStack]]:
    """Assemble + start validators on one Looper (in-process pool)."""
    info = load_pool_info(directory)
    names = names or list(info["validators"])
    looper = Looper()
    nodes, stacks = [], []
    for name in names:
        node, stack = build_node(directory, name, looper, config=config)
        node.start()
        looper.add(stack)
        nodes.append(node)
        stacks.append(stack)
    return looper, nodes, stacks
