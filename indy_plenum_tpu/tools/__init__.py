"""Operational tooling: pool provisioning + node runner (CLI back-end)."""
from .local_pool import (
    build_client,
    build_node,
    generate_pool_config,
    run_pool,
)

__all__ = ["build_client", "build_node", "generate_pool_config", "run_pool"]
