"""Rule: unordered iteration inside fingerprint-producing functions.

``unordered-fingerprint`` — ``ordered_hash`` / ``trace_hash`` /
``shed_hash`` / ``journey_hash`` are sha256 over a serialized walk of
host data structures. Iterating a ``set`` (arbitrary order under hash
randomization) or ``dict.values()`` (insertion order — deterministic
only if every insertion path is) inside a function whose output reaches
such a sink yields a fingerprint that can differ between identical
seeded runs. Taint-lite: the rule looks intra-function — a function
counts as "fingerprint context" when its NAME is a fingerprint
(``*_hash``) or its body calls a hash/serialization sink; any unordered
iteration inside it is flagged. The fix is ``sorted(...)`` with an
explicit key; sites whose order provably cannot reach the sink take a
pragma saying why.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Finding, ModuleInfo, Rule, is_sink_call, iter_scope

__all__ = ["UnorderedFingerprintRule"]


def _is_fingerprint_fn(fn) -> bool:
    if fn.name.endswith("_hash") or fn.name == "fingerprint":
        return True
    for node in iter_scope(fn):
        if isinstance(node, ast.Call) and is_sink_call(node):
            return True
    return False


class UnorderedFingerprintRule(Rule):
    name = "unordered-fingerprint"
    summary = ("set / dict.values() iteration inside a function that "
               "feeds a hash or serialization sink")

    def check_module(self, module: ModuleInfo) -> List[Finding]:
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _is_fingerprint_fn(fn):
                continue
            findings.extend(self._check_function(module, fn))
        return findings

    def _check_function(self, module: ModuleInfo, fn) -> List[Finding]:
        # names bound (anywhere in this scope) from set constructors;
        # nested functions are their own scopes (iter_scope)
        set_names: Set[str] = set()
        for node in iter_scope(fn):
            if isinstance(node, ast.Assign) \
                    and self._is_set_expr(node.value, set_names):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        set_names.add(tgt.id)

        findings: List[Finding] = []
        iters: List[ast.AST] = []
        for node in iter_scope(fn):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.DictComp, ast.GeneratorExp)):
                iters.extend(gen.iter for gen in node.generators)
        for it in iters:
            why = self._unordered_why(it, set_names)
            if why is not None:
                findings.append(Finding(
                    rule=self.name, path=module.path,
                    line=it.lineno, col=it.col_offset,
                    message=f"iteration over {why} inside fingerprint "
                            f"context {fn.name}() — order is not part "
                            "of the replay contract; wrap in "
                            "sorted(..., key=...)"))
        return findings

    @staticmethod
    def _is_set_expr(node: ast.AST, set_names: Set[str]) -> bool:
        if isinstance(node, ast.Set) or isinstance(node, ast.SetComp):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ("set", "frozenset"):
            return True
        if isinstance(node, ast.Name) and node.id in set_names:
            return True
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.Sub)):
            # set algebra keeps set-ness: s1 | s2, s & t, s - t
            return (UnorderedFingerprintRule._is_set_expr(
                        node.left, set_names)
                    or UnorderedFingerprintRule._is_set_expr(
                        node.right, set_names))
        return False

    @classmethod
    def _unordered_why(cls, it: ast.AST,
                       set_names: Set[str]) -> Optional[str]:
        if cls._is_set_expr(it, set_names):
            if isinstance(it, ast.Name):
                return f"set '{it.id}'"
            return "a set expression"
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute) \
                and it.func.attr == "values" and not it.args:
            return "dict.values() (insertion-order dependent)"
        return None
