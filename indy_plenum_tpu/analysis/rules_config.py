"""Rule: config-knob cross-check + the generated knob registry.

``config-knob`` — the knob surface (``Catchup*``, ``Governor*``,
``Ingress*``, ...) has grown PR-over-PR with no registry: a typo'd
``config.CatchupMaxRetrys`` read silently evaluates the getattr default
forever, and a knob nobody reads anymore ships as dead documentation.
This rule cross-checks both directions over the WHOLE package:

- every ``config.X`` / ``getattr(config, "X", ...)`` attribute read
  must resolve to a field (or method) of :class:`~indy_plenum_tpu.
  config.Config`;
- every field defined in ``config.py`` must be read somewhere in the
  analyzed paths (knobs consumed only by out-of-package scripts carry a
  pragma on their definition line saying so).

The collected read map doubles as the knob REGISTRY:
``scripts/lint_determinism.py --emit-knobs`` renders it as the markdown
table in the README — config knobs finally documented in one generated
place.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ModuleInfo, Project, Rule, resolve_call_name

__all__ = ["ConfigKnobRule"]

# receiver terminal names that denote a Config instance ("cfg" is NOT
# here: the repo uses it for non-Config locals; names assigned from
# getConfig(...) are tainted per-module instead)
_CONFIG_NAMES = {"config", "_config"}
# attribute names on Config that are machinery, not knobs
_NON_KNOB_ATTRS = {"overlay", "replicas_count", "governor_bounds"}


def _receiver_terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


@dataclass
class _KnobDef:
    name: str
    line: int
    default: str
    pragma_reason: str = ""  # the def-line pragma's justification


class ConfigKnobRule(Rule):
    name = "config-knob"
    summary = ("config.X reads must resolve to a default in config.py; "
               "every defined knob must be read somewhere")

    def __init__(self) -> None:
        # knob -> sorted reader module paths; populated by finalize and
        # consumed by the --emit-knobs registry renderer
        self.registry: Dict[str, List[str]] = {}
        self.knob_defs: Dict[str, _KnobDef] = {}
        self._config_path = "config.py"
        self._reads: List[Tuple[str, int, int, str]] = []

    # --- per-module: collect reads -------------------------------------

    def check_module(self, module: ModuleInfo) -> List[Finding]:
        is_config_py = module.path.endswith("/config.py") \
            or module.path == "config.py"
        # names assigned from getConfig(...) are Config instances too
        config_locals = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                canon = resolve_call_name(node.value.func, module.imports)
                if canon is not None and canon.endswith("getConfig"):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            config_locals.add(tgt.id)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute):
                recv = node.value
                term = _receiver_terminal(recv)
                if term in _CONFIG_NAMES or (
                        isinstance(recv, ast.Name)
                        and recv.id in config_locals):
                    # canonical dotted base through import aliases, so
                    # foreign `.config` objects (jax.config.update)
                    # are skipped
                    base = resolve_call_name(recv, module.imports)
                    if base is not None and (base.startswith("jax.")
                                             or base == "jax"):
                        continue
                    self._note_read(module.path, node.lineno,
                                    node.col_offset, node.attr)
                elif is_config_py and isinstance(recv, ast.Name) \
                        and recv.id == "self":
                    # Config methods reading their own fields count as
                    # consumption (callers reach them via the method)
                    self._note_read(module.path, node.lineno,
                                    node.col_offset, node.attr)
            elif isinstance(node, ast.Call):
                canon = resolve_call_name(node.func, module.imports)
                if canon == "getattr" and len(node.args) >= 2:
                    term = _receiver_terminal(node.args[0])
                    if (term in _CONFIG_NAMES or term in config_locals) \
                            and isinstance(node.args[1], ast.Constant) \
                            and isinstance(node.args[1].value, str):
                        self._note_read(module.path, node.lineno,
                                        node.col_offset,
                                        node.args[1].value)
        if is_config_py:
            self._collect_defs(module)
        return []

    def _note_read(self, path: str, line: int, col: int,
                   attr: str) -> None:
        if attr.startswith("__") or attr in _NON_KNOB_ATTRS:
            return
        self._reads.append((path, line, col, attr))

    def _collect_defs(self, module: ModuleInfo) -> None:
        self._config_path = module.path
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef) and node.name == "Config":
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) \
                            and isinstance(stmt.target, ast.Name):
                        default = (ast.unparse(stmt.value)
                                   if stmt.value is not None else "")
                        # same placement contract as suppressing_pragma:
                        # a line-above pragma counts only when
                        # standalone, or a trailing neighbor would leak
                        # its justification onto the NEXT knob
                        reason = ""
                        for line in (stmt.lineno, stmt.lineno - 1):
                            prag = module.pragmas.get(line)
                            if prag is None:
                                continue
                            if line == stmt.lineno - 1 \
                                    and not prag.standalone:
                                continue
                            if self.name in prag.rules:
                                reason = prag.reason
                                break
                        self.knob_defs[stmt.target.id] = _KnobDef(
                            name=stmt.target.id, line=stmt.lineno,
                            default=default, pragma_reason=reason)

    # --- cross-module verdicts -----------------------------------------

    def finalize(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        if not self.knob_defs:
            # config.py outside the analyzed set (rule fixtures): only
            # the read map is available, no cross-check possible
            self._reads.clear()
            return findings
        config_path = self._config_path
        read_by: Dict[str, Set[str]] = {}
        for path, line, col, attr in self._reads:
            if attr in self.knob_defs:
                read_by.setdefault(attr, set()).add(path)
            else:
                findings.append(Finding(
                    rule=self.name, path=path, line=line, col=col,
                    message=f"config knob '{attr}' has no default in "
                            "config.py — typo'd reads evaluate their "
                            "getattr fallback forever"))
        for knob, kdef in self.knob_defs.items():
            readers = read_by.get(knob, set())
            # a knob read ONLY inside config.py's own methods without
            # any caller module is still an orphan — require a reader
            # outside the defining module OR a method-mediated read
            # (method reads count: the method has package callers)
            if not readers:
                findings.append(Finding(
                    rule=self.name, path=config_path, line=kdef.line,
                    col=0,
                    message=f"config knob '{knob}' is defined but "
                            "never read in the analyzed paths — dead "
                            "surface (delete it, or pragma with where "
                            "it IS read)"))
        self.registry = {k: sorted(v) for k, v in read_by.items()}
        self._reads.clear()
        return findings

    # --- registry rendering (--emit-knobs) -----------------------------

    def render_registry(self) -> str:
        """Markdown table of every defined knob: default + readers.
        Deterministic: knobs in definition order, readers sorted."""
        lines = ["| Knob | Default | Read by |",
                 "| --- | --- | --- |"]
        for knob, kdef in sorted(self.knob_defs.items(),
                                 key=lambda kv: kv[1].line):
            readers = self.registry.get(knob, [])
            shown = ", ".join(
                f"`{r.split('indy_plenum_tpu/')[-1]}`" for r in readers
                if not r.endswith("config.py"))
            if not shown:
                shown = (f"_{kdef.pragma_reason}_"
                         if kdef.pragma_reason else "_(config.py only)_")
            default = kdef.default.replace("|", "\\|")
            lines.append(f"| `{knob}` | `{default}` | {shown} |")
        return "\n".join(lines)
