"""Rule-visitor framework for the determinism & hot-path analyzer.

Every fingerprint this repo gates on — ``ordered_hash``, ``trace_hash``,
``journey_hash``, ``shed_hash``, the chaos ``replay_command`` — rests on
seeded byte-identical replay, the property RBFT's master-vs-backup
monitoring needs (Aublin et al., ICDCS 2013). The dynamic gates in
``scripts/check_dispatch_budget.py`` re-run pools and diff those
fingerprints, but they only cover the paths their seeds exercise. This
package enforces the same contracts at the SOURCE level: pure-AST rule
visitors (no jax import — the analyzer must run anywhere, instantly)
walk every module and flag the hazard *class* once, for all current and
future code.

Architecture:

- :class:`Rule` — a named check. ``check_module`` sees one parsed
  module; ``finalize`` sees the whole project (for cross-module rules
  like the config-knob registry).
- :class:`ModuleInfo` / :class:`Project` — parsed source + pragma table
  + an import-alias map (``import time as _t`` resolves ``_t.monotonic``
  to the canonical ``time.monotonic``).
- :class:`Analyzer` — deterministic driver: files are discovered in
  sorted order, findings are sorted on a total key, and
  ``findings_hash`` (sha256 over the canonical JSON rendering) is
  byte-identical across runs — the static gate replays the analysis and
  diffs the hash exactly like the dynamic gates diff ``ordered_hash``.

Suppression is two-layer (:mod:`.pragmas`): inline
``# da: allow[rule] -- reason`` pragmas (reason REQUIRED — a reasonless
pragma is itself a finding) and an optional baseline file for staged
burn-downs. The shipped baseline is EMPTY: new findings fail closed.
"""
from __future__ import annotations

import ast
import hashlib
import json
from dataclasses import dataclass, field, replace
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .pragmas import Pragma, parse_pragmas, pragma_findings

__all__ = [
    "Finding", "ModuleInfo", "Project", "Rule", "Analyzer", "Report",
    "attach_parents", "resolve_call_name", "build_import_map",
    "iter_scope", "terminal_name", "is_sink_call", "SINK_TERMINALS",
]

# sink names whose inputs must be reproducible bytes — shared by the
# hash-id-flow and unordered-fingerprint rules so they can never
# disagree about what counts as a fingerprint sink
SINK_TERMINALS = frozenset({
    "sha256", "sha512", "sha1", "md5", "blake2b", "blake2s",
    "sha3_256", "to_jsonl",
})


@dataclass(frozen=True)
class Finding:
    """One analyzer hit. Frozen + totally ordered so reports sort and
    hash deterministically."""

    rule: str
    path: str  # repo-relative posix path
    line: int
    col: int
    message: str
    suppressed: str = ""  # "" | "pragma" | "baseline"
    reason: str = ""  # pragma justification when suppressed
    # occurrence ordinal among same-(rule, path, message) findings in
    # line order: keeps baseline keys line-drift-proof WITHOUT letting
    # one baselined entry suppress every future identical finding in
    # the file (assigned by the Analyzer)
    ordinal: int = 0

    def sort_key(self) -> Tuple:
        return (self.path, self.line, self.col, self.rule, self.message)

    def baseline_key(self) -> str:
        """Line-number-free identity for baseline matching (lines drift
        as files are edited; rule+path+message+ordinal do not)."""
        digest = hashlib.sha256(self.message.encode()).hexdigest()[:16]
        return f"{self.rule}|{self.path}|{digest}|{self.ordinal}"

    def to_dict(self) -> Dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message,
            "suppressed": self.suppressed, "reason": self.reason,
            "ordinal": self.ordinal,
        }

    def render(self) -> str:
        tag = f" [{self.suppressed}]" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}: {self.message}{tag}")


def terminal_name(func: ast.AST) -> Optional[str]:
    """The rightmost name of a call target (``x.y.sha256`` -> sha256)."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def is_sink_call(node: ast.Call) -> bool:
    """Does this call feed a fingerprint (hash/serialization) sink?"""
    name = terminal_name(node.func)
    if name is None:
        return False
    return name in SINK_TERMINALS or name.endswith("_hash")


def iter_scope(fn):
    """Nodes in ``fn``'s OWN scope: descends everything except nested
    function/lambda definitions, which are visited as their own scopes
    by per-function rules (prevents duplicate findings and cross-scope
    taint bleed)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def attach_parents(tree: ast.AST) -> None:
    """Annotate every node with ``.da_parent`` so rules can walk
    ancestor chains (guard detection needs enclosing If/IfExp/BoolOp)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.da_parent = node  # type: ignore[attr-defined]


def build_import_map(tree: ast.AST) -> Dict[str, str]:
    """alias -> canonical dotted path, from every import statement.

    ``import numpy as np``            -> {"np": "numpy"}
    ``from time import perf_counter`` -> {"perf_counter": "time.perf_counter"}
    ``from datetime import datetime`` -> {"datetime": "datetime.datetime"}
    """
    mapping: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                mapping[alias.asname or alias.name.split(".")[0]] = \
                    alias.name if alias.asname else alias.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            # relative imports map without the package prefix
            # (``from ..tpu import ed25519`` -> "tpu.ed25519"): enough
            # for scope checks like imports_module("tpu"). Bare
            # relative imports (``from . import ed25519``) map to the
            # sibling's own name.
            for alias in node.names:
                if alias.name == "*":
                    continue
                mapping[alias.asname or alias.name] = \
                    (f"{node.module}.{alias.name}" if node.module
                     else alias.name)
    return mapping


def resolve_call_name(func: ast.AST,
                      imports: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name of a call target, through import aliases:
    ``_time.perf_counter`` -> ``time.perf_counter``. None when the base
    is not a plain name (method calls on computed receivers)."""
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    base = imports.get(node.id, node.id)
    parts.append(base)
    return ".".join(reversed(parts))


@dataclass
class ModuleInfo:
    """One parsed module plus the per-line pragma table."""

    path: str  # repo-relative posix
    source: str
    tree: ast.Module
    pragmas: Dict[int, Pragma]
    imports: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_source(cls, source: str, path: str) -> "ModuleInfo":
        tree = ast.parse(source)
        attach_parents(tree)
        return cls(path=path, source=source, tree=tree,
                   pragmas=parse_pragmas(source),
                   imports=build_import_map(tree))

    def imports_module(self, dotted_prefix: str) -> bool:
        """True when any import resolves into ``dotted_prefix`` (e.g.
        ``jax`` matches ``import jax.numpy as jnp``)."""
        for canon in self.imports.values():
            if canon == dotted_prefix \
                    or canon.startswith(dotted_prefix + "."):
                return True
        return False

    def suppressing_pragma(self, finding: Finding) -> Optional[Pragma]:
        """The pragma covering ``finding``, if any: same line, a
        standalone pragma on the line above, or a file-level
        ``allow-file`` pragma."""
        for line in (finding.line, finding.line - 1):
            prag = self.pragmas.get(line)
            if prag is None:
                continue
            if line == finding.line - 1 and not prag.standalone:
                continue  # trailing pragma on the previous line covers
                # that line only; standalone pragmas cover the next
            if finding.rule in prag.rules:
                return prag
        for prag in self.pragmas.values():
            if prag.file_level and finding.rule in prag.rules:
                return prag
        return None


@dataclass
class Project:
    """Every analyzed module, in deterministic (sorted-path) order."""

    modules: List[ModuleInfo]

    def by_path(self, suffix: str) -> Optional[ModuleInfo]:
        for mod in self.modules:
            if mod.path.endswith(suffix):
                return mod
        return None


class Rule:
    """Base class: subclasses set ``name``/``summary`` and override
    ``check_module`` (per-module findings) and/or ``finalize``
    (cross-module findings, run after every module was seen)."""

    name: str = ""
    summary: str = ""

    def check_module(self, module: ModuleInfo) -> List[Finding]:
        return []

    def finalize(self, project: Project) -> List[Finding]:
        return []


@dataclass
class Report:
    """Sorted findings + the byte-stable fingerprint the gate diffs."""

    findings: List[Finding]
    files_analyzed: int
    rules: List[str]

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    @property
    def findings_hash(self) -> str:
        """sha256 over the canonical JSON rendering of EVERY finding,
        suppression state included — editing a pragma moves the hash, so
        the static gate's two-run diff covers the suppression layer too."""
        payload = json.dumps([f.to_dict() for f in self.findings],
                             sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(payload.encode()).hexdigest()

    def to_dict(self) -> Dict:
        return {
            "files_analyzed": self.files_analyzed,
            "rules": self.rules,
            "findings": [f.to_dict() for f in self.findings],
            "total": len(self.findings),
            "unsuppressed": len(self.unsuppressed),
            "suppressed": len(self.suppressed),
            "findings_hash": self.findings_hash,
        }


class Analyzer:
    """Deterministic driver: sorted file walk, sorted findings, pragma +
    baseline suppression applied uniformly."""

    def __init__(self, rules: Sequence[Rule],
                 known_rules: Optional[set] = None):
        """``known_rules``: the FULL catalog for the pragma self-lint.
        Defaults to the active rules; a filtered run (CLI ``--rule``)
        must pass the full set or pragmas naming unfiltered rules would
        false-positive as 'unknown rule'."""
        names = [r.name for r in rules]
        assert len(names) == len(set(names)), "duplicate rule names"
        self.rules = list(rules)
        self.known_rules = (set(known_rules) if known_rules is not None
                            else set(names))

    # --- discovery ------------------------------------------------------

    @staticmethod
    def discover(paths: Iterable[str]) -> List[Tuple[str, Path]]:
        """(repo-relative posix path, absolute Path) for every .py file
        under ``paths``, sorted — the walk order is part of the
        determinism contract. Relative names are anchored at each input
        path's parent, so ``lint indy_plenum_tpu`` names files
        ``indy_plenum_tpu/...`` regardless of the CWD they resolve from."""
        out: List[Tuple[str, Path]] = []
        for raw in paths:
            p = Path(raw).resolve()
            if not p.exists():
                # fail CLOSED: a typo'd path or wrong CWD must never
                # report the package clean
                raise FileNotFoundError(
                    f"analysis path does not exist: {raw}")
            # anchor at the PACKAGE root (nearest ancestor without an
            # __init__.py), so single-file and subdirectory runs name
            # modules exactly like a whole-package walk would —
            # path-prefix allowlists and scope checks depend on it
            root = p.parent
            probe = p if p.is_dir() else p.parent
            while (probe / "__init__.py").exists() \
                    and probe.parent != probe:
                probe = probe.parent
                root = probe
            if p.is_file():
                out.append((p.relative_to(root).as_posix(), p))
                continue
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" in f.parts:
                    continue
                out.append((f.relative_to(root).as_posix(), f))
        out.sort()
        return out

    # --- analysis -------------------------------------------------------

    def analyze_modules(self, modules: List[ModuleInfo],
                        baseline_keys: Optional[set] = None) -> Report:
        project = Project(modules=modules)
        findings: List[Finding] = []
        for mod in modules:
            findings.extend(pragma_findings(
                mod.path, mod.pragmas, known_rules=self.known_rules))
            for rule in self.rules:
                findings.extend(rule.check_module(mod))
        for rule in self.rules:
            findings.extend(rule.finalize(project))

        # occurrence ordinals per (rule, path, message) in line order,
        # BEFORE baseline matching — they are part of the baseline key
        findings.sort(key=Finding.sort_key)
        seen_counts: Dict[Tuple, int] = {}
        numbered: List[Finding] = []
        for f in findings:
            key = (f.rule, f.path, f.message)
            n = seen_counts.get(key, 0)
            seen_counts[key] = n + 1
            numbered.append(replace(f, ordinal=n) if n else f)
        findings = numbered

        by_path = {mod.path: mod for mod in modules}
        resolved: List[Finding] = []
        for f in findings:
            mod = by_path.get(f.path)
            prag = mod.suppressing_pragma(f) if mod is not None else None
            if f.rule == "pragma":
                pass  # the suppression layer's self-lint is never
                # suppressible — not by pragma, not by baseline
            elif prag is not None:
                f = replace(f, suppressed="pragma", reason=prag.reason)
            elif baseline_keys and f.baseline_key() in baseline_keys:
                f = replace(f, suppressed="baseline")
            resolved.append(f)
        resolved.sort(key=Finding.sort_key)
        return Report(findings=resolved, files_analyzed=len(modules),
                      rules=sorted(r.name for r in self.rules))

    def analyze_paths(self, paths: Iterable[str],
                      baseline_keys: Optional[set] = None) -> Report:
        modules = []
        for rel, abs_path in self.discover(paths):
            modules.append(ModuleInfo.from_source(
                abs_path.read_text(), path=rel))
        return self.analyze_modules(modules, baseline_keys=baseline_keys)
