"""Rule: hot-path trace call sites must guard allocation on `.enabled`.

``trace-guard`` — the flight recorder's disabled mode (``NULL_TRACE``)
makes ``record()``/``span()`` free, but the ARGUMENTS are built by the
caller before the no-op method ever sees them: a dict display, a tuple
key or an f-string allocates on every pass through the hot loop even
when tracing is off. The repo's contract (observability/trace.py
docstring, proven dynamically for exercised sites by the strict
NULL_TRACE test) is that every call site with allocating args is guarded
on ``trace.enabled`` — this rule covers ALL sites in the hot-path
packages statically, exercised or not.

Recognized guard shapes::

    if self.trace.enabled: self.trace.record(..., args={...})
    trace_on = self.trace.enabled        # guard-name
    if trace_on: ...
    with t.span(...) if t.enabled else _NO_SPAN: ...
    t.enabled and t.record(...)
    if not trace.enabled: return         # early-exit guard
    ...unguarded-after-return is guarded...

Calls whose every argument is a constant or a plain name/attribute load
are exempt — they allocate nothing.
"""
from __future__ import annotations

import ast
import itertools
from typing import List, Set

from .core import Finding, ModuleInfo, Rule, iter_scope

__all__ = ["TraceGuardRule"]

# hot-path packages: the dispatch plane, the 3PC services, admission,
# and both transports (the tick loop calls straight into all four)
_SCOPE = (
    "indy_plenum_tpu/tpu/",
    "indy_plenum_tpu/server/consensus/",
    "indy_plenum_tpu/ingress/",
    "indy_plenum_tpu/network/",
)


def _is_trace_name(name) -> bool:
    return name is not None and ("trace" in name.lower()
                                 or name in ("trc", "recorder"))


def _terminal_of(node: ast.AST):
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _receiver_is_trace(func: ast.Attribute) -> bool:
    """True for <recv>.record / <recv>.span where the receiver's
    terminal name smells like a trace recorder."""
    return _is_trace_name(_terminal_of(func.value))


def _allocates(node: ast.AST) -> bool:
    """Does evaluating this argument expression allocate? Constants and
    plain name/attribute loads don't; displays, calls, f-strings,
    arithmetic and subscripts do."""
    if isinstance(node, ast.Constant):
        return False
    if isinstance(node, ast.Name):
        return False
    if isinstance(node, ast.Attribute):
        return _allocates(node.value)
    if isinstance(node, ast.UnaryOp) \
            and isinstance(node.operand, ast.Constant):
        return False
    return True


def _mentions_enabled(expr: ast.AST, guard_names: Set[str]) -> bool:
    """A TRACE-enabled test: ``<trace-ish>.enabled`` or a guard-name
    derived from one. An unrelated feature flag's ``.enabled``
    (``self.metrics.enabled``) is NOT a trace guard — accepting it
    would let any flag silence the rule."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Attribute) and sub.attr == "enabled" \
                and _is_trace_name(_terminal_of(sub.value)):
            return True
        if isinstance(sub, ast.Name) and sub.id in guard_names:
            return True
    return False


def _test_polarity(test: ast.AST, guard_names: Set[str]) -> int:
    """+1 when the test is TRUE while tracing is on (plain mention),
    -1 when it is the negation (``not trace.enabled`` — true while
    tracing is OFF), 0 when tracing is not involved. Polarity decides
    WHICH branch of an If/IfExp counts as guarded."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return -1 if _mentions_enabled(test.operand, guard_names) else 0
    return 1 if _mentions_enabled(test, guard_names) else 0


class TraceGuardRule(Rule):
    name = "trace-guard"
    summary = ("trace.record()/span() with allocating args not guarded "
               "on trace.enabled in a hot-path package")

    def check_module(self, module: ModuleInfo) -> List[Finding]:
        if not any(module.path.startswith(p) for p in _SCOPE):
            return []
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            findings.extend(self._check_function(module, fn))
        return findings

    def _check_function(self, module: ModuleInfo, fn) -> List[Finding]:
        # per-scope walk (iter_scope): nested defs are their own scopes
        guard_names: Set[str] = set()
        for node in iter_scope(fn):
            # only POSITIVE derivations become guard names: `off = not
            # trace.enabled` guards the DISABLED branch, not this one
            if isinstance(node, ast.Assign) \
                    and _test_polarity(node.value, set()) > 0:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        guard_names.add(tgt.id)

        # early-exit guards: every node lexically after
        # `if not <enabled>: return/continue/raise` in the same block
        shielded: Set[int] = set()
        for node in itertools.chain((fn,), iter_scope(fn)):
            for block in (getattr(node, "body", None),
                          getattr(node, "orelse", None),
                          getattr(node, "finalbody", None)):
                if not isinstance(block, list):
                    continue
                for i, stmt in enumerate(block):
                    if self._is_early_exit_guard(stmt, guard_names):
                        for later in block[i + 1:]:
                            for sub in ast.walk(later):
                                shielded.add(id(sub))

        findings: List[Finding] = []
        for node in iter_scope(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("record", "span")
                    and _receiver_is_trace(node.func)):
                continue
            alloc_args = [a for a in list(node.args)
                          + [kw.value for kw in node.keywords]
                          if _allocates(a)]
            if not alloc_args:
                continue
            if id(node) in shielded:
                continue
            if self._is_guarded(node, fn, guard_names):
                continue
            findings.append(Finding(
                rule=self.name, path=module.path,
                line=node.lineno, col=node.col_offset,
                message=f"{ast.unparse(node.func)}(...) in {fn.name}() "
                        "builds allocating args unguarded — wrap in "
                        "'if trace.enabled:' (or '... if trace.enabled "
                        "else _NO_SPAN' for spans) so a disabled "
                        "recorder costs one branch"))
        return findings

    @staticmethod
    def _is_early_exit_guard(stmt: ast.AST,
                             guard_names: Set[str]) -> bool:
        if not isinstance(stmt, ast.If) or stmt.orelse:
            return False
        test = stmt.test
        if not (isinstance(test, ast.UnaryOp)
                and isinstance(test.op, ast.Not)
                and _mentions_enabled(test.operand, guard_names)):
            return False
        last = stmt.body[-1]
        return isinstance(last, (ast.Return, ast.Continue, ast.Raise))

    @staticmethod
    def _is_guarded(node: ast.AST, fn, guard_names: Set[str]) -> bool:
        cur = getattr(node, "da_parent", None)
        while cur is not None and cur is not fn.da_parent:  # type: ignore
            if isinstance(cur, ast.If):
                # polarity picks the guarded branch: body for
                # `if trace.enabled`, orelse for `if not trace.enabled`
                pol = _test_polarity(cur.test, guard_names)
                branch = cur.body if pol > 0 else \
                    cur.orelse if pol < 0 else []
                if any(id(node) == id(sub)
                       for s in branch for sub in ast.walk(s)):
                    return True
            if isinstance(cur, ast.IfExp):
                pol = _test_polarity(cur.test, guard_names)
                branch = cur.body if pol > 0 else \
                    cur.orelse if pol < 0 else None
                if branch is not None and any(
                        id(node) == id(sub)
                        for sub in ast.walk(branch)):
                    return True
            if isinstance(cur, ast.BoolOp) \
                    and isinstance(cur.op, ast.And):
                for i, val in enumerate(cur.values):
                    if any(id(node) == id(sub) for sub in ast.walk(val)):
                        if any(_mentions_enabled(prev, guard_names)
                               for prev in cur.values[:i]):
                            return True
                        break
            if cur is fn:
                break
            cur = getattr(cur, "da_parent", None)
        return False
