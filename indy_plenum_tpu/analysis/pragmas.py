"""Pragma & baseline suppression layer.

Grammar (one comment, anywhere on a line)::

    # da: allow[rule]               <- INVALID: reason required
    # da: allow[rule] -- reason     <- suppresses `rule` on this line
    # da: allow[r1,r2] -- reason    <- multiple rules
    # da: allow-file[rule] -- reason  <- suppresses `rule` module-wide

Placement: a trailing pragma covers its own physical line; a pragma on a
line of its own (``standalone``) covers the NEXT line too, for call
sites that don't fit a trailing comment. ``allow-file`` belongs near the
top of a module and sanctions a whole seam (e.g. a wall-clock
offload-steering module) — use sparingly, it also covers future code in
that file.

A pragma without a ``-- reason`` justification, or naming a rule the
analyzer doesn't ship, is ITSELF a finding (rule ``pragma``) — the
suppression layer cannot rot silently.

Baselines: a JSON file of ``Finding.baseline_key()`` strings lets a
staged burn-down land incrementally. The repo ships an EMPTY baseline
(``indy_plenum_tpu/analysis/baseline.json``) so every new finding fails
closed; ``--write-baseline`` exists for downstream forks mid-burn-down.
"""
from __future__ import annotations

import io
import json
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Set, Tuple

__all__ = ["Pragma", "parse_pragmas", "pragma_findings",
           "load_baseline", "write_baseline"]

_PRAGMA_RE = re.compile(
    r"#\s*da:\s*(?P<kind>allow|allow-file)\s*"
    r"\[(?P<rules>[^\]]*)\]\s*(?:--\s*(?P<reason>\S.*))?$")


@dataclass
class Pragma:
    line: int
    rules: Tuple[str, ...]
    reason: str
    file_level: bool = False
    standalone: bool = False  # comment-only line: also covers line + 1


def parse_pragmas(source: str) -> Dict[int, Pragma]:
    """line number (1-based) -> Pragma for every ``# da:`` COMMENT.

    Tokenize-based, so pragma grammar quoted inside docstrings or
    string literals (like the examples above) never parses as a real
    suppression."""
    out: Dict[int, Pragma] = {}
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _PRAGMA_RE.search(tok.string)
        if m is None:
            continue
        idx = tok.start[0]
        rules = tuple(sorted({r.strip() for r in
                              m.group("rules").split(",") if r.strip()}))
        out[idx] = Pragma(
            line=idx, rules=rules, reason=(m.group("reason") or "").strip(),
            file_level=m.group("kind") == "allow-file",
            standalone=tok.string.strip() == tok.line.strip())
    return out


def pragma_findings(path: str, pragmas: Dict[int, Pragma],
                    known_rules: Set[str]) -> List:
    """Self-lint of the suppression layer: reasonless pragmas and
    pragmas naming unknown rules are findings (rule ``pragma``, never
    itself suppressible)."""
    from .core import Finding  # local import: core imports this module

    findings: List[Finding] = []
    for prag in pragmas.values():
        if not prag.reason:
            findings.append(Finding(
                rule="pragma", path=path, line=prag.line, col=0,
                message="pragma missing justification: every "
                        "'# da: allow[...]' must carry '-- reason'"))
        if not prag.rules:
            findings.append(Finding(
                rule="pragma", path=path, line=prag.line, col=0,
                message="pragma names no rules"))
        for rule in prag.rules:
            if rule not in known_rules:
                findings.append(Finding(
                    rule="pragma", path=path, line=prag.line, col=0,
                    message=f"pragma names unknown rule '{rule}'"))
    return findings


def load_baseline(path: str) -> Set[str]:
    p = Path(path)
    if not p.exists():
        return set()
    data = json.loads(p.read_text())
    return set(data.get("findings", []))


def write_baseline(path: str, keys: List[str]) -> None:
    Path(path).write_text(json.dumps(
        {"findings": sorted(set(keys))}, indent=2) + "\n")
