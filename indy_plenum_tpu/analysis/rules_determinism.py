"""Rules: nondeterminism sources & builtin hash()/id() feeding sinks.

``nondet-source`` — every fingerprint in this repo (``ordered_hash``,
``trace_hash``, ``shed_hash``, ``journey_hash``) assumes a seeded run
replays byte-identically. A wall-clock read, an unseeded RNG or an
``os.urandom`` draw anywhere on a consensus-reachable path breaks that
silently — exactly the hazard class RBFT's master-vs-backup monitoring
cannot tolerate. Sanctioned seams (crypto key generation, the deployed
Node's injected ``perf_counter`` trace clock) are allowlisted by module;
everything else needs a line pragma naming WHY the reading never feeds
consensus state or a fingerprint.

``hash-id-flow`` — builtin ``hash()`` is salted per-process
(PYTHONHASHSEED) and ``id()`` is an allocator address: neither may ever
reach a ``*_hash`` / serialization sink. ``__hash__`` implementations
are exempt (dict/set identity is in-process by definition).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import (
    Finding,
    ModuleInfo,
    Rule,
    is_sink_call,
    iter_scope,
    resolve_call_name,
    terminal_name,
)

__all__ = ["NondeterminismSourceRule", "HashIdFlowRule"]

# canonical call targets that read wall clocks / entropy
_FORBIDDEN_EXACT = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "time.process_time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
    "uuid.uuid1", "uuid.uuid4",
    "os.urandom",
    "secrets.token_bytes", "secrets.token_hex", "secrets.token_urlsafe",
    "secrets.randbits", "secrets.choice", "secrets.randbelow",
}
# stdlib `random` module-level draws ride the shared unseeded instance
_RANDOM_DRAWS = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "getrandbits", "randbytes", "gauss",
    "betavariate", "expovariate", "normalvariate", "lognormvariate",
    "triangular", "vonmisesvariate", "paretovariate", "weibullvariate",
}
# numpy.random direct draws (the legacy global RandomState)
_NP_DRAWS = {
    "rand", "randn", "randint", "random", "random_sample", "ranf",
    "sample", "choice", "permutation", "shuffle", "normal", "uniform",
    "standard_normal", "bytes", "seed",
}
# constructors that are fine WHEN SEEDED (an argument present)
_SEEDABLE = {
    "random.Random", "numpy.random.RandomState",
    "numpy.random.default_rng", "numpy.random.SeedSequence",
    "numpy.random.Generator",
}


class NondeterminismSourceRule(Rule):
    name = "nondet-source"
    summary = ("wall-clock / entropy / unseeded-RNG reads outside the "
               "sanctioned clock & key-generation seams")

    # Sanctioned seams (module-path prefixes): crypto KEY GENERATION is
    # entropy by design; the analysis package itself never runs inside a
    # consensus process.
    ALLOWLIST = (
        "indy_plenum_tpu/crypto/",
        "indy_plenum_tpu/analysis/",
    )

    def check_module(self, module: ModuleInfo) -> List[Finding]:
        if any(module.path.startswith(p) for p in self.ALLOWLIST):
            return []
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            canon = resolve_call_name(node.func, module.imports)
            if canon is None:
                continue
            msg = self._classify(canon, node)
            if msg is not None:
                findings.append(Finding(
                    rule=self.name, path=module.path,
                    line=node.lineno, col=node.col_offset, message=msg))
        return findings

    @staticmethod
    def _classify(canon: str, node: ast.Call) -> Optional[str]:
        if canon in _FORBIDDEN_EXACT:
            return (f"call to {canon}() — wall-clock/entropy read; "
                    "seeded replay cannot reproduce it (inject the "
                    "timer/seed, or pragma a sanctioned seam)")
        if canon in _SEEDABLE:
            if not node.args and not node.keywords:
                return (f"{canon}() constructed WITHOUT a seed — every "
                        "RNG must derive from the run seed")
            return None
        if canon == "random.SystemRandom":
            return "random.SystemRandom is os-entropy by definition"
        parts = canon.split(".")
        if parts[0] == "random" and len(parts) == 2 \
                and parts[1] in _RANDOM_DRAWS:
            return (f"module-level {canon}() rides the shared UNSEEDED "
                    "random instance — draw from a random.Random(seed)")
        if canon.startswith("numpy.random.") \
                and parts[-1] in _NP_DRAWS:
            return (f"{canon}() rides numpy's global RandomState — "
                    "draw from np.random.RandomState(seed) / "
                    "default_rng(seed)")
        return None


class HashIdFlowRule(Rule):
    name = "hash-id-flow"
    summary = ("builtin hash()/id() feeding a *_hash or serialization "
               "sink (hash() is PYTHONHASHSEED-salted, id() is an "
               "address)")

    def check_module(self, module: ModuleInfo) -> List[Finding]:
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == "__hash__":
                continue  # in-process dict/set identity is the POINT
            findings.extend(self._check_function(module, fn))
        return findings

    def _check_function(self, module: ModuleInfo, fn) -> List[Finding]:
        # taint-lite: names assigned (directly) from hash()/id() calls;
        # iter_scope keeps nested functions out — they are visited as
        # their own scopes, so no duplicate findings or taint bleed.
        # Accumulator names assigned from sink constructors
        # (``acc = hashlib.sha256()``) make ``acc.update(...)`` a sink
        # too — the streaming idiom must not escape the rule.
        tainted: Set[str] = set()
        accumulators: Set[str] = set()
        for node in iter_scope(fn):
            if not isinstance(node, ast.Assign):
                continue
            if isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Name) \
                    and node.value.func.id in ("hash", "id"):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        tainted.add(tgt.id)
            elif isinstance(node.value, ast.Call) \
                    and is_sink_call(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        accumulators.add(tgt.id)

        def is_sink(node: ast.Call) -> bool:
            if is_sink_call(node):
                return True
            return (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "update"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in accumulators)

        findings: List[Finding] = []
        for node in iter_scope(fn):
            if not (isinstance(node, ast.Call) and is_sink(node)):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    hit: Optional[str] = None
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Name) \
                            and sub.func.id in ("hash", "id"):
                        hit = f"builtin {sub.func.id}()"
                    elif isinstance(sub, ast.Name) and sub.id in tainted:
                        # no line number in the message: baseline keys
                        # hash the message and must survive line drift
                        hit = (f"'{sub.id}' (assigned from builtin "
                               "hash()/id() in this function)")
                    if hit is not None:
                        sink = terminal_name(node.func)
                        findings.append(Finding(
                            rule=self.name, path=module.path,
                            line=node.lineno, col=node.col_offset,
                            message=f"{hit} flows into sink "
                                    f"'{sink}(...)' in {fn.name}() — "
                                    "process-salted/address values must "
                                    "never reach a fingerprint"))
        return findings
