"""Determinism & hot-path hygiene analyzer (pure AST — never imports
jax, so it runs anywhere instantly).

Entry points: :func:`analyze_paths` (what the CLI and the budget
script's ``static_gate`` call), :func:`analyze_source` (rule fixtures
in tests), :data:`ALL_RULES` (the shipped rule catalog) and
:data:`DEFAULT_BASELINE` (the shipped — empty — baseline, so new
findings fail closed).
"""
from __future__ import annotations

import os
from typing import Iterable, List, Optional, Sequence

from .core import (
    Analyzer,
    Finding,
    ModuleInfo,
    Project,
    Report,
    Rule,
)
from .pragmas import Pragma, load_baseline, write_baseline
from .rules_config import ConfigKnobRule
from .rules_determinism import HashIdFlowRule, NondeterminismSourceRule
from .rules_device import BufferDonationRule, DeviceSyncRule
from .rules_hotpath import TraceGuardRule
from .rules_ordering import UnorderedFingerprintRule

__all__ = [
    "Analyzer", "Finding", "ModuleInfo", "Project", "Report", "Rule",
    "Pragma", "ALL_RULES", "DEFAULT_BASELINE", "make_rules",
    "analyze_paths", "analyze_source", "load_baseline", "write_baseline",
]

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


def make_rules() -> List[Rule]:
    """Fresh instances of the full shipped catalog (ConfigKnobRule is
    stateful across a run — never share instances between analyses)."""
    return [
        NondeterminismSourceRule(),
        HashIdFlowRule(),
        UnorderedFingerprintRule(),
        TraceGuardRule(),
        DeviceSyncRule(),
        BufferDonationRule(),
        ConfigKnobRule(),
    ]


ALL_RULES = tuple(r.name for r in make_rules()) + ("pragma",)


def analyze_paths(paths: Iterable[str],
                  rules: Optional[Sequence[Rule]] = None,
                  baseline_path: Optional[str] = None) -> Report:
    """Analyze every .py file under ``paths`` with the shipped rules
    (or ``rules``), applying pragma suppression and the baseline at
    ``baseline_path`` (None -> the shipped empty default)."""
    analyzer = Analyzer(rules if rules is not None else make_rules())
    keys = load_baseline(baseline_path if baseline_path is not None
                         else DEFAULT_BASELINE)
    return analyzer.analyze_paths(paths, baseline_keys=keys)


def analyze_source(source: str, path: str = "fixture.py",
                   rules: Optional[Sequence[Rule]] = None) -> Report:
    """Analyze one in-memory module — the per-rule fixture entry point."""
    analyzer = Analyzer(rules if rules is not None else make_rules())
    return analyzer.analyze_modules(
        [ModuleInfo.from_source(source, path=path)])
