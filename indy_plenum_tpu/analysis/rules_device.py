"""Rules: device-sync discipline & buffer-donation aliasing.

``device-sync`` — the ordering fast path (PR 7) and per-shard pipelined
readbacks (PR 9) exist so the device→host round-trip overlaps a tick of
host work. ONE stray synchronizing call — ``np.asarray`` on a device
value, ``jax.device_get``, ``.block_until_ready()``, or an implicit
``float()``/``int()`` coercion of a jnp value — re-serializes the
pipeline and silently defeats the contract. Host↔device traffic is
sanctioned only inside the readback modules (``tpu/vote_plane.py``,
``tpu/quorum.py``); every other jax-importing module must either stay
on-device or carry a pragma naming why its sync is deliberate (e.g. the
auth batch must resolve before admission decides).

``buffer-donation`` — PR 3's corruption gotcha: on jax 0.4.37's CPU
backend ``jnp.asarray`` ZERO-COPIES suitably aligned host numpy buffers.
A reusable staging buffer (an attribute that outlives the call) handed
to the device through ``asarray`` aliases live in-flight dispatch
memory — the next host write corrupts a vote word mid-flight. Reused
buffers must cross with a forced copy (``jnp.array``); only FRESH
per-call buffers may take the zero-copy path. Until this rule, that
invariant lived in one comment in ``vote_plane.py``.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import (
    Finding,
    ModuleInfo,
    Rule,
    iter_scope,
    resolve_call_name,
)

__all__ = ["DeviceSyncRule", "BufferDonationRule"]


def _jax_tainted_names(fn, imports) -> Set[str]:
    """Names assigned from expressions that touch jax/jnp — one-hop
    intra-function taint, enough for the float()/int() coercion check."""
    tainted: Set[str] = set()
    for node in iter_scope(fn):
        if not isinstance(node, ast.Assign):
            continue
        touches_jax = False
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Name):
                canon = imports.get(sub.id, "")
                if canon == "jax" or canon.startswith("jax."):
                    touches_jax = True
                    break
        if touches_jax:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    tainted.add(tgt.id)
    return tainted


class DeviceSyncRule(Rule):
    name = "device-sync"
    summary = ("host<->device synchronization outside the sanctioned "
               "readback modules (defeats pipelined readbacks)")

    # the two modules whose JOB is the device->host boundary
    ALLOWLIST = (
        "indy_plenum_tpu/tpu/vote_plane.py",
        "indy_plenum_tpu/tpu/quorum.py",
    )

    def check_module(self, module: ModuleInfo) -> List[Finding]:
        if module.path in self.ALLOWLIST:
            return []
        if not self._in_scope(module):
            return []
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            tainted = _jax_tainted_names(fn, module.imports)
            for node in iter_scope(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._classify(node, module, tainted)
                if msg is not None:
                    findings.append(Finding(
                        rule=self.name, path=module.path,
                        line=node.lineno, col=node.col_offset,
                        message=msg + " — a sync outside vote_plane/"
                                "quorum stalls the pipelined-readback "
                                "contract; move it behind the compact "
                                "readback or pragma why this boundary "
                                "crossing is deliberate"))
        # module-level code (import-time table building etc.) is checked
        # too: walk calls not inside any function
        fn_calls = {id(n) for f in ast.walk(module.tree)
                    if isinstance(f, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                    for n in ast.walk(f) if isinstance(n, ast.Call)}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and id(node) not in fn_calls:
                msg = self._classify(node, module, set())
                if msg is not None:
                    findings.append(Finding(
                        rule=self.name, path=module.path,
                        line=node.lineno, col=node.col_offset,
                        message=msg + " at module scope — import-time "
                                "host<->device traffic; pragma if this "
                                "is deliberate table building"))
        return findings

    @staticmethod
    def _in_scope(module: ModuleInfo) -> bool:
        """Modules importing jax directly, any tpu kernel wrapper
        (``from ..tpu import ed25519`` hands back device arrays too),
        or living under tpu/ themselves (siblings get kernels via bare
        ``from . import ...`` imports)."""
        if module.path.startswith("indy_plenum_tpu/tpu/"):
            return True
        if module.imports_module("jax"):
            return True
        for canon in module.imports.values():
            if canon.startswith("tpu.") or ".tpu." in canon \
                    or canon.endswith(".tpu"):
                return True
        return False

    @staticmethod
    def _classify(node: ast.Call, module: ModuleInfo,
                  tainted: Set[str]) -> Optional[str]:
        canon = resolve_call_name(node.func, module.imports)
        if canon == "numpy.asarray":
            return "np.asarray() pulls its argument to host memory"
        if canon in ("jax.device_get", "jax.block_until_ready"):
            return f"{canon.split('.', 1)[1]}() synchronizes device state"
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "block_until_ready":
            return ".block_until_ready() blocks on the device stream"
        if isinstance(node.func, ast.Name) \
                and node.func.id in ("float", "int") and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in tainted:
                return (f"{node.func.id}('{arg.id}') implicitly syncs a "
                        "jnp value to host")
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name):
                    canon_a = module.imports.get(sub.id, "")
                    if canon_a == "jax" or canon_a.startswith("jax."):
                        return (f"{node.func.id}(...) over a jnp "
                                "expression implicitly syncs to host")
        return None


class BufferDonationRule(Rule):
    name = "buffer-donation"
    summary = ("jnp.asarray on a reusable staging buffer (jax 0.4.37 "
               "zero-copy aliasing: reused buffers need the forced "
               "jnp.array copy)")

    def check_module(self, module: ModuleInfo) -> List[Finding]:
        if not module.imports_module("jax"):
            return []
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # names bound from self-attributes in this function: a
            # local alias of a persistent buffer is still the buffer
            attr_aliases: Set[str] = set()
            for node in iter_scope(fn):
                if isinstance(node, ast.Assign) \
                        and self._is_self_attr_load(node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            attr_aliases.add(tgt.id)
            for node in iter_scope(fn):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                canon = resolve_call_name(node.func, module.imports)
                if canon != "jax.numpy.asarray":
                    continue
                if self._is_reused_buffer(node.args[0], attr_aliases):
                    findings.append(Finding(
                        rule=self.name, path=module.path,
                        line=node.lineno, col=node.col_offset,
                        message="jnp.asarray(...) over a persistent "
                                "buffer: jax 0.4.37's CPU backend "
                                "zero-copies aligned numpy memory, so "
                                "the reused buffer aliases in-flight "
                                "dispatch data — use jnp.array(...) "
                                "(forced copy) for buffers that "
                                "outlive the call"))
        return findings

    @staticmethod
    def _is_self_attr_load(node: ast.AST) -> bool:
        while isinstance(node, ast.Subscript):
            node = node.value
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self")

    @classmethod
    def _is_reused_buffer(cls, arg: ast.AST,
                          attr_aliases: Set[str]) -> bool:
        node = arg
        while isinstance(node, ast.Subscript):
            node = node.value
        if cls._is_self_attr_load(node):
            return True
        return isinstance(node, ast.Name) and node.id in attr_aliases
