"""Build-on-first-use loader for the repo's C extensions.

Reference analog: the reference ships prebuilt native wheels
(indy-crypto etc.); here the toolchain image has gcc + CPython headers,
so extensions compile lazily and cache next to their consumer. One
definition of the recipe — ABI-tagged artifact names, mtime-based
rebuild, atomic tmp+rename publish so a concurrent importer never loads
half an ELF — shared by every native module (BN254, base58).
"""
from __future__ import annotations

import importlib.util
import logging
import os
import subprocess
import sysconfig

logger = logging.getLogger(__name__)


def build_native_ext(src_path: str, build_dir: str, name: str,
                     opt: str = "-O3"):
    """Compile ``src_path`` into ``build_dir`` (if stale) and import it.

    Raises on any build/load failure — callers decide whether to fall
    back to a pure-Python implementation.
    """
    src = os.path.abspath(src_path)
    os.makedirs(build_dir, exist_ok=True)
    # ABI-tagged artifact name: a .so built by one CPython must never be
    # loaded into another (segfault or silent pure-Python fallback)
    ext = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so_path = os.path.join(build_dir, f"{name}{ext}")
    if (not os.path.exists(so_path)
            or os.path.getmtime(so_path) < os.path.getmtime(src)):
        include = sysconfig.get_paths()["include"]
        # build to a temp path + atomic rename: a concurrent importer must
        # never load a half-written ELF
        tmp_path = f"{so_path}.tmp.{os.getpid()}"
        cmd = ["gcc", opt, "-shared", "-fPIC", f"-I{include}",
               src, "-o", tmp_path]
        logger.info("building native extension: %s", " ".join(cmd))
        try:
            subprocess.run(cmd, check=True, capture_output=True)
            os.replace(tmp_path, so_path)
        finally:
            if os.path.exists(tmp_path):
                os.unlink(tmp_path)
    spec = importlib.util.spec_from_file_location(name, so_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
