"""Host-platform device provisioning for mesh-sharded CPU runs.

XLA fixes the device topology at backend init, so any script that wants
a virtual CPU mesh must have ``--xla_force_host_platform_device_count``
in ``XLA_FLAGS`` before jax initializes a backend. This helper is the
ONE definition of that append-if-absent dance (bench.py, chaos_run.py,
check_dispatch_budget.py, profile_rbft.py all provision through it) —
import-light: it touches ``os.environ`` only, so it is safe to call
before jax is even imported.

Callers must provision ONLY when a sharded run is actually requested:
baseline-tracked measurements (kernel benches, the 1-device dispatch
budgets) are calibrated on the unmodified host topology and must keep
running there.
"""
import os


def parse_mesh_shape(spec) -> tuple:
    """Parse a ``--mesh`` CLI value into a fabric mesh shape: ``"8"`` ->
    ``(8,)`` (member-sharded), ``"4x2"`` -> ``(4, 2)`` (the member x
    validator 2-axis fabric). Import-light (no jax) so the pre-argparse
    device-provisioning sniff can use it too. Raises ValueError on
    anything else."""
    dims = tuple(int(p) for p in str(spec).lower().split("x"))
    if not 1 <= len(dims) <= 2 or any(d < 1 for d in dims):
        raise ValueError(f"mesh shape must be M or MxV with dims >= 1: "
                         f"{spec!r}")
    return dims


def mesh_devices(shape) -> int:
    """Device count a fabric mesh shape needs (what to provision)."""
    n = 1
    for d in shape:
        n *= d
    return n


def ensure_host_platform_devices(n: int) -> None:
    """Append the host-device-count flag if no such flag is present yet
    (a preset flag — e.g. from tests/conftest.py or the operator — wins)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()


# the ONE name for the persistent XLA compile cache shared by the test
# suite and every entry-point script (tests/conftest.py, bench.py,
# profile_rbft.py, ingress_run.py): the SHA-512/Ed25519 kernels cost
# tens of seconds to minutes of XLA:CPU compile, and each cold process
# re-pays them without it
PERSISTENT_COMPILE_CACHE_DIR = "/tmp/jax_cache_indy_plenum_tests"


def enable_persistent_compile_cache(
        path: str = PERSISTENT_COMPILE_CACHE_DIR,
        min_compile_secs: float = 2.0) -> None:
    """Point jax at the shared persistent compile cache. Unlike the
    env-var helper above this IMPORTS jax — call it from entry points
    only, after any platform overrides are in place."""
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
