"""Minimal base58 (bitcoin alphabet) codec.

The environment has no ``base58`` package; identifiers, verkeys and merkle
roots are base58-encoded on the wire exactly as in the reference
(plenum/common/messages/fields.py `Base58Field`, `MerkleRootField`).

A native codec (native/codec/b58c.c, built on first use like the BN254
backend) serves the hot paths — BLS signature shares, roots and digests
all cross as base58; the pure-Python functions below remain the oracle
and the fallback when no compiler is available.
"""
from __future__ import annotations

ALPHABET = b"123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_INDEX = {c: i for i, c in enumerate(ALPHABET)}


try:
    import os as _os

    from .native_build import build_native_ext as _build

    _HERE = _os.path.dirname(_os.path.abspath(__file__))
    _C = _build(_os.path.join(_HERE, "..", "..", "native", "codec",
                              "b58c.c"),
                _os.path.join(_HERE, "_native_build"), "b58c", opt="-O2")
except Exception as _err:  # pragma: no cover — no compiler/headers
    import logging as _logging

    _logging.getLogger(__name__).warning(
        "native base58 codec unavailable (%s); using the ~10x slower "
        "pure-Python fallback", _err)
    _C = None


def b58encode(data: bytes) -> str:
    if _C is not None:
        return _C.b58_encode(bytes(data))
    n_zeros = len(data) - len(data.lstrip(b"\0"))
    num = int.from_bytes(data, "big")
    out = bytearray()
    while num > 0:
        num, rem = divmod(num, 58)
        out.append(ALPHABET[rem])
    out.extend(ALPHABET[0:1] * n_zeros)
    out.reverse()
    return out.decode("ascii")


_POW58 = [58 ** i for i in range(11)]


def b58decode(text: str | bytes) -> bytes:
    if _C is not None:
        return _C.b58_decode(text)
    if isinstance(text, str):
        text = text.encode("ascii")
    n_zeros = len(text) - len(text.lstrip(ALPHABET[0:1]))
    num = 0
    try:
        # 10-digit chunks: the inner loop stays on machine ints and the
        # big-int ops drop ~10x (signature decoding is a hot path)
        for i in range(0, len(text), 10):
            chunk = text[i:i + 10]
            v = 0
            for ch in chunk:
                v = v * 58 + _INDEX[ch]
            num = num * _POW58[len(chunk)] + v
    except KeyError as exc:
        # exc.args[0] is the raw byte (iterating bytes yields ints);
        # report the CHARACTER, same as the native codec
        raise ValueError(
            f"invalid base58 character {chr(exc.args[0])!r}") from None
    body = num.to_bytes((num.bit_length() + 7) // 8, "big") if num else b""
    return b"\0" * n_zeros + body
