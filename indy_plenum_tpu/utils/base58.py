"""Minimal base58 (bitcoin alphabet) codec.

The environment has no ``base58`` package; identifiers, verkeys and merkle
roots are base58-encoded on the wire exactly as in the reference
(plenum/common/messages/fields.py `Base58Field`, `MerkleRootField`).
"""
from __future__ import annotations

ALPHABET = b"123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_INDEX = {c: i for i, c in enumerate(ALPHABET)}


def b58encode(data: bytes) -> str:
    n_zeros = len(data) - len(data.lstrip(b"\0"))
    num = int.from_bytes(data, "big")
    out = bytearray()
    while num > 0:
        num, rem = divmod(num, 58)
        out.append(ALPHABET[rem])
    out.extend(ALPHABET[0:1] * n_zeros)
    out.reverse()
    return out.decode("ascii")


def b58decode(text: str | bytes) -> bytes:
    if isinstance(text, str):
        text = text.encode("ascii")
    n_zeros = len(text) - len(text.lstrip(ALPHABET[0:1]))
    num = 0
    for ch in text:
        try:
            num = num * 58 + _INDEX[ch]
        except KeyError:
            raise ValueError(f"invalid base58 character {ch!r}") from None
    body = num.to_bytes((num.bit_length() + 7) // 8, "big") if num else b""
    return b"\0" * n_zeros + body
