"""Batched pairing verification for the state-proof plane.

The per-root BLS cycle (aggregate + one pairing check) sits at ~155-180
cycles/sec on the native BN254 backend (BENCH_r04/r05, 64 sigs) — fine
for one committed root per ordered batch, hopeless for verifying proofs
across many roots/windows at read-client scale. This module amortizes:
``K`` aggregate signatures over ``K`` different roots verify in ONE
combined pairing pass via random-linear-combination batching (|apk
groups|+1 Miller loops + one shared final exponentiation, instead of 2K
Miller loops + K final exponentiations), so proofs/sec scales with the
batch size instead of the per-root cycle cost. Measured by ``bench.py
proofs`` and regression-guarded by ``scripts/check_dispatch_budget.py``'s
proof gate (batch-64 must stay >= 2x the per-root path).

Seeding contract: with ``seed`` set, the combination scalars are a pure
function of (seed, item index, signature, message), so a seeded run
replays bit-identically (the determinism discipline every plane here
follows). **Predictable scalars are only sound for TRUSTED input** — an
adversary who knows the scalars in advance can craft a batch whose
forgeries cancel in the combined equation. That is fine for the proof
plane's own windows (each multi-sig was already verified at aggregation
time by consensus) and for benches/gates; a client verifying replies
from an UNTRUSTED node must pass ``seed=None`` (fresh ``secrets``
randomness, the default) — then a forged item survives the combined
check with probability 2^-128 and is pinpointed exactly by the per-item
fallback.
"""
from __future__ import annotations

import hashlib
from typing import Callable, List, Optional, Sequence

from ..crypto.bls.bls_crypto import PAIRINGS, BlsCryptoVerifier


def seeded_scalar_fn(seed: int) -> Callable[[int, str, bytes], int]:
    """128-bit combination scalars as a pure function of
    (seed, index, signature, message) — the replay-deterministic source
    for :meth:`BlsCryptoVerifier.verify_multi_sig_batch`."""

    def scalar(idx: int, sig_b58: str, message: bytes) -> int:
        h = hashlib.sha256(
            b"proof-rlc|%d|%d|" % (seed, idx)
            + sig_b58.encode() + b"|" + message).digest()
        return int.from_bytes(h[:16], "big")

    return scalar


def verify_multi_sigs_batch(items: Sequence[tuple],
                            seed: Optional[int] = None,
                            trace=None,
                            metrics=None) -> List[bool]:
    """Verify K aggregate signatures across multiple roots/windows in one
    combined pairing pass; returns exact per-item verdicts.

    ``items``: (signature_b58, message: bytes, pks_b58) — one entry per
    root/window. ``seed`` selects the deterministic scalar source (see
    the module doc for when that is sound); ``None`` uses fresh
    randomness. ``trace``/``metrics`` record the pass as a
    ``proof.verify_batch`` event / ``proof.pairings`` series so the
    amortization is an observable, not a claim.
    """
    before = PAIRINGS.pairings
    verdicts = BlsCryptoVerifier.verify_multi_sig_batch(
        items, scalar_fn=None if seed is None else seeded_scalar_fn(seed))
    pairings = PAIRINGS.pairings - before
    if metrics is not None:
        from ..common.metrics_collector import MetricsName

        metrics.add_event(MetricsName.PROOF_PAIRINGS, pairings)
        metrics.add_event(MetricsName.PROOF_VERIFY_BATCH, len(items))
    if trace is not None and trace.enabled:
        trace.record("proof.verify_batch", cat="proof",
                     args={"k": len(items), "pairings": pairings,
                           "ok": int(sum(bool(v) for v in verdicts))})
    return verdicts
