"""Edge proof-serving tier: region-local UNTRUSTED replicas of sealed
window proofs.

The state-proof plane (``checkpoint_cache`` + ``client.state_proof``)
makes a read reply self-certifying: audit path + the pool's BLS
multi-signature over the window root verify offline with nothing but
the pool's keys. That property is exactly what makes an edge-CDN tier
sound — a cache that needs ZERO trust, because verification (not the
server) is the security boundary. This module is that tier:

- :class:`EdgeProofCache` — a region-local replica of the last sealed
  window's proof-attached replies, fed by ``replicate()`` snapshots of
  an origin :class:`~indy_plenum_tpu.ingress.read_service.ReadService`
  drain and miss-filled by ``store()``. Bounded two ways: newest
  ``EdgeProofCacheKeepWindows`` windows (invalidation rides the SAME
  ``CheckpointStabilized`` bus hook ``LedgerBacking`` and
  ``CheckpointProofCache`` use — a seal retires the oldest held window
  to make room for the incoming one) and ``EdgeProofCacheMaxEntries``
  entries LRU across windows. The serve path is dict lookups only —
  zero pairings, zero hashing (asserted by the budget script's geo
  gate). ``poison()`` arms the byzantine-edge mode: served replies are
  deterministically tampered (leaf flip / root flip / signature
  corruption), which clients MUST catch by verification — the
  cache-poisoning chaos arc's subject.

- :class:`GeoReadFabric` — the client half: routes each client's reads
  to its home-region edge, verifies EVERY edge reply offline (one full
  :func:`~indy_plenum_tpu.client.state_proof.verify_proved_read` per
  distinct signed window amortizes the pairing; further replies pay
  only the pairing-free
  :func:`~indy_plenum_tpu.client.state_proof.verify_read_binding`),
  enforces the ``EdgeProofCacheMaxAge`` freshness bound, and falls
  back to the origin validator over the WAN on miss / stale /
  verification failure (miss-filling the edge on the way back).
  Latency is MODELED per read from the pool's
  :class:`~indy_plenum_tpu.simulation.sim_network.RegionLatencyMatrix`
  bands using a DEDICATED seeded RNG — the pool's delivery RNG is
  never touched, so arming the fabric cannot move ``ordered_hash`` or
  any other fingerprint.
"""
from __future__ import annotations

import random
from collections import OrderedDict
from dataclasses import replace
from typing import Callable, Dict, List, Optional, Tuple


class EdgeProofCache:
    """An untrusted, bounded, region-local replica of proof-attached
    read replies. Holds per sealed window a ``{folded index -> reply}``
    map; :meth:`get` serves from the NEWEST held window containing the
    index. Nothing here is a trust anchor — a byzantine edge (see
    :meth:`poison`) can serve garbage, and the client catches 100% of
    it by offline verification."""

    def __init__(self, region: int,
                 keep_windows: Optional[int] = None,
                 max_entries: Optional[int] = None,
                 config=None, bus=None,
                 clock: Optional[Callable[[], float]] = None,
                 name: str = ""):
        if keep_windows is None or max_entries is None:
            if config is None:
                from ..config import getConfig

                config = getConfig()
            if keep_windows is None:
                keep_windows = config.EdgeProofCacheKeepWindows
            if max_entries is None:
                max_entries = config.EdgeProofCacheMaxEntries
        if keep_windows <= 0:
            raise ValueError(f"keep_windows must be positive: "
                             f"{keep_windows}")
        if max_entries <= 0:
            raise ValueError(f"max_entries must be positive: "
                             f"{max_entries}")
        self.region = int(region)
        self.keep_windows = int(keep_windows)
        self.max_entries = int(max_entries)
        self.name = name or ("edge-r%d" % self.region)
        self._clock = clock if clock is not None else (lambda: 0.0)
        # window -> {"replies": {idx: ProofRead}, "tree_size", ...};
        # insertion-ordered oldest-first (window GC pops the front)
        self._windows: "OrderedDict[Tuple[int, int], dict]" = OrderedDict()
        # entry LRU across ALL windows: (window, idx) touch order
        self._lru: "OrderedDict[Tuple, None]" = OrderedDict()
        self._queue: List[int] = []
        self.replicated_total = 0
        self.stored_total = 0
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.windows_evicted = 0
        self.entries_evicted = 0
        self.tampered_total = 0
        self._poison_rng: Optional[random.Random] = None
        if bus is not None:
            from ..common.messages.internal_messages import (
                CheckpointStabilized,
            )

            bus.subscribe(CheckpointStabilized,
                          self._on_checkpoint_stabilized)

    # --- feeding --------------------------------------------------------

    def replicate(self, window, replies) -> int:
        """Bulk-load one sealed window's proof-attached replies (an
        origin drain's output). Replies from OTHER windows are skipped —
        a replication batch must not smear roots across windows. Returns
        the number of entries stored."""
        if window is None:
            return 0
        window = tuple(window)
        bucket = self._bucket(window)
        stored = 0
        for reply in replies:
            if reply is None or reply.window is None \
                    or tuple(reply.window) != window:
                continue
            bucket["tree_size"] = reply.tree_size
            self._insert(window, bucket, reply)
            stored += 1
        self.replicated_total += stored
        self._gc_windows()
        return stored

    def store(self, reply) -> bool:
        """Miss-fill ONE reply fetched from the origin (must carry its
        proof window + multi-sig, or there is nothing worth caching)."""
        if reply is None or reply.window is None \
                or reply.multi_sig is None:
            return False
        window = tuple(reply.window)
        bucket = self._bucket(window)
        bucket["tree_size"] = reply.tree_size
        self._insert(window, bucket, reply)
        self.stored_total += 1
        self._gc_windows()
        return True

    def _bucket(self, window: Tuple[int, int]) -> dict:
        bucket = self._windows.get(window)
        if bucket is None:
            bucket = {"replies": {}, "tree_size": 0,
                      "replicated_at": self._clock()}
            self._windows[window] = bucket
        else:
            self._windows.move_to_end(window)
        return bucket

    def _insert(self, window, bucket, reply) -> None:
        bucket["replies"][reply.index] = reply
        key = (window, reply.index)
        self._lru[key] = None
        self._lru.move_to_end(key)
        while len(self._lru) > self.max_entries:
            (old_w, old_i), _ = self._lru.popitem(last=False)
            old_b = self._windows.get(old_w)
            if old_b is not None:
                old_b["replies"].pop(old_i, None)
            self.entries_evicted += 1

    def _gc_windows(self) -> None:
        while len(self._windows) > self.keep_windows:
            self._drop_oldest_window()

    def _drop_oldest_window(self) -> None:
        window, bucket = self._windows.popitem(last=False)
        for idx in bucket["replies"]:
            self._lru.pop((window, idx), None)
        self.windows_evicted += 1

    # --- invalidation ---------------------------------------------------

    def _on_checkpoint_stabilized(self, msg, *args) -> None:
        # master-instance seals only, same discipline as LedgerBacking /
        # CheckpointProofCache: a new window is sealed, so retire the
        # oldest held one when at capacity — the freshness bound
        # (EdgeProofCacheMaxAge, enforced client-side) covers whatever
        # staleness remains; verification is the security boundary
        if msg.inst_id != 0:
            return
        self.invalidations += 1
        if len(self._windows) >= self.keep_windows:
            self._drop_oldest_window()

    # --- serving --------------------------------------------------------

    def poison(self, seed: int) -> "EdgeProofCache":
        """Arm the byzantine-edge mode: every served reply is tampered
        (deterministically, per ``seed``) — a leaf flip, a root flip,
        or a corrupted multi-signature. The chaos plane's
        ``edge_cache_poisoning`` arc asserts clients catch ALL of it by
        offline verification."""
        self._poison_rng = random.Random("edge-poison-%d" % seed)
        return self

    def _tamper(self, reply):
        self.tampered_total += 1
        kind = self._poison_rng.randrange(3)
        if kind == 0 and reply.leaf:
            leaf = bytes([reply.leaf[0] ^ 0x01]) + bytes(reply.leaf[1:])
            return replace(reply, leaf=leaf)
        if kind == 1 and reply.root:
            root = bytes([reply.root[0] ^ 0x01]) + bytes(reply.root[1:])
            return replace(reply, root=root)
        ms = dict(reply.multi_sig or {})
        sig = str(ms.get("signature") or "")
        ms["signature"] = ("2" if not sig.startswith("2") else "3") \
            + sig[1:]
        return replace(reply, multi_sig=ms)

    def get(self, index: int):
        """Serve one read: the NEWEST held window containing the folded
        index wins. Dict lookups only — no hashing, no pairings. Returns
        None on miss (the fabric falls back to the origin)."""
        for window in reversed(self._windows):
            bucket = self._windows[window]
            size = bucket["tree_size"]
            if size <= 0:
                continue
            idx = index % size
            reply = bucket["replies"].get(idx)
            if reply is None:
                continue
            self.hits += 1
            key = (window, idx)
            if key in self._lru:
                self._lru.move_to_end(key)
            if self._poison_rng is not None:
                return self._tamper(reply)
            return reply
        self.misses += 1
        return None

    def submit(self, index: int) -> bool:
        """ReadService-shaped queueing (drain-based drivers plug an
        edge in where a ReadService went)."""
        self._queue.append(int(index))
        return True

    def drain(self) -> List:
        """Answer everything queued from the held windows, in
        submission order; misses are dropped (a standalone edge has no
        fallback — route through :class:`GeoReadFabric` for that)."""
        queued, self._queue = self._queue, []
        out = []
        for index in queued:
            reply = self.get(index)
            if reply is not None:
                out.append(reply)
        return out

    def window_age(self, now: float) -> Optional[float]:
        """Age of the newest held window's replication instant — the
        edge-side staleness signal (the CLIENT-side bound keys on the
        multi-sig's own timestamp, which the edge cannot forge)."""
        if not self._windows:
            return None
        newest = next(reversed(self._windows.values()))
        return now - newest["replicated_at"]

    def sized_resources(self, prefix: str = "edge_cache."):
        """Resource-ledger registration (observability.telemetry): the
        window buckets (keep_windows) and the reply LRU (max_entries)."""
        from ..observability.telemetry import SizedResource

        return (
            SizedResource(prefix + "windows", lambda: len(self._windows),
                          bound=self.keep_windows, entry_bytes=256),
            SizedResource(prefix + "lru", lambda: len(self._lru),
                          bound=self.max_entries, entry_bytes=1024),
        )

    def counters(self) -> Dict[str, object]:
        lookups = self.hits + self.misses
        return {
            "region": self.region,
            "windows_held": len(self._windows),
            "entries": len(self._lru),
            "replicated": self.replicated_total,
            "stored": self.stored_total,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / lookups, 4) if lookups
            else 0.0,
            "invalidations": self.invalidations,
            "windows_evicted": self.windows_evicted,
            "entries_evicted": self.entries_evicted,
            "tampered": self.tampered_total,
        }


def _pct(sorted_samples: List[float], q: float) -> float:
    if not sorted_samples:
        return 0.0
    pos = min(len(sorted_samples) - 1,
              max(0, int(round(q * (len(sorted_samples) - 1)))))
    return sorted_samples[pos]


class GeoReadFabric:
    """Region-aware read routing + the client verification loop.

    ``origin`` is a proof-attached ReadService at the home region
    (``origin_region``); ``edges`` maps region -> EdgeProofCache (an
    empty map IS the no-edge arm: every read pays the WAN band to the
    origin). ``matrix`` supplies the latency bands (duck-typed:
    ``intra_band`` + ``band(a, b)``); per-read latency is drawn from a
    DEDICATED seeded RNG so the pool's delivery RNG — and with it every
    fingerprint — is untouched by serving reads.

    Client region is ``client % n_regions`` (the same modular placement
    the pool uses for nodes). Every reply is verified offline before it
    counts: edge replies first pass the freshness bound (strict ``>``
    against ``EdgeProofCacheMaxAge``, matching
    ``verify_pool_multi_sig``), then the amortized verification — one
    full pairing-bearing ``verify_proved_read`` per distinct
    (window, signature, participants), pairing-free
    ``verify_read_binding`` after. Miss / stale / failed verification
    falls back to the origin over the WAN and miss-fills the edge."""

    def __init__(self, origin, matrix, pool_keys: Dict[str, str],
                 min_participants: int, n_regions: int,
                 origin_region: int = 0,
                 edges: Optional[Dict[int, EdgeProofCache]] = None,
                 seed: int = 0,
                 clock: Optional[Callable[[], float]] = None,
                 max_age: Optional[float] = None,
                 config=None):
        if max_age is None:
            if config is None:
                from ..config import getConfig

                config = getConfig()
            max_age = config.EdgeProofCacheMaxAge
        self.origin = origin
        self.matrix = matrix
        self.pool_keys = dict(pool_keys)
        self.min_participants = int(min_participants)
        self.n_regions = int(n_regions)
        self.origin_region = int(origin_region)
        self.edges = dict(edges) if edges else {}
        self.max_age = max_age
        self._clock = clock if clock is not None else (lambda: 0.0)
        # NEVER the pool's RNG: the fabric draws one latency per served
        # read, and that stream must not perturb delivery jitter
        self._lat_rng = random.Random("geo-fabric-%d" % seed)
        self._queue: List[Tuple[int, int]] = []
        # (window, signature, participants) triples whose full
        # verification already succeeded — the pairing amortization set
        self._trusted: set = set()
        # region -> [(latency, source)] completion records
        self.samples: Dict[int, List[Tuple[float, str]]] = {}
        self.verified_by_region: Dict[int, int] = {}
        self.edge_served = 0
        self.origin_served = 0
        self.verify_caught = 0
        self.stale_fallbacks = 0
        self.verify_failures = 0
        self.edge_serve_pairings = 0
        self._vt_first: Optional[float] = None
        self._vt_last: Optional[float] = None

    # ------------------------------------------------------------------

    def region_of(self, client: int) -> int:
        return client % self.n_regions

    def submit(self, client: int, index: int) -> bool:
        self._queue.append((self.region_of(client), int(index)))
        return True

    def _stale(self, reply, now: float) -> bool:
        if self.max_age is None:
            return False
        ms = reply.multi_sig
        value = ms.get("value") if isinstance(ms, dict) else None
        ts = (value or {}).get("timestamp")
        if not isinstance(ts, (int, float)):
            return True  # unfreshable material is never served as fresh
        # strict >, matching verify_pool_multi_sig: a window EXACTLY at
        # max_age is still fresh
        return (now - ts) > self.max_age

    def _client_verify(self, reply, now: float) -> bool:
        from ..client.state_proof import (
            verify_proved_read,
            verify_read_binding,
        )

        ms = reply.multi_sig
        if not isinstance(ms, dict):
            return False
        trust_key = (reply.window, reply.root, ms.get("signature"),
                     tuple(ms.get("participants") or ()))
        if trust_key in self._trusted:
            return verify_read_binding(reply)
        ok = verify_proved_read(reply, self.pool_keys,
                                self.min_participants,
                                now=now, max_age=self.max_age)
        if ok:
            self._trusted.add(trust_key)
        return ok

    def drain(self) -> List:
        """Serve everything queued: edge lookups per region, client
        verification, origin fallback, latency modeling. Returns the
        verified replies (a reply failing even the origin's answer is
        dropped — and counted)."""
        queued, self._queue = self._queue, []
        if not queued:
            return []
        from ..crypto.bls.bls_crypto import PAIRINGS

        now = self._clock()
        if self._vt_first is None:
            self._vt_first = now
        self._vt_last = now
        by_region: Dict[int, List[int]] = {}
        for region, index in queued:
            by_region.setdefault(region, []).append(index)
        out = []
        for region in sorted(by_region):
            indexes = by_region[region]
            edge = self.edges.get(region)
            served: List[Tuple[object, str]] = []
            fallback: List[int] = []
            if edge is not None:
                checks_before = PAIRINGS.checks
                replies = [edge.get(i) for i in indexes]
                # the EDGE serve path must stay pairing-free (client
                # verification below legitimately pays one per window)
                self.edge_serve_pairings += \
                    PAIRINGS.checks - checks_before
                for index, reply in zip(indexes, replies):
                    if reply is None:
                        fallback.append(index)
                    elif self._stale(reply, now):
                        self.stale_fallbacks += 1
                        fallback.append(index)
                    elif not self._client_verify(reply, now):
                        self.verify_caught += 1
                        fallback.append(index)
                    else:
                        served.append((reply, "edge"))
            else:
                fallback = list(indexes)
            if fallback:
                for index in fallback:
                    self.origin.submit(index)
                origin_replies = self.origin.drain()
                for reply in origin_replies:
                    if not self._client_verify(reply, now):
                        # the home validator's own reply failing the
                        # offline check is a pool-level fault, not a
                        # cache artifact — count it, don't serve it
                        self.verify_failures += 1
                        continue
                    if edge is not None:
                        edge.store(reply)
                    served.append((reply, "origin"))
            band_wan = self.matrix.band(region, self.origin_region)
            band_intra = self.matrix.intra_band
            for reply, source in served:
                lo, hi = band_intra if source == "edge" else band_wan
                latency = self._lat_rng.uniform(lo, hi)
                self.samples.setdefault(region, []).append(
                    (latency, source))
                self.verified_by_region[region] = \
                    self.verified_by_region.get(region, 0) + 1
                if source == "edge":
                    self.edge_served += 1
                else:
                    self.origin_served += 1
                out.append(reply)
        return out

    # ------------------------------------------------------------------

    def counters(self) -> Dict[str, object]:
        served = self.edge_served + self.origin_served
        span = ((self._vt_last - self._vt_first)
                if self._vt_first is not None else 0.0)
        per_region = {}
        for region in sorted(self.samples):
            samples = self.samples[region]
            latencies = sorted(lat for lat, _ in samples)
            verified = self.verified_by_region.get(region, 0)
            per_region[str(region)] = {
                "served": len(samples),
                "edge": sum(1 for _, s in samples if s == "edge"),
                "verified": verified,
                "verified_per_sec": round(verified / span, 1)
                if span > 0 else 0.0,
                "latency_p50": round(_pct(latencies, 0.50), 6),
                "latency_p99": round(_pct(latencies, 0.99), 6),
            }
        return {
            "served": served,
            "edge_served": self.edge_served,
            "origin_served": self.origin_served,
            "edge_hit_rate": round(self.edge_served / served, 4)
            if served else 0.0,
            "verify_caught": self.verify_caught,
            "stale_fallbacks": self.stale_fallbacks,
            "verify_failures": self.verify_failures,
            "edge_serve_pairings": self.edge_serve_pairings,
            "regions": per_region,
        }
