"""State-proof plane: BLS-multi-signed verifiable reads at
checkpoint-window granularity.

Two cooperating parts (README "State-proof plane"):

- :mod:`.checkpoint_cache` — rides the ``CheckpointStabilized`` bus and
  captures, per stabilized window, the pool's multi-signature over the
  committed root (already aggregated by consensus), so every read served
  inside the window shares ONE aggregation cost and a cache hit is a
  dict lookup with zero pairings;
- :mod:`.batch_verify` — random-linear-combination verification of K
  aggregate signatures across multiple roots/windows in one combined
  pairing pass (seedable for deterministic replay), so proofs/sec scales
  with batch size instead of the per-root cycle cost;
- :mod:`.edge_cache` — the geo plane's edge tier: region-local
  UNTRUSTED replicas of the last sealed window's proof-attached
  replies (``EdgeProofCache``) plus the region-routing client loop
  (``GeoReadFabric``) that verifies every edge reply offline and falls
  back to the origin validator over the WAN — verification, not the
  cache, is the security boundary (README "Planet-scale read fabric").

The client side closes the loop in
:func:`indy_plenum_tpu.client.state_proof.verify_proved_read`: a reply
from ONE node verifies with nothing but the pool's BLS keys.
"""
from .batch_verify import seeded_scalar_fn, verify_multi_sigs_batch
from .checkpoint_cache import CheckpointProofCache, ProofWindow
from .edge_cache import EdgeProofCache, GeoReadFabric

__all__ = [
    "CheckpointProofCache",
    "EdgeProofCache",
    "GeoReadFabric",
    "ProofWindow",
    "seeded_scalar_fn",
    "verify_multi_sigs_batch",
]
