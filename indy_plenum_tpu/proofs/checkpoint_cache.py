"""Checkpoint-window proof cache: one aggregation cost per stable window.

Plenum's defining client capability is the BLS-multi-signed state proof
(``BlsBftReplica`` / ``verify_pool_multi_sig``): a reply from ONE node
carries the pool's n-f co-signature over the committed root, so the
client needs zero server trust. The ingress plane's ``ReadService``
(PR 6/7) serves proofs against a LOCAL root only — externally worthless.
This cache closes the gap at checkpoint-window granularity, PBFT's
read-only-operation optimisation (Castro & Liskov 1999) taken to its
logical end: consensus already pays the aggregation + pairing cost once
per ordered batch (``BlsBftReplica.process_order``), so the cache never
does ANY cryptography — it rides the ``CheckpointStabilized`` bus (the
same hook ``LedgerBacking`` uses) and, per stabilized window, snapshots
the committed (ledger size, ledger root, state root) and looks the
matching :class:`~indy_plenum_tpu.crypto.bls.bls_crypto.MultiSignature`
up in the replica's :class:`~indy_plenum_tpu.bls.bls_store.BlsStore`
(keyed by state root). Every read served inside the window then shares
that ONE already-paid aggregation: attaching the proof is a dict lookup,
ZERO pairings (asserted via ``crypto.bls.bls_crypto.PAIRINGS`` by the
budget script's proof gate).

Window contract:

- a read served mid-window verifies against the LAST captured window's
  root — the serve snapshot only advances at stabilization events,
  mirroring ``LedgerBacking``'s refresh discipline;
- capture VERIFIES the binding ``multi_sig.value.txn_root_hash ==
  b58(ledger root)`` before publishing an entry. When the tip batch's
  aggregate is not assembled yet (deferred tick-mode verification
  flushes at tick end; stabilization can fire from a network checkpoint
  mid-tick), the capture parks as *pending* and resolves on the next
  :meth:`attach`/:meth:`capture` — the roots were snapshotted at the
  stabilization instant, so the late-resolved entry still binds exactly
  the stabilized state;
- entries GC with checkpoint GC: only the newest ``keep`` windows stay
  (old multi-sigs below the stable floor are exactly what checkpoint GC
  retires), and an evicted window is no longer served.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

from ..crypto.bls.bls_crypto import MultiSignature
from ..utils.base58 import b58encode


@dataclass
class ProofWindow:
    """One stabilized window's servable proof material. ``multi_sig_dict``
    is pre-serialized at capture so the per-read attach is a reference
    copy, never a re-serialization."""

    window: Tuple[int, int]  # (view_no, seq_no_end) — last_stable_3pc
    tree_size: int
    root: bytes
    state_root_b58: str
    multi_sig: MultiSignature
    multi_sig_dict: dict
    captured_at: float


class CheckpointProofCache:
    """``root_provider() -> (tree_size, root_bytes)`` and
    ``state_root_provider() -> b58 str`` snapshot the node's committed
    ledger/state; ``bls_replica`` supplies the store the consensus layer
    already filled. ``bus`` (a node's internal bus) auto-captures on
    ``CheckpointStabilized`` for the master instance; tests and benches
    may :meth:`install` pre-verified windows directly."""

    def __init__(self,
                 bls_replica,
                 root_provider: Callable[[], Tuple[int, bytes]],
                 state_root_provider: Callable[[], str],
                 bus=None,
                 keep: int = 2,
                 clock: Optional[Callable[[], float]] = None,
                 metrics=None,
                 trace=None,
                 node: str = ""):
        from ..observability.trace import NULL_TRACE

        if keep <= 0:
            raise ValueError(f"keep must be positive: {keep}")
        self._bls = bls_replica
        self._root_provider = root_provider
        self._state_root_provider = state_root_provider
        self.keep = int(keep)
        self._clock = clock if clock is not None else (lambda: 0.0)
        self.metrics = metrics
        self.trace = trace if trace is not None else NULL_TRACE
        self.node = node
        # insertion-ordered: oldest window first (GC pops from the front)
        self._entries: Dict[Tuple[int, int], ProofWindow] = {}
        # stabilizations whose multi-sig was not in the store yet:
        # window -> (tree_size, root, state_root_b58) — roots frozen at
        # the stabilization instant, each lookup retried lazily. A dict
        # (bounded by ``keep``, like the entries), NOT a single slot:
        # deferred aggregation lagging two windows must not drop the
        # older one — its multi-sig may still land first
        self._pending: Dict[Tuple[int, int], Tuple] = {}
        self.windows_signed = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.pending_retries = 0
        if bus is not None:
            from ..common.messages.internal_messages import (
                CheckpointStabilized,
            )

            bus.subscribe(CheckpointStabilized,
                          self._on_checkpoint_stabilized)

    @classmethod
    def for_domain(cls, db, bls_replica, bus=None, keep: int = 2,
                   clock=None, metrics=None, trace=None,
                   node: str = "") -> "CheckpointProofCache":
        """The composition seam ``Node`` and ``SimNode`` share: snapshot
        providers over the DOMAIN ledger + state of a
        ``LedgersBootstrap`` database — one copy of the root-binding
        convention, so deployed and simulated proofs can never drift."""
        from ..common.constants import DOMAIN_LEDGER_ID

        ledger = db.get_ledger(DOMAIN_LEDGER_ID)
        state = db.get_state(DOMAIN_LEDGER_ID)
        return cls(
            bls_replica=bls_replica,
            root_provider=lambda: (
                ledger.size,
                ledger.root_hash_at(ledger.size) if ledger.size else b""),
            state_root_provider=lambda: b58encode(
                state.committed_head_hash),
            bus=bus, keep=keep, clock=clock, metrics=metrics,
            trace=trace, node=node)

    # --- capture --------------------------------------------------------

    def _on_checkpoint_stabilized(self, msg, *args) -> None:
        if msg.inst_id != 0:
            return  # master windows only: backups share the ledger
        self.capture(tuple(msg.last_stable_3pc))

    def capture(self, window: Tuple[int, int]) -> Optional[ProofWindow]:
        """Snapshot the committed roots for ``window`` and publish the
        entry if the pool's multi-sig over them is already in the store;
        park as pending otherwise. Safe to call redundantly."""
        self._resolve_pending()
        if window in self._entries:
            return self._entries[window]
        tree_size, root = self._root_provider()
        if tree_size <= 0:
            return None
        state_root_b58 = self._state_root_provider()
        entry = self._lookup(window, tree_size, root, state_root_b58)
        if entry is None:
            # deferred aggregation (tick-mode flush) has not stored the
            # tip multi-sig yet; the ROOTS are frozen now, the lookup
            # retries on the next attach/capture
            self._pending[tuple(window)] = (tree_size, root,
                                            state_root_b58)
            while len(self._pending) > self.keep:
                del self._pending[next(iter(self._pending))]
        return entry

    def _lookup(self, window, tree_size, root,
                state_root_b58) -> Optional[ProofWindow]:
        if self._bls is None:
            return None
        ms = self._bls.store.get(state_root_b58)
        if ms is None or ms.value.txn_root_hash != b58encode(root):
            return None
        entry = ProofWindow(
            window=tuple(window), tree_size=tree_size, root=root,
            state_root_b58=state_root_b58, multi_sig=ms,
            multi_sig_dict=ms.as_dict(), captured_at=self._clock())
        self._install(entry)
        return entry

    def _resolve_pending(self) -> None:
        if not self._pending:
            return
        for window in list(self._pending):
            if window in self._entries:
                del self._pending[window]
                continue
            self.pending_retries += 1
            tree_size, root, state_root_b58 = self._pending[window]
            if self._lookup(window, tree_size, root, state_root_b58):
                del self._pending[window]

    def install(self, entry: ProofWindow) -> None:
        """The test/bench seam: publish a PRE-VERIFIED window proof
        directly (e.g. a manufactured corpus signed out-of-band)."""
        self._install(entry)

    def _install(self, entry: ProofWindow) -> None:
        # a pending older window resolving AFTER a newer capture must
        # not masquerade as the freshest proof: keep insertion ordered
        # by seq_no_end
        self._entries[entry.window] = entry
        self._entries = dict(
            sorted(self._entries.items(), key=lambda kv: kv[0][::-1]))
        while len(self._entries) > self.keep:
            # checkpoint GC: the oldest window falls off with the floor
            oldest = next(iter(self._entries))
            del self._entries[oldest]
        self.windows_signed += 1
        if self.metrics is not None:
            from ..common.metrics_collector import MetricsName

            self.metrics.add_event(MetricsName.PROOF_WINDOWS_SIGNED, 1)
        if self.trace.enabled:
            self.trace.record(
                "proof.window_signed", cat="proof", node=self.node,
                key=entry.window,
                args={"tree_size": entry.tree_size,
                      "participants": len(entry.multi_sig.participants)})

    # --- serving --------------------------------------------------------

    @property
    def depth(self) -> int:
        return len(self._entries)

    def windows(self) -> list:
        return list(self._entries)

    def get(self, window: Tuple[int, int]) -> Optional[ProofWindow]:
        return self._entries.get(tuple(window))

    def current(self) -> Optional[ProofWindow]:
        """The newest stabilized window's entry — what reads serve."""
        if not self._entries:
            return None
        return next(reversed(self._entries.values()))

    def attach(self, batch: int = 1) -> Optional[ProofWindow]:
        """The serve-path hook: the current entry, with hit/miss
        accounting per read. A hit is a dict lookup — no store access,
        no serialization, ZERO pairings."""
        if self._pending:
            self._resolve_pending()
        entry = self.current()
        if self.metrics is not None:
            from ..common.metrics_collector import MetricsName

            self.metrics.add_event(
                MetricsName.PROOF_CACHE_HIT if entry is not None
                else MetricsName.PROOF_CACHE_MISS, batch)
            if entry is not None:
                self.metrics.add_event(MetricsName.PROOF_SERVED, batch)
        if entry is None:
            self.cache_misses += batch
            return None
        self.cache_hits += batch
        if self.trace.enabled:
            self.trace.record(
                "proof.cache_hit", cat="proof", node=self.node,
                key=entry.window, args={"batch": batch})
        return entry

    def sized_resources(self, prefix: str = "proof_cache."):
        """Resource-ledger registration (observability.telemetry):
        servable windows and half-signed pending windows, both bounded
        by ``keep``."""
        from ..observability.telemetry import SizedResource

        return (
            SizedResource(prefix + "windows", lambda: len(self._entries),
                          bound=self.keep, entry_bytes=512),
            SizedResource(prefix + "pending", lambda: len(self._pending),
                          bound=self.keep, entry_bytes=512),
        )

    def counters(self) -> Dict[str, int]:
        return {
            "windows_signed": self.windows_signed,
            "windows_cached": self.depth,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "pending_retries": self.pending_retries,
        }
