"""Fused device consensus step: Ed25519 batch verify -> quorum tally.

This is the "flagship model forward step" of the framework: one jitted
program that (a) verifies the pending signed-message batch on device and
(b) scatters the surviving votes into the dense quorum tensors, returning
only quorum events to the host. It is the TPU composition of the reference's
``CoreAuthNr.authenticate`` hot loop with ``OrderingService``'s cert
collection (see SURVEY.md §3.1).

Sharding layout over a 1-D ``Mesh(("validators",))``:
- signature batch axis: sharded (each validator shard verifies its slice) —
  the data-parallel axis;
- vote tensors: validator rows sharded — the tensor-parallel axis;
- quorum counts: ``psum`` over the mesh; verdicts: ``all_gather``.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from . import ed25519 as ted
from . import quorum as q


def fused_step(
    state: q.VoteState,
    msgs: q.MsgBatch,
    pk: jnp.ndarray,
    rb: jnp.ndarray,
    s: jnp.ndarray,
    h: jnp.ndarray,
    *,
    n_validators: int,
) -> Tuple[q.VoteState, q.QuorumEvents, jnp.ndarray]:
    """Single-device fused step. msgs batch length == signature batch length."""
    ok = ted._verify_kernel(pk, rb, s, h)
    msgs = msgs._replace(valid=msgs.valid & ok)
    state, events = q.step(state, msgs, n_validators)
    return state, events, ok


def make_sharded_fused_step(
    mesh: Mesh, n_validators: int, axis: str = "validators"
):
    """Sharded fused step over ``mesh``: returns a jitted callable.

    Inputs: VoteState with (N, S) tensors sharded P(axis, None); MsgBatch
    replicated; signature arrays (B, 32) sharded P(axis, None) on the batch
    axis. B and the message batch M must be equal and divisible by the mesh
    size.
    """
    n_shards = mesh.shape[axis]
    assert n_validators % n_shards == 0
    local_rows = n_validators // n_shards

    def inner(state, msgs, pk, rb, s, h):
        ok_local = ted._verify_kernel(pk, rb, s, h)
        ok = lax.all_gather(ok_local, axis, tiled=True)
        msgs = msgs._replace(valid=msgs.valid & ok)
        offset = lax.axis_index(axis).astype(jnp.int32) * local_rows
        state = q._scatter_local(state, msgs, offset, local_rows)
        state, events = q._quorum_events(state, n_validators, axis)
        return state, events, ok

    row_sharded = q.VoteState(
        preprepare_seen=P(),
        prepare_votes=P(axis, None),
        commit_votes=P(axis, None),
        checkpoint_votes=P(axis, None),
        ordered=P(),
        prepared_acked=P(),
        frontier=P(),
    )
    replicated_msgs = q.MsgBatch(kind=P(), sender=P(), slot=P(), valid=P())
    batch_sharded = P(axis, None)
    events_spec = q.QuorumEvents(
        prepared=P(),
        newly_ordered=P(),
        ordered=P(),
        stable_checkpoints=P(),
        prepare_counts=P(),
        commit_counts=P(),
    )
    shard_fn = q.shard_map_compat(
        inner,
        mesh=mesh,
        in_specs=(
            row_sharded,
            replicated_msgs,
            batch_sharded,
            batch_sharded,
            batch_sharded,
            batch_sharded,
        ),
        out_specs=(row_sharded, events_spec, P()),
    )
    return jax.jit(shard_fn)
