"""Compilation plans for the grouped vote-plane step functions.

One helper owns the decision of HOW each step function compiles for a
given mesh shape (the Titanax pattern, SNIPPETS.md [3]): callers state
WHAT runs (the step/slide/zero bodies over the member-stacked
:class:`~indy_plenum_tpu.tpu.quorum.VoteState`) and receive a resolved
:class:`CompilePlan`; the strategy per function is picked here, in one
place, instead of hand-building a ``shard_map`` triple per case:

- **step** — ``jit`` on one device; ``shard_map`` on any mesh. The step
  is the hot dispatch and must be provably communication-free along the
  member axis (PR 4's contract: explicit SPMD, never a silent
  all-gather), and under the 2-axis member x validator fabric its body
  NEEDS manual collectives (``lax.axis_index`` for the scatter row
  offset, ``psum`` for the quorum counts) — both are exactly what
  ``shard_map`` expresses and ``pjit`` cannot guarantee.
- **slide / zero** — ``jit`` on one device; ``pjit`` with explicit
  NamedShardings on any mesh. Both bodies are pure per-member maps
  (roll/mask along unsharded trailing axes) whose layout the in/out
  shardings fully describe, so the partitioner cannot introduce
  communication — the "pjit when explicit shardings are provided"
  branch of the pattern, and one compilation instead of a hand-written
  shard_map wrapper per rare-path function.

The plan is cached per (mesh, n_validators, padded rows, delta cap) —
the same key space ``_sharded_group_fns`` used before this layer
replaced it.
"""
from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import quorum as q


# double-buffered device steps: donate the state operand so XLA writes
# the step's output state INTO the input's buffers (no state-sized
# alloc+copy per dispatch) while the freshly packed words ride their own
# host buffer — dispatch is async, so the device consumes buffer N while
# the host packs N+1. Every caller rebinds the state reference on
# return, which is exactly what donation requires. XLA:CPU doesn't
# implement donation (it would warn once per compile and ignore it), so
# gate it — but probe the backend LAZILY, at the first dispatch: probing
# at import would initialize the JAX backend before consumers
# (tests/conftest.py, any host-only code path) get to configure
# jax_platforms.
@functools.lru_cache(maxsize=None)
def _state_donation() -> tuple:
    return (0,) if jax.default_backend() != "cpu" else ()


class CompilePlan(NamedTuple):
    """Resolved compilation strategy for one group/mesh shape.

    ``step(states, words)`` -> (states, events, compact) — the grouped
    fast-path dispatch; ``slide(states, (M,) int32 deltas)`` and
    ``zero(states, (M,) uint8 mask)`` -> states — the rare-path window
    ops. ``strategy`` records which compilation path each function took
    (``jit`` / ``pjit`` / ``shard_map``) so surfaces can report it;
    ``mesh_shape`` is ``()`` unsharded, ``(M,)`` member-sharded,
    ``(M, V)`` on the 2-axis fabric."""

    step: Callable
    slide: Callable
    zero: Callable
    strategy: dict
    mesh_shape: Tuple[int, ...]


def _shardings(mesh: Mesh, spec_tree):
    """PartitionSpec tree -> NamedSharding tree (PartitionSpec is itself
    tuple-like on some jax versions, so mark it a leaf explicitly)."""
    return jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                        is_leaf=lambda x: isinstance(x, P))


def _zero_body(states: q.VoteState, mask: jnp.ndarray) -> q.VoteState:
    """Zero every leaf row of the masked members (a member MASK, not a
    row index: a mask is trivially partitionable along the member axis,
    a dynamic index is not)."""

    def z(x):
        hit = mask.reshape((-1,) + (1,) * (x.ndim - 1)) != 0
        return jnp.where(hit, jnp.zeros((), x.dtype), x)

    return jax.tree.map(z, states)


def _slide_body(states: q.VoteState, deltas: jnp.ndarray) -> q.VoteState:
    return jax.vmap(q.slide_state)(states, deltas)


@functools.lru_cache(maxsize=None)
def resident_plan_for(mesh: Optional[Mesh], n_validators: int,
                      n_validator_rows: int, delta_cap: int,
                      n_slots: int, width: int) -> Callable:
    """Fused multi-slot consume for the residency ring.

    ``step(states, slides, *words)`` -> (states, events, compact) where
    ``slides`` is ``(n_slots, M) int32`` (per-slot folded window-slide
    deltas, applied BEFORE that slot's scatter) and each of the
    ``n_slots`` word buffers is ``(M, width) uint32``. The kernel chains
    slide+scatter per slot and evaluates quorums ONCE at the end — k
    resident ticks ride one dispatch and one compact readback. Slot
    width is fixed by the caller (the group's ``flush_batch``) so the
    compile cache stays bounded by (mesh, n, rows, cap, k, width)
    instead of growing a kernel per adaptive ladder rung.

    Deferred eval is report-equivalent to per-tick eval (see
    :func:`~indy_plenum_tpu.tpu.quorum.eval_compact`): certs dedup via
    ``prepared_acked``/``ordered``, and a folded slide can only drop
    slots whose certs the host already absorbed — the host issues a
    slide only after SEEING checkpoint stability in a readback."""
    if mesh is None:
        def step_impl(states, slides, *words_seq):
            for k in range(n_slots):
                states = _slide_body(states, slides[k])
                msgs = q.unpack_words(words_seq[k])
                states = jax.vmap(q.scatter_batch)(states, msgs)
            return jax.vmap(
                lambda s: q.eval_compact(s, n_validators, delta_cap)
            )(states)

        return functools.partial(
            jax.jit, donate_argnums=_state_donation())(step_impl)

    axes = mesh.axis_names
    member_axis = axes[0]
    validator_axis = axes[1] if len(axes) > 1 else None
    state_spec, row_spec, events_spec, vec_spec = q.member_sharded_specs(
        member_axis, validator_axis)
    compact_spec = q.compact_member_specs(member_axis)
    slides_spec = P(None, member_axis)

    if validator_axis is None:
        def step_impl(states, slides, *words_seq):
            for k in range(n_slots):
                states = _slide_body(states, slides[k])
                msgs = q.unpack_words(words_seq[k])
                states = jax.vmap(q.scatter_batch)(states, msgs)
            return jax.vmap(
                lambda s: q.eval_compact(s, n_validators, delta_cap)
            )(states)
    else:
        v_shards = int(mesh.shape[validator_axis])
        assert n_validator_rows % v_shards == 0, (n_validator_rows, v_shards)
        v_local = n_validator_rows // v_shards

        def step_impl(states, slides, *words_seq):
            offset = (lax.axis_index(validator_axis).astype(jnp.int32)
                      * v_local)
            for k in range(n_slots):
                states = _slide_body(states, slides[k])
                msgs = q.unpack_words(words_seq[k])
                states = jax.vmap(
                    lambda s, m: q.scatter_batch(s, m, offset, v_local)
                )(states, msgs)
            return jax.vmap(
                lambda s: q.eval_compact(
                    s, n_validators, delta_cap, validator_axis)
            )(states)

    return functools.partial(jax.jit, donate_argnums=_state_donation())(
        q.shard_map_compat(
            step_impl, mesh=mesh,
            in_specs=(state_spec, slides_spec) + (row_spec,) * n_slots,
            out_specs=(state_spec, events_spec, compact_spec)))


@functools.lru_cache(maxsize=None)
def plan_for(mesh: Optional[Mesh], n_validators: int,
             n_validator_rows: int, delta_cap: int) -> CompilePlan:
    """Resolve the compilation plan for a :class:`VotePlaneGroup`.

    ``n_validators`` is the REAL validator count (quorum thresholds);
    ``n_validator_rows`` the padded row count the state tensors carry
    (== ``n_validators`` unless the 2-axis fabric pads the validator
    axis up to a mesh multiple — pad rows never receive votes, so the
    psum'd counts are exact)."""
    if mesh is None:
        def step_impl(states, words):
            msgs = q.unpack_words(words)
            return jax.vmap(
                lambda s, m: q.step_compact(s, m, n_validators, delta_cap)
            )(states, msgs)

        return CompilePlan(
            step=functools.partial(
                jax.jit, donate_argnums=_state_donation())(step_impl),
            slide=jax.jit(_slide_body),
            zero=jax.jit(_zero_body),
            strategy={"step": "jit", "slide": "jit", "zero": "jit"},
            mesh_shape=())

    axes = mesh.axis_names
    member_axis = axes[0]
    validator_axis = axes[1] if len(axes) > 1 else None
    state_spec, row_spec, events_spec, vec_spec = q.member_sharded_specs(
        member_axis, validator_axis)
    compact_spec = q.compact_member_specs(member_axis)
    mesh_shape = tuple(int(mesh.shape[a]) for a in axes)

    if validator_axis is None:
        def step_impl(states, words):
            msgs = q.unpack_words(words)
            return jax.vmap(
                lambda s, m: q.step_compact(s, m, n_validators, delta_cap)
            )(states, msgs)
    else:
        v_shards = mesh_shape[1]
        assert n_validator_rows % v_shards == 0, (n_validator_rows, v_shards)
        v_local = n_validator_rows // v_shards

        def step_impl(states, words):
            msgs = q.unpack_words(words)
            offset = (lax.axis_index(validator_axis).astype(jnp.int32)
                      * v_local)
            return jax.vmap(
                lambda s, m: q.step_compact_local(
                    s, m, n_validators, delta_cap, offset, v_local,
                    validator_axis)
            )(states, msgs)

    step = functools.partial(jax.jit, donate_argnums=_state_donation())(
        q.shard_map_compat(step_impl, mesh=mesh,
                           in_specs=(state_spec, row_spec),
                           out_specs=(state_spec, events_spec,
                                      compact_spec)))

    state_sh = _shardings(mesh, state_spec)
    vec_sh = _shardings(mesh, vec_spec)
    slide = jax.jit(_slide_body, in_shardings=(state_sh, vec_sh),
                    out_shardings=state_sh)
    zero = jax.jit(_zero_body, in_shardings=(state_sh, vec_sh),
                   out_shardings=state_sh)
    return CompilePlan(
        step=step, slide=slide, zero=zero,
        strategy={"step": "shard_map", "slide": "pjit", "zero": "pjit"},
        mesh_shape=mesh_shape)
