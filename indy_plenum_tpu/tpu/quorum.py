"""The device quorum plane: dense vote tensors + psum quorum detection.

TPU-native redesign of the per-message Python tallies in the reference's
``plenum/server/consensus/ordering_service.py`` (PREPARE/COMMIT cert
collection), ``checkpoint_service.py`` (checkpoint stabilization) and
``plenum/server/quorums.py`` (thresholds).

Instead of dict-of-sets bookkeeping, votes live in dense uint8 tensors:

    prepare_votes, commit_votes : (N_validators, LOG_SIZE_slots)
    preprepare_seen, ordered    : (LOG_SIZE_slots,)
    checkpoint_votes            : (N_validators, n_checkpoint_slots)

One jitted :func:`step` scatters a batch of validated protocol messages into
the tensors and recomputes quorum masks with masked sums + threshold
compares. Under ``shard_map`` the validator axis is sharded over the mesh
("validators" axis); vote counts become ``psum`` — the ICI is the vote bus.
Slots are watermark-relative (slot = ppSeqNo - h - 1, 0 <= slot < LOG_SIZE),
mirroring the reference's h/H watermark window; the host runtime slides the
window and resets slot columns on checkpoint stabilization.

Quorum thresholds (reference ``plenum/server/quorums.py``): f = (n-1)//3;
prepare quorum = n-f-1 (excludes the primary, which doesn't send PREPARE);
commit/checkpoint quorum = n-f.

**Vote-inclusion contract:** thresholds count votes over the FULL validator
axis, so the packer MUST scatter this node's OWN votes (its PREPARE row, its
COMMIT, its CHECKPOINT) into the batch alongside received messages. The host
services see only received messages (host ``Quorums.checkpoint`` is n-f-1 of
*others*); the device plane's n-f checkpoint threshold is equivalent only
when the own vote is present. ``pack_messages`` takes (kind, sender, slot)
triples — include ``(CHECKPOINT, own_index, slot)`` etc. explicitly.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

# message kinds in the packed device format
PREPREPARE = 0
PREPARE = 1
COMMIT = 2
CHECKPOINT = 3

# the quorum fabric's canonical mesh axis names: axis 0 shards the
# member axis M (= nodes x instances, independent planes), axis 1 — when
# present — shards each plane's validator axis N (quorum counts then
# ride the ICI as psum). A 1-axis ("members",) mesh is the PR 4 layout.
FABRIC_AXES = ("members", "validators")


def make_fabric_mesh(devices, shape) -> Mesh:
    """Build the quorum-fabric mesh from a device list and a 1- or 2-dim
    ``shape`` tuple: ``(8,)`` -> member-sharded only, ``(4, 2)`` -> the
    member x validator grid. The ONE constructor every surface
    (bench/profile/chaos/budget-gate/dryrun) builds its mesh through, so
    the axis names stay the :data:`FABRIC_AXES` contract."""
    shape = tuple(int(d) for d in shape)
    if not 1 <= len(shape) <= 2 or any(d < 1 for d in shape):
        raise ValueError(f"fabric mesh shape must be (M,) or (M, V): {shape}")
    n_dev = 1
    for d in shape:
        n_dev *= d
    if len(devices) < n_dev:
        raise ValueError(
            f"fabric mesh {shape} needs {n_dev} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n_dev]).reshape(shape),
                FABRIC_AXES[:len(shape)])


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: the top-level alias (with
    ``check_vma``) only exists on newer releases; older ones ship it as
    ``jax.experimental.shard_map`` (with ``check_rep``). Same semantics —
    replication checking off, because QuorumEvents mixes replicated and
    psum-derived outputs the checker cannot type."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


class VoteState(NamedTuple):
    """Device-resident per-instance vote tensors (slots are h-relative).

    ``ordered`` is the cumulative commit-quorum mask (slot had pp +
    prepare cert + commit cert at some step this window epoch);
    ``prepared_acked`` and ``frontier`` are the on-device ordering fast
    path's carried state: ``prepared_acked`` remembers which prepare
    certs were already REPORTED to the host (so :func:`step_compact`
    emits each slot exactly once per epoch) and ``frontier`` is the
    in-order ordering frontier — the length of the leading contiguous
    run of ``ordered`` slots, monotone within an epoch, slid with the
    window and zeroed on view reset."""

    preprepare_seen: jnp.ndarray  # (S,) uint8
    prepare_votes: jnp.ndarray  # (N, S) uint8  (sharded over N under a mesh)
    commit_votes: jnp.ndarray  # (N, S) uint8
    checkpoint_votes: jnp.ndarray  # (N, C) uint8
    ordered: jnp.ndarray  # (S,) uint8
    prepared_acked: jnp.ndarray  # (S,) uint8 — prepare certs already reported
    frontier: jnp.ndarray  # () int32 — in-order ordered frontier


class MsgBatch(NamedTuple):
    """A packed batch of validated consensus messages for the device plane."""

    kind: jnp.ndarray  # (M,) int32, one of the four kinds
    sender: jnp.ndarray  # (M,) int32 validator index
    slot: jnp.ndarray  # (M,) int32 h-relative slot (or checkpoint slot)
    valid: jnp.ndarray  # (M,) bool — invalid entries are padding


class QuorumEvents(NamedTuple):
    prepared: jnp.ndarray  # (S,) bool — prepare cert reached
    newly_ordered: jnp.ndarray  # (S,) bool — commit cert newly reached
    ordered: jnp.ndarray  # (S,) bool — cumulative
    stable_checkpoints: jnp.ndarray  # (C,) bool — checkpoint quorum reached
    prepare_counts: jnp.ndarray  # (S,) int32 (diagnostics / monitor feed)
    commit_counts: jnp.ndarray  # (S,) int32


def init_state(n_validators: int, log_size: int, n_checkpoints: int) -> VoteState:
    return VoteState(
        preprepare_seen=jnp.zeros((log_size,), jnp.uint8),
        prepare_votes=jnp.zeros((n_validators, log_size), jnp.uint8),
        commit_votes=jnp.zeros((n_validators, log_size), jnp.uint8),
        checkpoint_votes=jnp.zeros((n_validators, n_checkpoints), jnp.uint8),
        ordered=jnp.zeros((log_size,), jnp.uint8),
        prepared_acked=jnp.zeros((log_size,), jnp.uint8),
        frontier=jnp.zeros((), jnp.int32),
    )


def _scatter_local(
    state: VoteState, msgs: MsgBatch, row_offset: jnp.ndarray, local_rows: int
) -> VoteState:
    """Scatter message batch into the local shard of the vote tensors."""
    n_slots = state.prepare_votes.shape[1]
    n_cslots = state.checkpoint_votes.shape[1]
    local = msgs.sender - row_offset
    slot_ok = (msgs.slot >= 0) & (msgs.slot < n_slots)
    cslot_ok = (msgs.slot >= 0) & (msgs.slot < n_cslots)
    mine = msgs.valid & (local >= 0) & (local < local_rows)
    lidx = jnp.clip(local, 0, local_rows - 1)
    slot = jnp.clip(msgs.slot, 0, n_slots - 1)
    cslot = jnp.clip(msgs.slot, 0, n_cslots - 1)

    def hits(kind, ok):
        return (msgs.kind == kind) & mine & ok

    pv = state.prepare_votes.at[lidx, slot].max(
        hits(PREPARE, slot_ok).astype(jnp.uint8))
    cv = state.commit_votes.at[lidx, slot].max(
        hits(COMMIT, slot_ok).astype(jnp.uint8))
    ck = state.checkpoint_votes.at[lidx, cslot].max(
        hits(CHECKPOINT, cslot_ok).astype(jnp.uint8)
    )
    # PRE-PREPARE is per-slot, not per-validator: replicated across shards.
    pp_hit = (msgs.kind == PREPREPARE) & msgs.valid & slot_ok
    pp = state.preprepare_seen.at[slot].max(pp_hit.astype(jnp.uint8))
    return state._replace(preprepare_seen=pp, prepare_votes=pv,
                          commit_votes=cv, checkpoint_votes=ck)


def _quorum_events(
    state: VoteState, n: int, axis_name: Optional[str]
) -> Tuple[VoteState, QuorumEvents]:
    f = (n - 1) // 3
    prepare_q = n - f - 1
    commit_q = n - f

    def total(votes):  # sum over the (possibly sharded) validator axis
        local = jnp.sum(votes.astype(jnp.int32), axis=0)
        if axis_name is not None:
            return lax.psum(local, axis_name)
        return local

    prep_counts = total(state.prepare_votes)
    comm_counts = total(state.commit_votes)
    chk_counts = total(state.checkpoint_votes)

    pp = state.preprepare_seen.astype(bool)
    prepared = pp & (prep_counts >= prepare_q)
    commit_ok = pp & (comm_counts >= commit_q) & prepared
    newly = commit_ok & ~state.ordered.astype(bool)
    ordered = state.ordered.astype(bool) | commit_ok
    stable = chk_counts >= commit_q
    new_state = state._replace(ordered=ordered.astype(jnp.uint8))
    return new_state, QuorumEvents(
        prepared=prepared,
        newly_ordered=newly,
        ordered=ordered,
        stable_checkpoints=stable,
        prepare_counts=prep_counts,
        commit_counts=comm_counts,
    )


def step(
    state: VoteState, msgs: MsgBatch, n_validators: int
) -> Tuple[VoteState, QuorumEvents]:
    """Single-device step: scatter a message batch, recompute quorums."""
    state = _scatter_local(
        state, msgs, jnp.zeros((), jnp.int32), state.prepare_votes.shape[0]
    )
    return _quorum_events(state, n_validators, None)


# ----------------------------------------------------------------------
# on-device ordering fast path: quorum eval + frontier + compact deltas
# ----------------------------------------------------------------------

# fixed per-step delta capacity: a step whose newly-reached certs exceed
# it sets the TRUE count in CompactEvents.n_* and the host falls back to
# one full-events readback for that step (deterministic either way —
# overflow is a pure function of the vote trajectory)
ORDER_DELTA_CAP = 16


class CompactEvents(NamedTuple):
    """The fast path's per-step readback: O(newly ordered + frontier)
    bytes instead of the full (validator x window) event matrix.

    Slot lists are ascending, padded with S (the window size) — the host
    keeps everything ``< S``. ``n_prepared``/``n_committed`` carry the
    TRUE delta sizes so the host can detect an overflowed (> delta cap)
    step and reconcile from the full events, which stay device-resident."""

    frontier: jnp.ndarray  # () int32 — in-order ordering frontier (slots)
    new_prepared: jnp.ndarray  # (D,) int32 — newly prepare-certified slots
    n_prepared: jnp.ndarray  # () int32 — true count (> D means overflow)
    new_committed: jnp.ndarray  # (D,) int32 — newly commit-certified slots
    n_committed: jnp.ndarray  # () int32
    stable: jnp.ndarray  # (C,) uint8 — checkpoint-stable summary


def _delta_slots(newly: jnp.ndarray, cap: int):
    """Boolean slot mask -> (ascending slot ids padded with S, count).

    A full sort, deliberately: lax.top_k over a reversed score measures
    ~2x SLOWER than jnp.sort on XLA:CPU at (M=1408, S=300) — sort is
    the cheapest ascending-k-smallest XLA:CPU knows here."""
    s = newly.shape[0]
    idx = jnp.where(newly, jnp.arange(s, dtype=jnp.int32), jnp.int32(s))
    return jnp.sort(idx)[:cap], jnp.sum(newly).astype(jnp.int32)


def compact_from_events(
    state: VoteState, events: QuorumEvents, delta_cap: int,
) -> Tuple[VoteState, QuorumEvents, CompactEvents]:
    """Fold one step's :class:`QuorumEvents` into :class:`CompactEvents`
    + the carried fast-path state (``prepared_acked``/``frontier``) —
    the shared tail of :func:`step_compact` and the validator-sharded
    :func:`step_compact_local` (whose events are already psum'd, so this
    runs replicated across the validator axis and every shard emits the
    identical compact block)."""
    new_prep = events.prepared & ~state.prepared_acked.astype(bool)
    p_slots, p_n = _delta_slots(new_prep, delta_cap)
    c_slots, c_n = _delta_slots(events.newly_ordered, delta_cap)
    lead = jnp.sum(jnp.cumprod(events.ordered.astype(jnp.int32)))
    frontier = jnp.maximum(state.frontier, lead.astype(jnp.int32))
    state = state._replace(
        prepared_acked=events.prepared.astype(jnp.uint8),
        frontier=frontier)
    compact = CompactEvents(
        frontier=frontier,
        new_prepared=p_slots, n_prepared=p_n,
        new_committed=c_slots, n_committed=c_n,
        stable=events.stable_checkpoints.astype(jnp.uint8))
    return state, events, compact


def step_compact(
    state: VoteState, msgs: MsgBatch, n_validators: int,
    delta_cap: int = ORDER_DELTA_CAP,
) -> Tuple[VoteState, QuorumEvents, CompactEvents]:
    """Fused step for the ordering fast path: scatter + quorum eval +
    frontier advance, emitting :class:`CompactEvents` so the host reads
    back only what CHANGED. The full :class:`QuorumEvents` are still
    returned (device-resident) for the overflow fallback, diagnostics
    and ``host_eval`` differential runs — returning them costs no
    transfer unless fetched.

    Delta semantics: ``prepared_acked`` carries which prepare certs were
    already reported, so each slot appears in ``new_prepared`` exactly
    once per window epoch; ``new_committed`` rides the existing
    cumulative ``ordered`` mask the same way (``newly_ordered``). The
    frontier is the leading contiguous run of the cumulative ordered
    mask (pp + prepare cert + commit cert), monotone within the epoch —
    the host's in-order delivery point is ``h + frontier``."""
    state, events = step(state, msgs, n_validators)
    return compact_from_events(state, events, delta_cap)


def step_compact_local(
    state: VoteState, msgs: MsgBatch, n_validators: int, delta_cap: int,
    row_offset: jnp.ndarray, local_rows: int, axis_name: str,
) -> Tuple[VoteState, QuorumEvents, CompactEvents]:
    """:func:`step_compact` for a validator-SHARDED shard_map body (the
    2-axis quorum fabric): each shard scatters only the votes whose
    sender falls in its local row block ``[row_offset, row_offset +
    local_rows)`` and quorum counts reduce with ``psum`` over
    ``axis_name`` — the ICI is the vote bus. ``n_validators`` stays the
    REAL validator count (thresholds must not see pad rows; pad rows
    never receive votes, so the psum'd counts are exact)."""
    state = _scatter_local(state, msgs, row_offset, local_rows)
    state, events = _quorum_events(state, n_validators, axis_name)
    return compact_from_events(state, events, delta_cap)


def scatter_batch(
    state: VoteState, msgs: MsgBatch,
    row_offset: Optional[jnp.ndarray] = None,
    local_rows: Optional[int] = None,
) -> VoteState:
    """Scatter-only half of the fused step: fold a message batch into the
    vote tensors WITHOUT evaluating quorums. The multi-tick residency
    kernel (``compile_plan.resident_plan_for``) chains one scatter per
    ring slot — interleaved with the folded window slides — and runs
    :func:`eval_compact` once at the end, so k resident ticks cost one
    quorum evaluation instead of k. ``row_offset``/``local_rows`` carry
    the validator-sharded variant (2-axis fabric); defaults scatter the
    full local row block."""
    if row_offset is None:
        row_offset = jnp.zeros((), jnp.int32)
    if local_rows is None:
        local_rows = state.prepare_votes.shape[0]
    return _scatter_local(state, msgs, row_offset, local_rows)


def eval_compact(
    state: VoteState, n_validators: int,
    delta_cap: int = ORDER_DELTA_CAP, axis_name: Optional[str] = None,
) -> Tuple[VoteState, QuorumEvents, CompactEvents]:
    """Eval-only half of the fused step: quorum detection + frontier
    advance + compact deltas over the CURRENT vote tensors (no scatter).
    Deferring this behind k chained :func:`scatter_batch` calls is
    equivalent to per-tick evaluation for everything the host consumes:
    ``prepared_acked``/``ordered`` dedup each cert exactly once per
    window epoch regardless of which step detects it, and any slot a
    folded slide drops was (by the checkpoint-stabilization protocol)
    already certified AND reported before the host issued the slide."""
    state, events = _quorum_events(state, n_validators, axis_name)
    return compact_from_events(state, events, delta_cap)


def slide_state(state: VoteState, delta: jnp.ndarray) -> VoteState:
    """Roll the slot axis left by ``delta`` and zero the vacated columns
    (the checkpoint-stabilization window slide — the ONE definition both
    the standalone plane and every grouped compile plan jit)."""
    s = state.prepare_votes.shape[1]
    cols = jnp.arange(s)
    keep = cols < (s - delta)  # after roll, tail columns are new/empty

    def roll1(x):
        return jnp.where(keep, jnp.roll(x, -delta), 0)

    def roll2(x):
        return jnp.where(keep[None, :], jnp.roll(x, -delta, axis=1), 0)

    return VoteState(
        preprepare_seen=roll1(state.preprepare_seen),
        prepare_votes=roll2(state.prepare_votes),
        commit_votes=roll2(state.commit_votes),
        # delta == 0 must be a strict identity (the vmapped group slide
        # passes 0 for every member but the one actually sliding)
        checkpoint_votes=jnp.where(delta > 0, 0,
                                   state.checkpoint_votes),
        ordered=roll1(state.ordered),
        prepared_acked=roll1(state.prepared_acked),
        # the in-order frontier slides with the window (host mirrors
        # apply the identical clamp so device and host never disagree)
        frontier=jnp.maximum(
            state.frontier - delta, 0).astype(jnp.int32),
    )


def compact_member_specs(axis: str):
    """PartitionSpecs for :class:`CompactEvents` under a member-sharded
    group step (leading member axis M sharded over mesh axis ``axis``,
    nothing below it sharded — matches :func:`member_sharded_specs`)."""
    vec = P(axis)
    row = P(axis, None)
    return CompactEvents(
        frontier=vec,
        new_prepared=row, n_prepared=vec,
        new_committed=row, n_committed=vec,
        stable=row)


def make_sharded_step(mesh: Mesh, n_validators: int, axis: str = "validators"):
    """Build a pjit-ed step with the validator axis sharded over ``mesh``.

    The returned function takes a VoteState whose (N, S) tensors are sharded
    P(axis, None) and a replicated MsgBatch; vote counting rides the ICI as
    ``psum``. This is the "one pod simulates the pool" configuration from
    BASELINE.json's north star.
    """
    n_shards = mesh.shape[axis]
    assert n_validators % n_shards == 0, (n_validators, n_shards)
    local_rows = n_validators // n_shards

    def inner(state: VoteState, msgs: MsgBatch):
        offset = lax.axis_index(axis).astype(jnp.int32) * local_rows
        state = _scatter_local(state, msgs, offset, local_rows)
        return _quorum_events(state, n_validators, axis)

    row_sharded = VoteState(
        preprepare_seen=P(),
        prepare_votes=P(axis, None),
        commit_votes=P(axis, None),
        checkpoint_votes=P(axis, None),
        ordered=P(),
        prepared_acked=P(),
        frontier=P(),
    )
    replicated_msgs = MsgBatch(kind=P(), sender=P(), slot=P(), valid=P())
    events_spec = QuorumEvents(
        prepared=P(),
        newly_ordered=P(),
        ordered=P(),
        stable_checkpoints=P(),
        prepare_counts=P(),
        commit_counts=P(),
    )

    shard_fn = shard_map_compat(
        inner,
        mesh=mesh,
        in_specs=(row_sharded, replicated_msgs),
        out_specs=(row_sharded, events_spec),
    )
    return jax.jit(shard_fn)


def member_sharded_specs(axis: str, validator_axis: Optional[str] = None):
    """PartitionSpecs for a GROUP step whose LEADING axis is the member
    axis M (= nodes x instances), sharded over mesh axis ``axis``.

    Every VoteState/QuorumEvents leaf gains a leading member dim.
    Members are independent planes, so the grouped step needs no
    cross-member collectives and each chip keeps its member shard
    entirely local. With ``validator_axis`` (the 2-axis quorum fabric)
    the per-member vote matrices additionally shard their validator row
    axis over it — quorum counts then reduce with ``psum`` over that
    axis, and everything derived from the psum'd counts (events, compact
    deltas, the scatter words) stays replicated across it. Returns
    ``(state_spec, row_spec, events_spec, vec_spec)`` where ``row_spec``
    covers (M, B) operands (the packed scatter words) and ``vec_spec``
    covers (M,) operands (slide deltas, reset masks)."""
    vec = P(axis)
    row = P(axis, None)
    mat = P(axis, validator_axis, None)
    state_spec = VoteState(
        preprepare_seen=row,
        prepare_votes=mat,
        commit_votes=mat,
        checkpoint_votes=mat,
        ordered=row,
        prepared_acked=row,
        frontier=vec,
    )
    events_spec = QuorumEvents(
        prepared=row,
        newly_ordered=row,
        ordered=row,
        stable_checkpoints=row,
        prepare_counts=row,
        commit_counts=row,
    )
    return state_spec, row, events_spec, vec


def unpack_words(words: jnp.ndarray) -> MsgBatch:
    """Device-side decode of word-packed votes (see ``pack_words``).

    One uint32 per vote — valid(1) | kind(2) | sender(13) | slot(16) —
    quarters the host->device transfer vs four int32 arrays, which is the
    blocking cost of a group flush on a remote device link (and real
    bytes over PCIe/ICI on local hardware). Shifts/masks decode on the
    device, where they are free next to the scatter.
    """
    w = words.astype(jnp.uint32)
    return MsgBatch(
        kind=((w >> 29) & jnp.uint32(0x3)).astype(jnp.int32),
        sender=((w >> 16) & jnp.uint32(0x1FFF)).astype(jnp.int32),
        slot=(w & jnp.uint32(0xFFFF)).astype(jnp.int32),
        valid=(w >> 31) != 0,
    )


def pack_vote(kind: int, sender: int, slot: int) -> int:
    """ONE vote -> its uint32 word (the wire layout's single definition).

    Bounds: sender < 8192, slot < 65536, kind < 4 — far above any real
    pool, and ENFORCED: an out-of-range value would silently alias
    another sender/slot bit-field (the old MsgBatch path kept fields in
    separate int32 lanes; the packed word does not forgive). Packing at
    RECORD time keeps the hot flush path a single ``np.fromiter`` over
    ints instead of a tuple-list conversion."""
    if not (0 <= kind < 4 and 0 <= sender < 8192 and 0 <= slot < 65536):
        raise ValueError(
            f"vote field out of packed range: kind={kind} (<4), "
            f"sender={sender} (<8192), slot={slot} (<65536)")
    return 0x80000000 | (kind << 29) | (sender << 16) | slot


# The codec fast path for the dispatch plane: the same (kind, sender,
# slot) triple recurs constantly — every node (x f+1 instances) records
# node_j's PREPARE for slot s — so the packed word is memoized pool-wide.
# A hit skips the bounds re-validation in pack_vote; entries are 28-bit
# keys, so even a pathological run stays bounded by the cache size.
vote_word = functools.lru_cache(maxsize=1 << 18)(pack_vote)


def fill_words_row(row: np.ndarray, packed_words) -> None:
    """Write pre-packed uint32 vote ints into a zeroed row buffer — the
    ONE definition of the padded row layout every flush path uses (the
    single-plane path via :func:`words_row`, the group path writing
    straight into its (M, B) scatter buffer)."""
    row[: len(packed_words)] = np.fromiter(packed_words, np.uint32,
                                           len(packed_words))


def words_row(packed_words, max_batch: int) -> np.ndarray:
    """(already-packed uint32 vote ints) -> zero-padded (max_batch,) row."""
    out = np.zeros(max_batch, np.uint32)
    fill_words_row(out, packed_words)
    return out


def pack_words(entries, max_batch: int) -> np.ndarray:
    """Host helper: (kind, sender, slot) triples -> (max_batch,) uint32.

    Same vote-inclusion contract as :func:`pack_messages`."""
    return words_row([pack_vote(k, s, sl) for k, s, sl in entries],
                     max_batch)


def pack_messages(
    entries, max_batch: int
) -> MsgBatch:
    """Host helper: list of (kind, sender, slot) -> padded device MsgBatch.

    Entries must include this node's OWN votes, not just received messages
    (see the module docstring's vote-inclusion contract) — quorum thresholds
    are over the full validator axis.
    """
    m = len(entries)
    assert m <= max_batch
    kind = np.zeros(max_batch, np.int32)
    sender = np.zeros(max_batch, np.int32)
    slot = np.zeros(max_batch, np.int32)
    valid = np.zeros(max_batch, bool)
    for i, (k, s, sl) in enumerate(entries):
        kind[i], sender[i], slot[i], valid[i] = k, s, sl, True
    return MsgBatch(
        kind=jnp.asarray(kind),
        sender=jnp.asarray(sender),
        slot=jnp.asarray(slot),
        valid=jnp.asarray(valid),
    )
