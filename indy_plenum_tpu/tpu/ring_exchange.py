"""Ring-collective vote-plane exchange: device-to-device plane migration.

The scale-out quorum fabric shards the member axis across a mesh; when
the pool's membership or load shifts (a hot shard, a rebalance after
view change), whole member vote planes must MOVE between shards. The
host path for that is a gather + re-put — two PCIe crossings per plane.
This module prototypes the device-to-device path: every member shard
hands its block of planes to its ring neighbor over the interconnect,
no host hop.

Two implementations, one contract (``ring_shift_planes``):

- **reference** (any backend): ``shard_map`` + ``lax.ppermute`` — the
  collective XLA already knows. This is the semantics oracle and what
  CPU meshes (tests, virtual-device dryruns) execute.
- **pallas** (REAL TPU only, guarded): a ``pltpu.make_async_remote_copy``
  ring permute (SNIPPETS.md [1] / the Pallas ring-collective pattern) —
  each device RDMAs its local block straight into its right neighbor's
  buffer with send/recv DMA semaphores. Off TPU the builder raises
  ``NotImplementedError`` and callers fall back to the reference path;
  the kernel is the template the real-hardware run compiles.

Both shift the MEMBER-shard blocks by one ring step along mesh axis 0;
state carried per member (h, mirrors) must be rotated by the host-side
caller — this module moves the device tensors only.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from . import quorum as q


def _member_specs(state_like, axis: str, validator_axis=None):
    """Per-leaf member-sharded PartitionSpecs matching the group layout
    (ndim 3 leaves carry the validator axis under the 2-axis fabric)."""
    return jax.tree.map(
        lambda x: P(axis, validator_axis, None) if x.ndim == 3
        else P(axis, *([None] * (x.ndim - 1))), state_like)


def ring_shift_reference(states, mesh: Mesh, shift: int = 1):
    """Rotate every member-shard block ``shift`` steps to the RIGHT
    along mesh axis 0 via ``lax.ppermute`` — the backend-portable
    reference for the pallas kernel below. ``states`` is any pytree of
    member-leading arrays sharded over ``mesh`` (a
    :class:`~indy_plenum_tpu.tpu.quorum.VoteState` stack or a single
    tensor)."""
    axis = mesh.axis_names[0]
    validator_axis = mesh.axis_names[1] if len(mesh.axis_names) > 1 else None
    n_shards = int(mesh.shape[axis])
    perm = [(i, (i + shift) % n_shards) for i in range(n_shards)]
    specs = _member_specs(states, axis, validator_axis)

    def impl(s):
        return jax.tree.map(lambda x: lax.ppermute(x, axis, perm), s)

    return jax.jit(q.shard_map_compat(
        impl, mesh=mesh, in_specs=(specs,), out_specs=specs))(states)


def _ring_kernel(input_ref, output_ref, send_sem, recv_sem):
    """One ring step: RDMA the local block to the right neighbor (the
    SNIPPETS.md [1] permute, with the neighbor computed from the mesh
    position instead of baked in)."""
    from jax.experimental.pallas import tpu as pltpu

    my_idx = lax.axis_index("members")
    n = lax.axis_size("members")
    right = ((my_idx + 1) % n,)
    rdma = pltpu.make_async_remote_copy(
        src_ref=input_ref,
        dst_ref=output_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=right,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    rdma.start()
    rdma.wait()


@functools.lru_cache(maxsize=None)
def _pallas_ring_fn(mesh: Mesh, shape, dtype):
    """Compile the guarded pallas ring permute for one block shape."""
    if jax.default_backend() != "tpu":
        raise NotImplementedError(
            "pallas ring exchange needs a real TPU backend "
            f"(have {jax.default_backend()!r}); use ring_shift_reference")
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    axis = mesh.axis_names[0]

    def wrapper(x):
        return pl.pallas_call(
            _ring_kernel,
            out_shape=jax.ShapeDtypeStruct(shape, dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
            compiler_params=pltpu.TPUCompilerParams(collective_id=0),
        )(x)

    # shape is the per-device BLOCK (member dim included), so the spec
    # has exactly len(shape) entries: the sharded member axis + a None
    # per remaining dim
    spec = P(axis, *([None] * (len(shape) - 1)))
    return jax.jit(q.shard_map_compat(
        wrapper, mesh=mesh, in_specs=(spec,), out_specs=spec))


def ring_shift_pallas(x, mesh: Mesh):
    """One right-shift of a member-sharded array's blocks over the TPU
    interconnect (device-to-device RDMA, no host hop). Guarded: raises
    ``NotImplementedError`` off real TPU hardware."""
    block = (int(x.shape[0]) // int(mesh.shape[mesh.axis_names[0]]),
             *map(int, x.shape[1:]))
    return _pallas_ring_fn(mesh, block, x.dtype)(x)


def ring_shift_planes(states, mesh: Mesh, shift: int = 1):
    """Migrate member vote-plane blocks ``shift`` ring steps along mesh
    axis 0, device-to-device where the hardware allows it: the pallas
    RDMA path on a real TPU (single-step shifts), the ppermute reference
    everywhere else. Semantics are identical by construction — the
    reference IS the oracle the pallas path is tested against on
    hardware."""
    if shift % int(mesh.shape[mesh.axis_names[0]]) == 0:
        return states
    if shift == 1 and jax.default_backend() == "tpu" \
            and len(mesh.axis_names) == 1:
        try:
            return jax.tree.map(
                lambda x: ring_shift_pallas(x, mesh), states)
        except NotImplementedError:
            pass
    return ring_shift_reference(states, mesh, shift)
