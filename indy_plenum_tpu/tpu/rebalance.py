"""Occupancy-driven member-plane rebalancing for the sharded vote fabric.

The governor (tpu/governor.py) has WATCHED the per-cell occupancy grid
since PR 4 — its hottest-cell law narrows the tick for the whole pool
when one shard runs hot — but nothing ever ACTED on the placement. This
module closes the loop: a deterministic :class:`RebalancePolicy` folds
the governor's per-cell occupancy EWMAs into per-member-block heats,
and when the hottest/median skew holds above ``RebalanceSkewThreshold``
for ``RebalanceDwellTicks`` consecutive ticks, plans a ROTATION of the
member planes along mesh axis 0 — executed by the
:class:`~indy_plenum_tpu.tpu.vote_plane.VotePlaneGroup` at its next
checkpoint-boundary slide (the rebalance barrier: the only instant the
residency ring is guaranteed drained) through
:func:`~indy_plenum_tpu.tpu.ring_exchange.ring_shift_planes`.

Why a rotation and not an arbitrary permutation: the fabric's
device-to-device migration primitive is the ring exchange (ppermute
reference today, pallas RDMA on real TPUs), which moves whole
member-shard BLOCKS one ring step — so the policy plans in units the
interconnect can execute. Whole-block rotations alone are useless
(block heat is invariant under them), so the plan works in device ROWS:
a shift of ``s`` rows splits each old block's heat across two adjacent
new blocks at ratio ``(R - s%R)/R : (s%R)/R`` and block-shifts by
``s // R`` — :meth:`RebalancePolicy.plan` picks the ``s`` minimizing
the predicted hottest block.

Determinism: the policy is a pure fold over the EWMA series it is shown
(no clocks, no randomness) — same seeded run, same plans, asserted by
tests/test_residency.py and the residency gate.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from . import quorum as q
from .ring_exchange import _member_specs, ring_shift_planes


class RebalancePolicy:
    """Deterministic skew-threshold/dwell law over per-cell occupancy.

    ``observe(shard_ewmas)`` is called once per tick with the governor's
    flattened occupancy-EWMA grid (cell ``i * v_shards + j`` = member
    block i x validator block j) and returns the planned rotation in
    device ROWS (0 = no plan). After a plan, a cooldown window mutes the
    law while the post-rotation EWMAs re-learn the new placement —
    without it the stale pre-rotation transient would immediately
    re-trigger. ``force_tick`` (the testing/chaos hook behind the
    ``RebalanceForceTick`` knob) plans one rotation unconditionally at
    exactly that tick ordinal, so digest-identity arms can rebalance
    deterministically without engineering a hot shard."""

    def __init__(self, m_shards: int, shard_rows: int, v_shards: int = 1,
                 threshold: float = 0.0, dwell: int = 8,
                 force_tick: int = 0, cooldown: Optional[int] = None):
        if m_shards < 1 or shard_rows < 1 or v_shards < 1:
            raise ValueError("mesh shape must be positive")
        self._m = int(m_shards)
        self._rows = int(shard_rows)
        self._v = int(v_shards)
        self._threshold = float(threshold)
        self._dwell = max(1, int(dwell))
        self._force = int(force_tick)
        self._cool_len = (4 * self._dwell if cooldown is None
                          else max(0, int(cooldown)))
        self._tick = 0
        self._over = 0       # consecutive over-threshold ticks
        self._cooldown = 0   # ticks left before the law re-arms
        self.last_skew = 0.0
        self.planned = 0     # rotations this policy has planned

    @property
    def threshold(self) -> float:
        return self._threshold

    @property
    def dwell(self) -> int:
        return self._dwell

    @property
    def shard_rows(self) -> int:
        return self._rows

    def block_heat(self, shard_ewmas: Sequence[float]) -> List[float]:
        """Fold the flattened occupancy grid into per-member-block heat
        (mean over each block's validator cells — rotation moves member
        planes, so the member axis is the one the plan can change)."""
        return [
            sum(shard_ewmas[i * self._v:(i + 1) * self._v]) / self._v
            for i in range(self._m)]

    @staticmethod
    def skew(block_heat: Sequence[float]) -> float:
        """Hottest/median block heat (median of an even count is the
        mean of the middle two) — THE skew every surface reports."""
        heats = sorted(block_heat)
        n = len(heats)
        med = (heats[n // 2] if n % 2
               else (heats[n // 2 - 1] + heats[n // 2]) / 2.0)
        return max(heats) / max(med, 1e-9)

    def plan(self, block_heat: Sequence[float]) -> int:
        """Rotation (in device rows) minimizing the predicted hottest
        block, 0 if no rotation strictly improves it. A shift of ``s``
        rows re-partitions the member sequence so new block k holds the
        last ``s % R`` rows of old block ``k - s//R - 1`` and the first
        ``R - s%R`` rows of old block ``k - s//R`` — heat splits
        proportionally (rows within a block are not individually
        metered; the uniform split is the unbiased estimate). Smallest
        winning ``s`` ties-break, so plans are deterministic."""
        heat = list(block_heat)
        n_blocks = len(heat)
        rows = self._rows
        best_s, best_max = 0, max(heat)
        for s in range(1, n_blocks * rows):
            b0, r = divmod(s, rows)
            w_hi = (rows - r) / rows
            w_lo = r / rows
            pred = max(
                w_hi * heat[(k - b0) % n_blocks]
                + w_lo * heat[(k - b0 - 1) % n_blocks]
                for k in range(n_blocks))
            if pred < best_max - 1e-12:
                best_s, best_max = s, pred
        return best_s

    def observe(self, shard_ewmas: Optional[Sequence[float]]) -> int:
        """One tick of the law; returns the planned rotation in device
        rows (0 almost always)."""
        self._tick += 1
        if self._cooldown > 0:
            self._cooldown -= 1
            return 0
        heat = None
        if shard_ewmas is not None \
                and len(shard_ewmas) == self._m * self._v:
            heat = self.block_heat(shard_ewmas)
            self.last_skew = self.skew(heat)
        if self._force and self._tick == self._force:
            self._over = 0
            self._cooldown = self._cool_len
            s = self.plan(heat) if heat else 0
            if not s:
                s = max(1, self._rows // 2)  # forced arm always rotates
            self.planned += 1
            return s
        if self._threshold <= 0 or heat is None or self._m < 2:
            return 0
        if self.last_skew > self._threshold:
            self._over += 1
        else:
            self._over = 0
        if self._over >= self._dwell:
            self._over = 0
            self._cooldown = self._cool_len
            s = self.plan(heat)
            if s:
                self.planned += 1
            return s
        return 0

    @classmethod
    def from_config(cls, config, vote_group) -> Optional["RebalancePolicy"]:
        """The composition-root constructor: None unless the group is
        member-sharded AND a trigger is armed (skew law or force hook) —
        the common path pays nothing."""
        if vote_group is None or getattr(vote_group, "_m_shards", 1) < 2:
            return None
        if (config.RebalanceSkewThreshold <= 0
                and config.RebalanceForceTick <= 0):
            return None
        return cls(vote_group._m_shards, vote_group._shard_rows,
                   vote_group._v_shards,
                   threshold=config.RebalanceSkewThreshold,
                   dwell=config.RebalanceDwellTicks,
                   force_tick=config.RebalanceForceTick)


def rotate_planes(states, mesh, rows: int, shard_rows: int):
    """Rotate every member plane ``rows`` device rows along the member
    axis (row r's plane moves to row ``(r + rows) % M``).

    On a mesh this composes from primitives the interconnect can run:
    ``rows = b*R + s`` splits into whole-block ring shifts
    (:func:`ring_shift_planes` — ppermute reference / pallas RDMA) by
    ``b`` and ``b + 1``, merged shard-locally — new local row r takes
    the b-shift arm's row ``r - s`` when ``r >= s`` and the (b+1)-shift
    arm's row ``r - s + R`` otherwise (both are shard-local rolls, no
    extra collective). Unsharded it is a plain roll (tests and the
    degenerate 1-device mesh)."""
    if mesh is None:
        return jax.tree.map(
            lambda x: jnp.roll(x, rows, axis=0), states)
    b0, s = divmod(int(rows), int(shard_rows))
    shifted = ring_shift_planes(states, mesh, b0)
    if s == 0:
        return shifted
    shifted_up = ring_shift_planes(states, mesh, b0 + 1)
    axis = mesh.axis_names[0]
    validator_axis = (mesh.axis_names[1]
                      if len(mesh.axis_names) > 1 else None)
    specs = _member_specs(states, axis, validator_axis)

    def merge(a, b):
        def leaf(x, y):
            idx = jnp.arange(shard_rows).reshape(
                (-1,) + (1,) * (x.ndim - 1))
            return jnp.where(idx >= s,
                             jnp.roll(x, s, axis=0),
                             jnp.roll(y, s, axis=0))

        return jax.tree.map(leaf, a, b)

    return jax.jit(q.shard_map_compat(
        merge, mesh=mesh, in_specs=(specs, specs),
        out_specs=specs))(shifted, shifted_up)
