"""Host adapter making the device quorum tensors the consensus truth source.

Reference analog: the per-message Python tallies in
``plenum/server/consensus/ordering_service.py`` (prepare/commit cert
collection). Here the :class:`OrderingService` delegates quorum detection to
this plane: validated votes are buffered on the host, scattered into the
dense (validator x slot) tensors of :mod:`indy_plenum_tpu.tpu.quorum` in
fixed-size batches (stable shapes => one XLA compilation), and quorum
verdicts are read back as boolean events. The Python dicts remain only as
message logs (MessageReq replies, duplicate detection) — decisions come
from :class:`~indy_plenum_tpu.tpu.quorum.QuorumEvents`.

Slot addressing is watermark-relative (slot = pp_seq_no - h - 1), mirroring
the reference's h/H window; ``slide_to`` rolls the window on checkpoint
stabilization and ``reset`` clears it on view change.

Per the vote-inclusion contract in :mod:`indy_plenum_tpu.tpu.quorum`, the
caller records its OWN votes too, not just received messages.

Flush granularity: a quorum query flushes whatever is pending, so in the
per-message sim loop each message typically costs one padded device step —
correct but not amortized. Amortization comes from the tick-batched
dispatch plane (``simulation/quorum_driver.py`` / ``Node._quorum_tick``):
the event loop drains all deliveries due at the tick, then ONE grouped
device step carries every buffered vote from all members and f+1
instances (drain -> scatter -> single grouped step -> read events). The
ingress path likewise verifies whole request batches per tick.

Padded flush shapes come from a small ladder (``FLUSH_LADDER``): each
rung compiles exactly once, and a near-empty tick rides the smallest rung
instead of paying the full-width scatter for a handful of votes. With
:class:`AdaptiveLadder` the top rung is LEARNED from the observed
votes-per-dispatch distribution, so small pools stop compiling the
full-width shape. ``flush_occupancy`` (votes / padded capacity) is
recorded per dispatch so the amortization is a measured number, not a
docstring claim.

Scale past one chip: :class:`VotePlaneGroup` accepts a ``mesh`` and runs
the grouped step explicit-SPMD over the member axis via ``shard_map``
(pad M → shard → per-shard stage → single grouped step → gathered
events — README "Mesh-sharded dispatch plane"), with per-shard occupancy
series feeding the dispatch governor's hottest-shard law.
"""
from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..common.metrics_collector import MetricsCollector, MetricsName
from ..observability.trace import NULL_TRACE, _NO_SPAN
from . import quorum as q

# fixed flush granularity: stable shapes keep XLA from recompiling
FLUSH_BATCH = 128
# padded-shape ladder: a flush pads to the smallest rung that fits, so a
# single-vote tick costs a 16-wide scatter, not a 128-wide one. Every
# rung is a distinct static shape — each compiles once, then caches.
FLUSH_LADDER = (16, FLUSH_BATCH)


def ladder_shape(n_votes: int) -> int:
    """Smallest ladder rung holding ``n_votes`` (callers chunk at
    FLUSH_BATCH, so the top rung always fits)."""
    for rung in FLUSH_LADDER:
        if n_votes <= rung:
            return rung
    return FLUSH_BATCH


def pow2_rung(n_votes: int) -> int:
    """Smallest power-of-two rung >= ``n_votes``, clamped to the static
    ladder's bounds [FLUSH_LADDER[0], FLUSH_BATCH]."""
    rung = FLUSH_LADDER[0]
    while rung < min(n_votes, FLUSH_BATCH):
        rung *= 2
    return rung


class AdaptiveLadder:
    """Learned per-pool top flush rung (ROADMAP PR 3 "let the ladder
    itself adapt").

    The static ladder (16/128) makes a small pool whose busiest member
    buffers ~20 votes per dispatch pay a 128-wide scatter (and its XLA
    compile) forever. This controller watches the observed busiest-
    member votes-per-dispatch distribution and sets the pool's top rung
    to the p99 rounded UP to a power of two, clamped to the static
    ladder's bounds — so that pool settles on a 32-wide scatter and the
    128-wide shape is never compiled. Overflow dispatches beyond the
    learned top still get a containing power-of-two shape (each is one
    cached compilation, exactly like the static rungs).

    Deterministic: ``top`` is a pure function of the recorded sample
    series (integer percentile math, bounded window), so seeded runs
    replay the identical shape sequence. Learning starts only after
    ``min_samples`` dispatches — short runs (and most unit tests) keep
    the static ladder's exact behaviour.
    """

    def __init__(self, window: int = 512, min_samples: int = 64,
                 recompute_every: int = 32):
        from collections import deque

        self._samples: "deque[int]" = deque(maxlen=window)
        self._min_samples = min_samples
        # the p99 recompute sorts the whole window — done on a stride,
        # not per dispatch, so the hot flush loop (which PR 2/3 already
        # de-allocated) doesn't buy back an O(W log W) sort per flush
        self._recompute_every = recompute_every
        self._count = 0
        self.top = FLUSH_BATCH

    def record(self, busiest_votes: int) -> None:
        self._samples.append(busiest_votes)
        self._count += 1
        if (self._count >= self._min_samples
                and (self._count - self._min_samples)
                % self._recompute_every == 0):
            ordered = sorted(self._samples)
            # ceil(p99) index in pure integer math (determinism)
            idx = (99 * (len(ordered) - 1) + 99) // 100
            self.top = pow2_rung(ordered[idx])

    def shape(self, n_votes: int) -> int:
        if n_votes <= FLUSH_LADDER[0]:
            return FLUSH_LADDER[0]
        if n_votes <= self.top:
            return self.top
        return pow2_rung(n_votes)


class PlaneDeltas(NamedTuple):
    """One member's accumulated device-eval deltas since the last poll:
    ascending h-relative slots whose prepare / commit certificates newly
    completed, plus the member's current in-order ordering frontier
    (``h + frontier`` is the highest contiguously commit-certified
    seqNo). Consumed by ``OrderingService.service_quorum_tick`` instead
    of rescanning host snapshots."""

    prepared: List[int]
    committed: List[int]
    frontier: int


# the donation gate lives with the compilation plans now (one definition
# for the standalone jits here AND every plan compile_plan.py builds)
from .compile_plan import (  # noqa: E402
    _state_donation, plan_for, resident_plan_for)


@functools.lru_cache(maxsize=None)
def _jit_step_words():
    return functools.partial(
        jax.jit, static_argnums=(2,),
        donate_argnums=_state_donation())(_step_words_impl)


def _step_words_impl(state: q.VoteState, words, n_validators: int):
    return q.step(state, q.unpack_words(words), n_validators)


def _step_words(state: q.VoteState, words, n_validators: int):
    return _jit_step_words()(state, words, n_validators)


# the window-slide core lives in tpu.quorum (slide_state) so the
# compilation plans can jit it without a circular import
_slide = jax.jit(q.slide_state)


@functools.lru_cache(maxsize=None)
def _jit_step_words_compact():
    return functools.partial(
        jax.jit, static_argnums=(2, 3),
        donate_argnums=_state_donation())(_step_words_compact_impl)


def _step_words_compact_impl(state: q.VoteState, words, n_validators: int,
                             delta_cap: int):
    return q.step_compact(state, q.unpack_words(words), n_validators,
                          delta_cap)


def _step_words_compact(state: q.VoteState, words, n_validators: int,
                        delta_cap: int):
    """Single-plane ordering fast path: the standalone (deployed-Node)
    analog of the grouped compile-plan step — quorum eval + frontier
    advance on device, compact deltas read back."""
    return _jit_step_words_compact()(state, words, n_validators, delta_cap)


class DeviceVotePlane:
    """Per-instance device vote tensors + lazy flush/query interface.

    ``host_eval`` selects the readback mode (the ordering fast path):
    False (default) runs :func:`~indy_plenum_tpu.tpu.quorum.step_compact`
    — quorum verdicts and the in-order frontier are computed ON DEVICE
    and each flush reads back only the compact deltas, folded into host
    mirror planes; the plane then feeds ``poll_deltas``. True keeps the
    full event-matrix readback (differential-testing fallback). Both
    modes dispatch the identical device-step sequence."""

    def __init__(self, validators: List[str], log_size: int,
                 n_checkpoints: int = 4, h: int = 0,
                 host_eval: bool = False,
                 delta_cap: Optional[int] = None):
        self._validators = list(validators)
        self._index = {name: i for i, name in enumerate(self._validators)}
        self._n = len(self._validators)
        self._log_size = log_size
        self._n_chk = n_checkpoints
        self._h = h
        self.host_eval = host_eval
        self._delta_cap = int(delta_cap) if delta_cap else q.ORDER_DELTA_CAP
        self._state = q.init_state(self._n, log_size, n_checkpoints)
        self._pending: List[int] = []  # uint32 vote words (q.pack_vote)
        self._events: Optional[q.QuorumEvents] = None
        # host copies of the event arrays, refreshed once per flush (quorum
        # queries are per-message; don't re-transfer per query)
        self._host_prepared: Optional[np.ndarray] = None
        self._host_prepare_counts: Optional[np.ndarray] = None
        self._host_commit_counts: Optional[np.ndarray] = None
        self._host_commit_ok: Optional[np.ndarray] = None
        self._host_stable: Optional[np.ndarray] = None
        # device-eval mirrors (see VotePlaneGroup): incrementally
        # maintained from each step's CompactEvents deltas
        self._mir_prepared = np.zeros(log_size, bool)
        self._mir_commit_ok = np.zeros(log_size, bool)
        self._mir_stable = np.zeros(n_checkpoints, bool)
        self._mir_frontier = 0
        self._delta_prepared: List[int] = []
        self._delta_committed: List[int] = []
        self.flushes = 0
        # device->host transfer accounting (the fast path's contract is
        # measured in these, not asserted in prose)
        self.readback_bytes_total = 0
        self.readbacks = 0
        # cumulative scattered votes and padded scatter capacity: the
        # occupancy signal the dispatch governor closes its loop over
        # (per-tick deltas of these two counters)
        self.flush_votes_total = 0
        self.flush_capacity_total = 0
        # tick-batched mode: quorum queries read the last-synced snapshot
        # instead of flushing per query. There is NO built-in driver: the
        # runtime composition that sets this flag must call sync() (or, in
        # group mode, VotePlaneGroup.flush — what SimPool's tick does) once
        # per tick, or snapshots go permanently stale.
        self.defer_flush_on_query = False

    # --- recording ------------------------------------------------------

    @property
    def h(self) -> int:
        return self._h

    @property
    def has_buffered_votes(self) -> bool:
        """True if votes recorded since the last flush are still host-side
        (tick mode's lost-wakeup guard checks this)."""
        return bool(self._pending)

    def _slot(self, pp_seq_no: int) -> Optional[int]:
        slot = pp_seq_no - self._h - 1
        if 0 <= slot < self._log_size:
            return slot
        return None

    def _record(self, kind: int, sender: Optional[str],
                pp_seq_no: int) -> None:
        slot = self._slot(pp_seq_no)
        if slot is None:
            return
        idx = 0 if sender is None else self._index.get(sender)
        if idx is None:
            return
        self._pending.append(q.vote_word(kind, idx, slot))
        self._events = None

    def record_preprepare(self, pp_seq_no: int) -> None:
        self._record(q.PREPREPARE, None, pp_seq_no)

    def record_prepare(self, sender: str, pp_seq_no: int) -> None:
        self._record(q.PREPARE, sender, pp_seq_no)

    def record_commit(self, sender: str, pp_seq_no: int) -> None:
        self._record(q.COMMIT, sender, pp_seq_no)

    def record_checkpoint(self, sender: str, chk_slot: int) -> None:
        if 0 <= chk_slot < self._n_chk and sender in self._index:
            self._pending.append(
                q.vote_word(q.CHECKPOINT, self._index[sender], chk_slot))
            self._events = None

    def checkpoint_slot(self, seq_no_end: int, chk_freq: int) -> Optional[int]:
        """Checkpoint boundary seqNoEnd -> window-relative checkpoint slot.

        Boundaries sit at multiples of CHK_FREQ above the stable watermark
        h (itself a stabilized boundary), so slot = (end - h)/freq - 1.
        """
        delta = seq_no_end - self._h
        if delta <= 0 or delta % chk_freq != 0:
            return None
        slot = delta // chk_freq - 1
        return slot if slot < self._n_chk else None

    def record_checkpoint_vote(self, sender: str, seq_no_end: int,
                               chk_freq: int) -> None:
        slot = self.checkpoint_slot(seq_no_end, chk_freq)
        if slot is not None:
            self.record_checkpoint(sender, slot)

    def has_checkpoint_quorum(self, seq_no_end: int, chk_freq: int) -> bool:
        """n-f checkpoint votes at the boundary (OWN vote included — see
        the vote-inclusion contract in tpu.quorum)."""
        slot = self.checkpoint_slot(seq_no_end, chk_freq)
        if slot is None:
            return False
        self.events()
        return bool(self._host_stable[slot])

    # --- window management ---------------------------------------------

    def slide_to(self, new_h: int) -> None:
        """Checkpoint stabilized at ``new_h``: drop slots <= new_h."""
        if new_h <= self._h:
            return
        self._flush()
        delta = new_h - self._h
        self._state = _slide(self._state, jnp.int32(delta))
        self._h = new_h
        self._events = None
        self._host_prepared = None  # snapshot is void, even in defer mode
        self._roll_mirrors(delta)

    def _roll_mirrors(self, delta: int) -> None:
        """Mirror the device's window slide host-side: roll the eval
        mirrors left by ``delta``, clamp the frontier, re-base the
        unpolled delta slots (slots below the new h are stabilized —
        their consumers are done with them)."""
        s = self._log_size
        for mir in (self._mir_prepared, self._mir_commit_ok):
            if delta < s:
                mir[:s - delta] = mir[delta:]
                mir[s - delta:] = False
            else:
                mir[:] = False
        self._mir_stable[:] = False
        self._mir_frontier = max(self._mir_frontier - delta, 0)
        self._delta_prepared = [
            x - delta for x in self._delta_prepared if x >= delta]
        self._delta_committed = [
            x - delta for x in self._delta_committed if x >= delta]

    def _zero_mirrors(self) -> None:
        self._mir_prepared[:] = False
        self._mir_commit_ok[:] = False
        self._mir_stable[:] = False
        self._mir_frontier = 0
        self._delta_prepared = []
        self._delta_committed = []

    def reset(self, h: Optional[int] = None) -> None:
        """View change: clear all votes (they were for the old view)."""
        if h is not None:
            self._h = h
        self._state = q.init_state(self._n, self._log_size, self._n_chk)
        self._pending.clear()
        self._events = None
        self._host_prepared = None  # snapshot is void, even in defer mode
        self._zero_mirrors()

    # --- flush + queries ------------------------------------------------

    def _step_chunk(self, words) -> None:
        """One device step over a padded word row; in device-eval mode
        the compact deltas are folded into the mirrors immediately."""
        if self.host_eval:
            self._state, self._events = _step_words(
                self._state, words, self._n)
            return
        self._state, self._events, compact = _step_words_compact(
            self._state, words, self._n, self._delta_cap)
        self._apply_compact_single(compact)

    def _apply_compact_single(self, compact: "q.CompactEvents") -> None:
        """Fetch one step's compact deltas and fold them into the single-
        plane mirrors + delta accumulators (the standalone analog of
        VotePlaneGroup._apply_compact, same overflow fallback)."""
        host = jax.device_get(compact)
        bytes_n = sum(np.asarray(a).nbytes for a in host)
        s = self._log_size
        if int(host.n_prepared) > self._delta_cap:
            full = jax.device_get(self._events.prepared)
            bytes_n += full.nbytes
            new_p = np.nonzero(np.asarray(full, bool)
                               & ~self._mir_prepared)[0]
        else:
            row = np.asarray(host.new_prepared)
            new_p = row[row < s]
        if int(host.n_committed) > self._delta_cap:
            full = jax.device_get(self._events.ordered)
            bytes_n += full.nbytes
            new_c = np.nonzero(np.asarray(full, bool)
                               & ~self._mir_commit_ok)[0]
        else:
            row = np.asarray(host.new_committed)
            new_c = row[row < s]
        if new_p.size:
            self._mir_prepared[new_p] = True
            self._delta_prepared.extend(int(x) for x in new_p)
        if new_c.size:
            self._mir_commit_ok[new_c] = True
            self._delta_committed.extend(int(x) for x in new_c)
        np.copyto(self._mir_stable, np.asarray(host.stable, bool))
        self._mir_frontier = int(host.frontier)
        self.readback_bytes_total += bytes_n

    def _flush(self) -> None:
        while self._pending:
            chunk, self._pending = (self._pending[:FLUSH_BATCH],
                                    self._pending[FLUSH_BATCH:])
            shape = ladder_shape(len(chunk))
            words = jnp.asarray(q.words_row(chunk, shape))
            self._step_chunk(words)
            self.flushes += 1
            self.flush_votes_total += len(chunk)
            self.flush_capacity_total += shape

    def _refresh(self) -> None:
        self._flush()
        if self._events is None:  # nothing ever recorded
            self._step_chunk(
                jnp.asarray(q.words_row([], FLUSH_LADDER[0])))
            # a real device dispatch: count it like any other flush, or
            # the governor (and the dispatch budget) would see a post-
            # reset tick as free
            self.flushes += 1
            self.flush_capacity_total += FLUSH_LADDER[0]
        if not self.host_eval:
            # compact absorption already happened per step in _flush;
            # the snapshot IS the mirrors (counts stay device-resident)
            self._host_prepared = self._mir_prepared
            self._host_commit_ok = self._mir_commit_ok
            self._host_stable = self._mir_stable
            self._host_prepare_counts = None
            self._host_commit_counts = None
            self.readbacks += 1
            return
        (self._host_prepared, self._host_prepare_counts,
         self._host_commit_counts, self._host_stable) = jax.device_get(
            (self._events.prepared, self._events.prepare_counts,
             self._events.commit_counts, self._events.stable_checkpoints))
        self._host_commit_ok = (
            self._host_commit_counts >= self._n - (self._n - 1) // 3)
        self.readback_bytes_total += sum(
            a.nbytes for a in (self._host_prepared,
                               self._host_prepare_counts,
                               self._host_commit_counts, self._host_stable))
        self.readbacks += 1

    def sync(self) -> None:
        """Flush all buffered votes and refresh the host snapshot (the
        per-tick entry point in tick-batched mode)."""
        self._refresh()

    def events(self) -> q.QuorumEvents:
        if self._host_prepared is None or (
                not self.defer_flush_on_query
                and (self._pending or self._events is None)):
            self._refresh()
        return self._events

    def has_prepare_quorum(self, pp_seq_no: int) -> bool:
        """PRE-PREPARE seen AND n-f-1 matching PREPAREs (device verdict)."""
        slot = self._slot(pp_seq_no)
        if slot is None:
            return False
        self.events()
        return bool(self._host_prepared[slot])

    def has_commit_quorum(self, pp_seq_no: int) -> bool:
        slot = self._slot(pp_seq_no)
        if slot is None:
            return False
        self.events()
        return bool(self._host_commit_ok[slot])

    # the ordering fast path (device-side quorum eval): a plane that
    # feeds newly-certified deltas advertises delta_feed and serves
    # poll_deltas(); in host_eval mode services fall back to snapshot
    # re-scans (differential testing)
    @property
    def delta_feed(self) -> bool:
        return not self.host_eval

    @property
    def lagging(self) -> bool:
        """The standalone plane syncs synchronously — never a dispatched
        step awaiting absorb (the pipelined group overrides this; the
        governor's absorb clamp keys on it)."""
        return False

    def poll_deltas(self) -> Optional[PlaneDeltas]:
        """Drain the accumulated device-eval deltas (ascending h-relative
        slots whose prepare/commit certs newly completed since the last
        poll) + the current in-order frontier. Consumed once; None in
        host_eval mode AND on quiet polls (nothing completed — the
        common case for most members most ticks, kept allocation-free)."""
        if self.host_eval:
            return None
        if not self._delta_prepared and not self._delta_committed:
            return None
        prepared, self._delta_prepared = self._delta_prepared, []
        committed, self._delta_committed = self._delta_committed, []
        return PlaneDeltas(sorted(prepared), sorted(committed),
                           int(self._mir_frontier))

    def prepare_count(self, pp_seq_no: int) -> int:
        slot = self._slot(pp_seq_no)
        if slot is None:
            return 0
        self.events()
        if self._host_prepare_counts is not None:
            return int(self._host_prepare_counts[slot])
        # device-eval mode keeps counts device-resident; fetch the one
        # scalar on demand (diagnostics path, never the tick loop)
        if self._events is None:
            return 0
        return int(jax.device_get(self._events.prepare_counts[slot]))


class VotePlaneGroup:
    """M stacked vote planes stepped in ONE vmapped device dispatch.

    The "one pod co-processes the pool" configuration from BASELINE.json's
    north star: every simulated node holds a :class:`_MemberPlane` view onto
    a shared (M, ...) tensor stack; when any member queries quorum state,
    ALL members' buffered votes ride a single (M, FLUSH_BATCH) scatter.
    Against a high-latency device link this is the difference between one
    round-trip per node per tick and one per tick for the whole pool.
    """

    def __init__(self, n_members: int, validators: List[str], log_size: int,
                 n_checkpoints: int = 4, h: int = 0, metrics=None,
                 mesh=None, pipelined: bool = False,
                 adaptive_ladder: bool = False,
                 host_eval: bool = False,
                 delta_cap: Optional[int] = None,
                 resident_depth: int = 1):
        """``mesh``: an optional :class:`jax.sharding.Mesh` with one or
        two axes (build it with ``q.make_fabric_mesh``). Axis 0 shards
        the member axis of every vote tensor, so one pod's chips split
        the pool's planes and the grouped step runs explicit SPMD
        (members are independent — no cross-member collectives are
        needed; each chip's member shard stays local). Axis 1 — the
        2-axis quorum fabric — additionally shards each plane's
        VALIDATOR axis: quorum counts reduce with ``psum`` over it (the
        ICI is the vote bus), which is what lets n ≫ 100 pools keep
        per-chip vote tensors flat. Both axes pad UP to their mesh
        multiple: trailing pad member rows are real (zero) planes with
        no member view and pad validator rows never receive votes —
        neither perturbs counts, and occupancy accounting excludes
        them, so a 10-member pool on an 8-device mesh costs two idle
        rows, not a ValueError. HOW each step function compiles for the
        mesh shape (jit / pjit-with-shardings / shard_map) is resolved
        by :func:`~indy_plenum_tpu.tpu.compile_plan.plan_for`.
        ``adaptive_ladder`` hands the padded flush width to an
        :class:`AdaptiveLadder` (learned per-pool top rung).

        ``host_eval`` selects the readback/eval mode. False (the
        default, the ordering fast path): quorum decisions are made ON
        DEVICE (:func:`~indy_plenum_tpu.tpu.quorum.step_compact` —
        prepare/commit certificates, in-order frontier) and each
        dispatch reads back only its :class:`~indy_plenum_tpu.tpu
        .quorum.CompactEvents` deltas, which the group folds into
        incrementally-maintained host mirror planes; members additionally
        accumulate the deltas for ``poll_deltas``. True (the
        differential-testing fallback): the full (M, S) event matrix is
        fetched per flush exactly as before. Both modes dispatch the
        IDENTICAL device-step sequence — only the bytes crossing the
        link differ — so seeded runs order bit-identical digests either
        way (``check_dispatch_budget.py``'s readback gate)."""
        self._n = len(validators)
        self._log_size = log_size
        self._n_chk = n_checkpoints
        self.host_eval = host_eval
        self._delta_cap = int(delta_cap) if delta_cap else q.ORDER_DELTA_CAP
        self._mesh = mesh
        self._sharding = None
        self._m_shards = 1  # member-axis blocks (axis 0 of the mesh)
        self._v_shards = 1  # validator-axis blocks (axis 1, 2-axis fabric)
        self._shard_rows = n_members
        self._m_pad = n_members
        self._v_rows = self._n
        self._n_pad = self._n
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            axes = mesh.axis_names
            member_axis = axes[0]
            validator_axis = axes[1] if len(axes) > 1 else None
            self._m_shards = int(mesh.shape[member_axis])
            if validator_axis is not None:
                self._v_shards = int(mesh.shape[validator_axis])
            # BOTH axes pad up to their mesh multiple: pad member rows
            # are zero planes with no member view, pad validator rows
            # never receive votes (senders index the real validators) —
            # so neither perturbs quorum counts or occupancy capacity
            self._shard_rows = -(-n_members // self._m_shards)  # ceil
            self._m_pad = self._shard_rows * self._m_shards
            self._v_rows = -(-self._n // self._v_shards)
            self._n_pad = self._v_rows * self._v_shards
            # member axis sharded over axis 0; the per-member vote
            # matrices (ndim 3) additionally shard their validator row
            # axis over axis 1 when the fabric runs 2-axis
            specs = {
                1: PartitionSpec(member_axis),
                2: PartitionSpec(member_axis, None),
                3: PartitionSpec(member_axis, validator_axis, None),
            }
            self._sharding = lambda ndim: NamedSharding(mesh, specs[ndim])
            # member block -> owning device(s), resolved ONCE from the
            # sharding's own index map (the row-block assignment is
            # static per mesh; _stage_scatter must not recompute it —
            # or hop through the default device — per flush). Under the
            # 2-axis fabric each member block is REPLICATED across its
            # validator-axis devices, so a block owns several.
            imap = self._sharding(2).devices_indices_map((self._m_pad, 1))
            self._shard_devices = [[] for _ in range(self._m_shards)]
            for dev, idx in imap.items():
                self._shard_devices[
                    (idx[0].start or 0) // self._shard_rows].append(dev)
        # occupancy grid: one cell per (member block x validator block) —
        # flat index i * v_shards + j; with one validator shard this is
        # exactly the PR 4 per-member-shard series
        self._n_shards = self._m_shards * self._v_shards
        # the compilation plan (tpu.compile_plan): HOW step/slide/zero
        # compile for this mesh shape — jit / pjit-with-shardings /
        # shard_map — is decided there, in one place
        self._plan = plan_for(mesh, self._n, self._n_pad, self._delta_cap)
        # real (non-pad) member rows per member block: the capacity
        # denominator for per-shard occupancy — pad rows can never hold
        # votes and must not dilute the governor's signal
        self._real_rows = [
            min(max(n_members - si * self._shard_rows, 0), self._shard_rows)
            for si in range(self._m_shards)]
        # real validator rows per validator block (2-axis fabric): cell
        # capacity is apportioned by each block's share of real senders
        self._v_real = [
            min(max(self._n - vj * self._v_rows, 0), self._v_rows)
            for vj in range(self._v_shards)]
        proto = q.init_state(self._n_pad, log_size, n_checkpoints)
        self._states = jax.tree.map(
            lambda x: jnp.zeros((self._m_pad,) + x.shape, x.dtype), proto)
        if self._sharding is not None:
            self._states = jax.tree.map(
                lambda x: jax.device_put(x, self._sharding(x.ndim)),
                self._states)
        self._members = [
            _MemberPlane(self, i, validators, log_size, n_checkpoints, h)
            for i in range(n_members)]
        self.version = 0  # bumped on every device-state change
        # host snapshot. In BOTH modes `_host_prepared is None` means
        # "snapshot void" (cold start / post-slide / post-reset) and
        # drives the same empty-dispatch branches — the dispatch sequence
        # must never depend on the eval mode. In device-eval mode the
        # snapshot arrays point at the incrementally-maintained mirrors
        # below; in host_eval mode at the last fetched event matrix.
        self._host_prepared: Optional[np.ndarray] = None
        self._host_prepare_counts: Optional[np.ndarray] = None
        self._host_commit_counts: Optional[np.ndarray] = None
        self._host_commit_ok: Optional[np.ndarray] = None
        self._host_stable: Optional[np.ndarray] = None
        # device-eval mirrors: (M, S)/(M, C) boolean planes kept current
        # by folding each dispatch's CompactEvents deltas in — the host
        # never re-fetches what it already knows
        self._mir_prepared = np.zeros((self._m_pad, log_size), bool)
        self._mir_commit_ok = np.zeros((self._m_pad, log_size), bool)
        self._mir_stable = np.zeros((self._m_pad, n_checkpoints), bool)
        self._mir_frontier = np.zeros(self._m_pad, np.int64)
        # last absorbed step's device-resident full events: the overflow
        # fallback + on-demand diagnostics (prepare_count) read from it
        self._dev_events: Optional[q.QuorumEvents] = None
        # readback accounting: bytes actually crossing the device->host
        # boundary per absorb, and how many absorbs were overlapped
        # (consumed a step dispatched by an EARLIER flush call). On a
        # mesh the device-eval absorb runs PER MEMBER SHARD (one compact
        # block per shard, pipelined against the next shard's scatter
        # staging), so ``readbacks`` counts shard blocks there and the
        # per-shard byte series makes a hot shard visible.
        self.readback_bytes_total = 0
        self.readbacks = 0
        self.readbacks_overlapped = 0
        self.readback_bytes_per_shard = [0] * self._m_shards
        self._flush_seq = 0
        self.flushes = 0
        # occupancy counters (see DeviceVotePlane): per-tick deltas feed
        # the dispatch governor
        self.flush_votes_total = 0
        self.flush_capacity_total = 0
        # per-shard occupancy series (length 1 when unsharded): in mesh
        # mode the governor EWMAs each shard separately, so one hot
        # shard narrows the tick for the whole pool while idle siblings
        # cannot mask it behind the pool-wide average
        self.flush_votes_per_shard = [0] * self._n_shards
        self.flush_capacity_per_shard = [0] * self._n_shards
        # scale-out flush chunking: a full 3PC wave buffers ~2N votes
        # per member (N prepares + N commits), so past n=64 the static
        # 128-wide top rung makes every tick chain ceil(2N/128) grouped
        # dispatches and dispatches/ordered-batch GROWS with the pool —
        # the fabric's flat-scaling claim dies (measured: 7.5 vs 1.5 at
        # n=256 vs n=64 before this). The group's chunk limit holds one
        # wave, pow2 (each rung stays one cached compilation), and
        # never drops below the static FLUSH_BATCH — pools with n<=64
        # keep the PR 2 ladder bit-for-bit.
        self.flush_batch = FLUSH_BATCH
        while self.flush_batch < 2 * self._n and self.flush_batch < 4096:
            self.flush_batch *= 2
        # reusable host scatter staging (UNSHARDED path only): one
        # preallocated (M, B) buffer per ladder rung — the hot loop
        # stops paying an (M, B) np.zeros allocation per flush. Reuse is
        # safe ONLY because the device hand-off is a forced copy
        # (jnp.array, never jnp.asarray): on jax 0.4.37's CPU backend
        # asarray zero-copies suitably aligned numpy buffers (allocator
        # luck, reproduced empirically), and an aliased buffer reused
        # across `_dispatch_pending`'s chained async dispatches would
        # silently corrupt in-flight vote words. The mesh path stages
        # into FRESH per-shard buffers instead (see _stage_scatter) —
        # never reused, so they ship without the forced copy.
        self._scatter_bufs: dict = {}
        # learned top rung (None = static FLUSH_LADDER behaviour)
        self._ladder = AdaptiveLadder() if adaptive_ladder else None
        # device placement must be justifiable with data: flush count,
        # latency and votes-per-flush land here (injectable for a shared
        # or null collector)
        self.metrics = metrics if metrics is not None else MetricsCollector()
        # flight recorder (observability.trace): per-dispatch spans
        # (flush.dispatch with votes/shape, flush.readback) land here
        # when the composition roots hand in a recorder; NULL_TRACE
        # keeps the hot path free otherwise
        self.trace = NULL_TRACE
        # pipelined mode: flush() DISPATCHES this tick's step (async, JAX
        # never blocks on dispatch) and absorbs the PREVIOUS tick's events
        # into the host snapshot — the device round-trip overlaps a full
        # tick of host work instead of stalling the loop. Cost: quorum
        # verdicts lag one extra tick (votes are never lost; the services'
        # lost-wakeup guard re-arms while a step is in flight).
        self.pipelined = pipelined
        # in-flight steps: list of (events, compact) per chained dispatch
        # of the last flush, plus the flush seq that dispatched them
        # (overlap attribution)
        self._inflight: Optional[list] = None
        self._inflight_seq = 0
        # --- multi-tick device residency (README "Multi-tick device
        # residency & rebalancing"). With resident_depth N > 1, flush()
        # ENQUEUES each tick's scatter words into a device-side ring
        # (device_put is a transfer, not an XLA dispatch) and dispatches
        # ONE fused step per up-to-N ticks via resident_plan_for —
        # checkpoint slides FOLD into that step as a per-slot operand,
        # so a slide no longer forces a sync + host re-stage. Quorum
        # verdicts may lag up to N ticks; ordered CONTENT is
        # bit-identical to the per-tick path (PR 2's timing-robustness
        # law, asserted by the residency gate). Depth 1 (the default)
        # takes none of these paths — bit-identical to PR 7/9. Device
        # eval only: host_eval falls back to per-tick.
        self.resident_depth = max(1, int(resident_depth))
        self._resident = self.resident_depth > 1 and not host_eval
        # ring slots: (slide_vec | None, staged words, votes, shard_votes)
        self._ring: list = []
        self._ring_ticks = 0   # enqueued ticks since the last consume
        self.resident_ticks = 0        # total ticks that rode the ring
        self.readbacks_deferred = 0    # ticks whose readback deferred
        # one FIXED slot width bounds the resident-plan compile cache to
        # (slots, width) — the adaptive ladder stays a per-tick feature
        self._resident_width = self.flush_batch
        self._pending_slide = np.zeros(self._m_pad, np.int32)  # by ROW
        # cumulative slide per MEMBER, plus the snapshot taken when the
        # in-flight consume was dispatched: their difference is the
        # rebase the absorb applies to reported slot indices
        self._slide_cum = np.zeros(self._m_pad, np.int64)
        self._inflight_cum = self._slide_cum.copy()
        if self._resident:
            self.metrics.add_event(
                MetricsName.DEVICE_RESIDENT_DEPTH, self.resident_depth)
        # --- occupancy-driven rebalancing (tpu/rebalance.py): member
        # planes may ROTATE across device rows at a checkpoint-boundary
        # barrier; the placement map below translates member index <->
        # device row everywhere the host touches rows. Host mirrors stay
        # MEMBER-indexed — the translation IS the h/mirror rotation.
        self._row_shift = 0
        self._rebalance_pending = 0
        self.rebalances = 0
        self._rebuild_placement()

    def _rebuild_placement(self) -> None:
        """Recompute the row->member map from the current rotation shift
        (identity until the first rebalance)."""
        rows = (np.arange(self._m_pad) - self._row_shift) % self._m_pad
        self._row_member = np.where(
            rows < len(self._members), rows, -1).astype(np.int64)
        self._row_valid = self._row_member >= 0

    def _row_of(self, member_idx: int) -> int:
        """Device row currently holding this member's plane."""
        return (member_idx + self._row_shift) % self._m_pad

    @property
    def row_shift(self) -> int:
        """Current member->device-row rotation (0 until a rebalance)."""
        return self._row_shift

    def view(self, member_idx: int) -> "DeviceVotePlane":
        return self._members[member_idx]

    @property
    def shards(self) -> int:
        """Occupancy-grid cell count == mesh device count (1 unsharded;
        member blocks x validator blocks on the 2-axis fabric)."""
        return self._n_shards

    @property
    def mesh_shape(self) -> tuple:
        """() unsharded, (M,) member-sharded, (M, V) on the 2-axis
        member x validator fabric — the shape every surface reports
        alongside ``shards``."""
        return self._plan.mesh_shape

    @property
    def compile_strategy(self) -> dict:
        """Which compilation path each step function took (the resolved
        :class:`~indy_plenum_tpu.tpu.compile_plan.CompilePlan`)."""
        return dict(self._plan.strategy)

    @property
    def shard_occupancy(self) -> List[float]:
        """Cumulative per-shard occupancy (scattered votes / real-row
        capacity) — THE definition every surface reports (bench, budget
        gate, profile, dryrun). On the 2-axis fabric the list is the
        flattened grid (cell i*V + j = member block i x validator block
        j, capacity apportioned by block j's share of real senders), so
        a hot validator block shows exactly like a hot member block."""
        return [round(v / c, 4) if c else 0.0
                for v, c in zip(self.flush_votes_per_shard,
                                self.flush_capacity_per_shard)]

    @property
    def eval_mode(self) -> str:
        """Where quorum decisions are made: "device" (compact readback,
        the default) or "host" (full event-matrix readback fallback)."""
        return "host" if self.host_eval else "device"

    def _absorb_results(self, results: list, overlapped: bool) -> None:
        """Fold one flush's chained steps into the host snapshot (all
        shard blocks at once — the pipelined flush instead drives
        :meth:`_absorb_blocks` interleaved with its scatter staging)."""
        for _ in self._absorb_blocks(results, overlapped):
            pass

    def _absorb_blocks(self, results: list, overlapped: bool):
        """Generator folding one flush's chained steps into the host
        snapshot, one member-shard block at a time (yielding between
        blocks so the pipelined flush can overlap each block's absorb
        with the NEXT shard's scatter staging).

        host_eval mode: ONE bundled full-matrix transfer (the last
        chained step's events are cumulative) — the gather-all fallback.
        Device-eval mode: each step's CompactEvents deltas are fetched
        PER MEMBER SHARD (one addressable block per shard; under the
        2-axis fabric the validator-axis replicas are never fetched) and
        folded into the mirrors — O(newly certified + frontier) bytes,
        with a full-events fallback only for a member whose per-step
        delta overflowed the fixed capacity. Each block is one
        ``flush.readback`` span (``shard`` arg on a mesh) and one
        ``readbacks`` count — the fast path's acceptance contract."""
        trace_on = self.trace.enabled
        if self.host_eval:
            args = ({"bytes": 0, "overlapped": overlapped}
                    if trace_on else None)
            with self.trace.span("flush.readback", args=args) \
                    if trace_on else _NO_SPAN:
                events = results[-1][0]
                (self._host_prepared, self._host_prepare_counts,
                 self._host_commit_counts,
                 self._host_stable) = jax.device_get(
                    (events.prepared, events.prepare_counts,
                     events.commit_counts, events.stable_checkpoints))
                if self._row_shift:
                    # host_eval snapshots are ROW-indexed matrices but
                    # members read them BY INDEX — un-rotate the rows so
                    # member views keep slicing at their own index
                    perm = (np.arange(self._m_pad)
                            + self._row_shift) % self._m_pad
                    (self._host_prepared, self._host_prepare_counts,
                     self._host_commit_counts, self._host_stable) = (
                        self._host_prepared[perm],
                        self._host_prepare_counts[perm],
                        self._host_commit_counts[perm],
                        self._host_stable[perm])
                self._host_commit_ok = (
                    self._host_commit_counts
                    >= self._n - (self._n - 1) // 3)
                bytes_n = sum(a.nbytes for a in (
                    self._host_prepared, self._host_prepare_counts,
                    self._host_commit_counts, self._host_stable))
                if args is not None:
                    args["bytes"] = bytes_n
            self.readback_bytes_total += bytes_n
            self.readbacks += 1
            if overlapped:
                self.readbacks_overlapped += 1
            self.metrics.add_event(MetricsName.DEVICE_READBACK_BYTES,
                                   bytes_n)
        else:
            sharded = self._mesh is not None
            blocks = self._m_shards if sharded else 1
            for si in range(blocks):
                args = ({"bytes": 0, "overlapped": overlapped}
                        if trace_on else None)
                if args is not None and sharded:
                    args["shard"] = si
                with self.trace.span("flush.readback", args=args) \
                        if trace_on else _NO_SPAN:
                    bytes_n = 0
                    for events, compact in results:
                        bytes_n += self._apply_compact_block(
                            events, compact, si if sharded else None)
                    if args is not None:
                        args["bytes"] = bytes_n
                self.readback_bytes_total += bytes_n
                if sharded:
                    self.readback_bytes_per_shard[si] += bytes_n
                self.readbacks += 1
                if overlapped:
                    self.readbacks_overlapped += 1
                self.metrics.add_event(MetricsName.DEVICE_READBACK_BYTES,
                                       bytes_n)
                yield si
            self._host_prepared = self._mir_prepared
            self._host_commit_ok = self._mir_commit_ok
            self._host_stable = self._mir_stable
            self._host_prepare_counts = None
            self._host_commit_counts = None
        self._dev_events = results[-1][0]
        self.metrics.add_event(MetricsName.DEVICE_READBACK_COMPACT,
                               0 if self.host_eval else 1)
        self.version += 1

    def _block_shard(self, arr, row_lo: int):
        """The addressable shard of a member-sharded array whose member
        rows start at ``row_lo`` (first validator-axis replica wins —
        replicas are identical by the psum construction)."""
        for sh in arr.addressable_shards:
            if (sh.index[0].start or 0) == row_lo:
                return sh
        raise RuntimeError(f"no addressable shard at member row {row_lo}")

    def _apply_compact_block(self, events: q.QuorumEvents,
                             compact: "q.CompactEvents",
                             si: Optional[int]) -> int:
        """Fetch ONE step's compact deltas — the whole group (``si`` is
        None, unsharded) or one member shard's block — and fold them
        into the mirrors + per-member delta accumulators; returns the
        bytes that crossed the link. A member whose true delta count
        exceeds the fixed capacity triggers one full-events fetch (of
        the same block) for this step and reconciles by diffing against
        its mirror — same result, bigger readback, deterministic
        (overflow is a pure function of the seeded vote trajectory)."""
        if si is None:
            lo = 0
            host = jax.device_get(compact)
        else:
            lo = si * self._shard_rows
            host = q.CompactEvents(*[
                np.asarray(self._block_shard(leaf, lo).data)
                for leaf in compact])
        bytes_n = sum(np.asarray(a).nbytes for a in host)
        s = self._log_size
        cap = self._delta_cap
        members = self._members
        rows = host.frontier.shape[0]
        # pad rows hold nothing; a rotated placement maps each device
        # row back to its member (or -1) via the placement map
        row_member = self._row_member[lo:lo + rows]
        valid = self._row_valid[lo:lo + rows]
        over_p = np.asarray(host.n_prepared) > cap
        over_c = np.asarray(host.n_committed) > cap
        full_prep = full_ord = None
        if (over_p & valid).any() or (over_c & valid).any():
            if si is None:
                full_prep, full_ord = jax.device_get(
                    (events.prepared, events.ordered))
            else:
                full_prep = np.asarray(
                    self._block_shard(events.prepared, lo).data)
                full_ord = np.asarray(
                    self._block_shard(events.ordered, lo).data)
            bytes_n += full_prep.nbytes + full_ord.nbytes
        # rows with anything to fold: slot lists are ascending and
        # S-padded, so row[0] < S iff the row is non-empty
        touched = np.nonzero(
            ((host.new_prepared[:, 0] < s)
             | (host.new_committed[:, 0] < s)
             | over_p | over_c) & valid)[0]
        cum = self._inflight_cum
        for r in touched:
            mi = int(row_member[r])
            member = members[mi]
            # residency slide-fold rebase: slides folded INTO the
            # consumed steps moved the window AFTER those steps' certs
            # were detected, so reported slots are in pre-slide
            # coordinates; shift them down by the slides applied since
            # the consume was dispatched (0 on every per-tick path —
            # bit-identical fold)
            shift_d = int(self._slide_cum[mi] - cum[mi])
            if over_p[r]:
                full_row = full_prep[r]
                if shift_d:
                    full_row = np.concatenate(
                        [full_row[shift_d:],
                         np.zeros(min(shift_d, s), full_row.dtype)])
                new = np.nonzero(full_row
                                 & ~self._mir_prepared[mi])[0]
            else:
                row = host.new_prepared[r]
                new = row[row < s]
                if shift_d:
                    new = new[new >= shift_d] - shift_d
            if new.size:
                self._mir_prepared[mi, new] = True
                member._delta_prepared.extend(int(x) for x in new)
            if over_c[r]:
                full_row = full_ord[r]
                if shift_d:
                    full_row = np.concatenate(
                        [full_row[shift_d:],
                         np.zeros(min(shift_d, s), full_row.dtype)])
                new = np.nonzero(full_row
                                 & ~self._mir_commit_ok[mi])[0]
            else:
                row = host.new_committed[r]
                new = row[row < s]
                if shift_d:
                    new = new[new >= shift_d] - shift_d
            if new.size:
                self._mir_commit_ok[mi, new] = True
                member._delta_committed.extend(int(x) for x in new)
        mis = row_member[valid]
        stable = np.asarray(host.stable).astype(bool)[valid]
        frontier = np.asarray(host.frontier)[valid]
        deltas = self._slide_cum[mis] - cum[mis]
        plain = deltas == 0
        self._mir_stable[mis[plain]] = stable[plain]
        self._mir_frontier[mis[plain]] = frontier[plain]
        if not plain.all():
            # slid members: the device checkpoint votes the report saw
            # were zeroed by the folded slide's own roll — keep the
            # mirror's post-slide False state, and only advance (never
            # overwrite) the frontier by the rebased report
            sh = ~plain
            self._mir_frontier[mis[sh]] = np.maximum(
                self._mir_frontier[mis[sh]],
                np.maximum(frontier[sh] - deltas[sh], 0))
        return bytes_n

    @property
    def lagging(self) -> bool:
        """True while a dispatched step's events are not yet in the host
        snapshot (pipelined mode) — quorum state may be newer on device.
        A resident-but-unread ring slot counts the same way: its votes
        are device-bound but not yet evaluated, so the governor's absorb
        clamp and the services' lost-wakeup guard treat it as
        in-flight."""
        return self._inflight is not None or bool(self._ring)

    def _stage_scatter(self, chunks: List[List[int]], shape: int,
                       interleave=None):
        """Pack ``chunks`` into the rung's reusable host buffer(s) and
        hand the device its own copy (one vectorized row write per
        member; the staging buffers themselves are never reallocated).

        Mesh mode stages PER SHARD: each member shard's rows land in a
        FRESH (rows, shape) buffer shipped straight to that shard's
        device(s) (every validator-axis replica under the 2-axis
        fabric), then assemble into ONE global member-sharded array — no
        host-side (M_pad, B) concat, no default-device hop, no
        device-side resharding on the flush path. Fresh buffers (not the
        unsharded path's reusable ones): a buffer that is never touched
        again has no aliasing hazard, so the device hand-off needs no
        forced copy — one allocation per shard replaces the
        memset+fill+copy a reused buffer would cost. ``interleave``
        (the pipelined per-shard flush) is advanced once per member
        shard AFTER its device_put is in flight, so the previous tick's
        readback block for one shard folds host-side while the next
        shard's scatter rides the link."""
        if self._mesh is None:
            out = self._scatter_bufs.get(shape)
            if out is None:
                out = self._scatter_bufs[shape] = np.zeros(
                    (len(self._members), shape), np.uint32)
            out[...] = 0
            if self._row_shift:
                for i, entries in enumerate(chunks):
                    if entries:
                        q.fill_words_row(out[self._row_of(i)], entries)
            else:
                for i, entries in enumerate(chunks):
                    if entries:
                        q.fill_words_row(out[i], entries)
            # forced copy — see the staging-buffer comment in __init__
            # for why asarray would alias and corrupt in-flight
            # dispatches
            return jnp.array(out)
        arrs = []
        for si in range(self._m_shards):
            buf = np.zeros((self._shard_rows, shape), np.uint32)
            base = si * self._shard_rows
            if self._row_shift:
                for r in range(self._shard_rows):
                    mi = int(self._row_member[base + r])
                    if 0 <= mi < len(chunks) and chunks[mi]:
                        q.fill_words_row(buf[r], chunks[mi])
            else:
                for r in range(min(self._shard_rows, len(chunks) - base)):
                    if chunks[base + r]:
                        q.fill_words_row(buf[r], chunks[base + r])
            arrs.extend(jax.device_put(buf, dev)
                        for dev in self._shard_devices[si])
            if interleave is not None:
                next(interleave, None)
        return jax.make_array_from_single_device_arrays(
            (self._m_pad, shape), self._sharding(2), arrs)

    def _run_group_step(self, words):
        """ONE grouped device step over the whole (padded) member axis —
        compiled per the group's :class:`~indy_plenum_tpu.tpu
        .compile_plan.CompilePlan` (shard_map under a mesh, plain
        vmapped jit otherwise). Returns (new_states, events, compact):
        quorum eval AND the in-order frontier advance happen inside this
        dispatch (the ordering fast path), in both modes — host_eval
        only changes what gets read back, never what the device
        computes."""
        return self._plan.step(self._states, words)

    def _cell_votes(self, shard_votes: List[int], base: int, take) -> None:
        """Attribute one member's scattered votes to occupancy-grid
        cells: by member block alone (1-axis), or additionally by each
        vote's SENDER block under the 2-axis fabric (the validator axis
        shards the reduction, so a hot validator block is a real
        hot-spot the governor must see)."""
        if self._v_shards == 1:
            shard_votes[base] += len(take)
            return
        for w in take:
            shard_votes[base + min(((w >> 16) & 0x1FFF) // self._v_rows,
                                   self._v_shards - 1)] += 1

    def _collect_chunks(self):
        """Take one flush-batch chunk from every member's pending queue,
        attributing votes to occupancy-grid cells under the CURRENT
        placement map (a rotated member's votes land on — and heat — the
        rows now holding its plane)."""
        chunks = []
        votes = 0
        shard_votes = [0] * self._n_shards
        for i, m in enumerate(self._members):
            take, m._pending = (m._pending[:self.flush_batch],
                                m._pending[self.flush_batch:])
            chunks.append(take)
            votes += len(take)
            self._cell_votes(
                shard_votes,
                (self._row_of(i) // self._shard_rows) * self._v_shards,
                take)
        return chunks, votes, shard_votes

    def _dispatch_pending(self, interleave=None):
        """Chunk + scatter every member's pending votes (async dispatch);
        returns the list of chained (events, compact) step results, empty
        if nothing was pending. ``interleave`` threads the pipelined
        per-shard absorb generator through the scatter staging."""
        results = []
        while any(m._pending for m in self._members):
            chunks, votes, shard_votes = self._collect_chunks()
            # the padded width rides the busiest member: a quiet tick
            # (a few straggler votes) scatters 16-wide, a full protocol
            # wave 128-wide — each rung is one cached XLA compilation.
            # With the adaptive ladder, the top rung is LEARNED from the
            # observed busiest-member distribution instead of fixed.
            busiest = max(len(c) for c in chunks)
            if self._ladder is not None:
                self._ladder.record(busiest)
                shape = self._ladder.shape(busiest)
            else:
                shape = ladder_shape(busiest)
            if busiest > FLUSH_BATCH:
                # scale-out rungs above the static ladder (n > 64): the
                # containing pow2 up to the group's one-wave chunk limit
                shape = FLUSH_BATCH
                while shape < busiest:
                    shape *= 2
            args = None
            if self.trace.enabled:
                args = {"votes": votes, "shape": shape}
                if self._n_shards > 1:
                    # per-cell vote split: a hot shard is visible from a
                    # trace dump alone (trace_tool.py --overlap)
                    args["shard_votes"] = list(shard_votes)
            with self.trace.span("flush.dispatch", args=args) \
                    if self.trace.enabled else _NO_SPAN:
                words = self._stage_scatter(chunks, shape, interleave)
                self._states, events, compact = self._run_group_step(words)
            results.append((events, compact))
            self.flushes += 1
            capacity = len(self._members) * shape
            self.flush_votes_total += votes
            self.flush_capacity_total += capacity
            self._account_shards(shard_votes, shape)
            self.metrics.add_event(MetricsName.DEVICE_FLUSH)
            self.metrics.add_event(MetricsName.DEVICE_FLUSH_VOTES, votes)
            self.metrics.add_event(
                MetricsName.DEVICE_FLUSH_OCCUPANCY, votes / capacity)
        return results

    def _cell_capacity(self, shape: int) -> List[float]:
        """One dispatch's capacity per occupancy-grid cell. The capacity
        denominator counts REAL member rows only — pad rows cannot hold
        votes and must not dilute the governor's signal. Under the
        2-axis fabric each member block's capacity is apportioned across
        validator blocks by their share of real senders (sum over a
        block's cells == the member block's capacity, so totals match
        the 1-axis accounting); a block receiving more than its
        proportional share of votes runs hot — exactly the signal the
        hottest-cell governor law needs."""
        if self._v_shards == 1:
            return [r * shape for r in self._real_rows]
        return [r * shape * v / self._n
                for r in self._real_rows for v in self._v_real]

    def _account_shards(self, shard_votes: List[int], shape: int) -> None:
        """Fold one dispatch into the per-cell occupancy series."""
        caps = self._cell_capacity(shape)
        for si in range(self._n_shards):
            self.flush_votes_per_shard[si] += shard_votes[si]
            self.flush_capacity_per_shard[si] += caps[si]
        if self._n_shards > 1:
            self.metrics.add_event(
                MetricsName.DEVICE_SHARD_COUNT, self._n_shards)
            for si in range(self._n_shards):
                if caps[si]:
                    self.metrics.add_event(
                        f"{MetricsName.DEVICE_SHARD_FLUSH_VOTES}.{si}",
                        shard_votes[si])
                    self.metrics.add_event(
                        f"{MetricsName.DEVICE_SHARD_FLUSH_CAPACITY}.{si}",
                        caps[si])

    def _dispatch_empty(self):
        """One padded no-vote step (cold start needs SOME events)."""
        words = self._stage_scatter(
            [[] for _ in self._members], FLUSH_LADDER[0])
        self._states, events, compact = self._run_group_step(words)
        self.flushes += 1
        self.flush_capacity_total += len(self._members) * FLUSH_LADDER[0]
        self._account_shards([0] * self._n_shards, FLUSH_LADDER[0])
        self.metrics.add_event(MetricsName.DEVICE_FLUSH)
        return [(events, compact)]

    def _readback_arrays(self, events, compact):
        """The arrays an absorb of this step will fetch — what the
        pipelined path warms with copy_to_host_async so next tick's
        absorb finds the bytes already host-side."""
        if self.host_eval:
            return (events.prepared, events.prepare_counts,
                    events.commit_counts, events.stable_checkpoints)
        return tuple(compact)

    def _flush_pipelined(self) -> None:
        # 1. absorb the steps dispatched LAST tick (usually complete by
        # now: the whole tick's host work overlapped their round-trip).
        # On a mesh with votes pending, the absorb runs PER MEMBER SHARD
        # and interleaves with step 2's per-shard scatter staging: while
        # shard i+1's fresh scatter buffer rides the link (device_put is
        # async), shard i's readback block — already host-side thanks to
        # last tick's copy_to_host_async — folds into the mirrors. The
        # readback latency amortizes across the shard grid instead of
        # summing in front of the dispatch.
        absorb = None
        if self._inflight is not None:
            results, self._inflight = self._inflight, None
            overlapped = self._flush_seq > self._inflight_seq
            absorb = self._absorb_blocks(results, overlapped)
            if self._mesh is None or self.host_eval \
                    or not any(m._pending for m in self._members):
                for _ in absorb:  # nothing to interleave with
                    pass
                absorb = None
        # 2. dispatch this tick's votes; results ride to the host next
        # tick. Kick the device->host copies off NOW: by the time next
        # tick's absorb runs, the bytes are already host-side and
        # device_get returns without a link round-trip — and on the fast
        # path those bytes are the compact deltas, not the event matrix.
        results = self._dispatch_pending(interleave=absorb)
        if absorb is not None:
            for _ in absorb:  # drain any blocks staging didn't cover
                pass
        if results:
            for events, compact in results:
                for arr in self._readback_arrays(events, compact):
                    try:
                        arr.copy_to_host_async()
                    except Exception:  # noqa: BLE001 — backends without
                        break  # async copy: device_get pays the round-trip
            self._inflight = results
            self._inflight_seq = self._flush_seq
        if self._host_prepared is None:
            # cold start (or post-slide/reset): callers need SOME snapshot
            if self._inflight is None:
                self._inflight = self._dispatch_empty()
                self._inflight_seq = self._flush_seq
            self._sync_inflight()

    def flush(self) -> None:
        """Scatter every member's pending votes; refresh host event caches."""
        self._flush_seq += 1
        if self._resident:
            with self.metrics.measure_time(MetricsName.DEVICE_FLUSH_TIME):
                self._flush_resident()
            return
        if self.pipelined:
            with self.metrics.measure_time(MetricsName.DEVICE_FLUSH_TIME):
                self._flush_pipelined()
            return
        if (not any(m._pending for m in self._members)
                and self._host_prepared is not None):
            return
        with self.metrics.measure_time(MetricsName.DEVICE_FLUSH_TIME):
            results = self._dispatch_pending()
            if not results:  # cold start: no votes recorded anywhere yet
                results = self._dispatch_empty()
            # ONE bundled device->host transfer (separate np.asarray calls
            # cost one link round-trip each — painful on a remote device)
            self._absorb_results(results, overlapped=False)

    def _sync_inflight(self) -> None:
        """Absorb any in-flight steps NOW (window/view operations must not
        run with stale events pending under the OLD slot mapping)."""
        if self._inflight is not None:
            results, self._inflight = self._inflight, None
            # overlapped iff a LATER flush call absorbs it: the dispatch's
            # round-trip hid behind at least one full tick of host work
            self._absorb_results(
                results, overlapped=self._flush_seq > self._inflight_seq)

    # --- multi-tick residency ring ------------------------------------

    def _take_slide(self) -> Optional[np.ndarray]:
        """Detach the accumulated pending slide vector (row-indexed) for
        attachment to the NEXT ring slot — the fused step applies it
        before that slot's scatter."""
        if not self._pending_slide.any():
            return None
        vec = self._pending_slide
        self._pending_slide = np.zeros(self._m_pad, np.int32)
        return vec

    def _enqueue_chunks(self, count_tick: bool = True) -> None:
        """Stage every member's pending votes into ring slots — async
        device transfers (device_put), NO XLA dispatch. The host keeps
        only the slot list (its write cursor); the words live on device
        until a consume chains them through the fused resident step."""
        enqueued = False
        while any(m._pending for m in self._members):
            chunks, votes, shard_votes = self._collect_chunks()
            shape = self._resident_width
            args = None
            if self.trace.enabled:
                args = {"votes": votes, "shape": shape}
                if self._n_shards > 1:
                    args["shard_votes"] = list(shard_votes)
            with self.trace.span("flush.enqueue", args=args) \
                    if self.trace.enabled else _NO_SPAN:
                words = self._stage_scatter(chunks, shape)
            self._ring.append((self._take_slide(), words, votes,
                               shard_votes))
            capacity = len(self._members) * shape
            self.flush_votes_total += votes
            self.flush_capacity_total += capacity
            self._account_shards(shard_votes, shape)
            self.metrics.add_event(MetricsName.DEVICE_FLUSH_VOTES, votes)
            self.metrics.add_event(
                MetricsName.DEVICE_FLUSH_OCCUPANCY, votes / capacity)
            enqueued = True
        if enqueued and count_tick:
            self._ring_ticks += 1
            self.resident_ticks += 1
            self.metrics.add_event(MetricsName.DEVICE_RESIDENT_TICKS)

    def _consume_ring(self, sync: bool = False) -> None:
        """Dispatch ONE fused step consuming every ring slot (slides
        folded in per slot, quorums evaluated once at the end) and hand
        its compact readback to the pipeline — or absorb it now when
        ``sync`` (cold start, ring drain)."""
        if self._pending_slide.any():
            # a trailing slide with no votes recorded after it rides a
            # synthetic empty slot, so the fused step still applies it
            self._ring.append((
                self._take_slide(),
                self._stage_scatter([[] for _ in self._members],
                                    self._resident_width),
                0, [0] * self._n_shards))
            self.flush_capacity_total += (
                len(self._members) * self._resident_width)
            self._account_shards([0] * self._n_shards,
                                 self._resident_width)
        # absorb the PREVIOUS consume first: its readback overlapped the
        # resident ticks' host work
        self._sync_inflight()
        if not self._ring:
            results = self._dispatch_empty()  # cold start only
        else:
            slots, self._ring = self._ring, []
            ticks, self._ring_ticks = self._ring_ticks, 0
            slides = np.stack([
                vec if vec is not None
                else np.zeros(self._m_pad, np.int32)
                for vec, _, _, _ in slots]).astype(np.int32)
            args = None
            if self.trace.enabled:
                args = {"slots": len(slots), "ticks": ticks,
                        "resident": self.resident_depth}
            with self.trace.span("flush.dispatch", args=args) \
                    if self.trace.enabled else _NO_SPAN:
                step = resident_plan_for(
                    self._mesh, self._n, self._n_pad, self._delta_cap,
                    len(slots), self._resident_width)
                self._states, events, compact = step(
                    self._states, slides,
                    *[words for _, words, _, _ in slots])
            results = [(events, compact)]
            self.flushes += 1
            self.metrics.add_event(MetricsName.DEVICE_FLUSH)
        self._inflight_cum = self._slide_cum.copy()
        if self.pipelined and not sync:
            for events, compact in results:
                for arr in self._readback_arrays(events, compact):
                    try:
                        arr.copy_to_host_async()
                    except Exception:  # noqa: BLE001 — backends without
                        break  # async copy: device_get pays the trip
            self._inflight = results
            self._inflight_seq = self._flush_seq
        else:
            self._absorb_results(results, overlapped=False)

    def _drain_ring(self) -> None:
        """The residency barrier: consume + absorb everything device-
        bound NOW. View resets, rebalance rotations and per-query
        refreshes must observe (and mutate) fully-settled state —
        correctness over residency."""
        if self._resident and (self._ring or self._pending_slide.any()):
            self._consume_ring(sync=True)
        else:
            self._sync_inflight()

    def _flush_resident(self) -> None:
        """The resident flush: enqueue this tick's votes into the ring;
        dispatch the fused consume only when the ring holds
        ``resident_depth`` ticks, the pool went quiet, or the snapshot
        is void (cold start) — otherwise defer the readback and run the
        tick entirely host-side."""
        had_pending = any(m._pending for m in self._members)
        if had_pending:
            self._enqueue_chunks()
        if self._host_prepared is None:
            # cold start (or post-reset): callers need SOME snapshot
            self._consume_ring(sync=True)
            return
        if self._ring and (self._ring_ticks >= self.resident_depth
                           or not had_pending):
            self._consume_ring()
        elif self._ring:
            self.readbacks_deferred += 1
            self.metrics.add_event(MetricsName.DEVICE_READBACKS_DEFERRED)
            if self.trace.enabled:
                self.trace.record("flush.defer", cat="dispatch",
                                  args={"ring_ticks": self._ring_ticks})
        elif not had_pending and self._inflight is not None:
            # quiet tick with nothing resident but a consume in flight:
            # absorb now — residency must never stall verdict delivery
            self._sync_inflight()

    # --- occupancy-driven rebalancing ---------------------------------

    def schedule_rebalance(self, rows: int) -> None:
        """Plan a member-plane rotation by ``rows`` device rows along
        mesh axis 0 (planes move, members don't). Executed at the next
        checkpoint-boundary slide — the rebalance barrier, the only
        instant the ring is guaranteed drained."""
        rows = int(rows) % self._m_pad
        if rows:
            self._rebalance_pending = rows

    def rebalance_at_barrier(self) -> None:
        """Execute a scheduled rotation, if any. Called from the
        checkpoint-boundary slide (and directly by harnesses that model
        their own barriers)."""
        if self._rebalance_pending:
            self._execute_rebalance()

    def _execute_rebalance(self) -> None:
        from .rebalance import rotate_planes

        rows, self._rebalance_pending = self._rebalance_pending, 0
        # barrier: everything device-bound settles under the OLD
        # placement (ring slots were staged against it), THEN the
        # planes migrate and the placement map rewrites
        self._drain_ring()
        self._states = rotate_planes(self._states, self._mesh, rows,
                                     self._shard_rows)
        self._row_shift = (self._row_shift + rows) % self._m_pad
        self._rebuild_placement()
        self.rebalances += 1
        self.version += 1
        if self.trace.enabled:
            self.trace.record("rebalance.executed", cat="dispatch",
                              args={"rows": rows,
                                    "shift": self._row_shift})

    def _roll_member_mirrors(self, member_idx: int, delta: int) -> None:
        """Roll one member's host mirrors with its window (the device
        applies the identical roll/clamp in q.slide_state —
        prepared_acked rolled too, so surviving certs are NOT
        re-reported and the mirror must keep them)."""
        mi, s = member_idx, self._log_size
        for mir in (self._mir_prepared[mi], self._mir_commit_ok[mi]):
            if delta < s:
                mir[:s - delta] = mir[delta:]
                mir[s - delta:] = False
            else:
                mir[:] = False
        self._mir_stable[mi] = False
        self._mir_frontier[mi] = max(int(self._mir_frontier[mi]) - delta, 0)
        member = self._members[mi]
        member._delta_prepared = [
            x - delta for x in member._delta_prepared if x >= delta]
        member._delta_committed = [
            x - delta for x in member._delta_committed if x >= delta]

    def slide_member(self, member_idx: int, delta: int) -> None:
        if self._resident:
            # slide-fold: stage any votes recorded against the OLD
            # window coordinates first (they must scatter before the
            # slide), then just ACCUMULATE the delta — it rides the next
            # ring slot as a fused-step operand. No sync, no dispatch,
            # no snapshot void: the mirrors roll host-side and remain
            # the live snapshot.
            self._enqueue_chunks(count_tick=False)
            self.rebalance_at_barrier()
            self._pending_slide[self._row_of(member_idx)] += delta
            self._slide_cum[member_idx] += delta
            self._roll_member_mirrors(member_idx, delta)
            self.version += 1
            return
        self.flush()
        self._sync_inflight()
        # checkpoint-boundary barrier: a scheduled rebalance rotates now,
        # with the device state fully settled (timing-neutral — it adds
        # no dispatches and changes no member-visible state)
        self.rebalance_at_barrier()
        deltas = np.zeros(self._m_pad, np.int32)
        deltas[self._row_of(member_idx)] = delta
        # the plan's slide carries its own in_shardings (pjit on a mesh),
        # so the raw host array places correctly without an explicit put
        self._states = self._plan.slide(self._states, deltas)
        self.version += 1
        self._host_prepared = None
        self._roll_member_mirrors(member_idx, delta)

    def reset_member(self, member_idx: int) -> None:
        # pending for this member was cleared by the caller; other members'
        # buffered votes are untouched (flushed on their next query).
        # View reset drains the residency ring synchronously — old-view
        # events must not land post-reset (correctness over residency)
        self._drain_ring()
        # the zero rides a member MASK on every plan: a dynamic row index
        # cannot address a shard-local block, a mask shards trivially
        mask = np.zeros(self._m_pad, np.uint8)
        mask[self._row_of(member_idx)] = 1
        self._states = self._plan.zero(self._states, mask)
        self.version += 1
        self._host_prepared = None
        # the member's device plane is all-zero now; its mirrors must be
        # too, or stale certs from the old view would answer queries
        self._mir_prepared[member_idx] = False
        self._mir_commit_ok[member_idx] = False
        self._mir_stable[member_idx] = False
        self._mir_frontier[member_idx] = 0
        member = self._members[member_idx]
        member._delta_prepared = []
        member._delta_committed = []


class _MemberPlane(DeviceVotePlane):
    """One member's view of a :class:`VotePlaneGroup` (same interface as a
    standalone :class:`DeviceVotePlane`; storage and flushing are shared)."""

    def __init__(self, group: VotePlaneGroup, member_idx: int,
                 validators: List[str], log_size: int, n_checkpoints: int,
                 h: int):
        self._group = group
        self._mi = member_idx
        self._validators = list(validators)
        self._index = {name: i for i, name in enumerate(self._validators)}
        self._n = len(self._validators)
        self._log_size = log_size
        self._n_chk = n_checkpoints
        self._h = h
        self._pending: List[int] = []  # uint32 vote words (q.pack_vote)
        self._events = None
        self._seen_version = -1
        self._host_prepared = None
        self._host_prepare_counts = None
        self._host_commit_counts = None
        self._host_commit_ok = None
        self._host_stable = None
        # device-eval delta accumulators, filled by the group's
        # _apply_compact as each dispatch's compact events absorb
        self._delta_prepared: List[int] = []
        self._delta_committed: List[int] = []
        self.defer_flush_on_query = False

    @property
    def flushes(self) -> int:
        return self._group.flushes

    @flushes.setter
    def flushes(self, value) -> None:  # base-class compat; group owns it
        pass

    # occupancy counters live on the group (shared dispatches); read-only
    # views keep the DeviceVotePlane interface uniform for tick drivers

    @property
    def flush_votes_total(self) -> int:
        return self._group.flush_votes_total

    @flush_votes_total.setter
    def flush_votes_total(self, value) -> None:
        pass

    @property
    def flush_capacity_total(self) -> int:
        return self._group.flush_capacity_total

    @flush_capacity_total.setter
    def flush_capacity_total(self, value) -> None:
        pass

    @property
    def readback_bytes_total(self) -> int:
        return self._group.readback_bytes_total

    @readback_bytes_total.setter
    def readback_bytes_total(self, value) -> None:
        pass

    @property
    def readbacks(self) -> int:
        return self._group.readbacks

    @readbacks.setter
    def readbacks(self, value) -> None:
        pass

    @property
    def has_buffered_votes(self) -> bool:
        # pipelined group: votes dispatched but not yet in the snapshot
        # must keep the services' lost-wakeup guard armed, exactly like
        # host-buffered votes
        return bool(self._pending) or self._group.lagging

    def _flush(self) -> None:
        self._group.flush()

    def _copy_slices(self) -> None:
        g = self._group
        self._host_prepared = g._host_prepared[self._mi]
        self._host_commit_ok = g._host_commit_ok[self._mi]
        self._host_stable = g._host_stable[self._mi]
        # counts stay device-resident on the fast path (None => the
        # prepare_count diagnostic fetches its scalar on demand)
        pc, cc = g._host_prepare_counts, g._host_commit_counts
        self._host_prepare_counts = None if pc is None else pc[self._mi]
        self._host_commit_counts = None if cc is None else cc[self._mi]
        self._seen_version = g.version
        self._events = True

    def _refresh(self) -> None:
        self._group.flush()
        if not self.defer_flush_on_query:
            # per-query mode wants CURRENT state: a pipelined group must
            # absorb its in-flight step now, or the final batch's votes
            # sit on-device forever with no tick driver to absorb them —
            # and a resident group must consume its ring the same way
            self._group._drain_ring()
        self._copy_slices()

    def events(self):
        if (self._group._host_prepared is None
                or (not self.defer_flush_on_query
                    and (self._pending or self._events is None))):
            self._refresh()
        elif self._seen_version != self._group.version:
            self._copy_slices()
        return self._events

    def slide_to(self, new_h: int) -> None:
        if new_h <= self._h:
            return
        self._group.slide_member(self._mi, new_h - self._h)
        self._h = new_h
        self._events = None

    def reset(self, h: Optional[int] = None) -> None:
        if h is not None:
            self._h = h
        self._pending.clear()
        self._group.reset_member(self._mi)
        self._events = None

    # --- ordering fast path: the group feeds per-member deltas --------

    @property
    def host_eval(self) -> bool:
        return self._group.host_eval

    @host_eval.setter
    def host_eval(self, value) -> None:  # eval mode is a GROUP property
        raise AttributeError("set host_eval on the VotePlaneGroup")

    def poll_deltas(self) -> Optional[PlaneDeltas]:
        g = self._group
        if g.host_eval:
            return None
        if not self._delta_prepared and not self._delta_committed:
            return None  # quiet poll: allocation-free (most members/ticks)
        prepared, self._delta_prepared = self._delta_prepared, []
        committed, self._delta_committed = self._delta_committed, []
        return PlaneDeltas(sorted(prepared), sorted(committed),
                           int(g._mir_frontier[self._mi]))

    def prepare_count(self, pp_seq_no: int) -> int:
        slot = self._slot(pp_seq_no)
        if slot is None:
            return 0
        self.events()
        if self._host_prepare_counts is not None:
            return int(self._host_prepare_counts[slot])
        ev = self._group._dev_events
        if ev is None:
            return 0
        # one scalar fetched on demand from the device-resident events
        # (row-addressed: the placement map translates under rotation)
        return int(jax.device_get(
            ev.prepare_counts[self._group._row_of(self._mi), slot]))
