"""Host adapter making the device quorum tensors the consensus truth source.

Reference analog: the per-message Python tallies in
``plenum/server/consensus/ordering_service.py`` (prepare/commit cert
collection). Here the :class:`OrderingService` delegates quorum detection to
this plane: validated votes are buffered on the host, scattered into the
dense (validator x slot) tensors of :mod:`indy_plenum_tpu.tpu.quorum` in
fixed-size batches (stable shapes => one XLA compilation), and quorum
verdicts are read back as boolean events. The Python dicts remain only as
message logs (MessageReq replies, duplicate detection) — decisions come
from :class:`~indy_plenum_tpu.tpu.quorum.QuorumEvents`.

Slot addressing is watermark-relative (slot = pp_seq_no - h - 1), mirroring
the reference's h/H window; ``slide_to`` rolls the window on checkpoint
stabilization and ``reset`` clears it on view change.

Per the vote-inclusion contract in :mod:`indy_plenum_tpu.tpu.quorum`, the
caller records its OWN votes too, not just received messages.

Flush granularity: a quorum query flushes whatever is pending, so in the
per-message sim loop each message typically costs one padded device step —
correct but not amortized. Amortization comes from the tick-batched
dispatch plane (``simulation/quorum_driver.py`` / ``Node._quorum_tick``):
the event loop drains all deliveries due at the tick, then ONE grouped
device step carries every buffered vote from all members and f+1
instances (drain -> scatter -> single grouped step -> read events). The
ingress path likewise verifies whole request batches per tick.

Padded flush shapes come from a small ladder (``FLUSH_LADDER``): each
rung compiles exactly once, and a near-empty tick rides the smallest rung
instead of paying the full-width scatter for a handful of votes. With
:class:`AdaptiveLadder` the top rung is LEARNED from the observed
votes-per-dispatch distribution, so small pools stop compiling the
full-width shape. ``flush_occupancy`` (votes / padded capacity) is
recorded per dispatch so the amortization is a measured number, not a
docstring claim.

Scale past one chip: :class:`VotePlaneGroup` accepts a ``mesh`` and runs
the grouped step explicit-SPMD over the member axis via ``shard_map``
(pad M → shard → per-shard stage → single grouped step → gathered
events — README "Mesh-sharded dispatch plane"), with per-shard occupancy
series feeding the dispatch governor's hottest-shard law.
"""
from __future__ import annotations

import functools
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..common.metrics_collector import MetricsCollector, MetricsName
from ..observability.trace import NULL_TRACE, _NO_SPAN
from . import quorum as q

# fixed flush granularity: stable shapes keep XLA from recompiling
FLUSH_BATCH = 128
# padded-shape ladder: a flush pads to the smallest rung that fits, so a
# single-vote tick costs a 16-wide scatter, not a 128-wide one. Every
# rung is a distinct static shape — each compiles once, then caches.
FLUSH_LADDER = (16, FLUSH_BATCH)


def ladder_shape(n_votes: int) -> int:
    """Smallest ladder rung holding ``n_votes`` (callers chunk at
    FLUSH_BATCH, so the top rung always fits)."""
    for rung in FLUSH_LADDER:
        if n_votes <= rung:
            return rung
    return FLUSH_BATCH


def pow2_rung(n_votes: int) -> int:
    """Smallest power-of-two rung >= ``n_votes``, clamped to the static
    ladder's bounds [FLUSH_LADDER[0], FLUSH_BATCH]."""
    rung = FLUSH_LADDER[0]
    while rung < min(n_votes, FLUSH_BATCH):
        rung *= 2
    return rung


class AdaptiveLadder:
    """Learned per-pool top flush rung (ROADMAP PR 3 "let the ladder
    itself adapt").

    The static ladder (16/128) makes a small pool whose busiest member
    buffers ~20 votes per dispatch pay a 128-wide scatter (and its XLA
    compile) forever. This controller watches the observed busiest-
    member votes-per-dispatch distribution and sets the pool's top rung
    to the p99 rounded UP to a power of two, clamped to the static
    ladder's bounds — so that pool settles on a 32-wide scatter and the
    128-wide shape is never compiled. Overflow dispatches beyond the
    learned top still get a containing power-of-two shape (each is one
    cached compilation, exactly like the static rungs).

    Deterministic: ``top`` is a pure function of the recorded sample
    series (integer percentile math, bounded window), so seeded runs
    replay the identical shape sequence. Learning starts only after
    ``min_samples`` dispatches — short runs (and most unit tests) keep
    the static ladder's exact behaviour.
    """

    def __init__(self, window: int = 512, min_samples: int = 64,
                 recompute_every: int = 32):
        from collections import deque

        self._samples: "deque[int]" = deque(maxlen=window)
        self._min_samples = min_samples
        # the p99 recompute sorts the whole window — done on a stride,
        # not per dispatch, so the hot flush loop (which PR 2/3 already
        # de-allocated) doesn't buy back an O(W log W) sort per flush
        self._recompute_every = recompute_every
        self._count = 0
        self.top = FLUSH_BATCH

    def record(self, busiest_votes: int) -> None:
        self._samples.append(busiest_votes)
        self._count += 1
        if (self._count >= self._min_samples
                and (self._count - self._min_samples)
                % self._recompute_every == 0):
            ordered = sorted(self._samples)
            # ceil(p99) index in pure integer math (determinism)
            idx = (99 * (len(ordered) - 1) + 99) // 100
            self.top = pow2_rung(ordered[idx])

    def shape(self, n_votes: int) -> int:
        if n_votes <= FLUSH_LADDER[0]:
            return FLUSH_LADDER[0]
        if n_votes <= self.top:
            return self.top
        return pow2_rung(n_votes)


# double-buffered device steps: donate the state operand so XLA writes
# the step's output state INTO the input's buffers (no state-sized
# alloc+copy per dispatch) while the freshly packed words ride their own
# host buffer — dispatch is async, so the device consumes buffer N while
# the host packs N+1. Every caller rebinds the state reference on return,
# which is exactly what donation requires. XLA:CPU doesn't implement
# donation (it would warn once per compile and ignore it), so gate it —
# but probe the backend LAZILY, at the first dispatch: probing at import
# would initialize the JAX backend before consumers (tests/conftest.py,
# any host-only code path) get to configure jax_platforms.
@functools.lru_cache(maxsize=None)
def _state_donation() -> tuple:
    return (0,) if jax.default_backend() != "cpu" else ()


@functools.partial(jax.jit, static_argnums=(2,))
def _step(state: q.VoteState, msgs: q.MsgBatch, n_validators: int):
    return q.step(state, msgs, n_validators)


@functools.lru_cache(maxsize=None)
def _jit_step_words():
    return functools.partial(
        jax.jit, static_argnums=(2,),
        donate_argnums=_state_donation())(_step_words_impl)


def _step_words_impl(state: q.VoteState, words, n_validators: int):
    return q.step(state, q.unpack_words(words), n_validators)


def _step_words(state: q.VoteState, words, n_validators: int):
    return _jit_step_words()(state, words, n_validators)


def _slide_core(state: q.VoteState, delta: jnp.ndarray) -> q.VoteState:
    """Roll the slot axis left by ``delta`` and zero the vacated columns."""
    s = state.prepare_votes.shape[1]
    cols = jnp.arange(s)
    keep = cols < (s - delta)  # after roll, tail columns are new/empty

    def roll1(x):
        return jnp.where(keep, jnp.roll(x, -delta), 0)

    def roll2(x):
        return jnp.where(keep[None, :], jnp.roll(x, -delta, axis=1), 0)

    return q.VoteState(
        preprepare_seen=roll1(state.preprepare_seen),
        prepare_votes=roll2(state.prepare_votes),
        commit_votes=roll2(state.commit_votes),
        # delta == 0 must be a strict identity (the vmapped group slide
        # passes 0 for every member but the one actually sliding)
        checkpoint_votes=jnp.where(delta > 0, 0,
                                   state.checkpoint_votes),
        ordered=roll1(state.ordered),
    )


_slide = jax.jit(_slide_core)


@functools.partial(jax.jit, static_argnums=(2,))
def _group_step(states: q.VoteState, msgs: q.MsgBatch, n_validators: int):
    """Vmapped step over a leading member axis: M planes, ONE dispatch."""
    return jax.vmap(lambda s, m: q.step(s, m, n_validators))(states, msgs)


@functools.lru_cache(maxsize=None)
def _jit_group_step_words():
    return functools.partial(
        jax.jit, static_argnums=(2,),
        donate_argnums=_state_donation())(_group_step_words_impl)


def _group_step_words_impl(states: q.VoteState, words, n_validators: int):
    msgs = q.unpack_words(words)
    return jax.vmap(lambda s, m: q.step(s, m, n_validators))(states, msgs)


def _group_step_words(states: q.VoteState, words, n_validators: int):
    """Group step over word-packed votes: the (M, B) uint32 operand is a
    quarter the bytes of a MsgBatch — the host->device transfer is the
    blocking cost of a flush, so this is the wire format for groups. The
    states operand is donated (see _state_donation): tick N's output
    state lands in tick N-1's buffers while the host packs tick N+1."""
    return _jit_group_step_words()(states, words, n_validators)


@jax.jit
def _group_slide(states: q.VoteState, deltas: jnp.ndarray) -> q.VoteState:
    return jax.vmap(_slide_core)(states, deltas)


@jax.jit
def _group_zero_member(states: q.VoteState, member: jnp.ndarray) -> q.VoteState:
    return jax.tree.map(lambda x: x.at[member].set(0), states)


@functools.lru_cache(maxsize=None)
def _sharded_group_fns(mesh, axis: str, n_validators: int):
    """shard_map'd (step, slide, zero) for a member-sharded group.

    The member axis M is split across ``mesh``; inside each shard the
    PLAIN per-member step/slide runs vmapped over the local rows —
    members are independent planes, so no cross-member collectives exist
    and XLA keeps every shard's tensors on its own chip. This is the
    explicit-SPMD successor of the PR 2 auto-partitioned mesh path: the
    sharding of every operand and result is stated, not inferred, so the
    grouped dispatch can never silently fall back to an all-gather.

    The step is jitted with the state operand donated (same PR 3
    double-buffer contract as the unsharded `_group_step_words`, gated
    off XLA:CPU) and ``zero`` takes an (M,) member MASK instead of a
    scalar index — a dynamic row index cannot be resolved against a
    shard-local block, a mask shards trivially.
    """
    state_spec, row_spec, events_spec, vec_spec = q.member_sharded_specs(axis)

    def step_impl(states, words):
        msgs = q.unpack_words(words)
        return jax.vmap(lambda s, m: q.step(s, m, n_validators))(states, msgs)

    step = functools.partial(jax.jit, donate_argnums=_state_donation())(
        q.shard_map_compat(step_impl, mesh=mesh,
                           in_specs=(state_spec, row_spec),
                           out_specs=(state_spec, events_spec)))

    def slide_impl(states, deltas):
        return jax.vmap(_slide_core)(states, deltas)

    slide = jax.jit(q.shard_map_compat(
        slide_impl, mesh=mesh, in_specs=(state_spec, vec_spec),
        out_specs=state_spec))

    def zero_impl(states, mask):
        def z(x):
            hit = mask.reshape((-1,) + (1,) * (x.ndim - 1)) != 0
            return jnp.where(hit, jnp.zeros((), x.dtype), x)

        return jax.tree.map(z, states)

    zero = jax.jit(q.shard_map_compat(
        zero_impl, mesh=mesh, in_specs=(state_spec, vec_spec),
        out_specs=state_spec))

    return step, slide, zero


class DeviceVotePlane:
    """Per-instance device vote tensors + lazy flush/query interface."""

    def __init__(self, validators: List[str], log_size: int,
                 n_checkpoints: int = 4, h: int = 0):
        self._validators = list(validators)
        self._index = {name: i for i, name in enumerate(self._validators)}
        self._n = len(self._validators)
        self._log_size = log_size
        self._n_chk = n_checkpoints
        self._h = h
        self._state = q.init_state(self._n, log_size, n_checkpoints)
        self._pending: List[int] = []  # uint32 vote words (q.pack_vote)
        self._events: Optional[q.QuorumEvents] = None
        # host copies of the event arrays, refreshed once per flush (quorum
        # queries are per-message; don't re-transfer per query)
        self._host_prepared: Optional[np.ndarray] = None
        self._host_prepare_counts: Optional[np.ndarray] = None
        self._host_commit_counts: Optional[np.ndarray] = None
        self._host_stable: Optional[np.ndarray] = None
        self.flushes = 0
        # cumulative scattered votes and padded scatter capacity: the
        # occupancy signal the dispatch governor closes its loop over
        # (per-tick deltas of these two counters)
        self.flush_votes_total = 0
        self.flush_capacity_total = 0
        # tick-batched mode: quorum queries read the last-synced snapshot
        # instead of flushing per query. There is NO built-in driver: the
        # runtime composition that sets this flag must call sync() (or, in
        # group mode, VotePlaneGroup.flush — what SimPool's tick does) once
        # per tick, or snapshots go permanently stale.
        self.defer_flush_on_query = False

    # --- recording ------------------------------------------------------

    @property
    def h(self) -> int:
        return self._h

    @property
    def has_buffered_votes(self) -> bool:
        """True if votes recorded since the last flush are still host-side
        (tick mode's lost-wakeup guard checks this)."""
        return bool(self._pending)

    def _slot(self, pp_seq_no: int) -> Optional[int]:
        slot = pp_seq_no - self._h - 1
        if 0 <= slot < self._log_size:
            return slot
        return None

    def _record(self, kind: int, sender: Optional[str],
                pp_seq_no: int) -> None:
        slot = self._slot(pp_seq_no)
        if slot is None:
            return
        idx = 0 if sender is None else self._index.get(sender)
        if idx is None:
            return
        self._pending.append(q.vote_word(kind, idx, slot))
        self._events = None

    def record_preprepare(self, pp_seq_no: int) -> None:
        self._record(q.PREPREPARE, None, pp_seq_no)

    def record_prepare(self, sender: str, pp_seq_no: int) -> None:
        self._record(q.PREPARE, sender, pp_seq_no)

    def record_commit(self, sender: str, pp_seq_no: int) -> None:
        self._record(q.COMMIT, sender, pp_seq_no)

    def record_checkpoint(self, sender: str, chk_slot: int) -> None:
        if 0 <= chk_slot < self._n_chk and sender in self._index:
            self._pending.append(
                q.vote_word(q.CHECKPOINT, self._index[sender], chk_slot))
            self._events = None

    def checkpoint_slot(self, seq_no_end: int, chk_freq: int) -> Optional[int]:
        """Checkpoint boundary seqNoEnd -> window-relative checkpoint slot.

        Boundaries sit at multiples of CHK_FREQ above the stable watermark
        h (itself a stabilized boundary), so slot = (end - h)/freq - 1.
        """
        delta = seq_no_end - self._h
        if delta <= 0 or delta % chk_freq != 0:
            return None
        slot = delta // chk_freq - 1
        return slot if slot < self._n_chk else None

    def record_checkpoint_vote(self, sender: str, seq_no_end: int,
                               chk_freq: int) -> None:
        slot = self.checkpoint_slot(seq_no_end, chk_freq)
        if slot is not None:
            self.record_checkpoint(sender, slot)

    def has_checkpoint_quorum(self, seq_no_end: int, chk_freq: int) -> bool:
        """n-f checkpoint votes at the boundary (OWN vote included — see
        the vote-inclusion contract in tpu.quorum)."""
        slot = self.checkpoint_slot(seq_no_end, chk_freq)
        if slot is None:
            return False
        self.events()
        return bool(self._host_stable[slot])

    # --- window management ---------------------------------------------

    def slide_to(self, new_h: int) -> None:
        """Checkpoint stabilized at ``new_h``: drop slots <= new_h."""
        if new_h <= self._h:
            return
        self._flush()
        self._state = _slide(self._state, jnp.int32(new_h - self._h))
        self._h = new_h
        self._events = None
        self._host_prepared = None  # snapshot is void, even in defer mode

    def reset(self, h: Optional[int] = None) -> None:
        """View change: clear all votes (they were for the old view)."""
        if h is not None:
            self._h = h
        self._state = q.init_state(self._n, self._log_size, self._n_chk)
        self._pending.clear()
        self._events = None
        self._host_prepared = None  # snapshot is void, even in defer mode

    # --- flush + queries ------------------------------------------------

    def _flush(self) -> None:
        while self._pending:
            chunk, self._pending = (self._pending[:FLUSH_BATCH],
                                    self._pending[FLUSH_BATCH:])
            shape = ladder_shape(len(chunk))
            words = jnp.asarray(q.words_row(chunk, shape))
            self._state, self._events = _step_words(
                self._state, words, self._n)
            self.flushes += 1
            self.flush_votes_total += len(chunk)
            self.flush_capacity_total += shape

    def _refresh(self) -> None:
        self._flush()
        if self._events is None:  # nothing ever recorded
            self._state, self._events = _step_words(
                self._state, jnp.asarray(q.words_row([], FLUSH_LADDER[0])),
                self._n)
            # a real device dispatch: count it like any other flush, or
            # the governor (and the dispatch budget) would see a post-
            # reset tick as free
            self.flushes += 1
            self.flush_capacity_total += FLUSH_LADDER[0]
        (self._host_prepared, self._host_prepare_counts,
         self._host_commit_counts, self._host_stable) = jax.device_get(
            (self._events.prepared, self._events.prepare_counts,
             self._events.commit_counts, self._events.stable_checkpoints))

    def sync(self) -> None:
        """Flush all buffered votes and refresh the host snapshot (the
        per-tick entry point in tick-batched mode)."""
        self._refresh()

    def events(self) -> q.QuorumEvents:
        if self._host_prepared is None or (
                not self.defer_flush_on_query
                and (self._pending or self._events is None)):
            self._refresh()
        return self._events

    def has_prepare_quorum(self, pp_seq_no: int) -> bool:
        """PRE-PREPARE seen AND n-f-1 matching PREPAREs (device verdict)."""
        slot = self._slot(pp_seq_no)
        if slot is None:
            return False
        self.events()
        return bool(self._host_prepared[slot])

    def has_commit_quorum(self, pp_seq_no: int) -> bool:
        slot = self._slot(pp_seq_no)
        if slot is None:
            return False
        self.events()
        f = (self._n - 1) // 3
        return int(self._host_commit_counts[slot]) >= self._n - f

    def prepare_count(self, pp_seq_no: int) -> int:
        slot = self._slot(pp_seq_no)
        if slot is None:
            return 0
        self.events()
        return int(self._host_prepare_counts[slot])


class VotePlaneGroup:
    """M stacked vote planes stepped in ONE vmapped device dispatch.

    The "one pod co-processes the pool" configuration from BASELINE.json's
    north star: every simulated node holds a :class:`_MemberPlane` view onto
    a shared (M, ...) tensor stack; when any member queries quorum state,
    ALL members' buffered votes ride a single (M, FLUSH_BATCH) scatter.
    Against a high-latency device link this is the difference between one
    round-trip per node per tick and one per tick for the whole pool.
    """

    def __init__(self, n_members: int, validators: List[str], log_size: int,
                 n_checkpoints: int = 4, h: int = 0, metrics=None,
                 mesh=None, pipelined: bool = False,
                 adaptive_ladder: bool = False):
        """``mesh``: an optional :class:`jax.sharding.Mesh` with one axis;
        the member axis of every vote tensor is sharded across it via
        ``q.shard_map_compat``, so one pod's chips split the pool's
        planes and the grouped step runs explicit SPMD (members are
        independent — no cross-member collectives are needed; each
        chip's shard stays local). ``n_members`` is padded UP to a
        multiple of the mesh size: the trailing pad rows are real (zero)
        planes with no member view — they never receive votes, and
        occupancy accounting excludes them, so a 10-member pool on an
        8-device mesh costs two idle rows, not a ValueError.
        ``adaptive_ladder`` hands the padded flush width to an
        :class:`AdaptiveLadder` (learned per-pool top rung)."""
        self._n = len(validators)
        self._log_size = log_size
        self._n_chk = n_checkpoints
        proto = q.init_state(self._n, log_size, n_checkpoints)
        self._mesh = mesh
        self._sharding = None
        self._sharded_fns = None
        self._n_shards = 1
        self._shard_rows = n_members
        self._m_pad = n_members
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            axis = mesh.axis_names[0]
            self._n_shards = int(mesh.devices.size)
            self._shard_rows = -(-n_members // self._n_shards)  # ceil
            self._m_pad = self._shard_rows * self._n_shards
            # member axis sharded; everything below it stays local
            self._sharding = lambda ndim: NamedSharding(
                mesh, PartitionSpec(axis, *([None] * (ndim - 1))))
            self._sharded_fns = _sharded_group_fns(mesh, axis, self._n)
            # shard index -> owning device, resolved ONCE from the
            # sharding's own index map (the row-block assignment is
            # static per mesh; _stage_scatter must not recompute it —
            # or hop through the default device — per flush)
            imap = self._sharding(2).devices_indices_map((self._m_pad, 1))
            self._shard_devices = [None] * self._n_shards
            for dev, idx in imap.items():
                self._shard_devices[
                    (idx[0].start or 0) // self._shard_rows] = dev
        # real (non-pad) member rows per shard: the capacity denominator
        # for per-shard occupancy — pad rows can never hold votes and
        # must not dilute the governor's signal
        self._real_rows = [
            min(max(n_members - si * self._shard_rows, 0), self._shard_rows)
            for si in range(self._n_shards)]
        self._states = jax.tree.map(
            lambda x: jnp.zeros((self._m_pad,) + x.shape, x.dtype), proto)
        if self._sharding is not None:
            self._states = jax.tree.map(
                lambda x: jax.device_put(x, self._sharding(x.ndim)),
                self._states)
        self._members = [
            _MemberPlane(self, i, validators, log_size, n_checkpoints, h)
            for i in range(n_members)]
        self.version = 0  # bumped on every device-state change
        self._host_prepared: Optional[np.ndarray] = None
        self._host_prepare_counts: Optional[np.ndarray] = None
        self._host_commit_counts: Optional[np.ndarray] = None
        self._host_stable: Optional[np.ndarray] = None
        self.flushes = 0
        # occupancy counters (see DeviceVotePlane): per-tick deltas feed
        # the dispatch governor
        self.flush_votes_total = 0
        self.flush_capacity_total = 0
        # per-shard occupancy series (length 1 when unsharded): in mesh
        # mode the governor EWMAs each shard separately, so one hot
        # shard narrows the tick for the whole pool while idle siblings
        # cannot mask it behind the pool-wide average
        self.flush_votes_per_shard = [0] * self._n_shards
        self.flush_capacity_per_shard = [0] * self._n_shards
        # reusable host scatter staging (UNSHARDED path only): one
        # preallocated (M, B) buffer per ladder rung — the hot loop
        # stops paying an (M, B) np.zeros allocation per flush. Reuse is
        # safe ONLY because the device hand-off is a forced copy
        # (jnp.array, never jnp.asarray): on jax 0.4.37's CPU backend
        # asarray zero-copies suitably aligned numpy buffers (allocator
        # luck, reproduced empirically), and an aliased buffer reused
        # across `_dispatch_pending`'s chained async dispatches would
        # silently corrupt in-flight vote words. The mesh path stages
        # into FRESH per-shard buffers instead (see _stage_scatter) —
        # never reused, so they ship without the forced copy.
        self._scatter_bufs: dict = {}
        # learned top rung (None = static FLUSH_LADDER behaviour)
        self._ladder = AdaptiveLadder() if adaptive_ladder else None
        # device placement must be justifiable with data: flush count,
        # latency and votes-per-flush land here (injectable for a shared
        # or null collector)
        self.metrics = metrics if metrics is not None else MetricsCollector()
        # flight recorder (observability.trace): per-dispatch spans
        # (flush.dispatch with votes/shape, flush.readback) land here
        # when the composition roots hand in a recorder; NULL_TRACE
        # keeps the hot path free otherwise
        self.trace = NULL_TRACE
        # pipelined mode: flush() DISPATCHES this tick's step (async, JAX
        # never blocks on dispatch) and absorbs the PREVIOUS tick's events
        # into the host snapshot — the device round-trip overlaps a full
        # tick of host work instead of stalling the loop. Cost: quorum
        # verdicts lag one extra tick (votes are never lost; the services'
        # lost-wakeup guard re-arms while a step is in flight).
        self.pipelined = pipelined
        self._inflight: Optional[q.QuorumEvents] = None

    def view(self, member_idx: int) -> "DeviceVotePlane":
        return self._members[member_idx]

    @property
    def shards(self) -> int:
        """Mesh shard count (1 when unsharded)."""
        return self._n_shards

    @property
    def shard_occupancy(self) -> List[float]:
        """Cumulative per-shard occupancy (scattered votes / real-row
        capacity) — THE definition every surface reports (bench, budget
        gate, profile, dryrun)."""
        return [round(v / c, 4) if c else 0.0
                for v, c in zip(self.flush_votes_per_shard,
                                self.flush_capacity_per_shard)]

    def _absorb(self, events: q.QuorumEvents) -> None:
        """ONE bundled device->host transfer into the host snapshot."""
        with self.trace.span("flush.readback") if self.trace.enabled \
                else _NO_SPAN:
            (self._host_prepared, self._host_prepare_counts,
             self._host_commit_counts, self._host_stable) = jax.device_get(
                (events.prepared, events.prepare_counts,
                 events.commit_counts, events.stable_checkpoints))
        self.version += 1

    @property
    def lagging(self) -> bool:
        """True while a dispatched step's events are not yet in the host
        snapshot (pipelined mode) — quorum state may be newer on device."""
        return self._inflight is not None

    def _stage_scatter(self, chunks: List[List[int]], shape: int):
        """Pack ``chunks`` into the rung's reusable host buffer(s) and
        hand the device its own copy (one vectorized row write per
        member; the staging buffers themselves are never reallocated).

        Mesh mode stages PER SHARD: each shard's member rows land in a
        FRESH (rows, shape) buffer shipped straight to that shard's
        device, then assemble into ONE global member-sharded array — no
        host-side (M_pad, B) concat, no default-device hop, no
        device-side resharding on the flush path. Fresh buffers (not the
        unsharded path's reusable ones): a buffer that is never touched
        again has no aliasing hazard, so the device hand-off needs no
        forced copy — one allocation per shard replaces the
        memset+fill+copy a reused buffer would cost."""
        if self._mesh is None:
            out = self._scatter_bufs.get(shape)
            if out is None:
                out = self._scatter_bufs[shape] = np.zeros(
                    (len(self._members), shape), np.uint32)
            out[...] = 0
            for i, entries in enumerate(chunks):
                if entries:
                    q.fill_words_row(out[i], entries)
            # forced copy — see the staging-buffer comment in __init__
            # for why asarray would alias and corrupt in-flight
            # dispatches
            return jnp.array(out)
        bufs = [np.zeros((self._shard_rows, shape), np.uint32)
                for _ in range(self._n_shards)]
        for i, entries in enumerate(chunks):
            if entries:
                q.fill_words_row(
                    bufs[i // self._shard_rows][i % self._shard_rows],
                    entries)
        arrs = [
            jax.device_put(buf, dev)
            for buf, dev in zip(bufs, self._shard_devices)]
        return jax.make_array_from_single_device_arrays(
            (self._m_pad, shape), self._sharding(2), arrs)

    def _run_group_step(self, words):
        """ONE grouped device step over the whole (padded) member axis —
        shard_map'd under a mesh, plain vmapped jit otherwise."""
        if self._sharded_fns is not None:
            return self._sharded_fns[0](self._states, words)
        return _group_step_words(self._states, words, self._n)

    def _dispatch_pending(self):
        """Chunk + scatter every member's pending votes (async dispatch);
        returns the LAST chained step's events (they reflect every vote
        dispatched here), or None if nothing was pending."""
        events = None
        while any(m._pending for m in self._members):
            chunks = []
            votes = 0
            shard_votes = [0] * self._n_shards
            for i, m in enumerate(self._members):
                take, m._pending = (m._pending[:FLUSH_BATCH],
                                    m._pending[FLUSH_BATCH:])
                chunks.append(take)
                votes += len(take)
                shard_votes[i // self._shard_rows] += len(take)
            # the padded width rides the busiest member: a quiet tick
            # (a few straggler votes) scatters 16-wide, a full protocol
            # wave 128-wide — each rung is one cached XLA compilation.
            # With the adaptive ladder, the top rung is LEARNED from the
            # observed busiest-member distribution instead of fixed.
            busiest = max(len(c) for c in chunks)
            if self._ladder is not None:
                self._ladder.record(busiest)
                shape = self._ladder.shape(busiest)
            else:
                shape = ladder_shape(busiest)
            with self.trace.span(
                    "flush.dispatch",
                    args={"votes": votes, "shape": shape}) \
                    if self.trace.enabled else _NO_SPAN:
                words = self._stage_scatter(chunks, shape)
                self._states, events = self._run_group_step(words)
            self.flushes += 1
            capacity = len(self._members) * shape
            self.flush_votes_total += votes
            self.flush_capacity_total += capacity
            self._account_shards(shard_votes, shape)
            self.metrics.add_event(MetricsName.DEVICE_FLUSH)
            self.metrics.add_event(MetricsName.DEVICE_FLUSH_VOTES, votes)
            self.metrics.add_event(
                MetricsName.DEVICE_FLUSH_OCCUPANCY, votes / capacity)
        return events

    def _account_shards(self, shard_votes: List[int], shape: int) -> None:
        """Fold one dispatch into the per-shard occupancy series (the
        capacity denominator counts REAL member rows only — pad rows
        cannot hold votes and must not dilute the governor's signal)."""
        for si in range(self._n_shards):
            cap = self._real_rows[si] * shape
            self.flush_votes_per_shard[si] += shard_votes[si]
            self.flush_capacity_per_shard[si] += cap
        if self._n_shards > 1:
            self.metrics.add_event(
                MetricsName.DEVICE_SHARD_COUNT, self._n_shards)
            for si in range(self._n_shards):
                cap = self._real_rows[si] * shape
                if cap:
                    self.metrics.add_event(
                        f"{MetricsName.DEVICE_SHARD_FLUSH_VOTES}.{si}",
                        shard_votes[si])
                    self.metrics.add_event(
                        f"{MetricsName.DEVICE_SHARD_FLUSH_CAPACITY}.{si}",
                        cap)

    def _dispatch_empty(self):
        """One padded no-vote step (cold start needs SOME events)."""
        words = self._stage_scatter(
            [[] for _ in self._members], FLUSH_LADDER[0])
        self._states, events = self._run_group_step(words)
        self.flushes += 1
        self.flush_capacity_total += len(self._members) * FLUSH_LADDER[0]
        self._account_shards([0] * self._n_shards, FLUSH_LADDER[0])
        self.metrics.add_event(MetricsName.DEVICE_FLUSH)
        return events

    def _flush_pipelined(self) -> None:
        # 1. absorb the step dispatched LAST tick (usually complete by
        # now: the whole tick's host work overlapped its round-trip)
        self._sync_inflight()
        # 2. dispatch this tick's votes; events ride to the host next tick
        events = self._dispatch_pending()
        if events is not None:
            # the LAST chained step's events reflect every vote above.
            # Kick the device->host copy off NOW: by the time next tick's
            # absorb runs, the bytes are already host-side and device_get
            # returns without a link round-trip (measured: the blocking
            # cost of a flush drops to ~0 on a remote device link).
            for arr in (events.prepared, events.prepare_counts,
                        events.commit_counts, events.stable_checkpoints):
                try:
                    arr.copy_to_host_async()
                except Exception:  # noqa: BLE001 — backends without async
                    break  # copy: device_get pays the round-trip as before
            self._inflight = events
        if self._host_prepared is None:
            # cold start (or post-slide/reset): callers need SOME snapshot
            if self._inflight is None:
                self._inflight = self._dispatch_empty()
            self._sync_inflight()

    def flush(self) -> None:
        """Scatter every member's pending votes; refresh host event caches."""
        if self.pipelined:
            with self.metrics.measure_time(MetricsName.DEVICE_FLUSH_TIME):
                self._flush_pipelined()
            return
        if (not any(m._pending for m in self._members)
                and self._host_prepared is not None):
            return
        with self.metrics.measure_time(MetricsName.DEVICE_FLUSH_TIME):
            events = self._dispatch_pending()
            if events is None:  # cold start: no votes recorded anywhere yet
                events = self._dispatch_empty()
            # ONE bundled device->host transfer (separate np.asarray calls
            # cost one link round-trip each — painful on a remote device)
            self._absorb(events)

    def _sync_inflight(self) -> None:
        """Absorb any in-flight step NOW (window/view operations must not
        run with stale events pending under the OLD slot mapping)."""
        if self._inflight is not None:
            events, self._inflight = self._inflight, None
            self._absorb(events)

    def slide_member(self, member_idx: int, delta: int) -> None:
        self.flush()
        self._sync_inflight()
        deltas = np.zeros(self._m_pad, np.int32)
        deltas[member_idx] = delta
        if self._sharded_fns is not None:
            darr = jax.device_put(jnp.array(deltas), self._sharding(1))
            self._states = self._sharded_fns[1](self._states, darr)
        else:
            self._states = _group_slide(self._states, jnp.asarray(deltas))
        self.version += 1
        self._host_prepared = None

    def reset_member(self, member_idx: int) -> None:
        # pending for this member was cleared by the caller; other members'
        # buffered votes are untouched (flushed on their next query)
        self._sync_inflight()  # old-view events must not land post-reset
        if self._sharded_fns is not None:
            # shard_map zero rides a member MASK: a dynamic row index
            # cannot address a shard-local block, a mask shards trivially
            mask = np.zeros(self._m_pad, np.uint8)
            mask[member_idx] = 1
            marr = jax.device_put(jnp.array(mask), self._sharding(1))
            self._states = self._sharded_fns[2](self._states, marr)
        else:
            self._states = _group_zero_member(
                self._states, jnp.int32(member_idx))
        self.version += 1
        self._host_prepared = None


class _MemberPlane(DeviceVotePlane):
    """One member's view of a :class:`VotePlaneGroup` (same interface as a
    standalone :class:`DeviceVotePlane`; storage and flushing are shared)."""

    def __init__(self, group: VotePlaneGroup, member_idx: int,
                 validators: List[str], log_size: int, n_checkpoints: int,
                 h: int):
        self._group = group
        self._mi = member_idx
        self._validators = list(validators)
        self._index = {name: i for i, name in enumerate(self._validators)}
        self._n = len(self._validators)
        self._log_size = log_size
        self._n_chk = n_checkpoints
        self._h = h
        self._pending: List[int] = []  # uint32 vote words (q.pack_vote)
        self._events = None
        self._seen_version = -1
        self._host_prepared = None
        self._host_prepare_counts = None
        self._host_commit_counts = None
        self._host_stable = None
        self.defer_flush_on_query = False

    @property
    def flushes(self) -> int:
        return self._group.flushes

    @flushes.setter
    def flushes(self, value) -> None:  # base-class compat; group owns it
        pass

    # occupancy counters live on the group (shared dispatches); read-only
    # views keep the DeviceVotePlane interface uniform for tick drivers

    @property
    def flush_votes_total(self) -> int:
        return self._group.flush_votes_total

    @flush_votes_total.setter
    def flush_votes_total(self, value) -> None:
        pass

    @property
    def flush_capacity_total(self) -> int:
        return self._group.flush_capacity_total

    @flush_capacity_total.setter
    def flush_capacity_total(self, value) -> None:
        pass

    @property
    def has_buffered_votes(self) -> bool:
        # pipelined group: votes dispatched but not yet in the snapshot
        # must keep the services' lost-wakeup guard armed, exactly like
        # host-buffered votes
        return bool(self._pending) or self._group.lagging

    def _flush(self) -> None:
        self._group.flush()

    def _copy_slices(self) -> None:
        self._host_prepared = self._group._host_prepared[self._mi]
        self._host_prepare_counts = self._group._host_prepare_counts[self._mi]
        self._host_commit_counts = self._group._host_commit_counts[self._mi]
        self._host_stable = self._group._host_stable[self._mi]
        self._seen_version = self._group.version
        self._events = True

    def _refresh(self) -> None:
        self._group.flush()
        if not self.defer_flush_on_query:
            # per-query mode wants CURRENT state: a pipelined group must
            # absorb its in-flight step now, or the final batch's votes
            # sit on-device forever with no tick driver to absorb them
            self._group._sync_inflight()
        self._copy_slices()

    def events(self):
        if (self._group._host_prepared is None
                or (not self.defer_flush_on_query
                    and (self._pending or self._events is None))):
            self._refresh()
        elif self._seen_version != self._group.version:
            self._copy_slices()
        return self._events

    def slide_to(self, new_h: int) -> None:
        if new_h <= self._h:
            return
        self._group.slide_member(self._mi, new_h - self._h)
        self._h = new_h
        self._events = None

    def reset(self, h: Optional[int] = None) -> None:
        if h is not None:
            self._h = h
        self._pending.clear()
        self._group.reset_member(self._mi)
        self._events = None
