"""Batched SHA-512 on TPU in int32 lanes (64-bit words as uint32 pairs).

Closes the last host hash in the Ed25519 hot path: the reference's
libsodium verify (plenum/server/client_authn.py via
stp_core/crypto/nacl_wrappers.py) computes h = SHA512(R || A || M) mod L
on the CPU per signature; round 4 still did that on the protocol thread
(tpu/ed25519.py prepare_batch). Here the whole batch hashes on device:

- 64-bit words are (hi, lo) uint32 pairs — no jax x64 mode, pure int32
  lanes like every other kernel in this package;
- rounds/schedule run as scans over GROUPS of 16 unrolled steps (the
  same shape tpu/sha256.py uses — a full 80-round unroll is the known
  XLA:CPU trap);
- messages are padded to static block counts on the host (memcpy only,
  no hashing) and folded under a per-item active-block mask, so one
  compiled kernel serves every message length in the bucket;
- the mod-L reduction runs on device as a 260-step conditional-subtract
  ladder over precomputed L<<i tables in 16-bit limbs — negligible next
  to the curve math, and free of data-dependent control flow.

SHA-512 round constants and initial state are DERIVED at import (frac of
cube/square roots of the first primes, FIPS 180-4) rather than
transcribed; tests pin the kernel against hashlib.sha512.
"""
from __future__ import annotations

import math
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

# --- constants, derived (FIPS 180-4) ---------------------------------------


def _first_primes(n: int):
    out, cand = [], 2
    while len(out) < n:
        if all(cand % p for p in out if p * p <= cand):
            out.append(cand)
        cand += 1
    return out


def _icbrt(x: int) -> int:
    # integer Newton iteration (a float seed is off by thousands at
    # 2^192 — stepping that back by ones cost seconds of import time)
    r = 1 << ((x.bit_length() + 2) // 3)
    while True:
        nr = (2 * r + x // (r * r)) // 3
        if nr >= r:
            break
        r = nr
    while r * r * r > x:
        r -= 1
    while (r + 1) ** 3 <= x:
        r += 1
    return r


_PRIMES80 = _first_primes(80)
_K64 = [(_icbrt(p << 192)) & ((1 << 64) - 1) for p in _PRIMES80]
_H064 = [math.isqrt(p << 128) & ((1 << 64) - 1) for p in _PRIMES80[:8]]

_K_HI = np.array([k >> 32 for k in _K64], np.uint32)
_K_LO = np.array([k & 0xFFFFFFFF for k in _K64], np.uint32)
_H0_HI = np.array([h >> 32 for h in _H064], np.uint32)
_H0_LO = np.array([h & 0xFFFFFFFF for h in _H064], np.uint32)

# --- 64-bit ops on (hi, lo) uint32 pairs -----------------------------------


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _rotr64(h, lo, n: int):
    if n == 0:
        return h, lo
    if n < 32:
        return ((h >> n) | (lo << (32 - n)),
                (lo >> n) | (h << (32 - n)))
    if n == 32:
        return lo, h
    m = n - 32
    return ((lo >> m) | (h << (32 - m)),
            (h >> m) | (lo << (32 - m)))


def _shr64(h, lo, n: int):
    if n < 32:
        return h >> n, (lo >> n) | (h << (32 - n))
    return jnp.zeros_like(h), h >> (n - 32)


def _sigma(h, lo, r1, r2, r3, shift_last: bool):
    ah, al = _rotr64(h, lo, r1)
    bh, bl = _rotr64(h, lo, r2)
    ch, cl = _shr64(h, lo, r3) if shift_last else _rotr64(h, lo, r3)
    return ah ^ bh ^ ch, al ^ bl ^ cl


def _compress512(state: jnp.ndarray, w16: jnp.ndarray) -> jnp.ndarray:
    """One SHA-512 compression. state (..., 8, 2), w16 (..., 16, 2) uint32
    where [..., 0] = hi and [..., 1] = lo of each 64-bit word."""

    def sched_step(w, _):
        hs = [w[..., i, 0] for i in range(16)]
        ls = [w[..., i, 1] for i in range(16)]
        nh, nl = [], []
        for j in range(16):
            m15h = nh[j - 15] if j >= 15 else hs[j + 1]
            m15l = nl[j - 15] if j >= 15 else ls[j + 1]
            m2h = nh[j - 2] if j >= 2 else hs[j + 14]
            m2l = nl[j - 2] if j >= 2 else ls[j + 14]
            m7h = nh[j - 7] if j >= 7 else hs[j + 9]
            m7l = nl[j - 7] if j >= 7 else ls[j + 9]
            s0h, s0l = _sigma(m15h, m15l, 1, 8, 7, True)
            s1h, s1l = _sigma(m2h, m2l, 19, 61, 6, True)
            th, tl = _add64(hs[j], ls[j], s0h, s0l)
            th, tl = _add64(th, tl, m7h, m7l)
            th, tl = _add64(th, tl, s1h, s1l)
            nh.append(th)
            nl.append(tl)
        nw = jnp.stack([jnp.stack(nh, axis=-1),
                        jnp.stack(nl, axis=-1)], axis=-1)
        return nw, nw

    _, extra = lax.scan(sched_step, w16, None, length=4)  # (4, ..., 16, 2)
    extra = jnp.moveaxis(extra, 0, -3)
    extra = extra.reshape(w16.shape[:-2] + (64, 2))
    w_all = jnp.concatenate([w16, extra], axis=-2)  # (..., 80, 2)

    k_groups = jnp.stack(
        [jnp.asarray(_K_HI.reshape(5, 16)),
         jnp.asarray(_K_LO.reshape(5, 16))], axis=-1)  # (5, 16, 2)
    w_groups = jnp.moveaxis(
        w_all.reshape(w_all.shape[:-2] + (5, 16, 2)), -3, 0)  # (5, ..., 16, 2)

    def round_group(carry, inp):
        ks, ws = inp  # (16, 2) and (..., 16, 2)
        ah, al = carry[..., 0, 0], carry[..., 0, 1]
        bh, bl = carry[..., 1, 0], carry[..., 1, 1]
        ch, cl = carry[..., 2, 0], carry[..., 2, 1]
        dh, dl = carry[..., 3, 0], carry[..., 3, 1]
        eh, el = carry[..., 4, 0], carry[..., 4, 1]
        fh, fl = carry[..., 5, 0], carry[..., 5, 1]
        gh, gl = carry[..., 6, 0], carry[..., 6, 1]
        hh, hl = carry[..., 7, 0], carry[..., 7, 1]
        for i in range(16):
            s1h, s1l = _sigma(eh, el, 14, 18, 41, False)
            chh = (eh & fh) ^ (~eh & gh)
            chl = (el & fl) ^ (~el & gl)
            t1h, t1l = _add64(hh, hl, s1h, s1l)
            t1h, t1l = _add64(t1h, t1l, chh, chl)
            t1h, t1l = _add64(t1h, t1l, ks[i, 0], ks[i, 1])
            t1h, t1l = _add64(t1h, t1l, ws[..., i, 0], ws[..., i, 1])
            s0h, s0l = _sigma(ah, al, 28, 34, 39, False)
            mjh = (ah & bh) ^ (ah & ch) ^ (bh & ch)
            mjl = (al & bl) ^ (al & cl) ^ (bl & cl)
            t2h, t2l = _add64(s0h, s0l, mjh, mjl)
            hh, hl = gh, gl
            gh, gl = fh, fl
            fh, fl = eh, el
            eh, el = _add64(dh, dl, t1h, t1l)
            dh, dl = ch, cl
            ch, cl = bh, bl
            bh, bl = ah, al
            ah, al = _add64(t1h, t1l, t2h, t2l)
        out = jnp.stack([
            jnp.stack([ah, al], axis=-1), jnp.stack([bh, bl], axis=-1),
            jnp.stack([ch, cl], axis=-1), jnp.stack([dh, dl], axis=-1),
            jnp.stack([eh, el], axis=-1), jnp.stack([fh, fl], axis=-1),
            jnp.stack([gh, gl], axis=-1), jnp.stack([hh, hl], axis=-1),
        ], axis=-2)
        return out, None

    final, _ = lax.scan(round_group, state, (k_groups, w_groups))
    # state += final, per 64-bit word
    sh, sl = _add64(state[..., 0], state[..., 1],
                    final[..., 0], final[..., 1])
    return jnp.stack([sh, sl], axis=-1)


def _blocks_to_words(blocks: jnp.ndarray) -> jnp.ndarray:
    """(..., 128) uint8 big-endian -> (..., 16, 2) uint32 (hi, lo)."""
    b = blocks.astype(jnp.uint32)
    oct_ = b.reshape(b.shape[:-1] + (16, 8))
    hi = ((oct_[..., 0] << 24) | (oct_[..., 1] << 16)
          | (oct_[..., 2] << 8) | oct_[..., 3])
    lo = ((oct_[..., 4] << 24) | (oct_[..., 5] << 16)
          | (oct_[..., 6] << 8) | oct_[..., 7])
    return jnp.stack([hi, lo], axis=-1)


def sha512_blocks(blocks: jnp.ndarray, n_blocks: jnp.ndarray) -> jnp.ndarray:
    """SHA-512 over host-padded blocks with a per-item block count.

    blocks (B, NB, 128) uint8 (already FIPS-padded rows; rows past
    n_blocks[i] ignored); n_blocks (B,) int32. Returns (B, 64) uint8.
    """
    nb = blocks.shape[-2]
    words = _blocks_to_words(blocks)  # (B, NB, 16, 2)
    state0 = jnp.broadcast_to(
        jnp.stack([jnp.asarray(_H0_HI), jnp.asarray(_H0_LO)], axis=-1),
        blocks.shape[:-2] + (8, 2))

    def body(state, i):
        nxt = _compress512(state, words[..., i, :, :])
        active = (i < n_blocks)[..., None, None]
        return jnp.where(active, nxt, state), None

    state, _ = lax.scan(body, state0, jnp.arange(nb, dtype=jnp.int32))
    hi, lo = state[..., 0], state[..., 1]  # (B, 8) each

    def to_bytes(w):
        parts = [(w >> 24) & 0xFF, (w >> 16) & 0xFF,
                 (w >> 8) & 0xFF, w & 0xFF]
        return jnp.stack(parts, axis=-1)

    # big-endian per 64-bit word: hi bytes then lo bytes
    out = jnp.concatenate([to_bytes(hi), to_bytes(lo)], axis=-1)
    return out.reshape(out.shape[:-2] + (64,)).astype(jnp.uint8)


# --- h mod L on device ------------------------------------------------------

_L_INT = (1 << 252) + 27742317777372353535851937790883648493
_N_LIMBS = 33  # 16-bit limbs covering 512 bits + headroom
_LADDER = 260  # L << 259 > 2^511 >= any SHA-512 output


def _l_shift_table() -> np.ndarray:
    rows = []
    for i in range(_LADDER - 1, -1, -1):
        v = _L_INT << i
        rows.append([(v >> (16 * j)) & 0xFFFF for j in range(_N_LIMBS)])
    return np.array(rows, np.int32)  # descending shift order


_L_SHIFTS = _l_shift_table()


def reduce_mod_l(h_le_bytes: jnp.ndarray) -> jnp.ndarray:
    """(B, 64) uint8 little-endian -> (B, 32) uint8 of (h mod L), LE.

    Conditional-subtract ladder: h < 2^512 and L<<259 tops it, so 260
    compare-and-subtract steps leave h < L. Each step is a vectorized
    16-bit-limb borrow chain — trivial next to the curve math it feeds.
    """
    b = h_le_bytes.astype(jnp.int32)
    pairs = b.reshape(b.shape[:-1] + (32, 2))
    limbs = pairs[..., 0] | (pairs[..., 1] << 8)  # (B, 32) 16-bit LE
    limbs = jnp.concatenate(
        [limbs, jnp.zeros(limbs.shape[:-1] + (1,), jnp.int32)], axis=-1)

    def step(h, lrow):
        # h - lrow with borrow propagation over 33 limbs
        diffs = []
        borrow = jnp.zeros(h.shape[:-1], jnp.int32)
        for j in range(_N_LIMBS):
            d = h[..., j] - lrow[j] - borrow
            borrow = (d < 0).astype(jnp.int32)
            diffs.append(d + (borrow << 16))
        diff = jnp.stack(diffs, axis=-1)
        keep = borrow == 0  # no final borrow: h >= L<<i, take the diff
        return jnp.where(keep[..., None], diff, h), None

    limbs, _ = lax.scan(step, limbs, jnp.asarray(_L_SHIFTS))
    out16 = limbs[..., :16]  # < L < 2^253 fits 16 limbs
    lo = out16 & 0xFF
    hi = (out16 >> 8) & 0xFF
    out = jnp.stack([lo, hi], axis=-1)
    return out.reshape(out.shape[:-2] + (32,)).astype(jnp.uint8)


def ed25519_h(r_bytes: jnp.ndarray, pk: jnp.ndarray,
              msg_blocks: jnp.ndarray,
              n_blocks: jnp.ndarray) -> jnp.ndarray:
    """h = SHA512(R || A || M) mod L, fully on device.

    ``msg_blocks`` (B, NB, 128) uint8 are the host-padded blocks of the
    FULL hash input (R and A already spliced in front of M — the host
    only moves bytes, never hashes); returns (B, 32) uint8 LE scalars
    for the verify kernel. ``r_bytes``/``pk`` are accepted for shape
    documentation; they are already inside ``msg_blocks``.
    """
    del r_bytes, pk
    return reduce_mod_l(sha512_blocks(msg_blocks, n_blocks))


def pad_ed25519_messages(prefixes, msgs, max_blocks: int
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side packing: (R||A) prefixes + messages -> padded blocks.

    Returns (B, max_blocks, 128) uint8 + (B,) int32 block counts.
    Vectorized per distinct message length (protocol batches are nearly
    uniform) — a Python per-byte loop here would cost more than the
    hashlib call this path exists to eliminate.
    """
    from collections import defaultdict

    n = len(msgs)
    plen = len(prefixes[0]) if prefixes else 0
    buf = np.zeros((n, max_blocks * 128), np.uint8)
    counts = np.zeros(n, np.int32)
    if n == 0:
        return buf.reshape(n, max_blocks, 128), counts
    buf[:, :plen] = np.frombuffer(b"".join(prefixes),
                                  np.uint8).reshape(n, plen)
    by_len = defaultdict(list)
    for i, m in enumerate(msgs):
        by_len[len(m)].append(i)
    for mlen, idx_list in by_len.items():
        # da: allow[device-sync] -- host-side scatter-index packing (a python list); no device value involved
        idxs = np.asarray(idx_list)
        total = plen + mlen
        nb = (total + 17 + 127) // 128
        assert nb <= max_blocks, (total, max_blocks)
        if mlen:
            arr = np.frombuffer(
                b"".join(msgs[i] for i in idx_list),
                np.uint8).reshape(len(idx_list), mlen)
            buf[idxs, plen:total] = arr
        buf[idxs, total] = 0x80
        bits = np.frombuffer((total * 8).to_bytes(16, "big"), np.uint8)
        buf[np.ix_(idxs, np.arange(nb * 128 - 16, nb * 128))] = bits
        counts[idxs] = nb
    return buf.reshape(n, max_blocks, 128), counts


def sha512_host_oracle(data: bytes) -> bytes:  # pragma: no cover - test aid
    import hashlib

    return hashlib.sha512(data).digest()
