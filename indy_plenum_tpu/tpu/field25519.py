"""GF(2^255 - 19) field arithmetic as batched JAX limb vectors (int32).

TPU-native replacement for the field layer inside libsodium's ref10
(reference consumes it via ``stp_core/crypto/nacl_wrappers.py``). Design:

- A field element is a little-endian vector of ``NLIMBS = 22`` limbs in radix
  ``2^12``, dtype **int32** — native TPU VPU arithmetic, no 64-bit emulation.
  Batching is a leading axis: ``(..., 22)``; all ops are elementwise over the
  batch and vectorize on the VPU with no data-dependent control flow.
- Bounds: ops accept "loose" limbs (<= 2^13) and return loose limbs
  (<= 2^12 + epsilon). Schoolbook products of loose limbs are < 2^26 and
  column sums < 22 * 2^26 < 2^31, so int32 never overflows.
- Carry propagation is *parallel* (all limbs emit carries simultaneously,
  carries shift up one position, top carry folds by 2^264 = 9728 (mod p)):
  a few O(1)-depth vector passes instead of a sequential 22-step chain —
  this keeps both XLA compile time and the critical path short.
- Only :func:`freeze` produces canonical limbs (one sequential ripple pass +
  conditional subtracts); equality/encoding go through it.

Exponentiation chains (inversion, sqrt) run as ``lax.scan`` over static
exponent bits so the traced program stays small.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

P = 2**255 - 19
NLIMBS = 22
RADIX = 12
MASK = (1 << RADIX) - 1
# 2^(12*22) = 2^264 == 2^9 * 19 = 9728 (mod p)
TOP_FOLD = (1 << (RADIX * NLIMBS)) % P
assert TOP_FOLD == 9728

# d = -121665/121666 mod p (edwards25519 curve constant)
D = 37095705934669439343138083508754565189542113879843219016388785533085940283555
D2 = (2 * D) % P
# sqrt(-1) mod p
SQRT_M1 = pow(2, (P - 1) // 4, P)


def limbs_from_int(x: int) -> np.ndarray:
    out = np.zeros(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = (x >> (RADIX * i)) & MASK
    return out


def int_from_limbs(limbs) -> int:
    # da: allow[device-sync] -- host-side bignum reassembly for tests/constants (object dtype cannot live on device anyway)
    arr = np.asarray(limbs, dtype=object).reshape(-1)
    return sum(int(arr[i]) << (RADIX * i) for i in range(NLIMBS)) % P


def _make_kp_limbwise() -> np.ndarray:
    """Multiple of p with every limb in [4*2^12 - 4, 2^17+]: subtrahend-safe.

    ``sub(a, b) = carry(a + K - b)`` never goes negative limb-wise for any
    loose ``b`` (loose limbs stay well under 2^13; every K limb is ~2^14).
    """
    k = (1 << 14) * P  # top radix-12 limb holds the 2^17 overflow bits
    limbs = np.zeros(NLIMBS, dtype=np.int64)
    for i in range(NLIMBS - 1):
        limbs[i] = (k >> (RADIX * i)) & MASK
    limbs[NLIMBS - 1] = k >> (RADIX * (NLIMBS - 1))
    for i in range(NLIMBS - 1):
        limbs[i] += 4 << RADIX
        limbs[i + 1] -= 4
    assert (limbs >= (4 << RADIX) - 4).all(), limbs
    assert (limbs <= (1 << 17)).all(), limbs
    assert sum(int(l) << (RADIX * i) for i, l in enumerate(limbs)) == k
    return limbs.astype(np.int32)


_KP_LIMBS = _make_kp_limbwise()

ZERO = limbs_from_int(0)
ONE = limbs_from_int(1)
D_LIMBS = limbs_from_int(D)
D2_LIMBS = limbs_from_int(D2)
SQRT_M1_LIMBS = limbs_from_int(SQRT_M1)
P_LIMBS = limbs_from_int(P)


# Top-fold constants, split so every contribution stays far below 2^31:
# 2^264 = 9728 = 2*2^12 + 1536 (mod p); 2^276 = 9728 * 2^12 (mod p).
_FOLD_L0 = TOP_FOLD & MASK  # 1536, into limb 0
_FOLD_L1 = TOP_FOLD >> RADIX  # 2, into limb 1
assert _FOLD_L1 * (1 << RADIX) + _FOLD_L0 == TOP_FOLD
assert (TOP_FOLD << RADIX) % P == TOP_FOLD * (1 << RADIX)  # 2^276 mod p


def _parallel_carry_pass(c: jnp.ndarray) -> jnp.ndarray:
    """All limbs emit carries at once; carries shift up; top carry folds.

    Safe for any non-negative int32 input (limbs < 2^31): the top carry
    (< 2^19) is split into 12-bit halves before scaling, so every fold
    contribution is < 2^23. Repeated passes converge to loose limbs
    (<= 2^12 + 1) in at most 4 passes from 2^31, 2 passes from 2^14.
    """
    cr = c >> RADIX
    lo = c & MASK
    top = cr[..., -1]
    e_lo = top & MASK
    e_hi = top >> RADIX
    shifted = jnp.concatenate([jnp.zeros_like(cr[..., :1]), cr[..., :-1]], axis=-1)
    out = lo + shifted
    out = out.at[..., 0].add(e_lo * _FOLD_L0)
    out = out.at[..., 1].add(e_lo * _FOLD_L1 + e_hi * TOP_FOLD)
    return out


def carry(c: jnp.ndarray, passes: int = 4) -> jnp.ndarray:
    """Fast loose carry: limbs out <= 2^12 (+1 ripple), inputs < 2^31.

    Output limbs are <= 2^12 (a limb may be exactly 2^12 in rare ripple
    cases) — "loose", accepted by every op here. :func:`freeze` is strict.
    """
    for _ in range(passes):
        c = _parallel_carry_pass(c)
    return c


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b, passes=2)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + jnp.asarray(_KP_LIMBS) - b, passes=2)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return carry(jnp.asarray(_KP_LIMBS) - a, passes=2)


def _mul_wide(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook columns: (..., 2*NLIMBS-1) int32, each < 2^31."""
    pad_cfg_base = tuple((0, 0) for _ in range(a.ndim - 1))
    terms = []
    for i in range(NLIMBS):
        prod = a[..., i : i + 1] * b  # (..., 22), each < 2^26
        terms.append(jnp.pad(prod, pad_cfg_base + ((i, NLIMBS - 1 - i),)))
    return sum(terms)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Product of loose elements; output loose (<= 2^12)."""
    wide = _mul_wide(a, b)
    lo = wide[..., :NLIMBS]
    hi = wide[..., NLIMBS:]  # 21 columns, < 2^31
    # Carry the high part down to loose limbs before scaling by 9728 so the
    # fold stays within int32 (9728 * 2^13 < 2^27; lo + that < 2^31).
    pad_cfg = tuple((0, 0) for _ in range(hi.ndim - 1)) + ((0, 1),)
    hi = jnp.pad(hi, pad_cfg)  # 22 columns; own top folds at its 2^264
    hi = carry(hi, passes=4)
    return carry(lo + hi * TOP_FOLD)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    assert 0 <= k < (1 << 17)
    return carry(a * k)


def _pow_const(a: jnp.ndarray, exponent: int) -> jnp.ndarray:
    """a ** exponent via left-to-right square-and-multiply under lax.scan."""
    bits = np.array(
        [(exponent >> i) & 1 for i in reversed(range(exponent.bit_length()))],
        dtype=np.int32,
    )
    acc = jnp.broadcast_to(jnp.asarray(ONE), a.shape).astype(jnp.int32)

    def body(acc, bit):
        acc = sqr(acc)
        acc = jnp.where(bit == 1, mul(acc, a), acc)
        return acc, None

    acc, _ = lax.scan(body, acc, jnp.asarray(bits))
    return acc


def invert(a: jnp.ndarray) -> jnp.ndarray:
    return _pow_const(a, P - 2)


def pow_p58(a: jnp.ndarray) -> jnp.ndarray:
    """a ** ((p-5)/8), the core of the combined sqrt/division trick."""
    return _pow_const(a, (P - 5) // 8)


def freeze(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical representative in [0, p): strict carry + cond subtracts.

    Parallel passes kill all excess above the +1 ripple; one sequential
    ripple pass (carries <= 1) makes limbs strictly < 2^12; each top fold
    strictly decreases the value below 2^264, so a handful of passes
    suffices for any loose input. Then subtract p up to twice.
    """
    for _ in range(4):
        a = _parallel_carry_pass(a)
    # Sequential ripple passes (carries tiny now). Each top fold strictly
    # decreases the value below 2^264; from a loose value at most two folds
    # can ever fire, so three passes leave all limbs strictly < 2^12.
    for _ in range(3):
        for i in range(NLIMBS - 1):
            cr = a[..., i] >> RADIX
            a = a.at[..., i].add(-(cr << RADIX))
            a = a.at[..., i + 1].add(cr)
        top = a[..., NLIMBS - 1] >> RADIX
        a = a.at[..., NLIMBS - 1].add(-(top << RADIX))
        a = a.at[..., 0].add(top * TOP_FOLD)

    # The representation spans 264 bits, so the value can still be ~512*p.
    # Fold bits >= 255 (the top 9 bits of limb 21) down: 2^255 == 19 (mod p),
    # leaving the value < 2^255 + 512*19 < 2*p; then subtract p <= twice.
    hi = a[..., NLIMBS - 1] >> 3
    a = a.at[..., NLIMBS - 1].add(-(hi << 3))
    a = a.at[..., 0].add(hi * 19)
    for i in range(NLIMBS - 1):
        cr = a[..., i] >> RADIX
        a = a.at[..., i].add(-(cr << RADIX))
        a = a.at[..., i + 1].add(cr)

    p_limbs = jnp.asarray(P_LIMBS)
    for _ in range(2):
        diff = a - p_limbs
        borrow = jnp.zeros(a.shape[:-1], dtype=jnp.int32)
        out = jnp.zeros_like(a)
        for i in range(NLIMBS):
            d = diff[..., i] - borrow
            borrow = (d < 0).astype(jnp.int32)
            out = out.at[..., i].set(d + (borrow << RADIX))
        a = jnp.where((borrow == 0)[..., None], out, a)
    return a


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Batched field equality -> bool (...,)."""
    return jnp.all(freeze(a) == freeze(b), axis=-1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(freeze(a) == 0, axis=-1)


def parity(a: jnp.ndarray) -> jnp.ndarray:
    """Least significant bit of the canonical representative."""
    return freeze(a)[..., 0] & 1


# --- byte <-> limb conversion (static index/shift tables) -------------------

# limb j covers bits [12j, 12j+12); byte k covers bits [8k, 8k+8).
_DEC_BYTE_IDX = np.zeros((NLIMBS, 3), np.int32)
_DEC_SHIFT = np.zeros(NLIMBS, np.int32)
for _j in range(NLIMBS):
    bit = RADIX * _j
    k = bit // 8
    _DEC_BYTE_IDX[_j] = [k, k + 1, k + 2]  # input padded to 34 bytes
    _DEC_SHIFT[_j] = bit - 8 * k

_ENC_LIMB_IDX = np.zeros((32, 2), np.int32)
_ENC_SHIFT = np.zeros(32, np.int32)
for _k in range(32):
    bit = 8 * _k
    j = bit // RADIX
    _ENC_LIMB_IDX[_k] = [min(j, NLIMBS - 1), min(j + 1, NLIMBS - 1)]
    _ENC_SHIFT[_k] = bit - RADIX * j


def decode_bytes(b: jnp.ndarray) -> jnp.ndarray:
    """(..., 32) uint8 little-endian -> (..., 22) limbs (top bit cleared)."""
    b = b.astype(jnp.int32)
    b = b.at[..., 31].set(b[..., 31] & 0x7F)
    # pad two zero bytes so 3-byte windows never run off the end
    pad_cfg = tuple((0, 0) for _ in range(b.ndim - 1)) + ((0, 2),)
    b = jnp.pad(b, pad_cfg)
    b0 = b[..., _DEC_BYTE_IDX[:, 0]]
    b1 = b[..., _DEC_BYTE_IDX[:, 1]]
    b2 = b[..., _DEC_BYTE_IDX[:, 2]]
    sh = jnp.asarray(_DEC_SHIFT)
    word = b0 + (b1 << 8) + (b2 << 16)
    return (word >> sh) & MASK


def encode_bytes(a: jnp.ndarray) -> jnp.ndarray:
    """(..., 22) limbs -> canonical (..., 32) uint8 little-endian."""
    a = freeze(a)
    l0 = a[..., _ENC_LIMB_IDX[:, 0]]
    l1 = a[..., _ENC_LIMB_IDX[:, 1]]
    sh = jnp.asarray(_ENC_SHIFT)
    word = (l0 >> sh) + (l1 << (RADIX - sh))
    return (word & 0xFF).astype(jnp.uint8)
