"""Batched Ed25519 verification on TPU (JAX, vmapped limb arithmetic).

This is the device half of the ``CoreAuthNr.authenticate`` hot path
(reference: ``plenum/server/client_authn.py``, libsodium verify via
``stp_core/crypto/nacl_wrappers.py``). The whole pending-request batch is
verified as ONE jitted program: point decompression, a 4-bit-windowed
double-scalar multiplication ``S*B + h*(-A)`` under ``lax.scan``, and a
recompress-and-compare against R (ref10's strategy, batched).

Host side computes ``h = SHA512(R || A || M) mod L`` (cheap, C-speed hashlib)
and the ``S < L`` range check; the device does all curve math. Only a boolean
verdict vector returns to the consensus loop.

No data-dependent control flow: invalid points carry poison-free dummy values
and are masked out by the ``ok`` flags, so the program is a single static
SPMD kernel, shardable over the batch axis of a mesh.
"""
from __future__ import annotations

import hashlib
from functools import partial
from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import field25519 as fe
from ..crypto import ed25519 as ref

WINDOWS = 64  # 4-bit windows over 256-bit scalars

# ---------------------------------------------------------------------------
# Point representation: (..., 4, NLIMBS=22) int32 = extended (X, Y, Z, T)
# limbs. Cached form for addition: (..., 4, 22) = (Y+X, Y-X, 2d*T, 2Z).
# ---------------------------------------------------------------------------

_IDENTITY = np.stack(
    [fe.limbs_from_int(0), fe.limbs_from_int(1), fe.limbs_from_int(1), fe.limbs_from_int(0)]
)
_IDENTITY_CACHED = np.stack(
    [fe.limbs_from_int(1), fe.limbs_from_int(1), fe.limbs_from_int(0), fe.limbs_from_int(2)]
)


def _pt(x, y, z, t):
    return jnp.stack([x, y, z, t], axis=-2)


def point_double(p: jnp.ndarray) -> jnp.ndarray:
    X, Y, Z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    A = fe.sqr(X)
    B = fe.sqr(Y)
    C = fe.mul_small(fe.sqr(Z), 2)
    Dd = fe.neg(A)
    E = fe.sub(fe.sub(fe.sqr(fe.add(X, Y)), A), B)
    G = fe.add(Dd, B)
    F = fe.sub(G, C)
    H = fe.sub(Dd, B)
    return _pt(fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H))


def to_cached(p: jnp.ndarray) -> jnp.ndarray:
    X, Y, Z, T = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    d2 = jnp.asarray(fe.D2_LIMBS)
    return jnp.stack(
        [fe.add(Y, X), fe.sub(Y, X), fe.mul(T, d2), fe.mul_small(Z, 2)], axis=-2
    )


def point_add_cached(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Extended point + cached point (add-2008-hwcd-3, a=-1)."""
    X1, Y1, Z1, T1 = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    A = fe.mul(fe.sub(Y1, X1), q[..., 1, :])
    B = fe.mul(fe.add(Y1, X1), q[..., 0, :])
    C = fe.mul(q[..., 2, :], T1)
    Dd = fe.mul(q[..., 3, :], Z1)
    E = fe.sub(B, A)
    F = fe.sub(Dd, C)
    G = fe.add(Dd, C)
    H = fe.add(B, A)
    return _pt(fe.mul(E, F), fe.mul(G, H), fe.mul(F, G), fe.mul(E, H))


def point_neg(p: jnp.ndarray) -> jnp.ndarray:
    X, Y, Z, T = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    return _pt(fe.neg(X), Y, Z, fe.neg(T))


def decompress(b: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(..., 32) uint8 -> (point (...,4,22), ok (...,) bool). RFC 8032 rules."""
    y = fe.decode_bytes(b)
    sign = (b[..., 31].astype(jnp.int32) >> 7) & 1
    canonical = jnp.all(y == fe.freeze(y), axis=-1)
    one = jnp.asarray(fe.ONE)
    yy = fe.sqr(y)
    u = fe.sub(yy, one)
    v = fe.add(fe.mul(yy, jnp.asarray(fe.D_LIMBS)), one)
    v3 = fe.mul(v, fe.sqr(v))
    v7 = fe.mul(fe.sqr(v3), v)
    t = fe.pow_p58(fe.mul(u, v7))
    x = fe.mul(fe.mul(u, v3), t)
    vx2 = fe.mul(v, fe.sqr(x))
    ok_direct = fe.eq(vx2, u)
    ok_flipped = fe.eq(vx2, fe.neg(u))
    x = jnp.where(ok_flipped[..., None], fe.mul(x, jnp.asarray(fe.SQRT_M1_LIMBS)), x)
    ok = canonical & (ok_direct | ok_flipped)
    x_is_zero = fe.is_zero(x)
    ok = ok & ~(x_is_zero & (sign == 1))
    flip = fe.parity(x) != sign
    x = jnp.where(flip[..., None], fe.neg(x), x)
    return _pt(x, y, jnp.broadcast_to(one, x.shape), fe.mul(x, y)), ok


def compress(p: jnp.ndarray) -> jnp.ndarray:
    """Extended point -> (..., 32) uint8 canonical compressed encoding."""
    X, Y, Z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    zi = fe.invert(Z)
    x = fe.mul(X, zi)
    y = fe.mul(Y, zi)
    enc = fe.encode_bytes(y)
    sign = fe.parity(x).astype(jnp.uint8) << 7
    return enc.at[..., 31].set(enc[..., 31] | sign)


def _nibbles(s: jnp.ndarray) -> jnp.ndarray:
    """(..., 32) uint8 scalar bytes -> (..., 64) int32 nibbles, little-endian."""
    s = s.astype(jnp.int32)
    lo = s & 0xF
    hi = (s >> 4) & 0xF
    return jnp.stack([lo, hi], axis=-1).reshape(s.shape[:-1] + (WINDOWS,))


def _select(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """One-hot select: table (..., 16, 4, 22) x idx (...,) -> (..., 4, 22)."""
    oh = (idx[..., None] == jnp.arange(16, dtype=jnp.int32)).astype(jnp.int32)
    return jnp.sum(table * oh[..., :, None, None], axis=-3)


def _base_table() -> np.ndarray:
    """Static cached multiples j*B for j=0..15, shape (16, 4, 22)."""
    rows = [_IDENTITY_CACHED]
    for j in range(1, 16):
        X, Y, Z, T = ref.scalar_mult(j, ref.BASE)
        zi = pow(Z, ref.P - 2, ref.P)
        x, y = (X * zi) % ref.P, (Y * zi) % ref.P
        rows.append(
            np.stack(
                [
                    fe.limbs_from_int((y + x) % ref.P),
                    fe.limbs_from_int((y - x) % ref.P),
                    fe.limbs_from_int((2 * ref.D * x * y) % ref.P),
                    fe.limbs_from_int(2),
                ]
            )
        )
    return np.stack(rows)


_BASE_TABLE = _base_table()


def _verify_kernel(
    pk: jnp.ndarray, rb: jnp.ndarray, s: jnp.ndarray, h: jnp.ndarray
) -> jnp.ndarray:
    """(B,32)x4 uint8 (pk, R bytes, S scalar, h scalar) -> (B,) bool."""
    A, ok_a = decompress(pk)
    a_neg = point_neg(A)
    a_cached = to_cached(a_neg)

    # Per-signature table of cached multiples j*(-A), j = 0..15, via scan.
    ident_c = jnp.broadcast_to(jnp.asarray(_IDENTITY_CACHED), a_cached.shape)

    def table_step(pt, _):
        nxt = point_add_cached(pt, a_cached)
        return nxt, to_cached(nxt)

    _, higher = lax.scan(table_step, a_neg, None, length=14)  # (14, B, 4, 22)
    table_a = jnp.concatenate(
        [ident_c[None], a_cached[None], higher], axis=0
    )  # (16, B, 4, 22)
    table_a = jnp.moveaxis(table_a, 0, -3)  # (B, 16, 4, 22)

    base_table = jnp.asarray(_BASE_TABLE)
    s_nib = _nibbles(s)  # (B, 64)
    h_nib = _nibbles(h)

    acc0 = jnp.broadcast_to(jnp.asarray(_IDENTITY), a_cached.shape)
    # msb-first over the 64 windows
    xs = jnp.stack([s_nib, h_nib], axis=-1)  # (B, 64, 2)
    xs = jnp.moveaxis(xs, -2, 0)[::-1]  # (64, B, 2)

    def body(acc, nib):
        for _ in range(4):
            acc = point_double(acc)
        acc = point_add_cached(acc, _select(base_table, nib[..., 0]))
        acc = point_add_cached(acc, _select(table_a, nib[..., 1]))
        return acc, None

    acc, _ = lax.scan(body, acc0, xs)
    enc = compress(acc)
    return ok_a & jnp.all(enc == rb, axis=-1)


verify_kernel = jax.jit(_verify_kernel)


def _verify_kernel_full(pk: jnp.ndarray, rb: jnp.ndarray, s: jnp.ndarray,
                        msg_blocks: jnp.ndarray,
                        n_blocks: jnp.ndarray) -> jnp.ndarray:
    """Fully on-device verify: takes (R, A, M) directly.

    ``msg_blocks`` are host-padded SHA-512 blocks of R || A || M (byte
    moves only — see tpu/sha512.pad_ed25519_messages); the chip computes
    h = SHA512(R||A||M) mod L and the double-scalar equation. Round 4's
    host hash loop (the next scaling wall at ~70k verifies/sec) is gone.
    """
    from . import sha512 as s512

    h = s512.reduce_mod_l(s512.sha512_blocks(msg_blocks, n_blocks))
    return _verify_kernel(pk, rb, s, h)


verify_kernel_full = jax.jit(_verify_kernel_full)


# ---------------------------------------------------------------------------
# Host wrapper: hashing, range checks, padding to stable batch shapes.
# ---------------------------------------------------------------------------


def _reduce_mod_l(h64: bytes) -> bytes:
    return (int.from_bytes(h64, "little") % ref.L).to_bytes(32, "little")


def _structural_ok(pk: bytes, sig: bytes) -> bool:
    """Per-item admission shared by BOTH host-hash and device-hash prep:
    the two tiers must reject identically or the size>=256 ingress tier
    would silently weaken validation."""
    if len(pk) != 32 or len(sig) != 64:
        return False
    return int.from_bytes(sig[32:], "little") < ref.L


def prepare_batch(
    pks: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host-side prep: h scalars + structural checks. Returns uint8 arrays
    (pk, R, S, h) of shape (B, 32) and a bool prevalid mask."""
    n = len(sigs)
    pk_a = np.zeros((n, 32), np.uint8)
    r_a = np.zeros((n, 32), np.uint8)
    s_a = np.zeros((n, 32), np.uint8)
    h_a = np.zeros((n, 32), np.uint8)
    pre = np.zeros(n, bool)
    for i, (pk, msg, sig) in enumerate(zip(pks, msgs, sigs)):
        if not _structural_ok(pk, sig):
            continue
        pre[i] = True
        pk_a[i] = np.frombuffer(pk, np.uint8)
        r_a[i] = np.frombuffer(sig[:32], np.uint8)
        s_a[i] = np.frombuffer(sig[32:], np.uint8)
        h = hashlib.sha512(sig[:32] + pk + msg).digest()
        h_a[i] = np.frombuffer(_reduce_mod_l(h), np.uint8)
    return pk_a, r_a, s_a, h_a, pre


def _pad_to(n: int) -> int:
    size = 8
    while size < n:
        size *= 2
    return size


def prepare_batch_device(
    pks: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes],
    max_blocks: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray,
           np.ndarray]:
    """Host prep for the full-device path: structural checks + padded
    SHA-512 blocks. NO hashing on the host — only byte moves."""
    from . import sha512 as s512

    n = len(sigs)
    pk_a = np.zeros((n, 32), np.uint8)
    r_a = np.zeros((n, 32), np.uint8)
    s_a = np.zeros((n, 32), np.uint8)
    pre = np.zeros(n, bool)
    prefixes = []
    kept_msgs = []
    for i, (pk, msg, sig) in enumerate(zip(pks, msgs, sigs)):
        if not _structural_ok(pk, sig):
            prefixes.append(b"\x00" * 64)
            kept_msgs.append(b"")
            continue
        pre[i] = True
        pk_a[i] = np.frombuffer(pk, np.uint8)
        r_a[i] = np.frombuffer(sig[:32], np.uint8)
        s_a[i] = np.frombuffer(sig[32:], np.uint8)
        prefixes.append(sig[:32] + pk)
        kept_msgs.append(msg)
    blocks, counts = s512.pad_ed25519_messages(prefixes, kept_msgs,
                                               max_blocks)
    return pk_a, r_a, s_a, blocks, counts, pre


def max_blocks_for(msgs: Sequence[bytes]) -> int:
    """Static SHA-512 block bucket for a batch: power-of-two block
    counts so jit caches stay small across message-length mixes."""
    longest = max((len(m) for m in msgs), default=0)
    need = (64 + longest + 17 + 127) // 128
    bucket = 1
    while bucket < need:
        bucket *= 2
    return bucket


def batch_verify(
    pks: Sequence[bytes], msgs: Sequence[bytes], sigs: Sequence[bytes]
) -> np.ndarray:
    """Verify a batch of Ed25519 signatures on device; returns (B,) bool.

    Hashing happens ON DEVICE (verify_kernel_full); the host only packs
    padded blocks and range-checks S.
    """
    n = len(sigs)
    if n == 0:
        return np.zeros(0, bool)
    max_blocks = max_blocks_for(msgs)
    pk_a, r_a, s_a, blocks, counts, pre = prepare_batch_device(
        pks, msgs, sigs, max_blocks)
    size = _pad_to(n)
    pad = size - n

    def padded(a):
        widths = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
        return jnp.asarray(np.pad(a, widths))

    ok = verify_kernel_full(padded(pk_a), padded(r_a), padded(s_a),
                            padded(blocks), padded(counts))
    # da: allow[device-sync] -- verify_batch is the kernel's OWN blocking entry point (callers wanting overlap use verify_kernel_full + deferred resolve)
    return np.asarray(ok)[:n] & pre
