"""Batched SHA-256 on TPU (uint32 JAX, no control flow on data).

Device half of the Merkle machinery: catchup verifies tens of thousands of
audit paths against a target root (reference hot loop:
``plenum/server/catchup/catchup_rep_service.py`` verifying CATCHUP_REPs via
``ledger/merkle_verifier.py``); here each fold step is one batched SHA-256
compression over the whole batch.

Only fixed-size messages are needed on device (Merkle nodes: 65-byte inputs
= prefix byte + two 32-byte hashes -> exactly two 64-byte blocks with
padding). The generic ``sha256_fixed`` handles any static length.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], dtype=np.uint32)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19], dtype=np.uint32)


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> n) | (x << (32 - n))


def _compress(state: jnp.ndarray, block_words: jnp.ndarray) -> jnp.ndarray:
    """state (..., 8) uint32, block_words (..., 16) uint32 -> (..., 8)."""

    def sched_body(carry, _):
        w = carry  # (..., 16) rolling window
        w15 = w[..., 1]
        w2 = w[..., 14]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> 3)
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> 10)
        nxt = w[..., 0] + s0 + w[..., 9] + s1
        w = jnp.concatenate([w[..., 1:], nxt[..., None]], axis=-1)
        return w, nxt

    # full message schedule: first 16 words + 48 derived
    _, extra = lax.scan(sched_body, block_words, None, length=48)
    extra = jnp.moveaxis(extra, 0, -1)  # (..., 48)
    w_all = jnp.concatenate([block_words, extra], axis=-1)  # (..., 64)

    def round_body(carry, inputs):
        a, b, c, d, e, f_, g, h = [carry[..., i] for i in range(8)]
        k, w = inputs
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f_) ^ (~e & g)
        t1 = h + s1 + ch + k + w
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        out = jnp.stack([t1 + t2, a, b, c, d + t1, e, f_, g], axis=-1)
        return out, None

    w_scan = jnp.moveaxis(w_all, -1, 0)  # (64, ...)
    k_scan = jnp.asarray(_K)
    if w_scan.ndim > 1:
        k_scan = k_scan.reshape((64,) + (1,) * (w_scan.ndim - 1))
        k_scan = jnp.broadcast_to(k_scan, w_scan.shape)
    final, _ = lax.scan(round_body, state, (k_scan, w_scan))
    return state + final


def _bytes_to_words(b: jnp.ndarray) -> jnp.ndarray:
    """(..., 4k) uint8 big-endian -> (..., k) uint32."""
    b = b.astype(jnp.uint32)
    quads = b.reshape(b.shape[:-1] + (-1, 4))
    return ((quads[..., 0] << 24) | (quads[..., 1] << 16)
            | (quads[..., 2] << 8) | quads[..., 3])


def _words_to_bytes(w: jnp.ndarray) -> jnp.ndarray:
    parts = [(w >> 24) & 0xFF, (w >> 16) & 0xFF, (w >> 8) & 0xFF, w & 0xFF]
    out = jnp.stack(parts, axis=-1)
    return out.reshape(w.shape[:-1] + (-1,)).astype(jnp.uint8)


def sha256_fixed(msg: jnp.ndarray, msg_len: int) -> jnp.ndarray:
    """SHA-256 of fixed-length messages: (..., msg_len) uint8 -> (..., 32).

    ``msg_len`` is static; padding is computed at trace time.
    """
    assert msg.shape[-1] == msg_len
    n_blocks = (msg_len + 9 + 63) // 64
    total = n_blocks * 64
    pad_len = total - msg_len
    batch_shape = msg.shape[:-1]
    pad = np.zeros(pad_len, np.uint8)
    pad[0] = 0x80
    bitlen = msg_len * 8
    pad[-8:] = np.frombuffer(bitlen.to_bytes(8, "big"), np.uint8)
    padded = jnp.concatenate(
        [msg, jnp.broadcast_to(jnp.asarray(pad), batch_shape + (pad_len,))],
        axis=-1)
    words = _bytes_to_words(padded)  # (..., 16*n_blocks)
    state = jnp.broadcast_to(jnp.asarray(_H0), batch_shape + (8,))
    for i in range(n_blocks):
        state = _compress(state, words[..., 16 * i: 16 * (i + 1)])
    return _words_to_bytes(state)


def merkle_node_hash(left: jnp.ndarray, right: jnp.ndarray) -> jnp.ndarray:
    """H(0x01 || left || right) batched: (..., 32) x2 -> (..., 32)."""
    prefix = jnp.broadcast_to(
        jnp.asarray(np.array([1], np.uint8)), left.shape[:-1] + (1,))
    return sha256_fixed(
        jnp.concatenate([prefix, left, right], axis=-1), 65)


# ---------------------------------------------------------------------------
# word-oriented fast path for the audit-path fold
#
# The generic path above converts words<->bytes around every tree level and
# runs the schedule/rounds as lax.scan (a concatenate per schedule step, no
# fusion across rounds). The fold below keeps the whole reduction in uint32
# lanes and unrolls schedule+rounds at trace time, which is what lets XLA
# fuse a full double-block compression per tree level — measured ~3x on the
# catchup verify (BASELINE config 5).
# ---------------------------------------------------------------------------


def _compress_grouped(state8: jnp.ndarray, w16: jnp.ndarray) -> jnp.ndarray:
    """One SHA-256 compression; state8 (..., 8), w16 (..., 16) uint32.

    Schedule and rounds run as scans over GROUPS of 16 unrolled steps —
    small enough bodies that XLA:CPU compiles them (a full 64-round
    unroll sends its backend pathological), few enough steps that the
    per-scan-iteration overhead stops dominating the math.
    """

    def sched_step(w, _):
        ws = [w[..., i] for i in range(16)]
        new = []
        for j in range(16):
            a15 = new[j - 15] if j - 15 >= 0 else ws[j + 1]
            a2 = new[j - 2] if j - 2 >= 0 else ws[j + 14]
            s0 = _rotr(a15, 7) ^ _rotr(a15, 18) ^ (a15 >> 3)
            s1 = _rotr(a2, 17) ^ _rotr(a2, 19) ^ (a2 >> 10)
            prev16 = ws[j]
            prev7 = new[j - 7] if j - 7 >= 0 else ws[j + 9]
            new.append(prev16 + s0 + prev7 + s1)
        nw = jnp.stack(new, axis=-1)
        return nw, nw

    _, extra = lax.scan(sched_step, w16, None, length=3)
    extra = jnp.moveaxis(extra, 0, -2).reshape(w16.shape[:-1] + (48,))
    w_all = jnp.concatenate([w16, extra], axis=-1)  # (..., 64)

    k_groups = jnp.asarray(_K.reshape(4, 16))
    w_groups = jnp.moveaxis(
        w_all.reshape(w_all.shape[:-1] + (4, 16)), -2, 0)  # (4, ..., 16)

    def round_group(carry, inp):
        ks, ws = inp
        a, b, c, d, e, f_, g, h = [carry[..., i] for i in range(8)]
        for i in range(16):
            s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = (e & f_) ^ (~e & g)
            t1 = h + s1 + ch + ks[i] + ws[..., i]
            s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            maj = (a & b) ^ (a & c) ^ (b & c)
            t2 = s0 + maj
            a, b, c, d, e, f_, g, h = t1 + t2, a, b, c, d + t1, e, f_, g
        return jnp.stack([a, b, c, d, e, f_, g, h], axis=-1), None

    final, _ = lax.scan(round_group, state8, (k_groups, w_groups))
    return state8 + final


def _merkle_node_hash_words(left: jnp.ndarray,
                            right: jnp.ndarray) -> jnp.ndarray:
    """H(0x01 || left || right) in uint32 lanes: (..., 8) x2 -> (..., 8).

    Message words are assembled by shifts (the 1-byte prefix misaligns
    the 32-byte halves against word boundaries); no byte round-trips.
    """
    l = [left[..., i] for i in range(8)]
    r = [right[..., i] for i in range(8)]
    w = [jnp.uint32(0x01000000) | (l[0] >> 8)]
    for i in range(1, 8):
        w.append((l[i - 1] << 24) | (l[i] >> 8))
    w.append((l[7] << 24) | (r[0] >> 8))
    for i in range(1, 8):
        w.append((r[i - 1] << 24) | (r[i] >> 8))
    state = jnp.broadcast_to(jnp.asarray(_H0), left.shape)
    state = _compress_grouped(state, jnp.stack(w, axis=-1))
    # block 2: right[31], 0x80 pad, zeros, bit length 65*8 = 520
    batch = left.shape[:-1]
    zero = jnp.broadcast_to(jnp.uint32(0), batch)
    w2 = [(r[7] << 24) | jnp.uint32(0x00800000)]
    w2.extend([zero] * 14)
    w2.append(jnp.broadcast_to(jnp.uint32(520), batch))
    return _compress_grouped(state, jnp.stack(w2, axis=-1))


def _audit_fold(leaf_hash: jnp.ndarray, index: jnp.ndarray,
                get_sibling, depth: int, path_len: jnp.ndarray,
                tree_size: jnp.ndarray, root: jnp.ndarray,
                words: bool) -> jnp.ndarray:
    """Shared RFC 6962 audit-path fold.

    ``words=True`` runs the uint32-lane grouped-unroll compression (the
    TPU fast path: no byte round-trips per level, fused round groups);
    ``words=False`` runs the portable byte-oriented path (XLA:CPU —
    the test platform — compiles the scan-based ``_compress`` fine but
    degenerates on the grouped kernel at batch sizes that matter).
    ``get_sibling(level)`` returns (B, 8) uint32 or (B, 32) uint8
    accordingly.
    """

    def body(carry, level):
        r, fn, fsn, consumed, ok = carry
        sibling = get_sibling(level)
        active = level < path_len
        use_left = (fn % 2 == 1) | (fn == fsn)  # sibling on the left
        left = jnp.where(use_left[..., None], sibling, r)
        right = jnp.where(use_left[..., None], r, sibling)
        combined = (_merkle_node_hash_words(left, right) if words
                    else merkle_node_hash(left, right))
        new_r = jnp.where(active[..., None], combined, r)
        # index/size shifting mirrors the scalar verifier
        shift_extra = use_left & active
        fn2, fsn2 = fn, fsn
        # while fn % 2 == 0 and fn != 0: fn >>=1; fsn >>=1 — bounded unroll
        for _ in range(depth):
            do = shift_extra & (fn2 % 2 == 0) & (fn2 != 0)
            fn2 = jnp.where(do, fn2 >> 1, fn2)
            fsn2 = jnp.where(do, fsn2 >> 1, fsn2)
        fn3 = jnp.where(active, fn2 >> 1, fn)
        fsn3 = jnp.where(active, fsn2 >> 1, fsn)
        # a level consumed while fsn already exhausted => malformed
        ok = ok & (~active | (fsn > 0))
        return (new_r, fn3, fsn3, consumed + active.astype(jnp.int32), ok), None

    b = leaf_hash.shape[0]
    init = (leaf_hash, index, tree_size - 1,
            jnp.zeros(b, jnp.int32), jnp.ones(b, bool))
    (r, fn, fsn, consumed, ok), _ = lax.scan(
        body, init, jnp.arange(depth, dtype=jnp.int32))
    ok = ok & (fsn == 0) & (consumed == path_len)
    return ok & jnp.all(r == root, axis=-1)


def _use_word_path() -> bool:
    return jax.default_backend() == "tpu"


def _verify_audit_paths(leaf_hash: jnp.ndarray, index: jnp.ndarray,
                        path: jnp.ndarray, path_len: jnp.ndarray,
                        tree_size: jnp.ndarray,
                        root: jnp.ndarray) -> jnp.ndarray:
    """Batched RFC 6962 audit-path fold (dense per-item paths).

    leaf_hash (B, 32) uint8; index (B,) int32; path (B, D, 32) uint8 padded;
    path_len (B,) int32 actual depths; tree_size (B,) int32; root (B, 32).
    Returns (B,) bool. D is the static max depth.
    """
    if _use_word_path():
        path_words = _bytes_to_words(path)  # (B, D, 8)
        return _audit_fold(
            _bytes_to_words(leaf_hash), index,
            lambda level: path_words[..., level, :],
            path.shape[-2], path_len, tree_size,
            _bytes_to_words(root), words=True)
    return _audit_fold(leaf_hash, index, lambda level: path[..., level, :],
                       path.shape[-2], path_len, tree_size, root,
                       words=False)


def _verify_audit_paths_indexed(leaf_hash: jnp.ndarray, index: jnp.ndarray,
                                node_table: jnp.ndarray,
                                path_idx: jnp.ndarray,
                                path_len: jnp.ndarray,
                                tree_size: jnp.ndarray,
                                root: jnp.ndarray) -> jnp.ndarray:
    """Audit-path fold over a deduplicated node table.

    Consecutive txn ranges (the catchup shape) share most sibling nodes, so
    the host sends node_table (U, 32) uint8 + path_idx (B, D) int32 instead
    of (B, D, 32) raw paths — an order of magnitude less host->device
    traffic for CATCHUP_REP verification.
    """
    if _use_word_path():
        table_words = _bytes_to_words(node_table)  # (U, 8)
        return _audit_fold(
            _bytes_to_words(leaf_hash), index,
            lambda level: table_words[path_idx[..., level], :],
            path_idx.shape[-1], path_len, tree_size,
            _bytes_to_words(root), words=True)
    return _audit_fold(
        leaf_hash, index,
        lambda level: node_table[path_idx[..., level], :],
        path_idx.shape[-1], path_len, tree_size, root, words=False)


verify_audit_paths = jax.jit(_verify_audit_paths)
verify_audit_paths_indexed = jax.jit(_verify_audit_paths_indexed)


def _merkle_node_hash_batch(left: jnp.ndarray,
                            right: jnp.ndarray) -> jnp.ndarray:
    """Platform-dispatched batched node hash: (B, 32) x2 -> (B, 32)."""
    if _use_word_path():
        return _words_to_bytes(_merkle_node_hash_words(
            _bytes_to_words(left), _bytes_to_words(right)))
    return merkle_node_hash(left, right)


merkle_node_hash_batch = jax.jit(_merkle_node_hash_batch)


def merkle_node_hash_bytes(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    """Host-array seam for the state-commit hash waves.

    (n, 32) uint8 host arrays in, resolved (n, 32) uint8 host array out —
    one per-level wave of the batched SMT commit rides one call. Waves
    are padded to the next power of two so jit specializes on
    O(log max-wave) shapes instead of one compile per distinct wave size.
    """
    n = left.shape[0]
    padded = 1
    while padded < n:
        padded <<= 1
    if padded != n:
        pad = np.zeros((padded - n, 32), np.uint8)
        left = np.concatenate([left, pad])
        right = np.concatenate([right, pad])
    out = merkle_node_hash_batch(jnp.asarray(left), jnp.asarray(right))
    # the wave result IS the product here (the commit cannot proceed to
    # the next level without these digests), and commits run off the
    # vote-plane tick loop — blocking is the contract, not a leak
    return np.asarray(out)[:n]  # da: allow[device-sync] -- wave result is the product; state commit runs outside the consensus tick loop


def sha256_host_oracle(data: bytes) -> bytes:  # pragma: no cover - test aid
    import hashlib

    return hashlib.sha256(data).digest()
